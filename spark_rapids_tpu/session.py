"""User-facing session + DataFrame API.

The reference plugs into Spark's existing frontend; this framework ships
its own minimal DataFrame surface (SURVEY.md §7: "a small DataFrame/plan
frontend plus a CPU engine that plays the role of CPU Spark").  The API
deliberately mirrors PySpark's shape (select/where/groupBy/agg/join/
orderBy/limit/collect/explain) so reference test cases translate
directly."""

from __future__ import annotations

from typing import Optional, Sequence, Union

import dataclasses

import pyarrow as pa

from spark_rapids_tpu import types as T
from spark_rapids_tpu.config import SQL_ENABLED, TpuConf, get_conf
from spark_rapids_tpu.execs.sort import SortKey
from spark_rapids_tpu.exprs.aggregates import (
    Average,
    Count,
    CountStar,
    First,
    Last,
    Max,
    Min,
    NamedAgg,
    Sum,
)
from spark_rapids_tpu.exprs.base import ColumnReference, Expression, lit
from spark_rapids_tpu.plan import logical as L
from spark_rapids_tpu.plan.planner import collect_exec, plan_query

ExprLike = Union[str, Expression]
AggLike = Union[NamedAgg, tuple]


class AnalysisException(TypeError):
    """Engine-layer analysis failure (ref: Spark's AnalysisException):
    the plan is rejected before execution — e.g. UNION members with no
    common column type.  Subclasses TypeError so generic type-error
    handling keeps working, but frontends should catch THIS (a blanket
    `except TypeError` would rebrand incidental engine bugs as user
    errors)."""


def col(name: str) -> ColumnReference:
    return ColumnReference(name)


def _expr(e: ExprLike) -> Expression:
    return ColumnReference(e) if isinstance(e, str) else e


def _coerce_union_member(plan: "L.LogicalPlan",
                         widened: Sequence[Optional[T.DataType]]):
    """Project a UNION member onto the widened column types (positional
    bound references: name-based ones would resolve duplicate output
    names to the first occurrence); no-op when nothing changes."""
    from spark_rapids_tpu.exprs.base import Alias, BoundReference
    from spark_rapids_tpu.exprs.cast import Cast

    exprs: list[Expression] = []
    changed = False
    for i, (f, ct) in enumerate(zip(plan.schema.fields, widened)):
        ref = BoundReference(i, f.dtype, f.nullable, f.name)
        if ct is not None and f.dtype != ct:
            exprs.append(Alias(Cast(ref, ct), f.name))
            changed = True
        else:
            exprs.append(ref)
    return L.Project(exprs, plan) if changed else plan


# function-style aggregate constructors (pyspark.sql.functions shape)
def sum_(e: ExprLike) -> Sum:
    return Sum(_expr(e))


def count(e: ExprLike) -> Count:
    return Count(_expr(e))


def count_distinct(e: ExprLike):
    from spark_rapids_tpu.exprs.aggregates import CountDistinct

    return CountDistinct(_expr(e))


def count_star() -> CountStar:
    return CountStar()


def min_(e: ExprLike) -> Min:
    return Min(_expr(e))


def max_(e: ExprLike) -> Max:
    return Max(_expr(e))


def avg(e: ExprLike) -> Average:
    return Average(_expr(e))


def collect_list(e: ExprLike):
    from spark_rapids_tpu.exprs.aggregates import CollectList

    return CollectList(_expr(e))


def collect_set(e: ExprLike):
    from spark_rapids_tpu.exprs.aggregates import CollectSet

    return CollectSet(_expr(e))


def first(e: ExprLike, ignore_nulls: bool = False) -> First:
    return First(_expr(e), ignore_nulls)


def last(e: ExprLike, ignore_nulls: bool = False) -> Last:
    return Last(_expr(e), ignore_nulls)


def array(*exprs: ExprLike):
    from spark_rapids_tpu.exprs.collections import CreateArray

    return CreateArray(*[_expr(e) for e in exprs])


def from_unixtime(e: ExprLike, fmt: str = "yyyy-MM-dd HH:mm:ss"):
    from spark_rapids_tpu.exprs.datetime import FromUnixTime

    return FromUnixTime(_expr(e), fmt)


def date_format(e: ExprLike, fmt: str = "yyyy-MM-dd"):
    from spark_rapids_tpu.exprs.datetime import DateFormatClass

    return DateFormatClass(_expr(e), fmt)


def scalar_subquery(df) -> Expression:
    """A 1x1 DataFrame as a scalar expression (ref: GpuScalarSubquery);
    evaluated once at planning and spliced in as a literal."""
    from spark_rapids_tpu.exprs.subquery import ScalarSubquery

    return ScalarSubquery(df._plan)


def rand(seed: int = 0):
    from spark_rapids_tpu.exprs.nondeterministic import Rand

    return Rand(seed)


def monotonically_increasing_id():
    from spark_rapids_tpu.exprs.nondeterministic import (
        MonotonicallyIncreasingID,
    )

    return MonotonicallyIncreasingID()


def spark_partition_id():
    from spark_rapids_tpu.exprs.nondeterministic import SparkPartitionID

    return SparkPartitionID()


def nanvl(a: ExprLike, b: ExprLike):
    from spark_rapids_tpu.exprs.math import NaNvl

    return NaNvl(_expr(a), _expr(b))


def replace_(e: ExprLike, search: str, replacement: str):
    from spark_rapids_tpu.exprs.strings import StringReplace

    return StringReplace(_expr(e), lit(search), lit(replacement))


def regexp_replace(e: ExprLike, pattern: str, replacement: str):
    from spark_rapids_tpu.exprs.strings import RegExpReplace

    return RegExpReplace(_expr(e), lit(pattern), lit(replacement))


def lpad(e: ExprLike, length: int, pad: str = " "):
    from spark_rapids_tpu.exprs.strings import StringLPad

    return StringLPad(_expr(e), lit(length), lit(pad))


def rpad(e: ExprLike, length: int, pad: str = " "):
    from spark_rapids_tpu.exprs.strings import StringRPad

    return StringRPad(_expr(e), lit(length), lit(pad))


def locate(substr: str, e: ExprLike, start: int = 1):
    from spark_rapids_tpu.exprs.strings import StringLocate

    return StringLocate(lit(substr), _expr(e), lit(start))


def substring_index(e: ExprLike, delim: str, count: int):
    from spark_rapids_tpu.exprs.strings import SubstringIndex

    return SubstringIndex(_expr(e), lit(delim), lit(count))


def initcap(e: ExprLike):
    from spark_rapids_tpu.exprs.strings import InitCap

    return InitCap(_expr(e))


def concat_ws(sep: str, *exprs: ExprLike):
    from spark_rapids_tpu.exprs.strings import ConcatWs

    return ConcatWs(lit(sep), *[_expr(e) for e in exprs])


def _forbid_nested_explode(e: Expression) -> None:
    """Explode is only valid at the top level of a select list (Spark
    raises the same analysis error for nested generators)."""
    from spark_rapids_tpu.exprs.collections import Explode

    for c in e.children:
        if isinstance(c, Explode):
            raise ValueError(
                "explode/posexplode must be at the top level of a "
                "select list")
        _forbid_nested_explode(c)


def explode(e: ExprLike):
    from spark_rapids_tpu.exprs.collections import Explode

    return Explode(_expr(e))


def explode_outer(e: ExprLike):
    from spark_rapids_tpu.exprs.collections import Explode

    return Explode(_expr(e), outer=True)


def posexplode(e: ExprLike):
    from spark_rapids_tpu.exprs.collections import Explode

    return Explode(_expr(e), pos=True)


def posexplode_outer(e: ExprLike):
    from spark_rapids_tpu.exprs.collections import Explode

    return Explode(_expr(e), pos=True, outer=True)


def array_size(e: ExprLike):
    from spark_rapids_tpu.exprs.collections import Size

    return Size(_expr(e))


def get_item(e: ExprLike, index: int):
    from spark_rapids_tpu.exprs.collections import GetArrayItem

    return GetArrayItem(_expr(e), lit(index))


def array_contains(e: ExprLike, value):
    from spark_rapids_tpu.exprs.collections import ArrayContains

    return ArrayContains(_expr(e), lit(value))


def _extract_windows(e: Expression, acc: list) -> Expression:
    """Replace every WindowExpression subtree with a reference to a
    generated column the Window node will produce."""
    from spark_rapids_tpu.exprs.window import WindowExpression

    if isinstance(e, WindowExpression):
        name = f"__w{len(acc)}"
        acc.append((e, name))
        return ColumnReference(name)
    kids = e.children
    if not kids:
        return e
    new = [_extract_windows(c, acc) for c in kids]
    if all(n is o for n, o in zip(new, kids)):
        return e
    return e.with_children(new)


class TpuSession:
    """Counterpart of the SparkSession with the plugin installed
    (ref: SQLPlugin.scala — here session == plugin)."""

    def __init__(self, conf: Optional[TpuConf] = None,
                 tenant: str = "default",
                 priority: Optional[int] = None):
        from spark_rapids_tpu.eventlog import maybe_writer
        from spark_rapids_tpu.tools.profiling import (
            HISTORY_CAPACITY,
            QueryHistory,
        )

        self.conf = conf or get_conf()
        #: serving-tier identity: which admission queue this session's
        #: queries join, and with what weighted-fair share (None =
        #: spark.rapids.tpu.serving.defaultPriority).  Inert unless
        #: serving.maxConcurrent > 0 (docs/serving.md).
        self.tenant = tenant
        self.priority = priority
        #: recent TPU-collected queries, input to the profiling tool
        self.history = QueryHistory(
            int(self.conf.get(HISTORY_CAPACITY)))
        #: persistent event-log writer, or None when
        #: spark.rapids.tpu.eventLog.enabled=false — the disabled
        #: path's entire per-query cost is one `is not None` check in
        #: _collect_tpu (docs/eventlog.md)
        self._eventlog = maybe_writer(self.conf)
        self._plan_cache = None  # lazy; most sessions never prepare
        #: in-flight CancelTokens of this session's queries (the
        #: session.cancel() surface; serving/cancel.py) — empty and
        #: untouched while serving.cancellation.enabled is false
        from spark_rapids_tpu.serving.cancel import TokenSet

        self._tokens = TokenSet()

    @property
    def plan_cache(self):
        """This session's prepared-plan cache (LRU of lowered exec
        trees, spark.rapids.tpu.serving.planCache.capacity); created on
        first use so non-serving sessions pay nothing."""
        if self._plan_cache is None:
            from spark_rapids_tpu.serving import PLAN_CACHE_CAPACITY
            from spark_rapids_tpu.serving.plan_cache import PlanCache

            self._plan_cache = PlanCache(
                int(self.conf.get(PLAN_CACHE_CAPACITY)))
        return self._plan_cache

    def prepare(self, df: "DataFrame") -> "PreparedQuery":
        """Prepare a DataFrame template: lower it ONCE into the plan
        cache and return a PreparedQuery whose execute()/
        execute_stream() re-drain the cached lowered plan — repeated
        templates skip parse/plan/tag/lower entirely (docs/serving.md).
        SQL-text templates with :name parameters prepare through
        ``frontends.sql.SqlSession.prepare``."""
        from spark_rapids_tpu.serving.prepared import PreparedQuery

        if not isinstance(df, DataFrame):
            raise TypeError(
                "TpuSession.prepare takes a DataFrame; for SQL text "
                "use frontends.sql.SqlSession.prepare(sql)")
        pq = PreparedQuery(self, df=df)
        pq._resolve(None)  # warm: pay the lowering at prepare time
        return pq

    def cancel(self, query_id: Optional[int] = None,
               reason: str = "cancelled") -> int:
        """Cooperatively cancel this session's in-flight queries (all
        of them, or just ``query_id`` — the id ``_collect_tpu``
        returns and the history/event log record).  The cancelled
        collect/stream raises
        :class:`~spark_rapids_tpu.serving.cancel.QueryCancelled` at
        its next checkpoint and unwinds cleanly (admission slot
        released, pipeline stages joined, exec tree closed); its
        event-log record carries ``engine="cancelled"``.  Returns how
        many queries this call newly cancelled (0 when none matched —
        a query that already finished cannot be cancelled).  Requires
        spark.rapids.tpu.serving.cancellation.enabled (the default);
        queries still waiting in the admission queue have no id yet
        and are only reached by the cancel-all form
        (docs/robustness.md)."""
        return self._tokens.cancel(query_id, reason)

    @property
    def event_log_path(self) -> Optional[str]:
        """Path of this session's event-log file (None when the event
        log is disabled).  Records are appended by the history snapshot
        worker; reading ``session.history.events`` drains it, so the
        file is complete afterwards."""
        return self._eventlog.path if self._eventlog is not None \
            else None

    def export_trace(self, path: str) -> str:
        """Write the process's collected engine trace as Chrome Trace
        Format JSON (viewable in Perfetto / chrome://tracing).  Run
        queries with spark.rapids.tpu.trace.enabled=true first; see
        docs/observability.md for overlaying the device_trace()
        XPlane capture."""
        from spark_rapids_tpu.trace.export import export_chrome_trace

        return export_chrome_trace(path)

    # -- sources -------------------------------------------------------- #

    def create_dataframe(self, data: Union[pa.Table, dict]) -> "DataFrame":
        table = data if isinstance(data, pa.Table) else pa.table(data)
        return DataFrame(L.InMemoryRelation(table), self)

    def read_parquet(self, *paths: str,
                     columns: Optional[Sequence[str]] = None) -> "DataFrame":
        return DataFrame(L.ParquetRelation(list(paths), columns), self)

    def read_orc(self, *paths: str,
                 columns: Optional[Sequence[str]] = None) -> "DataFrame":
        return DataFrame(L.OrcRelation(list(paths), columns), self)

    def read_csv(self, *paths: str,
                 schema: Optional[T.Schema] = None) -> "DataFrame":
        return DataFrame(L.CsvRelation(list(paths), schema), self)

    def range(self, start: int, end: Optional[int] = None,
              step: int = 1) -> "DataFrame":
        if end is None:
            start, end = 0, start
        return DataFrame(L.RangeRel(start, end, step), self)

    def enable_collective_shuffle(self, n_devices: Optional[int] = None,
                                  mesh=None):
        """Activate the tier-2 collective shuffle transport over a device
        mesh: grouped aggregates lower to fused all_to_all SPMD programs
        (ref: the spark.rapids.shuffle.transport.enabled switch +
        UCXShuffleTransport bring-up, re-designed for ICI collectives)."""
        from spark_rapids_tpu.parallel.mesh import make_mesh, set_active_mesh
        from spark_rapids_tpu.shuffle.transport import SHUFFLE_TRANSPORT

        mesh = mesh or make_mesh(n_devices)
        set_active_mesh(mesh)
        self.conf.set(SHUFFLE_TRANSPORT.key, "collective")
        return mesh

    def disable_collective_shuffle(self) -> None:
        from spark_rapids_tpu.parallel.mesh import set_active_mesh
        from spark_rapids_tpu.shuffle.transport import SHUFFLE_TRANSPORT

        set_active_mesh(None)
        self.conf.set(SHUFFLE_TRANSPORT.key, "local")


def _begin_query(session: "TpuSession", conf) -> tuple:
    """Per-query prologue, ONE definition shared by the materialized
    (`_collect_tpu_admitted`) and streaming (`_stream_tpu`) collect
    paths so they can never drift: align the process-global subsystems
    with this session's conf — the tracer (spans carry this query),
    the fault registry (conf-armed chaos schedules take effect per
    query), the device semaphore (per-session concurrentTpuTasks
    changes resize the live permit pool, which also re-sizes serving
    admission), the device-utilization ledger and the telemetry
    sampler (which also attaches this session's event-log writer for
    periodic `telemetry` records) and the live ops plane (one conf
    read when disabled; enabled, the query registers in-flight under
    /queries with its tenant and cancel token) — then allocate the query id, snapshot the event-log
    counters (the per-query event-log check: `elog` is None when
    disabled — no writer thread, nothing on the batch loop) and stamp
    the clocks.

    Returns (qid, elog, pre, conf_hash, start_ts, t0, t0_ns)."""
    import time as _time

    from spark_rapids_tpu import obs as _obs
    from spark_rapids_tpu import trace as _trace
    from spark_rapids_tpu.eventlog import conf_fingerprint
    from spark_rapids_tpu.memory.semaphore import TpuSemaphore
    from spark_rapids_tpu.robustness import faults as _faults
    from spark_rapids_tpu.robustness import lock_tracker as _locks
    from spark_rapids_tpu.trace import ledger as _ledger
    from spark_rapids_tpu.trace import telemetry as _telemetry

    _trace.sync_conf(conf)
    _faults.sync_conf(conf)
    _locks.sync_conf(conf)
    TpuSemaphore.sync_conf(conf)
    _ledger.sync_conf(conf)
    _telemetry.sync_conf(conf, writer=session._eventlog)
    _obs.sync_conf(conf, writer=session._eventlog)
    qid = session.history.allocate_id()
    conf_hash = conf_fingerprint(conf)
    if _obs.REGISTRY.enabled:
        # register in the live ops plane (/queries) with whatever is
        # known at the prologue; plan/plan_hash arrive via annotate()
        # once planning renders them
        from spark_rapids_tpu.serving import cancel as _cancel

        _obs.REGISTRY.begin(qid, tenant=session.tenant,
                            token=_cancel.current_token(),
                            conf_hash=conf_hash)
    elog = session._eventlog
    pre = elog.query_begin() if elog is not None else None
    return (qid, elog, pre, conf_hash, _time.time(),
            _time.perf_counter(), _time.perf_counter_ns())


def _record_query(session: "TpuSession", explain_text: str, exec_tree,
                  qid: int, conf_hash: str, start_ts: float, t0: float,
                  t0_ns: int, on_event, baseline=None,
                  engine: str = "tpu") -> None:
    """Per-query epilogue shared by the collect paths: the history
    record with the full clock set (the event-log hook rides
    `on_event` onto the snapshot worker).  `baseline` — a settled
    pre-drain metric snapshot — makes the record report THIS
    execution's deltas on a re-drained cached exec tree (the metrics
    on the long-lived tree itself accumulate); `exec_tree` may be
    None for executions that ran no operators at all (a result-cache
    hit).  With the ops plane on, the query deregisters from the live
    registry here and its (tenant, wall, admission wait) observation
    feeds the SLO watchdog's rolling windows — `engine` labels the
    outcome ("tpu", "cancelled", "deadline_exceeded", ...)."""
    import time as _time

    from spark_rapids_tpu import obs as _obs

    # deregister BEFORE the history record: the serving context (the
    # admission wait the watchdog windows) is still live here, and the
    # registry must never show a query whose record already landed
    _obs.REGISTRY.finish(qid, engine=engine)
    session.history.record(
        explain_text, exec_tree, _time.perf_counter() - t0,
        query_id=qid, start_ts=start_ts, end_ts=_time.time(),
        start_ns=t0_ns, end_ns=_time.perf_counter_ns(),
        conf_hash=conf_hash, on_event=on_event, baseline=baseline)


def _prune_scan_columns(plan, exprs):
    """Column pruning into file scans (Spark's ColumnPruning rule, at
    the logical-build seam where references are still by NAME): a
    select directly above an unpruned file relation rebuilds the
    relation to read only the referenced columns — fewer bytes
    decoded, and rebase/fastpar checks see the true read schema."""
    import copy as _copy

    from spark_rapids_tpu.plan.logical import OrcRelation, ParquetRelation

    if not isinstance(plan, (ParquetRelation, OrcRelation)) \
            or plan.columns is not None:
        return plan
    refs: set = set()

    def walk(e) -> bool:
        """Collect referenced names; False = unprunable reference."""
        from spark_rapids_tpu.exprs.base import BoundReference
        from spark_rapids_tpu.exprs.nondeterministic import InputFileName
        from spark_rapids_tpu.exprs.window import WindowExpression

        if isinstance(e, BoundReference):
            return False  # pre-bound ordinals would shift
        if isinstance(e, InputFileName):
            return True  # rewritten later; reads no file column
        if isinstance(e, ColumnReference):
            refs.add(e.col_name)
            return True
        return all(walk(c) for c in e.children)

    if not all(walk(e) for e in exprs):
        return plan
    names = [f.name for f in plan.schema.fields if f.name in refs]
    if not names or len(names) == len(plan.schema.fields):
        # nothing referenced (pure generated columns) or nothing to
        # prune: keep the full scan — the zero-column count-only path
        # belongs to aggregates, not projections
        return plan
    # COPY the relation instead of re-running __init__: the ctor would
    # re-expand paths (losing Hive partition discovery on bare file
    # lists) and re-read a footer
    part_names = {f.name for f in plan.partition_fields}
    by_name = {f.name: f for f in plan.schema.fields}
    rel2 = _copy.copy(plan)
    rel2.columns = [n for n in names if n not in part_names]
    rel2.partition_fields = [f for f in plan.partition_fields
                             if f.name in refs]
    rel2._schema = T.Schema(
        [by_name[n] for n in names if n not in part_names]
        + rel2.partition_fields)
    return rel2


class _CoGrouped:
    def __init__(self, left: "GroupedData", right: "GroupedData"):
        self._left = left
        self._right = right

    def apply_in_pandas(self, fn, schema) -> "DataFrame":
        from spark_rapids_tpu.columnar.arrow import schema_from_arrow

        if isinstance(schema, pa.Schema):
            schema = schema_from_arrow(schema)
        return DataFrame(
            L.CoGroupedPandas(
                self._left._key_names(), self._right._key_names(),
                fn, schema, self._left._df._plan,
                self._right._df._plan),
            self._left._df._session)


class GroupedData:
    """Grouped frame; `grouping_sets` (a list of included-key-name sets)
    switches to the Expand-based grouping-set rewrite that Spark's
    analyzer performs for rollup/cube (ref: GpuExpandExec.scala:67)."""

    def __init__(self, df: "DataFrame", keys: list[Expression],
                 grouping_sets: Optional[list[frozenset]] = None):
        self._df = df
        self._keys = keys
        self._sets = grouping_sets
        self._pivot: Optional[tuple] = None

    def pivot(self, pivot_col: ExprLike,
              values: Sequence) -> "GroupedData":
        """pyspark-shaped pivot with an EXPLICIT value list (ref:
        GpuPivotFirst; Spark's implicit-distinct-values mode needs a
        pre-query and is not supported): each aggregate expands into
        one masked aggregate per pivot value, named `{value}` for a
        single aggregate or `{value}_{name}` otherwise."""
        if self._sets is not None:
            raise ValueError("pivot over rollup/cube is not supported")
        self._pivot = (_expr(pivot_col), list(values))
        return self

    def _named(self, aggs) -> list[NamedAgg]:
        named = []
        for i, a in enumerate(aggs):
            if isinstance(a, NamedAgg):
                named.append(a)
            elif isinstance(a, tuple):
                fn, name = a
                named.append(NamedAgg(fn, name))
            else:
                named.append(NamedAgg(a, f"{a.name}_{i}"))
        return named

    def agg(self, *aggs: AggLike) -> "DataFrame":
        from spark_rapids_tpu.exprs.aggregates import CountDistinct

        named = self._named(aggs)
        if self._pivot is not None:
            named = self._expand_pivot(named)
        named = [na2 for na in named
                 for na2 in (na.fn.expand(na.out_name)
                             if hasattr(na.fn, "expand") else (na,))]
        if any(isinstance(na.fn, CountDistinct) for na in named):
            return self._agg_distinct(named)
        if self._sets is not None:
            return self._agg_grouping_sets(named)
        return DataFrame(
            L.Aggregate(self._keys, named, self._df._plan),
            self._df._session)

    def _key_names(self) -> list[str]:
        names = []
        for k in self._keys:
            if isinstance(k, ColumnReference):
                names.append(k.col_name)
            elif hasattr(k, "out_name"):
                names.append(k.out_name)
            else:
                raise ValueError(
                    "grouped pandas UDFs need plain column keys")
        return names

    def cogroup(self, other: "GroupedData") -> "_CoGrouped":
        """pyspark cogroup: pair with another grouped frame for
        applyInPandas over co-grouped frames."""
        return _CoGrouped(self, other)

    def apply_in_pandas(self, fn, schema) -> "DataFrame":
        """pyspark applyInPandas (ref: GpuFlatMapGroupsInPandasExec):
        fn(pd.DataFrame per group) -> pd.DataFrame with `schema`."""
        from spark_rapids_tpu.columnar.arrow import schema_from_arrow

        if isinstance(schema, pa.Schema):
            schema = schema_from_arrow(schema)
        return DataFrame(
            L.GroupedPandas(self._key_names(), fn, schema, "flatmap",
                            self._df._plan),
            self._df._session)

    def agg_in_pandas(self, *aggs) -> "DataFrame":
        """Pandas UDAFs (ref: GpuAggregateInPandasExec): each agg is
        (out_name, fn(pd.Series) -> scalar, input_col); output =
        group keys + one DOUBLE column per agg."""
        from spark_rapids_tpu import types as T

        child_schema = self._df._plan.schema
        key_names = self._key_names()
        fields = [child_schema.field(k) for k in key_names]
        fields += [T.Field(name, T.DOUBLE, True)
                   for name, _fn, _c in aggs]
        return DataFrame(
            L.GroupedPandas(key_names, list(aggs), T.Schema(fields),
                            "agg", self._df._plan),
            self._df._session)

    def transform_in_pandas(self, *fns) -> "DataFrame":
        """Pandas window UDFs over unbounded frames (ref:
        GpuWindowInPandasExecBase): each entry is (out_name,
        fn(pd.Series) -> scalar, input_col); the scalar broadcasts to
        every row of its group, appended after the child's columns."""
        from spark_rapids_tpu import types as T

        child_schema = self._df._plan.schema
        fields = list(child_schema.fields) + [
            T.Field(name, T.DOUBLE, True) for name, _fn, _c in fns]
        return DataFrame(
            L.GroupedPandas(self._key_names(), list(fns),
                            T.Schema(fields), "window",
                            self._df._plan),
            self._df._session)

    def _expand_pivot(self, named: list[NamedAgg]) -> list[NamedAgg]:
        from spark_rapids_tpu.exprs.aggregates import expand_pivot_aggs

        pcol, values = self._pivot
        return expand_pivot_aggs(pcol, values, named,
                                 single=len(named) == 1)

    def _agg_distinct(self, named: list[NamedAgg]) -> "DataFrame":
        """count(DISTINCT x) as a two-level aggregate: group by
        (keys, x) to dedupe, then count x per key group (the
        single-distinct specialization of Spark's
        RewriteDistinctAggregates)."""
        from spark_rapids_tpu.exprs.aggregates import Count, CountDistinct
        from spark_rapids_tpu.execs.jit_cache import expr_key

        if self._sets is not None:
            raise ValueError(
                "count_distinct over rollup/cube is not supported yet")
        dist = [na for na in named if isinstance(na.fn, CountDistinct)]
        others = [na for na in named if not isinstance(na.fn, CountDistinct)]
        if others:
            raise ValueError(
                "mixing count_distinct with other aggregates is not "
                "supported yet")
        key0 = expr_key(dist[0].fn.child)
        if any(expr_key(na.fn.child) != key0 for na in dist[1:]):
            raise ValueError(
                "multiple count_distinct over different expressions are "
                "not supported yet")
        inner_x = dist[0].fn.child.alias("__dist")
        inner = L.Aggregate(self._keys + [inner_x], [], self._df._plan)
        key_names = [f.name for f in inner.schema.fields[:len(self._keys)]]
        outer = L.Aggregate(
            [ColumnReference(n) for n in key_names],
            [NamedAgg(Count(ColumnReference("__dist")), na.out_name)
             for na in dist],
            inner)
        return DataFrame(outer, self._df._session)

    def _agg_grouping_sets(self, named: list[NamedAgg]) -> "DataFrame":
        from spark_rapids_tpu.exprs import base as B

        child = self._df._plan
        key_names = []
        for k in self._keys:
            if not isinstance(k, ColumnReference):
                raise ValueError(
                    "rollup/cube keys must be plain columns")
            key_names.append(k.col_name)
        names = [f.name for f in child.schema.fields] + ["__gid"]
        projections = []
        for gid, included in enumerate(self._sets):
            proj: list[Expression] = []
            for f in child.schema.fields:
                if f.name in key_names and f.name not in included:
                    proj.append(B.Literal(None, f.dtype))
                else:
                    proj.append(ColumnReference(f.name))
            proj.append(B.Literal.of(gid))
            projections.append(proj)
        expand = L.Expand(projections, names, child)
        agg = L.Aggregate(
            list(self._keys) + [ColumnReference("__gid")], named, expand)
        out_names = key_names + [na.out_name for na in named]
        return DataFrame(
            L.Project([ColumnReference(n) for n in out_names], agg),
            self._df._session)


class DataFrame:
    def __init__(self, plan: L.LogicalPlan, session: TpuSession):
        self._plan = plan
        self._session = session

    @property
    def schema(self) -> T.Schema:
        return self._plan.schema

    # -- transformations ------------------------------------------------ #

    def select(self, *exprs: ExprLike) -> "DataFrame":
        """Projection; window expressions anywhere in the select list are
        extracted into Window nodes under the projection (one node per
        (partition_by, order_by) group), mirroring Spark's
        ExtractWindowExpressions analysis rule."""
        from spark_rapids_tpu.exprs.window import WindowExpression

        from spark_rapids_tpu.exprs.base import Alias
        from spark_rapids_tpu.exprs.collections import Explode

        exprs_ = [_expr(e) for e in exprs]
        acc: list[tuple[WindowExpression, str]] = []
        rewritten = [_extract_windows(e, acc) for e in exprs_]
        # prune on the ORIGINAL exprs: window/generator extraction
        # introduces synthetic refs that hide the real columns
        plan = _prune_scan_columns(self._plan, exprs_)

        # generator extraction (ref: Spark's ExtractGenerator rule):
        # a top-level explode/posexplode becomes a Generate node under
        # the projection
        gens = [(i, e) for i, e in enumerate(rewritten)
                if isinstance(e, Explode)
                or (isinstance(e, Alias) and isinstance(e.child, Explode))]
        if gens:
            if len(gens) > 1:
                raise ValueError("only one explode per select")
            i, e = gens[0]
            alias_name = e.out_name if isinstance(e, Alias) else None
            gen = e.child if isinstance(e, Alias) else e
            if gen.pos and alias_name is not None:
                raise ValueError(
                    "posexplode yields two columns (pos, col); alias "
                    "them with a following select")
            out_name = alias_name or "col"
            plan = L.Generate(gen, plan, out_name=out_name)
            repl: list[Expression] = []
            if gen.pos:
                repl.append(ColumnReference("pos"))
            repl.append(ColumnReference(out_name))
            rewritten[i:i + 1] = repl
        for e in rewritten:
            _forbid_nested_explode(e)

        if acc:
            from spark_rapids_tpu.execs.jit_cache import exprs_key

            from spark_rapids_tpu.execs.jit_cache import expr_key

            groups: dict[tuple, list] = {}
            for we, name in acc:
                # structural keys for BOTH components: display repr is
                # name-only and would merge distinct order-by exprs that
                # share a name (or split structurally identical ones)
                gk = (exprs_key(we.spec.partition_by),
                      tuple((expr_key(k.expr), k.descending, k.nulls_last)
                            for k in we.spec.order_by))
                groups.setdefault(gk, []).append((we, name))
            for group in groups.values():
                plan = L.Window(group, plan)
        return DataFrame(L.Project(rewritten, plan), self._session)

    def where(self, cond: Expression) -> "DataFrame":
        return DataFrame(L.Filter(cond, self._plan), self._session)

    filter = where

    def with_column(self, name: str, e: Expression) -> "DataFrame":
        exprs: list[Expression] = [
            ColumnReference(f.name) for f in self.schema.fields
            if f.name != name]
        exprs.append(e.alias(name))
        return self.select(*exprs)

    def group_by(self, *keys: ExprLike) -> GroupedData:
        return GroupedData(self, [_expr(k) for k in keys])

    def rollup(self, *keys: str) -> GroupedData:
        """GROUP BY ROLLUP: hierarchical grouping sets
        (a,b,c) -> {(a,b,c), (a,b), (a), ()}."""
        sets = [frozenset(keys[:i]) for i in range(len(keys), -1, -1)]
        return GroupedData(self, [_expr(k) for k in keys],
                           grouping_sets=sets)

    def cube(self, *keys: str) -> GroupedData:
        """GROUP BY CUBE: all subsets of the grouping keys."""
        import itertools

        sets = [frozenset(c)
                for r in range(len(keys), -1, -1)
                for c in itertools.combinations(keys, r)]
        return GroupedData(self, [_expr(k) for k in keys],
                           grouping_sets=sets)

    def grouping_sets(self, sets: Sequence[Sequence[str]],
                      keys: Sequence[str]) -> GroupedData:
        return GroupedData(self, [_expr(k) for k in keys],
                           grouping_sets=[frozenset(s) for s in sets])

    def agg(self, *aggs: AggLike) -> "DataFrame":
        return GroupedData(self, []).agg(*aggs)

    def join(self, other: "DataFrame", on: Union[str, Sequence[str], None]
             = None, how: str = "inner",
             left_on: Optional[Sequence[ExprLike]] = None,
             right_on: Optional[Sequence[ExprLike]] = None,
             condition: Optional[Expression] = None) -> "DataFrame":
        if on is not None:
            names = [on] if isinstance(on, str) else list(on)
            lk = [ColumnReference(n) for n in names]
            rk = [ColumnReference(n) for n in names]
        else:
            lk = [_expr(e) for e in (left_on or [])]
            rk = [_expr(e) for e in (right_on or [])]
        return DataFrame(
            L.Join(self._plan, other._plan, lk, rk, how, condition),
            self._session)

    def union(self, other: "DataFrame") -> "DataFrame":
        """Spark's WidenSetOperationTypes, enforced at the engine layer
        (every frontend funnels through here): members are coerced
        per-column to a common type, or analysis fails.  Without this,
        TpuUnionExec re-tags every member batch with the first member's
        schema, silently truncating e.g. DOUBLE data shipped under an
        INT tag.  The lint dtype-flow checker (DT001) remains the
        backstop for hand-built L.Union plans that bypass this method."""
        lf, rf = self.schema.fields, other.schema.fields
        if len(lf) != len(rf):
            raise AnalysisException(
                f"UNION members must have the same column count "
                f"({len(lf)} vs {len(rf)})")
        widened: list[Optional[T.DataType]] = []
        for i, (a, b) in enumerate(zip(lf, rf)):
            if a.dtype == b.dtype:
                widened.append(None)
                continue
            ct = T.common_type(a.dtype, b.dtype)
            if ct is None:
                raise AnalysisException(
                    f"UNION member column {i + 1} ({a.name!r}) has "
                    f"incompatible types {a.dtype.name} and "
                    f"{b.dtype.name}")
            widened.append(ct)
        return DataFrame(
            L.Union([_coerce_union_member(self._plan, widened),
                     _coerce_union_member(other._plan, widened)]),
            self._session)

    def order_by(self, *keys, desc: bool = False) -> "DataFrame":
        sks = []
        for k in keys:
            if isinstance(k, SortKey):
                sks.append(k)
            else:
                sks.append(SortKey(_expr(k), descending=desc,
                                   nulls_last=desc))
        return DataFrame(L.Sort(sks, self._plan), self._session)

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(L.Limit(n, self._plan), self._session)

    def cache(self) -> "DataFrame":
        """Mark this frame for materialize-once re-serving (Spark
        df.cache; ref: InMemoryTableScanExec, SURVEY Appendix A).  The
        first TPU collect that fully drains the subtree stores its
        batches in the spillable BufferStore; later collects (of this
        frame or frames derived AFTER cache()) skip the subtree."""
        if not isinstance(self._plan, L.Cached):
            self._plan = L.Cached(self._plan)
        return self

    persist = cache

    def unpersist(self) -> "DataFrame":
        """Drop the cached batches (store entries close; accounting
        returns to zero)."""
        if isinstance(self._plan, L.Cached):
            self._plan.slot.clear()
        return self

    def map_in_pandas(self, fn, schema) -> "DataFrame":
        """pyspark mapInPandas (ref: GpuMapInPandasExec): fn over
        pd.DataFrame batches in the isolated python worker pool."""
        from spark_rapids_tpu.columnar.arrow import schema_from_arrow

        if isinstance(schema, pa.Schema):
            eng_schema = schema_from_arrow(schema)
        else:
            eng_schema = schema
        node = L.MapInArrow(fn, eng_schema, self._plan)
        node.pandas = True
        return DataFrame(node, self._session)

    def map_in_arrow(self, fn, schema) -> "DataFrame":
        """Apply `fn(pa.Table) -> pa.Table` batch-wise in a
        process-isolated python worker pool (the mapInArrow analog;
        ref: GpuArrowEvalPythonExec + python/rapids/worker.py).
        `schema` (pyarrow or engine Schema) is the declared output
        contract; `fn` must be picklable (module-level)."""
        import pyarrow as _pa

        from spark_rapids_tpu.columnar.arrow import schema_from_arrow

        if isinstance(schema, _pa.Schema):
            schema = schema_from_arrow(schema)
        return DataFrame(L.MapInArrow(fn, schema, self._plan),
                         self._session)

    # -- writes ---------------------------------------------------------- #

    @property
    def write(self) -> "DataFrameWriter":
        """Spark-shaped writer: df.write.mode('overwrite')
        .partition_by('k').parquet(path)."""
        return DataFrameWriter(self)

    def write_parquet(self, path: str, mode: str = "error",
                      partition_by: Sequence[str] = ()):
        return self.write.mode(mode).partition_by(
            *partition_by).parquet(path)

    def write_csv(self, path: str, mode: str = "error",
                  partition_by: Sequence[str] = ()):
        return self.write.mode(mode).partition_by(*partition_by).csv(path)

    def write_orc(self, path: str, mode: str = "error",
                  partition_by: Sequence[str] = ()):
        return self.write.mode(mode).partition_by(*partition_by).orc(path)

    # -- actions --------------------------------------------------------- #

    def to_device_arrays(self) -> list[dict]:
        """Execute on TPU and hand back the DEVICE-RESIDENT results as
        jax arrays — no D2H round trip (the ColumnarRdd analog, ref:
        sql/rapids/execution/InternalColumnarRddConverter.scala /
        ColumnarRdd.scala exposing GPU Tables to ML libraries
        zero-copy).  Returns one dict per batch:
        {column_name: jax.Array (physical values),
         column_name + "__valid": jax.Array bool} plus "__num_rows";
        a jax model consumes the SQL output straight from HBM.

        Nested (struct/map/list) output columns are not exposed this
        way — project to flat columns first."""
        from spark_rapids_tpu.columnar.column import Column

        conf = self._session.conf
        exec_, _meta = plan_query(self._plan, conf)
        out = []
        for b in exec_.execute():
            d: dict = {}
            for f, c in zip(b.schema.fields, b.columns):
                if not isinstance(c, Column):
                    raise TypeError(
                        f"column {f.name!r} ({f.dtype.name}) has no "
                        "flat device array form — project it first")
                d[f.name] = c.data
                d[f.name + "__valid"] = c.validity
            d["__num_rows"] = b.num_rows
            out.append(d)
        return out

    def collect(self, engine: Optional[str] = None) -> pa.Table:
        """engine: 'tpu' (plan rewrite + fallback), 'cpu' (reference
        engine), default from spark.rapids.tpu.sql.enabled."""
        conf = self._session.conf
        if engine is None:
            engine = "tpu" if conf.get(SQL_ENABLED) else "cpu"
        if engine == "cpu":
            from spark_rapids_tpu.cpu.engine import execute_cpu

            return execute_cpu(self._plan)
        return self._collect_tpu()[0]

    def _collect_tpu(self, exec_=None, meta=None, drain_lock=None,
                     serving_facts=None,
                     token_sink=None) -> tuple[pa.Table, int]:
        """TPU-engine collect; returns (result, query_id) so callers
        that need the history/trace correlation key (EXPLAIN ANALYZE)
        can find THEIR event instead of trusting events[-1] under
        concurrent collects.

        With a prebuilt (exec_, meta) — the prepared-plan-cache hit
        path (serving/prepared.py) — planning is skipped entirely: no
        query.plan/tag/lower spans, the cached lowered tree is drained
        directly.  Either way the query passes through the serving
        tier's admission control first (a single conf read when
        serving.maxConcurrent is 0, the default).

        `drain_lock` (the cache entry's re-drain lock) is acquired
        INSIDE admission: taking it before would deadlock when an
        admitted query nested-executes the template a waiting thread
        already locked.  `serving_facts` (the plan-cache verdict,
        plus the binding-independent `admission_group` template key
        that admission-aware batching coalesces on) is deposited into
        the serving context inside the query's admission scope, so a
        nested query's facts land in ITS record and never pollute the
        outer query's.

        With cross-tenant sharing on (serving.sharing.enabled), the
        process-wide result cache is consulted INSIDE admission and
        before the drain lock: a hit returns the cached result with
        zero plan/lower/compile/scan work, and a completed miss
        offers its result back (docs/work_sharing.md).  Disabled =
        one conf read.

        Cancellation (serving/cancel.py): the query carries a
        CancelToken (one conf read + None when
        serving.cancellation.enabled is false) honoring
        session.cancel(), the serving deadline and the tenant
        breaker; a cancelled query unwinds through the normal
        teardown paths, is recorded with engine="cancelled"/
        "deadline_exceeded", and raises QueryCancelled.
        ``token_sink`` (a cancel.TokenSet) additionally tracks the
        token for a narrower cancel scope (PreparedQuery.cancel)."""
        import contextlib

        conf = self._session.conf
        from spark_rapids_tpu.serving import update_serving_context
        from spark_rapids_tpu.serving import cancel as _cancel
        from spark_rapids_tpu.serving.scheduler import admission

        facts = dict(serving_facts) if serving_facts else None
        group = facts.pop("admission_group", None) if facts else None
        tok = _cancel.begin(conf, tenant=self._session.tenant)
        self._session._tokens.add(tok)
        if token_sink is not None:
            token_sink.add(tok)
        try:
            with (_cancel.attach_token(tok) if tok is not None
                  else contextlib.nullcontext()), \
                    admission(conf, tenant=self._session.tenant,
                              priority=self._session.priority,
                              group=group, token=tok):
                if facts:
                    update_serving_context(**facts)
                from spark_rapids_tpu.serving import work_share as _ws

                sharing = _ws.enabled(conf)
                if sharing:
                    cached, verdict = _ws.lookup_result(self._plan,
                                                        conf)
                    if verdict is not None:
                        update_serving_context(result_cache=verdict)
                    if cached is not None:
                        return self._result_cache_hit(cached, meta)
                with drain_lock if drain_lock is not None \
                        else contextlib.nullcontext():
                    out, qid = self._collect_tpu_admitted(exec_, meta)
                if sharing:
                    _ws.offer_result(self._plan, conf, out)
                return out, qid
        except _cancel.QueryCancelled as e:
            self._record_cancelled(e, facts)
            raise
        finally:
            self._session._tokens.discard(tok)
            if token_sink is not None:
                token_sink.discard(tok)
            _cancel.end(tok)

    def _record_cancelled(self, e, facts=None) -> None:
        """Cancellation epilogue: count the outcome once, and when the
        query unwound BEFORE its execution prologue ran (deadline
        expired in the admission queue), emit the per-query record
        HERE with ``engine=e.reason`` and a zero counter delta — a
        cancelled query is an observable outcome, not a gap.
        Mid-flight cancels were already recorded (with their partial
        metrics) by the admitted/stream paths.

        ``facts`` are the caller's undeposited serving facts: the
        connect front door's wire section (peer, wire_bytes,
        translate_ms) normally lands in the serving context INSIDE
        admission, AFTER admit() succeeds — a query shed in the queue
        unwinds before that deposit, so without re-depositing here its
        deadline_exceeded record would silently drop the ``connect``
        section (the fleet's shed-by-peer attribution)."""
        from spark_rapids_tpu import trace as _trace
        from spark_rapids_tpu.serving import (
            clear_serving_context,
            current_serving_context,
            update_serving_context,
        )
        from spark_rapids_tpu.serving import cancel as _cancel

        _cancel.tick_outcome(e.reason)
        if e.recorded:
            return
        conf = self._session.conf
        qid, elog, pre, conf_hash, start_ts, t0, t0_ns = \
            _begin_query(self._session, conf)
        if e.query_id is None:
            e.query_id = qid
        expl = (f"CancelledBeforeExecution [{e.reason}: shed in the "
                f"admission queue; no operator ran]\n")
        deposited = False
        prev_ctx = None
        if facts and facts.get("connect"):
            # admission never deposited the wire facts (shed in the
            # queue): deposit them NOW so query_end's serving-context
            # capture — which runs inside _on_event() below, on this
            # thread — folds the connect section into the record.
            # Save/restore around it (the nested-admission idiom): an
            # outer query's restored context must survive this record.
            prev_ctx = current_serving_context()
            update_serving_context(connect=facts["connect"])
            deposited = True

        def _on_event():
            if elog is None:
                return None
            post = elog.query_end(pre)
            return lambda ev: elog.log_query(ev, post, expl, e.reason)

        try:
            with _trace.trace_context(query_id=qid):
                if _trace.TRACER.enabled:
                    _trace.event("cancel.shed", query_id=qid,
                                 reason=e.reason)
            _record_query(self._session, expl, None, qid, conf_hash,
                          start_ts, t0, t0_ns, _on_event(),
                          engine=e.reason)
        finally:
            if deposited:
                clear_serving_context()
                if prev_ctx:
                    update_serving_context(**prev_ctx)
        e.recorded = True

    def _result_cache_hit(self, out: pa.Table,
                          meta) -> tuple[pa.Table, int]:
        """Serve a collect from the cross-tenant result cache: no exec
        tree ever exists, but the query still runs the full history/
        event-log lifecycle (the record carries the real digest and
        rows, the serving context's result_cache verdict, and a
        near-zero counter delta) so fleet tooling sees served traffic,
        not a gap."""
        from spark_rapids_tpu import trace as _trace
        from spark_rapids_tpu.eventlog import table_digest

        conf = self._session.conf
        qid, elog, pre, conf_hash, start_ts, t0, t0_ns = \
            _begin_query(self._session, conf)
        expl = meta.explain() if meta is not None else \
            "ResultCacheHit [plan not lowered — served from the " \
            "cross-tenant result cache]\n"

        def _on_event():
            if elog is None:
                return None
            post = elog.query_end(pre)
            return lambda ev: elog.log_query(
                ev, post, expl, "tpu",
                result_digest=table_digest(out), rows=out.num_rows)

        with _trace.trace_context(query_id=qid):
            if _trace.TRACER.enabled:
                _trace.event("serve.result_cache_hit", query_id=qid,
                             rows=out.num_rows)
        _record_query(self._session, expl, None, qid, conf_hash,
                      start_ts, t0, t0_ns, _on_event())
        return out, qid

    def _collect_tpu_admitted(self, exec_=None,
                              meta=None) -> tuple[pa.Table, int]:
        conf = self._session.conf

        from spark_rapids_tpu import obs as _obs
        from spark_rapids_tpu.eventlog import table_digest

        qid, elog, pre, conf_hash, start_ts, t0, t0_ns = \
            _begin_query(self._session, conf)
        from spark_rapids_tpu.serving import cancel as _cancel

        tok = _cancel.current_token()
        if tok is not None:
            # the id session.cancel(query_id) targets from now on
            tok.query_id = qid
        baseline = None
        if exec_ is not None:
            # re-draining a CACHED exec tree (the prepared-plan hit
            # path): its metrics accumulate across executions, so
            # snapshot the settled pre-drain totals — the history/
            # event-log record then reports THIS execution's deltas,
            # not the running total (docs/serving.md)
            from spark_rapids_tpu.tools.profiling import snapshot_exec

            baseline = snapshot_exec(exec_)

        def _on_event(render_plan, engine: str, result):
            """History-worker hook appending the event-log record once
            metrics have settled (None when the log is disabled).
            Counter/pipeline/fault capture happens HERE, at query end
            on the calling thread — a later reset/disarm (bench
            between queries, tests tearing down chaos) must not erase
            this query's attribution.  The result digest and the
            annotated-plan render are deferred to the worker: both
            read immutable state, and neither belongs on collect()'s
            critical path.  `result` is None for unwound (cancelled)
            queries: no digest, no rows — the record still lands."""
            if elog is None:
                return None
            post = elog.query_end(pre)
            return lambda ev: elog.log_query(
                ev, post, render_plan(), engine,
                result_digest=table_digest(result)
                if result is not None else None,
                rows=result.num_rows if result is not None else None)

        try:
            return self._collect_tpu_admitted_registered(
                exec_, meta, conf, qid, elog, pre, conf_hash,
                start_ts, t0, t0_ns, baseline, _on_event)
        finally:
            # safety net for paths that never reach an epilogue (a
            # crash that is not CPU-degradable): the live registry
            # must not keep a dead query in flight
            _obs.REGISTRY.drop(qid)

    def _collect_tpu_admitted_registered(
            self, exec_, meta, conf, qid, elog, pre, conf_hash,
            start_ts, t0, t0_ns, baseline, _on_event):
        from spark_rapids_tpu import obs as _obs
        from spark_rapids_tpu import trace as _trace
        from spark_rapids_tpu.eventlog import render_plan_report
        from spark_rapids_tpu.serving import cancel as _cancel

        with _trace.trace_context(query_id=qid):
            if exec_ is None:
                with _trace.span("query.plan"):
                    exec_, meta = plan_query(self._plan, conf)
            if _obs.REGISTRY.enabled:
                from spark_rapids_tpu.eventlog import plan_fingerprint

                ptext = meta.explain()
                _obs.REGISTRY.annotate(
                    qid, plan=ptext,
                    plan_hash=plan_fingerprint(ptext))
            try:
                with _trace.span("query.execute"):
                    out = collect_exec(exec_)
            except _cancel.QueryCancelled as e:
                # cooperative unwind mid-flight: the drain loop's
                # close-on-raise already tore the tree down (pipeline
                # stages joined, shuffle blocks dropped); record the
                # query as an observable cancelled outcome with its
                # partial metric deltas, then let it propagate
                if e.query_id is None:
                    e.query_id = qid
                expl = (meta.explain()
                        + f"\n[query unwound: {e.reason}]")
                _record_query(
                    self._session, expl, exec_, qid, conf_hash,
                    start_ts, t0, t0_ns,
                    _on_event(lambda: expl, e.reason, None),
                    baseline=baseline, engine=e.reason)
                e.recorded = True
                raise
            except BaseException as e:
                from spark_rapids_tpu.execs.retry import (
                    should_cpu_fallback,
                )

                if not should_cpu_fallback(e):
                    raise
                # device lost / exhausted after task retries: degrade
                # the query to the CPU engine (executor-blacklisting
                # analog) — the LAST rung of the escalation ladder
                import warnings

                from spark_rapids_tpu.cpu.engine import execute_cpu
                from spark_rapids_tpu.execs import retry as _retry

                warnings.warn(
                    f"TPU execution failed with a device error ({e}); "
                    "re-running this query on the CPU engine",
                    RuntimeWarning, stacklevel=2)
                out = execute_cpu(self._plan)
                _retry.note_cpu_fallback(e)
                # degraded queries are the ones operators most need to
                # see in the history (and the event log: the health
                # checker's CPU-fallback rule keys off this record)
                expl = (meta.explain() + "\n[degraded to CPU engine: "
                        f"{type(e).__name__}]")
                _record_query(
                    self._session, expl, exec_, qid, conf_hash,
                    start_ts, t0, t0_ns,
                    _on_event(lambda: expl, "cpu_fallback", out),
                    baseline=baseline, engine="cpu_fallback")
                return out, qid
            _record_query(
                self._session, meta.explain(), exec_, qid, conf_hash,
                start_ts, t0, t0_ns,
                _on_event(lambda: render_plan_report(exec_, meta),
                          "tpu", out),
                baseline=baseline)
        return out, qid

    def _stream_tpu(self, exec_=None, meta=None,
                    batch_rows: Optional[int] = None,
                    drain_lock=None, serving_facts=None,
                    token_sink=None):
        """Streaming TPU collect (serving tier): yield the result as
        Arrow record batches INCREMENTALLY off the pipelined fetch path
        (planner.stream_exec) instead of one materialized table, with
        backpressure from the prefetch stage's bounded queue.  Admitted,
        traced and history/event-log-recorded like _collect_tpu (the
        record carries rows but no result digest — the batches were
        never held together); no CPU-degrade ladder mid-stream: a
        device failure raises to the consumer, who may re-run via
        collect().  The admission slot is held until the stream drains
        or the generator is closed."""
        import contextlib
        import time as _time

        from spark_rapids_tpu import trace as _trace
        from spark_rapids_tpu.plan.planner import stream_exec
        from spark_rapids_tpu.serving import update_serving_context
        from spark_rapids_tpu.serving import cancel as _cancel
        from spark_rapids_tpu.serving.scheduler import admission

        conf = self._session.conf
        facts = dict(serving_facts) if serving_facts else None
        group = facts.pop("admission_group", None) if facts else None
        tok = _cancel.begin(conf, tenant=self._session.tenant)
        self._session._tokens.add(tok)
        if token_sink is not None:
            token_sink.add(tok)
        qid_box: list = []
        try:
            yield from self._stream_tpu_cancellable(
                exec_, meta, batch_rows, drain_lock, facts, group,
                tok, qid_box)
        except _cancel.QueryCancelled as e:
            self._record_cancelled(e, facts)
            raise
        finally:
            self._session._tokens.discard(tok)
            if token_sink is not None:
                token_sink.discard(tok)
            _cancel.end(tok)
            if qid_box:
                # safety net: an ABANDONED stream (generator closed
                # early) records nothing — but it must not keep a dead
                # query in the live registry either (no-op after a
                # drained stream's normal finish)
                from spark_rapids_tpu import obs as _obs

                _obs.REGISTRY.drop(qid_box[0])

    def _stream_tpu_cancellable(self, exec_, meta, batch_rows,
                                drain_lock, facts, group, tok,
                                qid_box=None):
        import contextlib
        import time as _time

        from spark_rapids_tpu import trace as _trace
        from spark_rapids_tpu.plan.planner import stream_exec
        from spark_rapids_tpu.serving import update_serving_context
        from spark_rapids_tpu.serving import cancel as _cancel
        from spark_rapids_tpu.serving.scheduler import admission

        conf = self._session.conf
        with admission(conf, tenant=self._session.tenant,
                       priority=self._session.priority, group=group,
                       token=tok), \
                (drain_lock if drain_lock is not None
                 else contextlib.nullcontext()):
            if facts:
                update_serving_context(**facts)
            from spark_rapids_tpu.serving import work_share as _ws

            sharing = _ws.enabled(conf)
            #: sharing-on miss path: accumulate the streamed batches
            #: (bounded to the result cache's own single-result cap,
            #: budget/4) so a fully-drained stream populates the
            #: cross-tenant result cache exactly like a collect — the
            #: wire front door streams every query, and a front door
            #: that never fills the cache would defeat the sharing
            #: economics (docs/connect.md).  None = not accumulating.
            share_acc: Optional[list] = None
            share_cap = 0
            if sharing:
                cached, verdict = _ws.lookup_result(self._plan, conf)
                if verdict is not None:
                    update_serving_context(result_cache=verdict)
                if cached is not None:
                    # serve the stream from the cached result: the
                    # same record-batch surface, the same per-query
                    # record, zero execution
                    out, _qid = self._result_cache_hit(cached, meta)
                    for rb in out.to_batches(max_chunksize=batch_rows):
                        yield rb
                    return
                share_acc = []
                share_cap = conf.get(_ws.RESULT_CACHE_BUDGET) // 4
            qid, elog, pre, conf_hash, start_ts, t0, t0_ns = \
                _begin_query(self._session, conf)
            if qid_box is not None:
                qid_box.append(qid)
            if tok is not None:
                tok.query_id = qid
            baseline = None
            if exec_ is not None:
                # cached-tree re-drain: record per-execution metric
                # deltas, not the tree's running totals
                from spark_rapids_tpu.tools.profiling import (
                    snapshot_exec,
                )

                baseline = snapshot_exec(exec_)
            with _trace.trace_context(query_id=qid), \
                    _cancel.attach_token(tok):
                if exec_ is None:
                    with _trace.span("query.plan"):
                        exec_, meta = plan_query(self._plan, conf)
                tctx = _trace.current_context()
            from spark_rapids_tpu import obs as _obs

            if _obs.REGISTRY.enabled:
                from spark_rapids_tpu.eventlog import plan_fingerprint

                ptext = meta.explain()
                _obs.REGISTRY.annotate(
                    qid, plan=ptext,
                    plan_hash=plan_fingerprint(ptext), token=tok)
            rows = 0
            gen = stream_exec(exec_, stage="serve.stream.fetch")
            try:
                #: wire frames re-chunked from the current engine
                #: table — drained with a cancellation checkpoint per
                #: frame, so a cancel lands between frames even when
                #: the whole result arrived as ONE table (otherwise a
                #: stalled consumer's cancel could not interrupt the
                #: re-chunk loop; the connect server's disconnect
                #: cancellation rests on this)
                pending: list = []
                while True:
                    # re-attach the query's trace context AND cancel
                    # token around each pull (NOT across yields: the
                    # consumer's own work between pulls must not
                    # inherit this query's id or its cancel scope)
                    with _trace.attach_context(tctx), \
                            _cancel.attach_token(tok):
                        try:
                            if pending:
                                _cancel.check_point()
                                rb = pending.pop(0)
                            else:
                                tbl = next(gen)
                                rows += tbl.num_rows
                                _obs.REGISTRY.note_batch(
                                    qid, tbl.num_rows)
                                if share_acc is not None:
                                    share_acc.append(tbl)
                                    if sum(t.nbytes
                                           for t in share_acc) \
                                            > share_cap:
                                        # past the cache's single-
                                        # result cap: stop
                                        # accumulating, free the held
                                        share_acc = None
                                pending = list(tbl.to_batches(
                                    max_chunksize=batch_rows))
                                continue
                        except StopIteration:
                            break
                        except _cancel.QueryCancelled as e:
                            # record the unwound stream (partial rows,
                            # no digest) before propagating — an
                            # ABANDONED stream records nothing, a
                            # CANCELLED one is an observable outcome
                            if e.query_id is None:
                                e.query_id = qid
                            # bind NOW: the except-variable `e` is
                            # unbound when the block exits, but the
                            # closure runs later on the history worker
                            reason = e.reason
                            expl = (meta.explain()
                                    + f"\n[stream unwound: {reason}]")

                            def _on_cancel_event():
                                if elog is None:
                                    return None
                                post = elog.query_end(pre)
                                return lambda ev: elog.log_query(
                                    ev, post, expl, reason,
                                    result_digest=None, rows=rows)

                            _record_query(
                                self._session, expl, exec_, qid,
                                conf_hash, start_ts, t0, t0_ns,
                                _on_cancel_event(), baseline=baseline,
                                engine=reason)
                            e.recorded = True
                            raise
                    yield rb
            finally:
                gen.close()
            if share_acc:
                # fully drained with sharing on: offer the result so
                # the next tenant's identical query is a cache hit
                # (offer_result re-checks shareability and size;
                # empty results are simply not offered)
                _ws.offer_result(self._plan, conf,
                                 pa.concat_tables(share_acc))
            # fully drained: record the query (an ABANDONED stream —
            # generator closed early — records nothing; its partial
            # metrics would read as a complete run).  The execute span
            # is recorded whole-drain so span-derived busy/self
            # analytics see streamed queries like collected ones.
            if _trace.TRACER.enabled:
                _trace.record_complete(
                    "query.execute", t0_ns,
                    _time.perf_counter_ns() - t0_ns, query_id=qid,
                    streamed=True)
            streamed = rows

            def _on_event(render_plan):
                if elog is None:
                    return None
                post = elog.query_end(pre)
                return lambda ev: elog.log_query(
                    ev, post, render_plan(), "tpu",
                    result_digest=None, rows=streamed)

            from spark_rapids_tpu.eventlog import render_plan_report

            _record_query(
                self._session, meta.explain(), exec_, qid, conf_hash,
                start_ts, t0, t0_ns,
                _on_event(lambda: render_plan_report(exec_, meta)),
                baseline=baseline)

    def to_batches(self, batch_rows: Optional[int] = None):
        """Stream the result as Arrow record batches (the ColumnarRdd
        export analog — hand accelerated data to external libraries
        without one giant materialization)."""
        from spark_rapids_tpu.columnar.rows import columnar_export

        return columnar_export(self, batch_rows)

    def rows(self):
        """Iterate result rows as tuples (the columnar->row boundary,
        ref: GpuColumnarToRowExec)."""
        for rb in self.to_batches():
            cols = [c.to_pylist() for c in rb.columns]
            for i in range(rb.num_rows):
                yield tuple(c[i] for c in cols)

    def explain(self, mode: str = "simple") -> str:
        """Plan explanation.  mode="simple" (default): the static
        replacement/lint/pipeline report.  mode="analyze": EXPLAIN
        ANALYZE — run the query on the TPU engine, then render the
        plan annotated per-operator with SETTLED metrics (device-synced
        wall time, rows, batches) and, when tracing is on, span-derived
        busy/self/overlap times (docs/observability.md)."""
        if mode.lower() == "analyze":
            from spark_rapids_tpu import trace as _trace
            from spark_rapids_tpu.execs.jit_cache import cache_stats
            from spark_rapids_tpu.execs.retry import retry_stats
            from spark_rapids_tpu.plan import runtime_filter as _rf
            from spark_rapids_tpu.robustness import faults as _faults
            from spark_rapids_tpu.tools.profiling import render_analyze

            from spark_rapids_tpu.serving import plan_cache as _pc
            from spark_rapids_tpu.trace import ledger as _ledger

            before = cache_stats()
            retry0 = retry_stats()
            faults0 = _faults.recovered_total()
            rf0 = _rf.stats()
            pc0 = _pc.stats()
            # sync NOW (normally a _begin_query job) so the pre-collect
            # snapshot sees a conf-enabled ledger on the first analyze
            _ledger.sync_conf(self._session.conf)
            led0 = _ledger.snapshot() if _ledger.LEDGER.enabled \
                else None
            _out, qid = self._collect_tpu()
            after = cache_stats()
            # per-QUERY deltas (counters are process-wide cumulative;
            # concurrent collects can bleed into the diff, which is
            # fine for a diagnostics footer) — the same counter
            # surface the event log persists per query
            cs = {"hits": after["hits"] - before["hits"],
                  "misses": after["misses"] - before["misses"]}
            retry1 = retry_stats()
            rf1 = _rf.stats()
            pc1 = _pc.stats()
            counters = {
                "retry": {k: max(0, retry1[k] - retry0[k])
                          for k in retry1},
                "faults_recovered": max(
                    0, _faults.recovered_total() - faults0),
                "rf": {k: max(0, rf1[k] - rf0[k]) for k in rf1},
                # prepared-plan cache activity in this window (nonzero
                # when the analyzed collect rode a PreparedQuery or a
                # concurrent session resolved one — docs/serving.md)
                "plan_cache": {
                    k: max(0, pc1[k] - pc0[k])
                    for k in ("hits", "misses", "evictions")},
            }
            # find OUR event by id — events[-1] may be a concurrent
            # collect's record (fall back to it only if concurrent
            # collects evicted ours from a tiny history ring)
            # per-query device-ledger attribution (the roofline column
            # + top-programs footer; docs/device_ledger.md) — settled
            # off the critical path, bounded-waited here
            led = None
            if led0 is not None and _ledger.LEDGER.enabled:
                _ledger.LEDGER.flush(timeout=2.0)
                led = _ledger.summarize(
                    _ledger.delta(led0, _ledger.snapshot()))
            events_ = self._session.history.events
            ev = next((e for e in reversed(events_)
                       if e.query_id == qid), events_[-1])
            events = _trace.snapshot() if _trace.is_enabled() else None
            return render_analyze(ev, events, cache_stats=cs,
                                  counters=counters, ledger=led)
        exec_, meta = plan_query(self._plan, self._session.conf)
        # the lowered plan + its static annotation sections (lint
        # findings, pipeline stages, runtime-filter sites) — shared
        # with the event-log writer so the persisted plan matches this
        # in-process view exactly (docs/eventlog.md)
        from spark_rapids_tpu.eventlog import render_plan_report

        return render_plan_report(exec_, meta)

    def __repr__(self) -> str:
        return f"DataFrame[{self.schema}]"


class DataFrameWriter:
    """Builder for durable output (ref: the GpuDataSource /
    GpuFileFormatWriter entry surface, sql/rapids/GpuDataSource.scala).
    The child query runs through the normal planner (plan rewrite + CPU
    fallback); encoding happens in per-partition write tasks."""

    def __init__(self, df: DataFrame):
        self._df = df
        self._mode = "error"
        self._partition_by: list[str] = []
        self._compression = "snappy"

    def mode(self, m: str) -> "DataFrameWriter":
        self._mode = m
        return self

    def partition_by(self, *cols: str) -> "DataFrameWriter":
        self._partition_by.extend(cols)
        return self

    def compression(self, c: str) -> "DataFrameWriter":
        self._compression = c
        return self

    def parquet(self, path: str):
        from spark_rapids_tpu.io.write import ParquetWriteExec

        return self._run(ParquetWriteExec, path)

    def csv(self, path: str):
        from spark_rapids_tpu.io.write import CsvWriteExec

        return self._run(CsvWriteExec, path)

    def orc(self, path: str):
        from spark_rapids_tpu.io.write import OrcWriteExec

        return self._run(OrcWriteExec, path)

    def _run(self, exec_cls, path: str):
        from spark_rapids_tpu.io.write import prepare_target

        if not prepare_target(path, self._mode):
            return None  # mode=ignore on existing target
        df = self._df
        child, _meta = plan_query(df._plan, df._session.conf)
        kwargs = {}
        if exec_cls.FORMAT in ("parquet", "orc"):
            kwargs["compression"] = self._compression
        w = exec_cls(path, child, partition_by=self._partition_by,
                     **kwargs)
        return w.run()
