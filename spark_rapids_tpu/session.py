"""User-facing session + DataFrame API.

The reference plugs into Spark's existing frontend; this framework ships
its own minimal DataFrame surface (SURVEY.md §7: "a small DataFrame/plan
frontend plus a CPU engine that plays the role of CPU Spark").  The API
deliberately mirrors PySpark's shape (select/where/groupBy/agg/join/
orderBy/limit/collect/explain) so reference test cases translate
directly."""

from __future__ import annotations

from typing import Optional, Sequence, Union

import pyarrow as pa

from spark_rapids_tpu import types as T
from spark_rapids_tpu.config import SQL_ENABLED, TpuConf, get_conf
from spark_rapids_tpu.execs.sort import SortKey
from spark_rapids_tpu.exprs.aggregates import (
    Average,
    Count,
    CountStar,
    First,
    Last,
    Max,
    Min,
    NamedAgg,
    Sum,
)
from spark_rapids_tpu.exprs.base import ColumnReference, Expression, lit
from spark_rapids_tpu.plan import logical as L
from spark_rapids_tpu.plan.planner import collect_exec, plan_query

ExprLike = Union[str, Expression]
AggLike = Union[NamedAgg, tuple]


def col(name: str) -> ColumnReference:
    return ColumnReference(name)


def _expr(e: ExprLike) -> Expression:
    return ColumnReference(e) if isinstance(e, str) else e


# function-style aggregate constructors (pyspark.sql.functions shape)
def sum_(e: ExprLike) -> Sum:
    return Sum(_expr(e))


def count(e: ExprLike) -> Count:
    return Count(_expr(e))


def count_star() -> CountStar:
    return CountStar()


def min_(e: ExprLike) -> Min:
    return Min(_expr(e))


def max_(e: ExprLike) -> Max:
    return Max(_expr(e))


def avg(e: ExprLike) -> Average:
    return Average(_expr(e))


def first(e: ExprLike, ignore_nulls: bool = False) -> First:
    return First(_expr(e), ignore_nulls)


def last(e: ExprLike, ignore_nulls: bool = False) -> Last:
    return Last(_expr(e), ignore_nulls)


def _extract_windows(e: Expression, acc: list) -> Expression:
    """Replace every WindowExpression subtree with a reference to a
    generated column the Window node will produce."""
    from spark_rapids_tpu.exprs.window import WindowExpression

    if isinstance(e, WindowExpression):
        name = f"__w{len(acc)}"
        acc.append((e, name))
        return ColumnReference(name)
    kids = e.children
    if not kids:
        return e
    new = [_extract_windows(c, acc) for c in kids]
    if all(n is o for n, o in zip(new, kids)):
        return e
    return e.with_children(new)


class TpuSession:
    """Counterpart of the SparkSession with the plugin installed
    (ref: SQLPlugin.scala — here session == plugin)."""

    def __init__(self, conf: Optional[TpuConf] = None):
        self.conf = conf or get_conf()

    # -- sources -------------------------------------------------------- #

    def create_dataframe(self, data: Union[pa.Table, dict]) -> "DataFrame":
        table = data if isinstance(data, pa.Table) else pa.table(data)
        return DataFrame(L.InMemoryRelation(table), self)

    def read_parquet(self, *paths: str,
                     columns: Optional[Sequence[str]] = None) -> "DataFrame":
        return DataFrame(L.ParquetRelation(list(paths), columns), self)

    def read_csv(self, *paths: str,
                 schema: Optional[T.Schema] = None) -> "DataFrame":
        return DataFrame(L.CsvRelation(list(paths), schema), self)

    def range(self, start: int, end: Optional[int] = None,
              step: int = 1) -> "DataFrame":
        if end is None:
            start, end = 0, start
        return DataFrame(L.RangeRel(start, end, step), self)


class GroupedData:
    def __init__(self, df: "DataFrame", keys: list[Expression]):
        self._df = df
        self._keys = keys

    def agg(self, *aggs: AggLike) -> "DataFrame":
        named = []
        for i, a in enumerate(aggs):
            if isinstance(a, NamedAgg):
                named.append(a)
            elif isinstance(a, tuple):
                fn, name = a
                named.append(NamedAgg(fn, name))
            else:
                named.append(NamedAgg(a, f"{a.name}_{i}"))
        return DataFrame(
            L.Aggregate(self._keys, named, self._df._plan),
            self._df._session)


class DataFrame:
    def __init__(self, plan: L.LogicalPlan, session: TpuSession):
        self._plan = plan
        self._session = session

    @property
    def schema(self) -> T.Schema:
        return self._plan.schema

    # -- transformations ------------------------------------------------ #

    def select(self, *exprs: ExprLike) -> "DataFrame":
        """Projection; window expressions anywhere in the select list are
        extracted into Window nodes under the projection (one node per
        (partition_by, order_by) group), mirroring Spark's
        ExtractWindowExpressions analysis rule."""
        from spark_rapids_tpu.exprs.window import WindowExpression

        exprs_ = [_expr(e) for e in exprs]
        acc: list[tuple[WindowExpression, str]] = []
        rewritten = [_extract_windows(e, acc) for e in exprs_]
        plan = self._plan
        if acc:
            from spark_rapids_tpu.execs.jit_cache import exprs_key

            from spark_rapids_tpu.execs.jit_cache import expr_key

            groups: dict[tuple, list] = {}
            for we, name in acc:
                # structural keys for BOTH components: display repr is
                # name-only and would merge distinct order-by exprs that
                # share a name (or split structurally identical ones)
                gk = (exprs_key(we.spec.partition_by),
                      tuple((expr_key(k.expr), k.descending, k.nulls_last)
                            for k in we.spec.order_by))
                groups.setdefault(gk, []).append((we, name))
            for group in groups.values():
                plan = L.Window(group, plan)
        return DataFrame(L.Project(rewritten, plan), self._session)

    def where(self, cond: Expression) -> "DataFrame":
        return DataFrame(L.Filter(cond, self._plan), self._session)

    filter = where

    def with_column(self, name: str, e: Expression) -> "DataFrame":
        exprs: list[Expression] = [
            ColumnReference(f.name) for f in self.schema.fields
            if f.name != name]
        exprs.append(e.alias(name))
        return self.select(*exprs)

    def group_by(self, *keys: ExprLike) -> GroupedData:
        return GroupedData(self, [_expr(k) for k in keys])

    def agg(self, *aggs: AggLike) -> "DataFrame":
        return GroupedData(self, []).agg(*aggs)

    def join(self, other: "DataFrame", on: Union[str, Sequence[str], None]
             = None, how: str = "inner",
             left_on: Optional[Sequence[ExprLike]] = None,
             right_on: Optional[Sequence[ExprLike]] = None,
             condition: Optional[Expression] = None) -> "DataFrame":
        if on is not None:
            names = [on] if isinstance(on, str) else list(on)
            lk = [ColumnReference(n) for n in names]
            rk = [ColumnReference(n) for n in names]
        else:
            lk = [_expr(e) for e in (left_on or [])]
            rk = [_expr(e) for e in (right_on or [])]
        return DataFrame(
            L.Join(self._plan, other._plan, lk, rk, how, condition),
            self._session)

    def union(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(L.Union([self._plan, other._plan]), self._session)

    def order_by(self, *keys, desc: bool = False) -> "DataFrame":
        sks = []
        for k in keys:
            if isinstance(k, SortKey):
                sks.append(k)
            else:
                sks.append(SortKey(_expr(k), descending=desc,
                                   nulls_last=desc))
        return DataFrame(L.Sort(sks, self._plan), self._session)

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(L.Limit(n, self._plan), self._session)

    # -- writes ---------------------------------------------------------- #

    @property
    def write(self) -> "DataFrameWriter":
        """Spark-shaped writer: df.write.mode('overwrite')
        .partition_by('k').parquet(path)."""
        return DataFrameWriter(self)

    def write_parquet(self, path: str, mode: str = "error",
                      partition_by: Sequence[str] = ()):
        return self.write.mode(mode).partition_by(
            *partition_by).parquet(path)

    def write_csv(self, path: str, mode: str = "error",
                  partition_by: Sequence[str] = ()):
        return self.write.mode(mode).partition_by(*partition_by).csv(path)

    # -- actions --------------------------------------------------------- #

    def collect(self, engine: Optional[str] = None) -> pa.Table:
        """engine: 'tpu' (plan rewrite + fallback), 'cpu' (reference
        engine), default from spark.rapids.tpu.sql.enabled."""
        conf = self._session.conf
        if engine is None:
            engine = "tpu" if conf.get(SQL_ENABLED) else "cpu"
        if engine == "cpu":
            from spark_rapids_tpu.cpu.engine import execute_cpu

            return execute_cpu(self._plan)
        exec_, _meta = plan_query(self._plan, conf)
        return collect_exec(exec_)

    def explain(self) -> str:
        _, meta = plan_query(self._plan, self._session.conf)
        return meta.explain()

    def __repr__(self) -> str:
        return f"DataFrame[{self.schema}]"


class DataFrameWriter:
    """Builder for durable output (ref: the GpuDataSource /
    GpuFileFormatWriter entry surface, sql/rapids/GpuDataSource.scala).
    The child query runs through the normal planner (plan rewrite + CPU
    fallback); encoding happens in per-partition write tasks."""

    def __init__(self, df: DataFrame):
        self._df = df
        self._mode = "error"
        self._partition_by: list[str] = []
        self._compression = "snappy"

    def mode(self, m: str) -> "DataFrameWriter":
        self._mode = m
        return self

    def partition_by(self, *cols: str) -> "DataFrameWriter":
        self._partition_by.extend(cols)
        return self

    def compression(self, c: str) -> "DataFrameWriter":
        self._compression = c
        return self

    def parquet(self, path: str):
        from spark_rapids_tpu.io.write import ParquetWriteExec

        return self._run(ParquetWriteExec, path)

    def csv(self, path: str):
        from spark_rapids_tpu.io.write import CsvWriteExec

        return self._run(CsvWriteExec, path)

    def _run(self, exec_cls, path: str):
        from spark_rapids_tpu.io.write import prepare_target

        if not prepare_target(path, self._mode):
            return None  # mode=ignore on existing target
        df = self._df
        child, _meta = plan_query(df._plan, df._session.conf)
        kwargs = {}
        if exec_cls.FORMAT == "parquet":
            kwargs["compression"] = self._compression
        w = exec_cls(path, child, partition_by=self._partition_by,
                     **kwargs)
        return w.run()
