"""SQL data-type system and TPU physical-type mapping.

Mirrors the role of the reference's Spark `DataType` handling plus the
GPU-physical mapping in GpuColumnVector.getNonNestedRapidsType
(ref: sql-plugin/src/main/java/com/nvidia/spark/rapids/GpuColumnVector.java:497)
and the declarative per-operator type signatures of TypeChecks/TypeSig
(ref: sql-plugin/.../TypeChecks.scala:129,483).

Physical mapping (TPU-first, not a cudf translation):
- fixed-width SQL types -> a single JAX array plus a boolean validity array;
- DATE -> int32 days since epoch; TIMESTAMP -> int64 microseconds UTC
  (the reference is likewise UTC-only, GpuOverrides.scala:439);
- DECIMAL(p<=18, s) -> int64 unscaled values (the reference uses
  DECIMAL64, DecimalUtil.scala);
- STRING -> fixed-width uint8 byte matrix (n, width) + int32 lengths.
  XLA wants static shapes, so variable-width UTF-8 is padded to the
  batch's max byte length instead of cudf's offset+chars layout.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


class DataType:
    """Base class for SQL-level data types."""

    #: short name used in TypeSig strings and explain output
    name: str = "?"

    def __repr__(self) -> str:
        return self.name

    def __eq__(self, other) -> bool:
        return type(self) is type(other)

    def __hash__(self) -> int:
        return hash(type(self))

    @property
    def is_numeric(self) -> bool:
        return False

    @property
    def is_fixed_width(self) -> bool:
        return True


class BooleanType(DataType):
    name = "boolean"


class IntegralType(DataType):
    bits = 64

    @property
    def is_numeric(self) -> bool:
        return True


class ByteType(IntegralType):
    name = "tinyint"
    bits = 8


class ShortType(IntegralType):
    name = "smallint"
    bits = 16


class IntegerType(IntegralType):
    name = "int"
    bits = 32


class LongType(IntegralType):
    name = "bigint"
    bits = 64


class FractionalType(DataType):
    @property
    def is_numeric(self) -> bool:
        return True


class FloatType(FractionalType):
    name = "float"


class DoubleType(FractionalType):
    name = "double"


class StringType(DataType):
    name = "string"

    @property
    def is_fixed_width(self) -> bool:
        return False


class DateType(DataType):
    """Days since unix epoch, int32."""

    name = "date"


class TimestampType(DataType):
    """Microseconds since unix epoch, UTC only (parity with the reference:
    GpuOverrides.scala:439 UTC_TIMEZONE_ID)."""

    name = "timestamp"


@dataclasses.dataclass(frozen=True)
class DecimalType(DataType):
    """Decimal with precision <= 18 backed by int64 unscaled values."""

    precision: int = 10
    scale: int = 0
    MAX_PRECISION = 18

    def __post_init__(self):
        if self.precision > self.MAX_PRECISION:
            raise ValueError(
                f"decimal precision {self.precision} > {self.MAX_PRECISION}"
            )

    @property
    def name(self) -> str:  # type: ignore[override]
        return f"decimal({self.precision},{self.scale})"

    @property
    def is_numeric(self) -> bool:
        return True

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, DecimalType)
            and other.precision == self.precision
            and other.scale == self.scale
        )

    def __hash__(self) -> int:
        return hash((DecimalType, self.precision, self.scale))


class NullType(DataType):
    name = "null"


class ListType(DataType):
    """list<element> with fixed-width primitive elements; the device
    layout is a dense (capacity, max_len) element matrix + per-row
    lengths (the same dense-matrix answer to ragged data the string
    column uses — XLA wants static shapes, cudf's offset encoding does
    not map)."""

    def __init__(self, element: DataType):
        # string elements are representable LOGICALLY (schemas flowing
        # through CPU-fallback plans, e.g. collect_list over strings);
        # the DEVICE layout supports primitives only — TypeSig /
        # check_supported route string-element lists to the CPU engine
        if isinstance(element, ListType):
            raise TypeError(
                f"list element type {element} not supported (no nested "
                "lists)")
        self.element = element

    @property
    def name(self) -> str:  # type: ignore[override]
        return f"array<{self.element.name}>"

    def __eq__(self, other) -> bool:
        return isinstance(other, ListType) and other.element == self.element

    def __hash__(self) -> int:
        return hash((ListType, self.element))


class StructType(DataType):
    """struct<name: type, ...> — device layout is struct-of-columns: one
    child column per field plus a row validity, so every field access is
    zero-copy and field-wise ops stay dense vector code (ref: the
    reference's nested TypeSig support, TypeChecks.scala:129, and
    complexTypeExtractors.scala GpuGetStructField)."""

    def __init__(self, fields):
        self.fields = tuple(fields)

    @property
    def name(self) -> str:  # type: ignore[override]
        inner = ", ".join(f"{f.name}: {f.dtype.name}" for f in self.fields)
        return f"struct<{inner}>"

    def field_index(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise KeyError(name)

    def __eq__(self, other) -> bool:
        return isinstance(other, StructType) and other.fields == self.fields

    def __hash__(self) -> int:
        return hash((StructType, self.fields))


class MapType(DataType):
    """map<key, value> — device layout is two aligned dense list
    matrices (keys + values sharing per-row lengths).  Lookup is a
    vectorized compare + argmax over the key matrix (ref:
    GpuGetMapValue, complexTypeExtractors.scala)."""

    def __init__(self, key: DataType, value: DataType):
        self.key = key
        self.value = value

    @property
    def name(self) -> str:  # type: ignore[override]
        return f"map<{self.key.name},{self.value.name}>"

    def __eq__(self, other) -> bool:
        return (isinstance(other, MapType) and other.key == self.key
                and other.value == self.value)

    def __hash__(self) -> int:
        return hash((MapType, self.key, self.value))


# Singletons
BOOLEAN = BooleanType()
BYTE = ByteType()
SHORT = ShortType()
INT = IntegerType()
LONG = LongType()
FLOAT = FloatType()
DOUBLE = DoubleType()
STRING = StringType()
DATE = DateType()
TIMESTAMP = TimestampType()
NULL = NullType()

INTEGRAL_TYPES = (BYTE, SHORT, INT, LONG)
NUMERIC_TYPES = INTEGRAL_TYPES + (FLOAT, DOUBLE)
ALL_BASIC_TYPES = NUMERIC_TYPES + (BOOLEAN, STRING, DATE, TIMESTAMP)

#: decimal integral digits needed to hold each integral type losslessly
#: (Spark's DecimalType.forType precision counts).  Shared by
#: common_type and Cast.cast_supported: they MUST agree, or union
#: widening would pick a target the cast then rejects.
INTEGRAL_DECIMAL_DIGITS = {ByteType: 3, ShortType: 5, IntegerType: 10,
                           LongType: 19}


_NUMPY_DTYPES = {
    BooleanType: np.bool_,
    ByteType: np.int8,
    ShortType: np.int16,
    IntegerType: np.int32,
    LongType: np.int64,
    FloatType: np.float32,
    DoubleType: np.float64,
    DateType: np.int32,
    TimestampType: np.int64,
    DecimalType: np.int64,
    NullType: np.bool_,
}


def to_numpy_dtype(dt: DataType) -> np.dtype:
    """Physical numpy/JAX dtype backing a fixed-width SQL type."""
    try:
        return np.dtype(_NUMPY_DTYPES[type(dt)])
    except KeyError:
        raise TypeError(f"no fixed-width physical type for {dt}") from None


def from_arrow_type(at) -> DataType:
    """Map a pyarrow DataType to ours."""
    import pyarrow as pa

    if pa.types.is_dictionary(at):
        # dictionary encoding is a physical detail (fastpar keeps the
        # Parquet dict); the logical type is the value type
        return from_arrow_type(at.value_type)
    if pa.types.is_boolean(at):
        return BOOLEAN
    if pa.types.is_int8(at):
        return BYTE
    if pa.types.is_int16(at):
        return SHORT
    if pa.types.is_int32(at):
        return INT
    if pa.types.is_int64(at):
        return LONG
    if pa.types.is_float32(at):
        return FLOAT
    if pa.types.is_float64(at):
        return DOUBLE
    if pa.types.is_string(at) or pa.types.is_large_string(at):
        return STRING
    if pa.types.is_date32(at):
        return DATE
    if pa.types.is_timestamp(at):
        return TIMESTAMP
    if pa.types.is_decimal(at):
        if at.precision > DecimalType.MAX_PRECISION:
            raise TypeError(f"decimal precision {at.precision} unsupported")
        return DecimalType(at.precision, at.scale)
    if pa.types.is_list(at) or pa.types.is_large_list(at):
        return ListType(from_arrow_type(at.value_type))
    if pa.types.is_struct(at):
        return StructType([Field(at.field(i).name,
                                 from_arrow_type(at.field(i).type),
                                 at.field(i).nullable)
                           for i in range(at.num_fields)])
    if pa.types.is_map(at):
        return MapType(from_arrow_type(at.key_type),
                       from_arrow_type(at.item_type))
    raise TypeError(f"unsupported arrow type {at}")


def to_arrow_type(dt: DataType):
    import pyarrow as pa

    m = {
        BooleanType: pa.bool_(),
        ByteType: pa.int8(),
        ShortType: pa.int16(),
        IntegerType: pa.int32(),
        LongType: pa.int64(),
        FloatType: pa.float32(),
        DoubleType: pa.float64(),
        StringType: pa.string(),
        DateType: pa.date32(),
        TimestampType: pa.timestamp("us", tz="UTC"),
    }
    if isinstance(dt, DecimalType):
        return pa.decimal128(dt.precision, dt.scale)
    if isinstance(dt, ListType):
        return pa.list_(to_arrow_type(dt.element))
    if isinstance(dt, StructType):
        return pa.struct([pa.field(f.name, to_arrow_type(f.dtype),
                                   nullable=f.nullable)
                          for f in dt.fields])
    if isinstance(dt, MapType):
        return pa.map_(to_arrow_type(dt.key), to_arrow_type(dt.value))
    try:
        return m[type(dt)]
    except KeyError:
        raise TypeError(f"unsupported type {dt}") from None


@dataclasses.dataclass(frozen=True)
class Field:
    name: str
    dtype: DataType
    nullable: bool = True

    def __repr__(self) -> str:
        n = "" if self.nullable else " not null"
        return f"{self.name}: {self.dtype}{n}"


@dataclasses.dataclass(frozen=True)
class Schema:
    fields: tuple[Field, ...]

    def __init__(self, fields):
        object.__setattr__(self, "fields", tuple(fields))

    @property
    def names(self) -> list[str]:
        return [f.name for f in self.fields]

    @property
    def dtypes(self) -> list[DataType]:
        return [f.dtype for f in self.fields]

    def field(self, name: str) -> Field:
        for f in self.fields:
            if f.name == name:
                return f
        raise KeyError(name)

    def index_of(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise KeyError(name)

    def __len__(self) -> int:
        return len(self.fields)

    def __iter__(self):
        return iter(self.fields)

    def __repr__(self) -> str:
        return "Schema(" + ", ".join(map(repr, self.fields)) + ")"


def common_type(a: DataType, b: DataType) -> Optional[DataType]:
    """Numeric widening a la Spark's implicit cast promotion; NULL
    widens to anything (a NULL literal branch takes the other side's
    type, as in Spark's TypeCoercion)."""
    if a == b:
        return a
    if isinstance(a, NullType):
        return b
    if isinstance(b, NullType):
        return a
    order = {ByteType: 0, ShortType: 1, IntegerType: 2, LongType: 3,
             FloatType: 4, DoubleType: 5}
    ta, tb = type(a), type(b)
    if ta in order and tb in order:
        return [BYTE, SHORT, INT, LONG, FLOAT, DOUBLE][max(order[ta], order[tb])]
    if ta is DecimalType and tb is DecimalType:
        # Spark's DecimalPrecision.widerDecimalType: keep every integral
        # digit and every fractional digit of both sides.  Past the
        # int64-backed MAX_PRECISION Spark starts dropping scale; this
        # engine cannot (no 128-bit unscaled), so that pair has no
        # lossless common type here.
        scale = max(a.scale, b.scale)
        integral = max(a.precision - a.scale, b.precision - b.scale)
        if integral + scale > DecimalType.MAX_PRECISION:
            return None
        return DecimalType(integral + scale, scale)
    if ta is DecimalType or tb is DecimalType:
        dec, other = (a, b) if ta is DecimalType else (b, a)
        if type(other) in (FloatType, DoubleType):
            # Spark's DecimalPrecision: decimal + fractional -> double
            return DOUBLE
        # integral -> decimal via DecimalType.forType digit counts;
        # LONG needs 19 integral digits, past the int64-backed
        # MAX_PRECISION, so decimal+long has no lossless common type
        digits = INTEGRAL_DECIMAL_DIGITS.get(type(other))
        if digits is None:
            return None
        integral = max(dec.precision - dec.scale, digits)
        if integral + dec.scale > DecimalType.MAX_PRECISION:
            return None
        return DecimalType(integral + dec.scale, dec.scale)
    if {ta, tb} == {DateType, TimestampType}:
        # Spark's findWiderTypeForTwo promotes date+timestamp to
        # timestamp (the date side casts to midnight UTC)
        return TIMESTAMP
    return None
