"""spark_rapids_tpu: a TPU-native columnar SQL accelerator.

A ground-up TPU re-design of the capability set of NVIDIA's RAPIDS
Accelerator for Apache Spark (reference: /root/reference, v21.06):

- a columnar data plane of accelerator-resident batches
  (ref: sql-plugin/.../GpuColumnVector.java) built on JAX arrays with
  static padded shapes, validity masks, and fixed-width string encoding;
- an expression + operator library executing as XLA programs
  (ref: GpuExpressions.scala, basicPhysicalOperators.scala);
- a plan-rewriting engine that tags every operator supported/unsupported
  and falls back to a CPU reference engine per-subtree
  (ref: GpuOverrides.scala, RapidsMeta.scala);
- a tiered HBM -> host -> disk spill store (ref: RapidsBufferStore.scala);
- partitioned shuffle exchanges over jax.sharding Mesh collectives
  (ref: shuffle-plugin UCX transport, GpuShuffleExchangeExec.scala).

Unlike the reference, which plugs into Spark's JVM, this package ships its
own small DataFrame/plan frontend plus a CPU engine (pyarrow-backed) that
plays the role of "CPU Spark" for differential testing and fallback.
"""

__version__ = "0.1.0"

# SQL semantics demand real int64/float64 (Spark's BIGINT/DOUBLE); JAX
# defaults to 32-bit, so importing this package enables the process-global
# x64 flag.  This is a deliberate, documented side effect — the framework
# owns the process the way a Spark executor plugin owns its JVM.  Embedders
# co-hosting f32 JAX models can opt out by setting
# SPARK_RAPIDS_TPU_NO_X64=1 before import (device columns then degrade to
# 32-bit physical types and the parity test suite will not pass).
import os as _os

if _os.environ.get("SPARK_RAPIDS_TPU_NO_X64", "") != "1":
    import jax as _jax

    _jax.config.update("jax_enable_x64", True)

from spark_rapids_tpu.config import TpuConf, get_conf, set_conf  # noqa: F401
