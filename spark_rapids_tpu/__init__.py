"""spark_rapids_tpu: a TPU-native columnar SQL accelerator.

A ground-up TPU re-design of the capability set of NVIDIA's RAPIDS
Accelerator for Apache Spark (reference: /root/reference, v21.06):

- a columnar data plane of accelerator-resident batches
  (ref: sql-plugin/.../GpuColumnVector.java) built on JAX arrays with
  static padded shapes, validity masks, and fixed-width string encoding;
- an expression + operator library executing as XLA programs
  (ref: GpuExpressions.scala, basicPhysicalOperators.scala);
- a plan-rewriting engine that tags every operator supported/unsupported
  and falls back to a CPU reference engine per-subtree
  (ref: GpuOverrides.scala, RapidsMeta.scala);
- a tiered HBM -> host -> disk spill store (ref: RapidsBufferStore.scala);
- partitioned shuffle exchanges over jax.sharding Mesh collectives
  (ref: shuffle-plugin UCX transport, GpuShuffleExchangeExec.scala).

Unlike the reference, which plugs into Spark's JVM, this package ships its
own small DataFrame/plan frontend plus a CPU engine (pyarrow-backed) that
plays the role of "CPU Spark" for differential testing and fallback.
"""

__version__ = "0.1.0"

# SQL semantics demand real int64/float64 (Spark's BIGINT/DOUBLE); JAX
# defaults to 32-bit, so importing this package enables the process-global
# x64 flag.  This is a deliberate, documented side effect — the framework
# owns the process the way a Spark executor plugin owns its JVM.  Embedders
# co-hosting f32 JAX models can opt out by setting
# SPARK_RAPIDS_TPU_NO_X64=1 before import (device columns then degrade to
# 32-bit physical types and the parity test suite will not pass).
import os as _os

if _os.environ.get("SPARK_RAPIDS_TPU_NO_X64", "") != "1":
    import jax as _jax

    _jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: remote-compile backends take 20-100s+
# PER sort/scan program, and every new process would pay it again.  The
# cache is keyed by program+topology, survives across processes, and was
# measured cutting a 20s sort compile to 0.2s on the tunneled TPU
# backend.  Default lives under the user cache dir (XDG) — NOT the
# package parent, which for pip installs would pollute site-packages.
# Opt out with SPARK_RAPIDS_TPU_JAX_CACHE=0, or redirect it.
_cache_dir = _os.environ.get("SPARK_RAPIDS_TPU_JAX_CACHE")
if _cache_dir is None:
    _xdg = _os.environ.get("XDG_CACHE_HOME",
                           _os.path.expanduser("~/.cache"))
    _cache_dir = _os.path.join(_xdg, "spark_rapids_tpu", "jax-cache")
if _cache_dir and _cache_dir != "0":
    import jax as _jax

    try:
        _os.makedirs(_cache_dir, exist_ok=True)
        _jax.config.update("jax_compilation_cache_dir", _cache_dir)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs",
                           1.0)
    except Exception:
        pass  # unwritable cache home: in-memory cache only

from spark_rapids_tpu.config import TpuConf, get_conf, set_conf  # noqa: F401
