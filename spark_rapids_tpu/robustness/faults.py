"""Deterministic fault injection: a registry of named injection points
planted at the engine's recovery-critical seams.

The reference proves its OOM/spill/retry machinery with the
`RmmRapidsRetryIterator` test harness (forced split-and-retry, forced
OOM on the Nth allocation); nothing equivalent existed here — the
recovery paths (spill-on-pressure, shuffle refetch, task retry, CPU
degrade) only ran when real hardware happened to misbehave.  This
module makes every one of them exercisable *deterministically*, in
tier-1 and under ``bench.py --chaos``.

Sites (each planted at exactly one seam):

- ``alloc.device``    — memory/device_manager.device_alloc_checkpoint,
  called by BufferStore.reserve before admitting a device reservation;
- ``transfer.upload`` — columnar/transfer.upload_components, the single
  batched H2D ``jax.device_put`` the scan/serde paths route through;
- ``shuffle.fetch``   — shuffle/net.fetch_blocks, per fetch attempt;
- ``jit.compile``     — execs/jit_cache.cached_jit, on a cache miss;
- ``pipeline.stage``  — parallel/pipeline.prefetch, per produced item
  on the producer thread (recovered in place, stage never torn down);
- ``exec.batch``      — execs/retry.with_split_retry, once per guarded
  batch attempt in the join/aggregate/sort/exchange stream loops (the
  drill site for the OOM escalation ladder);
- ``cancel.check``    — serving/cancel.check_point, once per
  cooperative cancellation checkpoint WHEN a query token is attached;
  an injected hit cancels the current token, so chaos schedules drive
  deterministic cancellations through the real unwind path
  (docs/robustness.md).

Policies are conf-driven (``spark.rapids.tpu.robustness.faults.spec``)
and fully deterministic: fail-the-Nth-call (optionally N consecutive
calls), fail-every-Nth, seeded per-site probability, injected latency.
Counters per site (calls / injected / recovered) feed the chaos parity
tests and the ``bench.py --chaos`` ``*_recovered_faults`` fields;
``fault.inject`` / ``fault.recovered`` trace events land on the
correlated timeline (docs/observability.md).

Disabled (the default) every checkpoint is one module-global read —
the subsystem asserts behavior-identical to the un-instrumented engine
(tests/test_chaos.py).
"""

from __future__ import annotations

import random
import threading
import time
import weakref
from typing import Optional

from spark_rapids_tpu import trace as _tr
from spark_rapids_tpu.config import register, get_conf

FAULTS_ENABLED = register(
    "spark.rapids.tpu.robustness.faults.enabled", False,
    "Arm the deterministic fault-injection registry for queries run "
    "with this conf (chaos mode).  Sites and policies come from "
    "spark.rapids.tpu.robustness.faults.spec; disabled, every "
    "injection point is a single global read.")

FAULTS_SPEC = register(
    "spark.rapids.tpu.robustness.faults.spec", "",
    "Semicolon-separated per-site fault policies: "
    "'site:key=val,key=val;site2:...'.  Sites: alloc.device, "
    "transfer.upload, shuffle.fetch, jit.compile, pipeline.stage, "
    "exec.batch, cancel.check.  Keys: nth=N (fail the Nth call, "
    "1-based), times=K "
    "(with nth: fail K consecutive calls from the Nth; default 1), "
    "every=N (fail every Nth call), prob=P (seeded per-call "
    "probability), seed=S (per-site RNG seed for prob), latency=MS "
    "(sleep MS milliseconds per call, injected without failing), "
    "marker=TEXT (override the error text; the default per site is a "
    "retryable marker like RESOURCE_EXHAUSTED).")

#: the registered sites (a checkpoint at an unknown site is a no-op so
#: schedules stay forward-compatible, but tests assert against this)
SITES = ("alloc.device", "transfer.upload", "shuffle.fetch",
         "jit.compile", "pipeline.stage", "exec.batch",
         "cancel.check")

#: default injected-error text per site — every default carries a
#: marker execs/retry.is_retryable classifies as transient, so the
#: engine's real recovery ladder (not a test-only path) handles it
_DEFAULT_MARKERS = {
    "alloc.device":
        "RESOURCE_EXHAUSTED: injected device allocation failure",
    "transfer.upload":
        "UNAVAILABLE: injected H2D transfer fault",
    "shuffle.fetch":
        "injected shuffle fetch fault: connection reset by peer",
    "jit.compile":
        "UNAVAILABLE: injected compile fault",
    "pipeline.stage":
        "RESOURCE_EXHAUSTED: injected pipeline stage fault",
    "exec.batch":
        "RESOURCE_EXHAUSTED: injected batch processing fault",
    # deliberately NO retryable marker: an injected cancellation is
    # converted by check_point into a real token cancel and must fail
    # fast through the ladder, exactly like a user cancel
    "cancel.check":
        "injected cancellation at a cancel.check checkpoint",
}


class InjectedFault(RuntimeError):
    """An error raised by a fault_point.  Subclasses RuntimeError so
    the standard marker classification (execs/retry.is_retryable) sees
    it exactly like a real XlaRuntimeError; carries its site so
    recovery paths can attribute the save (note_recovered)."""

    def __init__(self, site: str, message: str):
        super().__init__(message)
        self.site = site


class _SiteState:
    __slots__ = ("site", "nth", "times", "every", "prob", "latency_s",
                 "marker", "rng", "calls", "injected", "recovered",
                 "lock")

    def __init__(self, site: str, nth: int = 0, times: int = 1,
                 every: int = 0, prob: float = 0.0, seed: int = 0,
                 latency_s: float = 0.0, marker: Optional[str] = None):
        self.site = site
        self.nth = nth
        self.times = max(1, times)
        self.every = every
        self.prob = prob
        self.latency_s = latency_s
        self.marker = marker or _DEFAULT_MARKERS.get(
            site, "RESOURCE_EXHAUSTED: injected fault")
        # seeded per site so a multi-site schedule stays deterministic
        # regardless of cross-site call interleaving; crc32, NOT
        # hash() — string hashing is salted per process, which would
        # make a prob= schedule irreproducible across runs
        import zlib

        self.rng = random.Random(zlib.crc32(site.encode()) ^ seed)
        self.calls = 0
        self.injected = 0
        self.recovered = 0
        self.lock = threading.Lock()

    def snapshot(self) -> dict:
        with self.lock:
            return {"calls": self.calls, "injected": self.injected,
                    "recovered": self.recovered}


def parse_spec(spec: str) -> dict[str, _SiteState]:
    """'site:nth=3,times=2;site2:prob=0.5,seed=7' -> site states.
    Malformed entries raise ValueError (a chaos schedule that silently
    no-ops would report green recovery coverage that never ran)."""
    out: dict[str, _SiteState] = {}
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        if ":" not in part:
            raise ValueError(f"fault spec entry {part!r} missing ':'")
        site, _, body = part.partition(":")
        site = site.strip()
        if site not in SITES:
            # a typo'd site would arm a schedule no checkpoint ever
            # matches — the run would read as "recovery survives" when
            # nothing was injected
            raise ValueError(
                f"unknown fault site {site!r}; sites: {SITES}")
        kw: dict = {}
        for item in body.split(","):
            item = item.strip()
            if not item:
                continue
            k, _, v = item.partition("=")
            k = k.strip()
            v = v.strip()
            if k in ("nth", "times", "every", "seed"):
                kw[k] = int(v)
            elif k == "prob":
                kw["prob"] = float(v)
            elif k == "latency":
                kw["latency_s"] = float(v) / 1e3
            elif k == "marker":
                kw["marker"] = v
            else:
                raise ValueError(
                    f"unknown fault policy key {k!r} for site {site!r}")
        out[site] = _SiteState(site, **kw)
    return out


# process-global armed state (like the tracer: injection points run on
# producer/map-pool threads whose thread-local conf is a snapshot; the
# schedule itself must be one per process)
_ARMED = False
_FORCED = False
_SITES_STATE: dict[str, _SiteState] = {}
_SPEC_STR: Optional[str] = None
_OWNER: Optional["weakref.ref"] = None
_LOCK = threading.Lock()


def install(spec: str, forced: bool = False) -> None:
    """Arm the registry with a schedule (fresh counters).  ``forced``
    installs (tests, bench --chaos) survive sync_conf."""
    global _ARMED, _FORCED, _SITES_STATE, _SPEC_STR
    with _LOCK:
        _SITES_STATE = parse_spec(spec)
        _SPEC_STR = spec
        _ARMED = True
        _FORCED = forced


def disarm() -> None:
    global _ARMED, _FORCED, _SPEC_STR, _OWNER, _SITES_STATE
    with _LOCK:
        _ARMED = False
        _FORCED = False
        _SPEC_STR = None
        _OWNER = None
        _SITES_STATE = {}


def sync_conf(conf=None) -> None:
    """Align the process registry with the session conf at a query
    boundary (mirrors trace.sync_conf): a conf that enables faults arms
    its schedule; only the conf that armed may disarm; a programmatic
    forced install wins."""
    global _OWNER
    if _FORCED:
        return
    conf = conf or get_conf()
    want = bool(conf.get(FAULTS_ENABLED))
    if want:
        spec = str(conf.get(FAULTS_SPEC))
        with _LOCK:
            reinstall = not _ARMED or spec != _SPEC_STR
        if reinstall:
            install(spec)
        with _LOCK:
            _OWNER = weakref.ref(conf)
    elif _ARMED and _OWNER is not None and _OWNER() is conf:
        disarm()


def fault_point(site: str, **ctx) -> None:
    """The injection checkpoint.  Disabled: one global read.  Armed:
    evaluate the site's policy — maybe sleep (latency), maybe raise an
    InjectedFault whose text carries a retryable marker."""
    if not _ARMED:
        return
    st = _SITES_STATE.get(site)
    if st is None:
        return
    with st.lock:
        st.calls += 1
        call_no = st.calls
        fire = False
        if st.nth and st.nth <= call_no < st.nth + st.times:
            fire = True
        elif st.every and call_no % st.every == 0:
            fire = True
        elif st.prob and st.rng.random() < st.prob:
            fire = True
        if fire:
            st.injected += 1
        latency = st.latency_s
    if latency:
        time.sleep(latency)
    if fire:
        if _tr.TRACER.enabled:
            _tr.event("fault.inject", site=site, call=call_no, **ctx)
        raise InjectedFault(
            site, f"{st.marker} (site={site}, call #{call_no})")


def _injected_in_chain(exc: BaseException) -> Optional[InjectedFault]:
    seen = 0
    e: Optional[BaseException] = exc
    while e is not None and seen < 16:
        if isinstance(e, InjectedFault):
            return e
        e = e.__cause__ or e.__context__
        seen += 1
    return None


def note_recovered(exc: BaseException, action: str = "") -> None:
    """A recovery path absorbed ``exc`` (spill+retry, batch split, task
    re-run, fetch re-attempt, CPU degrade).  If an InjectedFault is in
    its cause chain, credit the site's recovered counter and emit the
    ``fault.recovered`` trace event; real (non-injected) failures pass
    through untouched — their recoveries are counted by the retry-layer
    stats instead (execs/retry.retry_stats)."""
    if not _ARMED:
        return
    inj = _injected_in_chain(exc)
    if inj is None:
        return
    st = _SITES_STATE.get(inj.site)
    if st is None:
        return
    with st.lock:
        st.recovered += 1
    if _tr.TRACER.enabled:
        _tr.event("fault.recovered", site=inj.site, action=action)


def fault_stats() -> dict[str, dict]:
    """{site: {calls, injected, recovered}} for the armed schedule."""
    with _LOCK:
        states = list(_SITES_STATE.values())
    return {st.site: st.snapshot() for st in states}


def recovered_total() -> int:
    return sum(s["recovered"] for s in fault_stats().values())


def injected_total() -> int:
    return sum(s["injected"] for s in fault_stats().values())


def reset_stats() -> None:
    """Zero every site's counters (the schedule itself stays armed) —
    bench.py resets per query so nth-call policies re-fire and the
    recovery fields attribute per query."""
    with _LOCK:
        states = list(_SITES_STATE.values())
    for st in states:
        with st.lock:
            st.calls = 0
            st.injected = 0
            st.recovered = 0
