"""Robustness subsystem: deterministic fault injection (faults.py),
the batch-granular OOM split-and-retry ladder (execs/retry.py builds
on it), and the runtime lock-order/deadlock tracker (lock_tracker.py
— the dynamic sibling of the CON* lint family; docs/concurrency.md).
See docs/robustness.md."""

from spark_rapids_tpu.robustness.faults import (  # noqa: F401
    InjectedFault,
    fault_point,
    fault_stats,
    install,
    disarm,
    note_recovered,
    recovered_total,
    reset_stats,
    sync_conf,
)
