"""Runtime lock-order/deadlock tracker: the dynamic sibling of the
CON* static rules (lint/concurrency_rules.py, docs/concurrency.md).

The static pass proves LEXICAL nesting acyclic; it cannot see orders
composed through call chains, callbacks, or data-dependent branches.
This module watches the real thing: engine locks constructed through
:func:`tracked_lock` carry a NAME, and — when the tracker is armed —
every acquisition records the per-thread holding stack, feeds a
process-wide runtime lock-order graph, and raises
:class:`LockCycleError` the moment an acquisition would CLOSE a cycle
(the observed deadlock reported BEFORE it hangs, lockdep-style, instead
of a wedged process 40 minutes into a soak).  Per-name counters
(acquisitions, contention waits, max hold time) surface through
``lock_stats()`` into the event-log counter surface (``lock.*``) and
the HC014 health rule (max hold > lockTracker.holdBudgetMs inside one
query).

Ownership mirrors robustness/faults exactly: conf-gated
(``spark.rapids.tpu.robustness.lockTracker.enabled``), a programmatic
forced :func:`install` (tests, bench storms) survives sync_conf, only
the arming conf may disarm.  DISARMED — the default — a tracked lock
is one module-global read plus the plain inner acquire: the serving
hot path pays nothing for the instrumentation existing.

What is tracked: the engine's registry/cache MUTEXES (plan cache,
result cache, scan-share registry, breaker registry, stage-metrics
map, scheduler registry, active-token gauge).  Condition variables
stay plain ``threading.Condition`` — their wait() releases the lock,
which a hold-stack model would misread as a held edge.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Optional

from spark_rapids_tpu.config import get_conf, register

LOCK_TRACKER_ENABLED = register(
    "spark.rapids.tpu.robustness.lockTracker.enabled", False,
    "Arm the runtime lock-order tracker for queries run with this "
    "conf: named engine locks record per-thread acquisition stacks, "
    "maintain the process lock-order graph, raise LockCycleError on "
    "cycle formation (an observed deadlock, reported before it "
    "hangs), and publish lock.* counters into the event log.  "
    "Disarmed (the default), every tracked lock is one global read "
    "plus the plain acquire.")

LOCK_HOLD_BUDGET_MS = register(
    "spark.rapids.tpu.robustness.lockTracker.holdBudgetMs", 250.0,
    "Health-rule budget (HC014): a query whose event-log record "
    "shows any tracked lock held longer than this (lock.max_hold_ms) "
    "is flagged — a long hold on a registry mutex serializes every "
    "thread population behind it.  Only meaningful with the tracker "
    "armed.", check=lambda v: v > 0)


class LockCycleError(RuntimeError):
    """Acquiring this lock would close a cycle in the runtime
    lock-order graph — the acquisition that would deadlock, caught at
    formation time.  Carries the offending edge and the established
    path it contradicts."""

    def __init__(self, message: str, edge: tuple[str, str],
                 path: list[str]):
        super().__init__(message)
        self.edge = edge
        self.path = list(path)


class _NameStats:
    """Aggregated per-NAME counters (all instances constructed under
    one name — e.g. every session's PlanCache mutex — pool here)."""

    __slots__ = ("acquisitions", "contention_waits", "max_hold_ns")

    def __init__(self):
        self.acquisitions = 0
        self.contention_waits = 0
        self.max_hold_ns = 0


# process-global armed state (faults.py ownership discipline: arming
# is per process — tracked locks are process singletons' locks, and
# acquisition runs on worker threads holding conf SNAPSHOTS)
_ARMED = False
_FORCED = False
_OWNER: Optional["weakref.ref"] = None
_MU = threading.Lock()
#: name -> aggregated stats (under _MU)
_STATS: dict[str, _NameStats] = {}
#: runtime lock-order graph: edge a -> b means "held a while
#: acquiring b" was OBSERVED (under _MU)
_EDGES: dict[str, set[str]] = {}
#: cycle formations detected (under _MU); nonzero after a
#: LockCycleError was raised
_CYCLES = 0

_TLS = threading.local()


def _held_stack() -> list:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


def _reaches(src: str, dst: str) -> Optional[list[str]]:
    """Path src -> ... -> dst in _EDGES (caller holds _MU), or None."""
    if src == dst:
        return [src]
    seen = {src}
    stack = [(src, [src])]
    while stack:
        node, path = stack.pop()
        for nxt in _EDGES.get(node, ()):
            if nxt == dst:
                return path + [dst]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


class TrackedLock:
    """A named mutex: plain ``threading.Lock``/``RLock`` semantics,
    plus (armed-only) order tracking and contention/hold accounting.
    Construct through :func:`tracked_lock`."""

    __slots__ = ("name", "reentrant", "_inner")

    def __init__(self, name: str, reentrant: bool = False):
        self.name = name
        self.reentrant = reentrant
        self._inner = threading.RLock() if reentrant \
            else threading.Lock()
        # no stats seeding here: lock_stats() lists names ACQUIRED
        # while armed, not every lock the process ever constructed

    # -- armed path --------------------------------------------------- #

    def _depths(self) -> dict:
        d = getattr(_TLS, "depths", None)
        if d is None:
            d = _TLS.depths = {}
        return d

    def _acquire_tracked(self) -> None:
        stack = _held_stack()
        if self.reentrant:
            depths = self._depths()
            if depths.get(id(self), 0) > 0:
                # re-entry on the owning thread: no new edge, no new
                # stack frame — the outermost acquisition owns both
                self._inner.acquire()
                depths[id(self)] = depths.get(id(self), 0) + 1
                return
        held = [name for name, _t0, _lk in stack]
        if held:
            with _MU:
                global _CYCLES
                for h in held:
                    if h == self.name:
                        continue
                    path = _reaches(self.name, h)
                    if path is not None:
                        _CYCLES += 1
                        raise LockCycleError(
                            f"lock-order cycle: acquiring "
                            f"{self.name!r} while holding {h!r} "
                            f"contradicts the established order "
                            f"{' -> '.join(path)} (this acquisition "
                            "WOULD deadlock under the right "
                            "interleaving; docs/concurrency.md)",
                            edge=(h, self.name), path=path)
                for h in held:
                    if h != self.name:
                        _EDGES.setdefault(h, set()).add(self.name)
        contended = False
        if not self._inner.acquire(blocking=False):
            contended = True
            self._inner.acquire()
        with _MU:
            st = _STATS.setdefault(self.name, _NameStats())
            st.acquisitions += 1
            if contended:
                st.contention_waits += 1
        stack.append((self.name, time.monotonic_ns(), self))
        if self.reentrant:
            self._depths()[id(self)] = 1

    def _release_tracked(self) -> None:
        if self.reentrant:
            depths = self._depths()
            n = depths.get(id(self), 0)
            if n > 1:
                depths[id(self)] = n - 1
                self._inner.release()
                return
            depths.pop(id(self), None)
        stack = _held_stack()
        # tolerate an arm/disarm flip between acquire and release:
        # only account frames this tracker actually pushed
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][2] is self:
                _name, t0, _lk = stack.pop(i)
                held_ns = time.monotonic_ns() - t0
                with _MU:
                    st = _STATS.setdefault(self.name, _NameStats())
                    if held_ns > st.max_hold_ns:
                        st.max_hold_ns = held_ns
                break
        self._inner.release()

    # -- public Lock interface ---------------------------------------- #

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        if not _ARMED:
            return self._inner.acquire(blocking, timeout)
        if not blocking or timeout != -1:
            # non-blocking/timed acquires cannot deadlock-by-waiting;
            # count them, skip order edges (they give up instead of
            # blocking, so they are not a cycle hazard)
            ok = self._inner.acquire(blocking, timeout)
            if ok:
                with _MU:
                    st = _STATS.setdefault(self.name, _NameStats())
                    st.acquisitions += 1
                _held_stack().append(
                    (self.name, time.monotonic_ns(), self))
                if self.reentrant:
                    d = self._depths()
                    d[id(self)] = d.get(id(self), 0) + 1
            return ok
        self._acquire_tracked()
        return True

    def release(self) -> None:
        if not _ARMED:
            # still pop any frame a previously-armed acquire pushed,
            # or a later armed window would see a stale "held" lock
            stack = getattr(_TLS, "stack", None)
            if stack:
                for i in range(len(stack) - 1, -1, -1):
                    if stack[i][2] is self:
                        stack.pop(i)
                        break
            self._inner.release()
            return
        self._release_tracked()

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def locked(self) -> bool:
        inner = self._inner
        return inner.locked() if hasattr(inner, "locked") else False


def tracked_lock(name: str, reentrant: bool = False) -> TrackedLock:
    """A named engine lock (see module doc).  `name` is the stats and
    graph identity — instances sharing a name pool their counters and
    their order constraints (they guard the same KIND of state)."""
    return TrackedLock(name, reentrant=reentrant)


# ------------------------------------------------------------------ #
# Arming (faults.py ownership idiom)
# ------------------------------------------------------------------ #


def install(forced: bool = False) -> None:
    """Arm the tracker (fresh graph + counters).  ``forced`` installs
    (tests, bench storms) survive sync_conf."""
    global _ARMED, _FORCED
    with _MU:
        _reset_locked()
        _ARMED = True
        _FORCED = forced


def disarm() -> None:
    global _ARMED, _FORCED, _OWNER
    with _MU:
        _ARMED = False
        _FORCED = False
        _OWNER = None


def sync_conf(conf=None) -> None:
    """Align the process tracker with the session conf at a query
    boundary: an enabling conf arms and owns it; only the owner's
    disable disarms; a programmatic forced install wins."""
    global _OWNER
    if _FORCED:
        return
    conf = conf or get_conf()
    want = bool(conf.get(LOCK_TRACKER_ENABLED))
    if want:
        if not _ARMED:
            install()
        with _MU:
            _OWNER = weakref.ref(conf)
    elif _ARMED and _OWNER is not None and _OWNER() is conf:
        disarm()


def tracker_armed() -> bool:
    return _ARMED


# ------------------------------------------------------------------ #
# Reading
# ------------------------------------------------------------------ #


def _reset_locked() -> None:
    global _CYCLES
    _STATS.clear()
    _EDGES.clear()
    _CYCLES = 0


def reset_stats() -> None:
    """Zero counters and the order graph (armed state unchanged) —
    bench/test phase boundaries."""
    with _MU:
        _reset_locked()


def lock_stats() -> dict[str, dict]:
    """{name: {acquisitions, contention_waits, max_hold_ms}} for every
    lock name seen since arming."""
    with _MU:
        return {
            name: {
                "acquisitions": st.acquisitions,
                "contention_waits": st.contention_waits,
                "max_hold_ms": round(st.max_hold_ns / 1e6, 3),
            }
            for name, st in sorted(_STATS.items())
        }


def aggregate_stats() -> dict:
    """Process totals for the event-log counter surface: monotonic
    ``acquisitions``/``contention_waits``/``cycles``, plus the
    ``max_hold_ms`` high-water gauge across every tracked lock."""
    with _MU:
        return {
            "acquisitions": sum(s.acquisitions
                                for s in _STATS.values()),
            "contention_waits": sum(s.contention_waits
                                    for s in _STATS.values()),
            "max_hold_ms": round(
                max((s.max_hold_ns for s in _STATS.values()),
                    default=0) / 1e6, 3),
            "cycles": _CYCLES,
        }


def cycle_count() -> int:
    with _MU:
        return _CYCLES


def order_graph() -> dict[str, list[str]]:
    """The observed runtime acquisition order (name -> successors) —
    tests assert against it; operators can dump it when diagnosing."""
    with _MU:
        return {a: sorted(bs) for a, bs in sorted(_EDGES.items())}
