"""UDF expression nodes.

- JaxScalarUDF: the TPU-native UDF interface — the analog of the
  reference's RapidsUDF (sql-plugin/src/main/java/com/nvidia/spark/
  RapidsUDF.java:22-40 `evaluateColumnar(ColumnVector...)`): the user
  supplies a columnar function over device arrays (jax.numpy / pallas)
  that is traced INTO the surrounding fused XLA program — zero
  per-batch Python cost after compile.

- OpaquePythonUDF: an arbitrary Python scalar function.  Not TPU
  replaceable; the planner's tagging walk leaves it on the CPU engine,
  which evaluates it row-wise in-process — the analog of the
  reference's Python-worker fallback path (2.15: python/ worker
  pieces), minus the process boundary a JVM needs and Python doesn't.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import AnyColumn, Column
from spark_rapids_tpu.exprs.base import EvalContext, Expression


@dataclasses.dataclass(repr=False)
class JaxScalarUDF(Expression):
    """User columnar function over the children's device data arrays.

    NULL semantics: result row is NULL iff any input row is NULL (the
    common deterministic-UDF contract); the function sees raw data
    arrays (garbage in NULL slots, like any expression eval)."""

    fn: Callable
    _dtype: T.DataType
    args: tuple[Expression, ...]
    fn_name: str = "jax_udf"

    @property
    def dtype(self) -> T.DataType:
        return self._dtype

    @property
    def name(self) -> str:
        return self.fn_name

    def eval(self, ctx: EvalContext) -> AnyColumn:
        cols = [a.eval(ctx) for a in self.args]
        data = self.fn(*[c.data for c in cols])
        data = jnp.asarray(data)
        if data.shape != (ctx.batch.capacity,):
            raise ValueError(
                f"jax UDF {self.fn_name!r} returned shape {data.shape}, "
                f"expected ({ctx.batch.capacity},)")
        valid = ctx.row_mask
        for c in cols:
            valid = valid & c.validity
        return Column(data.astype(T.to_numpy_dtype(self._dtype)), valid,
                      self._dtype)


@dataclasses.dataclass(repr=False)
class OpaquePythonUDF(Expression):
    """Uncompiled Python scalar function; CPU-engine only (the tagging
    walk reports it as not replaceable, ref: GpuOverrides' unsupported-
    expression fallback)."""

    fn: Callable
    _dtype: T.DataType
    args: tuple[Expression, ...]
    fn_name: str = "python_udf"

    @property
    def dtype(self) -> T.DataType:
        return self._dtype

    @property
    def name(self) -> str:
        return self.fn_name

    def eval(self, ctx: EvalContext) -> AnyColumn:  # pragma: no cover
        raise NotImplementedError(
            "OpaquePythonUDF runs on the CPU engine only")
