"""UDF-to-expression compiler: translate plain Python scalar functions
into this engine's Expression trees so they run entirely on TPU.

TPU analog of the reference's udf-compiler (udf-compiler/src/main/scala/
com/nvidia/spark/udf/CatalystExpressionBuilder.scala — JVM bytecode ->
Catalyst expressions).  Python functions carry their AST, so this
translates `ast` nodes instead of bytecode, with the same contract:
a supported subset compiles to a pure expression tree (no Python at
eval time, fused into the XLA program); anything else is rejected and
the caller falls back to an opaque UDF.

Supported subset (mirroring the reference's Instruction tables):
arithmetic, comparisons, boolean logic, `x is (not) None`, ternaries,
if/return chains, `in (literals)`, math.* calls, abs/min/max/len/round,
and string methods (upper/lower/strip/startswith/endswith/replace).
"""

from __future__ import annotations

import ast
import inspect
import math
import textwrap
from typing import Callable, Optional, Sequence

from spark_rapids_tpu.exprs import arithmetic as A
from spark_rapids_tpu.exprs import math as M
from spark_rapids_tpu.exprs import predicates as P
from spark_rapids_tpu.exprs import strings as S
from spark_rapids_tpu.exprs.base import Expression, Literal


class UncompilableUDF(Exception):
    """Function uses constructs outside the compilable subset."""


_BINOPS = {
    ast.Add: A.Add, ast.Sub: A.Subtract, ast.Mult: A.Multiply,
    ast.Div: A.Divide, ast.FloorDiv: A.IntegralDivide,
    ast.Mod: A.Remainder, ast.Pow: M.Pow,
}
_CMPOPS = {ast.Lt: P.LessThan, ast.LtE: P.LessThanOrEqual,
           ast.Gt: P.GreaterThan, ast.GtE: P.GreaterThanOrEqual,
           ast.Eq: P.EqualTo}
_MATH_CALLS = {
    "sqrt": M.Sqrt, "exp": M.Exp, "expm1": M.Expm1, "log": M.Log,
    "log10": M.Log10, "log2": M.Log2, "log1p": M.Log1p, "sin": M.Sin,
    "cos": M.Cos, "tan": M.Tan, "asin": M.Asin, "acos": M.Acos,
    "atan": M.Atan, "sinh": M.Sinh, "cosh": M.Cosh, "tanh": M.Tanh,
    "degrees": M.ToDegrees, "radians": M.ToRadians,
}
_MATH_CONSTS = {"pi": math.pi, "e": math.e, "inf": math.inf,
                "nan": math.nan}
_STR_METHODS = {"upper": S.Upper, "lower": S.Lower, "strip": S.StringTrim,
                "lstrip": S.StringTrimLeft, "rstrip": S.StringTrimRight}


class _Translator:
    def __init__(self, params: Sequence[str]):
        self.params = list(params)

    def fail(self, node, why: str):
        raise UncompilableUDF(
            f"{why} (line {getattr(node, 'lineno', '?')})")

    # -- statements ------------------------------------------------------ #

    def block(self, stmts, args) -> Expression:
        """A statement list that must RETURN on every path; if/return
        chains become If expressions (the reference's basic-block ->
        CaseWhen translation, CatalystExpressionBuilder.scala)."""
        if not stmts:
            self.fail(stmts, "missing return")
        st, rest = stmts[0], stmts[1:]
        if isinstance(st, ast.Return):
            if st.value is None:
                self.fail(st, "bare return")
            return self.expr(st.value, args)
        if isinstance(st, ast.If):
            pred = self.expr(st.test, args)
            then = self.block(st.body, args)
            other = self.block(st.orelse or rest, args)
            return P.If(pred, then, other)
        self.fail(st, f"unsupported statement {type(st).__name__}")

    # -- expressions ----------------------------------------------------- #

    def expr(self, node: ast.AST, args) -> Expression:
        if isinstance(node, ast.Constant):
            v = node.value
            if v is None or isinstance(v, (bool, int, float, str)):
                return Literal.of(v)
            self.fail(node, f"unsupported constant {v!r}")
        if isinstance(node, ast.Name):
            if node.id in self.params:
                return args[self.params.index(node.id)]
            self.fail(node, f"free variable {node.id!r}")
        if isinstance(node, ast.BinOp):
            op = _BINOPS.get(type(node.op))
            if op is None:
                self.fail(node, f"operator {type(node.op).__name__}")
            return op(self.expr(node.left, args),
                      self.expr(node.right, args))
        if isinstance(node, ast.UnaryOp):
            c = self.expr(node.operand, args)
            if isinstance(node.op, ast.USub):
                return A.UnaryMinus(c)
            if isinstance(node.op, ast.UAdd):
                return A.UnaryPositive(c)
            if isinstance(node.op, ast.Not):
                return P.Not(c)
            self.fail(node, f"operator {type(node.op).__name__}")
        if isinstance(node, ast.BoolOp):
            parts = [self.expr(v, args) for v in node.values]
            cls = P.And if isinstance(node.op, ast.And) else P.Or
            out = parts[0]
            for p in parts[1:]:
                out = cls(out, p)
            return out
        if isinstance(node, ast.Compare):
            return self._compare(node, args)
        if isinstance(node, ast.IfExp):
            return P.If(self.expr(node.test, args),
                        self.expr(node.body, args),
                        self.expr(node.orelse, args))
        if isinstance(node, ast.Call):
            return self._call(node, args)
        if isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name) \
                    and node.value.id == "math" \
                    and node.attr in _MATH_CONSTS:
                return Literal.of(_MATH_CONSTS[node.attr])
            self.fail(node, f"attribute {node.attr!r}")
        self.fail(node, f"unsupported syntax {type(node).__name__}")

    def _compare(self, node: ast.Compare, args) -> Expression:
        # chained comparisons (a < b < c) fold into AND
        out: Optional[Expression] = None
        left = node.left
        for op, right in zip(node.ops, node.comparators):
            term = self._compare_one(left, op, right, node, args)
            out = term if out is None else P.And(out, term)
            left = right
        return out  # type: ignore[return-value]

    def _compare_one(self, left, op, right, node, args) -> Expression:
        def is_none(n):
            return isinstance(n, ast.Constant) and n.value is None

        if isinstance(op, (ast.Is, ast.IsNot)):
            if is_none(right):
                child = self.expr(left, args)
            elif is_none(left):
                child = self.expr(right, args)
            else:
                self.fail(node, "`is` only supported against None")
            return P.IsNull(child) if isinstance(op, ast.Is) \
                else P.IsNotNull(child)
        if isinstance(op, (ast.In, ast.NotIn)):
            if not isinstance(right, (ast.List, ast.Tuple, ast.Set)) \
                    or not all(isinstance(e, ast.Constant)
                               for e in right.elts):
                self.fail(node, "`in` needs a literal collection")
            vals = tuple(e.value for e in right.elts)
            out = P.In(self.expr(left, args), vals)
            return P.Not(out) if isinstance(op, ast.NotIn) else out
        cls = _CMPOPS.get(type(op))
        if cls is not None:
            return cls(self.expr(left, args), self.expr(right, args))
        if isinstance(op, ast.NotEq):
            return P.Not(P.EqualTo(self.expr(left, args),
                                   self.expr(right, args)))
        self.fail(node, f"comparison {type(op).__name__}")

    def _call(self, node: ast.Call, args) -> Expression:
        if node.keywords:
            self.fail(node, "keyword arguments")
        cargs = [self.expr(a, args) for a in node.args]
        f = node.func
        if isinstance(f, ast.Attribute):
            if isinstance(f.value, ast.Name) and f.value.id == "math":
                cls = _MATH_CALLS.get(f.attr)
                if cls is not None and len(cargs) == 1:
                    return cls(cargs[0])
                if f.attr == "floor" and len(cargs) == 1:
                    return M.Floor(cargs[0])
                if f.attr == "ceil" and len(cargs) == 1:
                    return M.Ceil(cargs[0])
                if f.attr == "pow" and len(cargs) == 2:
                    return M.Pow(cargs[0], cargs[1])
                self.fail(node, f"math.{f.attr}")
            # string methods on an expression receiver
            recv = self.expr(f.value, args)
            if f.attr in _STR_METHODS and not cargs:
                return _STR_METHODS[f.attr](recv)
            if f.attr == "startswith" and len(cargs) == 1:
                return S.StartsWith(recv, cargs[0])
            if f.attr == "endswith" and len(cargs) == 1:
                return S.EndsWith(recv, cargs[0])
            if f.attr == "replace" and len(cargs) == 2:
                return S.StringReplace(recv, cargs[0], cargs[1])
            self.fail(node, f"method .{f.attr}()")
        if isinstance(f, ast.Name):
            if f.id == "abs" and len(cargs) == 1:
                return A.Abs(cargs[0])
            if f.id == "len" and len(cargs) == 1:
                return S.Length(cargs[0])
            if f.id == "min" and len(cargs) >= 2:
                return A.Least(*cargs)
            if f.id == "max" and len(cargs) >= 2:
                return A.Greatest(*cargs)
            if f.id == "round" and len(cargs) in (1, 2):
                from spark_rapids_tpu.exprs.math import Round

                scale = 0
                if len(cargs) == 2:
                    if not (isinstance(cargs[1], Literal)
                            and isinstance(cargs[1].value, int)):
                        self.fail(node, "round() scale must be literal")
                    scale = cargs[1].value
                return Round(cargs[0], scale)
            self.fail(node, f"call {f.id}()")
        self.fail(node, "computed call target")


def compile_udf(fn: Callable) -> Callable[..., Expression]:
    """Compile `fn` into an Expression-tree factory: calling the result
    with child Expressions substitutes them for the parameters.  Raises
    UncompilableUDF outside the supported subset."""
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError) as e:
        raise UncompilableUDF(f"no source available: {e}") from None
    try:
        tree = ast.parse(src)
    except SyntaxError:
        # lambdas inside expressions (e.g. udf(lambda x: ...)) can make
        # the extracted source unparsable on its own
        raise UncompilableUDF("cannot parse function source") from None

    fndef = None
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.Lambda)):
            fndef = n
            break
    if fndef is None:
        raise UncompilableUDF("no function definition found")
    a = fndef.args
    if a.vararg or a.kwarg or a.kwonlyargs or a.defaults or a.posonlyargs:
        raise UncompilableUDF("only plain positional parameters")
    params = [p.arg for p in a.args]
    tr = _Translator(params)

    def factory(*child_exprs: Expression) -> Expression:
        if len(child_exprs) != len(params):
            raise TypeError(
                f"UDF takes {len(params)} args, got {len(child_exprs)}")
        if isinstance(fndef, ast.Lambda):
            return tr.expr(fndef.body, list(child_exprs))
        return tr.block(fndef.body, list(child_exprs))

    # compile eagerly once with placeholder columns to surface errors at
    # registration (the reference compiles at udf-registration too)
    from spark_rapids_tpu.exprs.base import ColumnReference

    factory(*[ColumnReference(f"__p{i}") for i in range(len(params))])
    return factory
