"""User-defined functions, TPU-first.

Three tiers, best first (the reference's UDF story re-architected for
XLA):

1. `@udf(T)` — tries the AST compiler (compiler.py, the udf-compiler
   analog): a compilable Python function becomes a pure Expression tree
   and fuses into the XLA program like any built-in expression.
2. `@jax_udf(T)` — the RapidsUDF analog (RapidsUDF.java:22-40): the
   user writes the columnar kernel themselves against jax.numpy (or a
   pallas_call) and it traces into the fused program.
3. Anything else — an OpaquePythonUDF evaluated row-wise by the CPU
   engine via planner fallback (the python-worker analog).

`@udf` automatically degrades 1 -> 3; explain() shows which tier ran.
"""

from __future__ import annotations

from typing import Callable, Optional

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exprs.base import Expression
from spark_rapids_tpu.udf.compiler import UncompilableUDF, compile_udf
from spark_rapids_tpu.udf.exprs import JaxScalarUDF, OpaquePythonUDF


class UserDefinedFunction:
    """Callable wrapper binding a Python function to column expressions
    (ref: sql/rapids/execution/python/ GpuPythonUDF + the compiled
    GpuScalaUDF route)."""

    def __init__(self, fn: Callable, return_type: Optional[T.DataType],
                 columnar: bool = False):
        self.fn = fn
        self.return_type = return_type
        self.columnar = columnar
        self.name = getattr(fn, "__name__", "udf")
        self._factory = None
        self.tier = "opaque"
        if columnar:
            self.tier = "jax"
        else:
            try:
                self._factory = compile_udf(fn)
                self.tier = "compiled"
            except UncompilableUDF:
                if return_type is None:
                    raise
        if self.tier != "compiled" and return_type is None:
            raise TypeError(
                f"UDF {self.name!r} is not compilable to expressions, "
                "so an explicit return_type is required")

    def __call__(self, *cols) -> Expression:
        from spark_rapids_tpu.session import _expr

        args = tuple(_expr(c) for c in cols)
        if self.tier == "compiled":
            out = self._factory(*args)
            if self.return_type is not None:
                # dtype may be unresolvable before reference binding;
                # a same-type Cast is a no-op, so wrap when in doubt
                try:
                    same = out.dtype == self.return_type
                except Exception:
                    same = False
                if not same:
                    from spark_rapids_tpu.exprs.cast import Cast

                    out = Cast(out, self.return_type)
            return out
        if self.tier == "jax":
            return JaxScalarUDF(self.fn, self.return_type, args,
                                self.name)
        return OpaquePythonUDF(self.fn, self.return_type, args,
                               self.name)


def udf(return_type: Optional[T.DataType] = None):
    """Decorator/factory: `@udf(T.DOUBLE)` or `udf(T.DOUBLE)(fn)`.
    Compiles to a TPU expression tree when possible, else falls back to
    a CPU-evaluated opaque UDF (return_type then required)."""
    if callable(return_type):  # bare @udf usage
        return UserDefinedFunction(return_type, None)

    def wrap(fn: Callable) -> UserDefinedFunction:
        return UserDefinedFunction(fn, return_type)

    return wrap


def jax_udf(return_type: T.DataType):
    """Decorator for columnar TPU UDFs: the function receives the
    children's device data arrays (jax arrays, batch-capacity length)
    and returns one; it is traced into the fused XLA program.  The
    RapidsUDF.evaluateColumnar analog."""

    def wrap(fn: Callable) -> UserDefinedFunction:
        return UserDefinedFunction(fn, return_type, columnar=True)

    return wrap
