"""Plugin entry point and lifecycle.

TPU analog of the reference's plugin bring-up (ref: SQLPlugin.scala +
Plugin.scala:179 RapidsExecutorPlugin — driver/executor init, config
snapshot, shutdown hooks).  In this in-process engine the "plugin" owns
process-wide runtime state: the buffer store, the task semaphore, the
compiled-program cache, and the frontend adapter (shim).

Frontend shims (ref: the shims/ spark301..spark311 version adapters,
SURVEY §2.11): the reference re-targets one plugin across Spark
versions by routing version-specific APIs through a shim layer.  Here
the equivalent seam is the FRONTEND adapter — what translates a user
API into this engine's logical plans.  The native DataFrame frontend is
the default; a SQL-text or Substrait frontend plugs in through the
same registry without touching the engine."""

from __future__ import annotations

import atexit
import threading
from typing import Callable, Optional

_SHIMS: dict[str, Callable] = {}
_lock = threading.Lock()


def register_frontend(name: str, factory: Callable) -> None:
    """Register a frontend adapter: factory(conf) -> session-like
    object exposing this engine's DataFrame surface."""
    with _lock:
        _SHIMS[name] = factory


def frontend(name: str = "native"):
    with _lock:
        try:
            return _SHIMS[name]
        except KeyError:
            raise KeyError(
                f"no frontend {name!r} registered "
                f"(have: {sorted(_SHIMS)})") from None


class TpuPlugin:
    """Process-wide lifecycle owner (SQLPlugin analog)."""

    _instance: Optional["TpuPlugin"] = None

    def __init__(self, conf=None):
        from spark_rapids_tpu.config import TpuConf, set_conf

        self.conf = conf or TpuConf()
        set_conf(self.conf)
        self._closed = False
        self.device_info = None
        try:
            # device discovery + memory-budget sizing (the
            # GpuDeviceManager.initializeGpuAndMemory step); never
            # fatal — a budget-from-conf store works everywhere
            from spark_rapids_tpu.memory import device_manager

            self.device_info = device_manager.initialize(self.conf)
        except Exception:
            pass
        atexit.register(self.shutdown)

    @classmethod
    def get_or_create(cls, conf=None) -> "TpuPlugin":
        with _lock:
            if cls._instance is None or cls._instance._closed:
                cls._instance = TpuPlugin(conf)
            return cls._instance

    def session(self, frontend_name: str = "native"):
        return frontend(frontend_name)(self.conf)

    def shutdown(self) -> None:
        """Release process-wide resources (executor shutdown hook,
        ref: RapidsExecutorPlugin.shutdown)."""
        if self._closed:
            return
        self._closed = True
        from spark_rapids_tpu.execs import jit_cache
        from spark_rapids_tpu.memory import reset_store

        try:
            # reset_store() closes any existing store itself; calling
            # get_store() here would lazily build one just to close it
            reset_store()
        except Exception:
            pass
        try:
            jit_cache.clear()
        except Exception:
            pass


def _native_frontend(conf):
    from spark_rapids_tpu.session import TpuSession

    return TpuSession(conf)


register_frontend("native", _native_frontend)
