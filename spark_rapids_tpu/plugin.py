"""Plugin entry point and lifecycle.

TPU analog of the reference's plugin bring-up (ref: SQLPlugin.scala +
Plugin.scala:179 RapidsExecutorPlugin — driver/executor init, config
snapshot, shutdown hooks).  In this in-process engine the "plugin" owns
process-wide runtime state: the buffer store, the task semaphore, the
compiled-program cache, and the frontend adapter (shim).

Frontend shims (ref: the shims/ spark301..spark311 version adapters,
SURVEY §2.11): the reference re-targets one plugin across Spark
versions by routing version-specific APIs through a shim layer.  Here
the equivalent seam is the FRONTEND adapter — what translates a user
API into this engine's logical plans.  The native DataFrame frontend is
the default; a SQL-text or Substrait frontend plugs in through the
same registry without touching the engine."""

from __future__ import annotations

import atexit
import threading
from typing import Callable, Optional

from spark_rapids_tpu.config import register

NET_SHUFFLE_REGISTRY = register(
    "spark.rapids.tpu.shuffle.registry.address", "",
    "host:port of the shuffle peer registry (shuffle/net.py "
    "HeartbeatServer).  When set, plugin bring-up starts a TCP block "
    "server for this process's shuffle outputs and joins the registry "
    "with heartbeats (ref: Plugin.scala:197 heartbeat endpoint + "
    "RapidsShuffleHeartbeatManager).  Empty disables the network tier.")

NET_SHUFFLE_ADVERTISE = register(
    "spark.rapids.tpu.shuffle.server.advertiseHost", "",
    "Routable address peers should fetch this executor's blocks from. "
    "Empty = auto: loopback when the registry is on loopback, else "
    "this host's resolved address (cross-machine peers must never be "
    "handed 127.0.0.1 — they would fetch from themselves).  The block "
    "server binds 0.0.0.0 whenever the advertised host is non-local.")

_SHIMS: dict[str, Callable] = {}
_lock = threading.Lock()


def register_frontend(name: str, factory: Callable) -> None:
    """Register a frontend adapter: factory(conf) -> session-like
    object exposing this engine's DataFrame surface."""
    with _lock:
        _SHIMS[name] = factory


def frontend(name: str = "native"):
    with _lock:
        fe = _SHIMS.get(name)
    if fe is not None:
        return fe
    # bundled adapters register on import; load them before giving up
    try:
        import spark_rapids_tpu.frontends  # noqa: F401
    except ImportError:
        pass
    with _lock:
        try:
            return _SHIMS[name]
        except KeyError:
            raise KeyError(
                f"no frontend {name!r} registered "
                f"(have: {sorted(_SHIMS)})") from None


class TpuPlugin:
    """Process-wide lifecycle owner (SQLPlugin analog)."""

    _instance: Optional["TpuPlugin"] = None

    def __init__(self, conf=None):
        from spark_rapids_tpu.config import TpuConf, set_conf

        self.conf = conf or TpuConf()
        set_conf(self.conf)
        self._closed = False
        self.device_info = None
        self.block_server = None
        self.heartbeat_client = None
        try:
            # device discovery + memory-budget sizing (the
            # GpuDeviceManager.initializeGpuAndMemory step); never
            # fatal — a budget-from-conf store works everywhere
            from spark_rapids_tpu.memory import device_manager

            self.device_info = device_manager.initialize(self.conf)
        except Exception:
            pass
        self._maybe_start_network_shuffle()
        atexit.register(self.shutdown)

    def _maybe_start_network_shuffle(self) -> None:
        """Executor bring-up of the cross-process shuffle tier (ref:
        Plugin.scala:197 RapidsShuffleHeartbeatEndpoint start): when a
        registry address is configured, serve this process's blocks
        over TCP and join the peer registry with periodic heartbeats."""
        registry = self.conf.get(NET_SHUFFLE_REGISTRY)
        if not registry:
            return
        try:
            import os
            import socket as _socket

            from spark_rapids_tpu.shuffle.net import (
                HeartbeatClient,
                ShuffleBlockServer,
            )

            host, port = registry.rsplit(":", 1)
            local_registry = host in ("127.0.0.1", "localhost", "::1")
            advertise = self.conf.get(NET_SHUFFLE_ADVERTISE)
            if not advertise:
                advertise = "127.0.0.1" if local_registry \
                    else _socket.gethostbyname(_socket.gethostname())
            bind = "127.0.0.1" if advertise in ("127.0.0.1",
                                                "localhost") \
                else "0.0.0.0"
            from spark_rapids_tpu.columnar.serde import (
                SHUFFLE_COMPRESSION,
            )

            self.block_server = ShuffleBlockServer(
                host=bind,
                codec=self.conf.get(SHUFFLE_COMPRESSION)).start()
            bport = self.block_server.address[1]
            self.heartbeat_client = HeartbeatClient(
                host, int(port), f"executor-{os.getpid()}",
                advertise, bport)
            self.heartbeat_client.register()
            self.heartbeat_client.start_background()
        except Exception:
            # degraded mode: local + collective tiers still work (the
            # reference likewise falls back when UCX cannot start)
            if self.block_server is not None:
                self.block_server.shutdown()
                self.block_server = None
            self.heartbeat_client = None

    @classmethod
    def get_or_create(cls, conf=None) -> "TpuPlugin":
        with _lock:
            if cls._instance is None or cls._instance._closed:
                cls._instance = TpuPlugin(conf)
            return cls._instance

    def session(self, frontend_name: str = "native"):
        return frontend(frontend_name)(self.conf)

    def shutdown(self) -> None:
        """Release process-wide resources (executor shutdown hook,
        ref: RapidsExecutorPlugin.shutdown)."""
        if self._closed:
            return
        self._closed = True
        from spark_rapids_tpu.execs import jit_cache
        from spark_rapids_tpu.memory import reset_store

        if self.heartbeat_client is not None:
            self.heartbeat_client.stop()
            self.heartbeat_client = None
        if self.block_server is not None:
            try:
                self.block_server.shutdown()
            except Exception:
                pass
            self.block_server = None
        try:
            # reset_store() closes any existing store itself; calling
            # get_store() here would lazily build one just to close it
            reset_store()
        except Exception:
            pass
        try:
            jit_cache.clear()
        except Exception:
            pass


def _native_frontend(conf):
    from spark_rapids_tpu.session import TpuSession

    return TpuSession(conf)


register_frontend("native", _native_frontend)
