"""Device & memory runtime (SURVEY.md L1).

TPU re-design of the reference's tiered buffer stores
(RapidsBufferCatalog / Rapids{Device,Host,Disk}MemoryStore /
DeviceMemoryEventHandler, SURVEY.md §2.4).  The reference reacts to RMM
allocation failures; XLA/PJRT exposes no alloc-failure callback, so the
TPU design is a *proactive budget manager*: operators register their
resident batches, reserve budget before materializing new ones, and the
store synchronously spills lowest-priority buffers down the
DEVICE -> HOST -> DISK chain to make room (SURVEY.md §7 hard-part #3).
"""

from spark_rapids_tpu.memory.store import (  # noqa: F401
    BufferStore,
    SpillableBatch,
    SpillPriorities,
    StorageTier,
    get_store,
    reset_store,
)
from spark_rapids_tpu.memory.semaphore import TpuSemaphore  # noqa: F401
