"""Spill-tiered buffer store.

Maps the reference's architecture onto JAX/TPU:

- `StorageTier` DEVICE/HOST/DISK (ref: RapidsBuffer.scala:53-58; the GDS
  tier has no TPU analog and is dropped);
- `SpillableBatch` = SpillableColumnarBatch: a handle that lets the
  store move the batch down-tier while unused; `.get()` re-materializes
  on device (ref: SpillableColumnarBatch.scala:29);
- `BufferStore` = RapidsBufferCatalog + the per-tier stores: one
  priority-ordered registry with byte accounting per tier
  (ref: RapidsBufferStore.scala:145-207 synchronousSpill);
- `reserve()` replaces DeviceMemoryEventHandler.onAllocFailure: callers
  reserve device bytes *before* materializing, and the store spills
  lowest-priority resident buffers until the budget fits (proactive —
  XLA has no alloc-failure hook);
- spill priorities (ref: SpillPriorities.scala): exchange outputs spill
  first, active working batches last.

Device -> host movement is `jax.device_get` + explicit `.delete()` on
the device arrays (deterministic HBM release); host -> disk is a .npz
file in the configured spill directory."""

from __future__ import annotations

import dataclasses
import enum
import os
import tempfile
import threading
from typing import Optional

import jax
import numpy as np

from spark_rapids_tpu import trace as _trace
from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import (
    MapColumn,
    StructColumn,
    AnyColumn,
    Column,
    ListColumn,
    StringColumn,
)
from spark_rapids_tpu.config import register


class StorageTier(enum.IntEnum):
    DEVICE = 0
    HOST = 1
    DISK = 2


class SpillPriorities:
    """Lower value spills first (ref: SpillPriorities.scala:26-60)."""

    OUTPUT_FOR_SHUFFLE = -100
    COALESCE_PENDING = 0
    #: cross-tenant shared-result entries (serving/work_share.py):
    #: pure cache — always rebuildable by re-running the query, and
    #: entirely host/disk-tier (Arrow-IPC frames, never device
    #: buffers) — so they yield host memory before any working data
    #: does; the disk hop is their designed pressure valve
    SHARED_RESULT = 10
    #: cached (df.cache) batches are re-served across queries but are
    #: rebuildable by re-running the subtree: spill them before the
    #: working set of the running query
    CACHED = 20
    AGGREGATE_PARTIAL = 50
    JOIN_BUILD = 80
    #: broadcast builds are shared across every stream partition, so
    #: respilling is paid many times over — spill them last (ref:
    #: GpuBroadcastExchangeExec keeping broadcast batches as catalog
    #: entries, GpuBroadcastExchangeExec.scala:237,271)
    BROADCAST = 90
    ACTIVE_ON_DECK = 100


HBM_BUDGET_BYTES = register(
    "spark.rapids.tpu.memory.hbm.budgetBytes", 12 << 30,
    "Device-memory budget the buffer store manages batches within "
    "(ref: spark.rapids.memory.gpu.pool sizing, RapidsConf.scala:413). "
    "Proactive: reservations beyond this trigger synchronous spill.")
HOST_SPILL_BYTES = register(
    "spark.rapids.tpu.memory.host.spillStorageSize", 4 << 30,
    "Host-memory bound for spilled batches before they continue to disk "
    "(ref: spark.rapids.memory.host.spillStorageSize, "
    "RapidsConf.scala:357).")
SPILL_DIR = register(
    "spark.rapids.tpu.memory.spill.dir", "",
    "Directory for disk-tier spill files (default: a temp dir).")
SPILL_HOST_COMPRESS = register(
    "spark.rapids.tpu.memory.spill.compressHostTier", False,
    "Serialize device->host spills through the spill codec "
    "(spark.rapids.tpu.memory.spill.compression.codec, shared "
    "wire-codec registry) so the HOST tier holds compressed frames: "
    "more batches fit under spillStorageSize before the disk tier "
    "engages, and a later host->disk spill writes the frame as-is "
    "(no recompression).  Costs a decompress on restore.  Snapshotted "
    "at store construction, like the codec itself.")


def _col_device_bytes(c) -> int:
    if isinstance(c, StringColumn):
        n = c.chars.size * 1 + c.lengths.size * 4 + c.validity.size
        if c.codes is not None:
            n += (c.codes.size * 4 + c.dict_chars.size
                  + c.dict_lens.size * 4)
        return n
    if isinstance(c, ListColumn):
        return (c.values.size * c.values.dtype.itemsize
                + c.lengths.size * 4 + c.elem_validity.size
                + c.validity.size)
    if isinstance(c, StructColumn):
        return sum(_col_device_bytes(k) for k in c.children) \
            + c.validity.size
    if isinstance(c, MapColumn):
        return (c.keys.size * c.keys.dtype.itemsize
                + c.values.size * c.values.dtype.itemsize
                + c.entry_validity.size + c.lengths.size * 4
                + c.validity.size)
    return c.data.size * c.data.dtype.itemsize + c.validity.size


def batch_device_bytes(batch: ColumnarBatch) -> int:
    total = sum(_col_device_bytes(c) for c in batch.columns)
    if not isinstance(batch.num_rows, int):
        total += 4
    return total


def _col_leaves(c, prefix: str) -> list[tuple[str, object]]:
    """(name, device array) leaves of one column (recursive)."""
    if isinstance(c, StringColumn):
        out = [(f"{prefix}_chars", c.chars),
               (f"{prefix}_lengths", c.lengths),
               (f"{prefix}_valid", c.validity)]
        if c.codes is not None:  # dict sidecar spills/restores with it
            out += [(f"{prefix}_codes", c.codes),
                    (f"{prefix}_dchars", c.dict_chars),
                    (f"{prefix}_dlens", c.dict_lens)]
            if c.dict_len is not None:
                # static entry-count bound: a host scalar leaf (passes
                # device_get untouched, skipped by _delete) — dropping
                # it would demote restored keys to padded-capacity
                # domains and fork the pytree aux
                out.append((f"{prefix}_dictlen",
                            np.asarray(c.dict_len, np.int64)))
        return out
    if isinstance(c, ListColumn):
        return [(f"{prefix}_lvalues", c.values),
                (f"{prefix}_lengths", c.lengths),
                (f"{prefix}_levalid", c.elem_validity),
                (f"{prefix}_valid", c.validity)]
    if isinstance(c, StructColumn):
        out = []
        for j, k in enumerate(c.children):
            out += _col_leaves(k, f"{prefix}_f{j}")
        return out + [(f"{prefix}_valid", c.validity)]
    if isinstance(c, MapColumn):
        return [(f"{prefix}_mkeys", c.keys),
                (f"{prefix}_mvalues", c.values),
                (f"{prefix}_mevalid", c.entry_validity),
                (f"{prefix}_lengths", c.lengths),
                (f"{prefix}_valid", c.validity)]
    out = [(f"{prefix}_data", c.data), (f"{prefix}_valid", c.validity)]
    if getattr(c, "codes", None) is not None:
        # numeric dict sidecar spills/restores with the column (as the
        # StringColumn sidecar does): dropping it would silently demote
        # a restored group-by key to the lexsort path
        out += [(f"{prefix}_codes", c.codes),
                (f"{prefix}_dvals", c.dict_values)]
        if c.dict_len is not None:
            out.append((f"{prefix}_dictlen",
                        np.asarray(c.dict_len, np.int64)))
    return out


#: leaf-name suffixes of DICTIONARY SIDECAR arrays.  gather/compact/
#: split pass the row-invariant dictionary through BY REFERENCE, so
#: every child batch of a dict-encoded column shares ONE device array —
#: spilling one registered child must not .delete() it out from under
#: its siblings (the "Array has been deleted" crash under a tight
#: budget).  Skipping the explicit delete only defers release to the
#: last Python reference dropping; dictionaries are bounded at 0xFFFF
#: entries, so the nondeterminism is a few KB, not a batch.
_SHARED_SIDECAR_SUFFIXES = ("_dchars", "_dlens", "_dvals")


def _batch_to_host(batch: ColumnarBatch,
                   delete: bool = True) -> dict:
    """Materialize to numpy; `delete` releases the device buffers
    (spill), False leaves them resident (host VIEW, e.g. serve_host)."""
    n = batch.concrete_num_rows()
    leaves: list[tuple[str, object]] = []
    for i, c in enumerate(batch.columns):
        leaves += _col_leaves(c, f"c{i}")
    # ONE batched D2H round for every leaf (per-leaf gets would pay
    # link latency per buffer)
    host = jax.device_get([a for _, a in leaves])
    arrays: dict[str, np.ndarray] = {
        name: np.asarray(h) for (name, _), h in zip(leaves, host)}
    # per-leaf device commitment, in leaf order (-1 = uncommitted or
    # multi-device): a per-shard batch adopted onto its mesh device
    # (parallel/placement.py) restores THERE, not onto the default
    # device — spill must not silently undo stage-input locality
    dev_ids = []
    for _, a in leaves:
        did = -1
        if isinstance(a, jax.Array):
            try:
                ds = a.devices()
                if len(ds) == 1:
                    did = next(iter(ds)).id
            except Exception:
                pass
        dev_ids.append(did)
    arrays["__leaf_devices"] = np.asarray(dev_ids, np.int64)
    if delete:
        for name, a in leaves:
            if not name.endswith(_SHARED_SIDECAR_SUFFIXES):
                _delete(a)
    arrays["__num_rows"] = np.asarray(n, np.int64)
    return arrays


def _delete(a) -> None:
    from spark_rapids_tpu.columnar.column import is_shared_array

    if isinstance(a, jax.Array) and not is_shared_array(a):
        try:
            a.delete()
        except Exception:
            pass  # already consumed/donated


def _host_to_col(arrays: dict, prefix: str, dtype: T.DataType):
    import jax.numpy as jnp

    if isinstance(dtype, T.StringType):
        codes = arrays.get(f"{prefix}_codes")
        return StringColumn(
            jnp.asarray(arrays[f"{prefix}_chars"]),
            jnp.asarray(arrays[f"{prefix}_lengths"]),
            jnp.asarray(arrays[f"{prefix}_valid"]), dtype,
            jnp.asarray(codes) if codes is not None else None,
            jnp.asarray(arrays[f"{prefix}_dchars"])
            if codes is not None else None,
            jnp.asarray(arrays[f"{prefix}_dlens"])
            if codes is not None else None,
            _restore_dict_len(arrays, prefix))
    if isinstance(dtype, T.ListType):
        return ListColumn(
            jnp.asarray(arrays[f"{prefix}_lvalues"]),
            jnp.asarray(arrays[f"{prefix}_lengths"]),
            jnp.asarray(arrays[f"{prefix}_levalid"]),
            jnp.asarray(arrays[f"{prefix}_valid"]), dtype)
    if isinstance(dtype, T.StructType):
        kids = tuple(_host_to_col(arrays, f"{prefix}_f{j}", cf.dtype)
                     for j, cf in enumerate(dtype.fields))
        return StructColumn(kids,
                            jnp.asarray(arrays[f"{prefix}_valid"]),
                            dtype)
    if isinstance(dtype, T.MapType):
        return MapColumn(
            jnp.asarray(arrays[f"{prefix}_mkeys"]),
            jnp.asarray(arrays[f"{prefix}_mvalues"]),
            jnp.asarray(arrays[f"{prefix}_mevalid"]),
            jnp.asarray(arrays[f"{prefix}_lengths"]),
            jnp.asarray(arrays[f"{prefix}_valid"]), dtype)
    codes = arrays.get(f"{prefix}_codes")
    return Column(jnp.asarray(arrays[f"{prefix}_data"]),
                  jnp.asarray(arrays[f"{prefix}_valid"]), dtype,
                  None if codes is None else jnp.asarray(codes),
                  None if codes is None
                  else jnp.asarray(arrays[f"{prefix}_dvals"]),
                  _restore_dict_len(arrays, prefix))


def _restore_dict_len(arrays: dict, prefix: str):
    v = arrays.get(f"{prefix}_dictlen")
    return None if v is None else int(np.asarray(v))


def _host_to_batch(arrays: dict, schema: T.Schema) -> ColumnarBatch:
    cols: list[AnyColumn] = [
        _host_to_col(arrays, f"c{i}", f.dtype)
        for i, f in enumerate(schema.fields)]
    n = int(np.asarray(arrays["__num_rows"]).reshape(-1)[0])
    batch = ColumnarBatch(cols, n, schema)
    # restore stage-input locality (mesh serving only — the default
    # path stays byte-identical: everything lands on the default
    # device as ever): a batch whose leaves were all committed to one
    # mesh device re-adopts that device
    devs = arrays.get("__leaf_devices")
    if devs is not None:
        ids = {int(x) for x in np.asarray(devs).reshape(-1)
               if int(x) >= 0}
        if len(ids) == 1:
            from spark_rapids_tpu.serving import mesh_serving_enabled

            if mesh_serving_enabled():
                want = ids.pop()
                target = next((d for d in jax.devices()
                               if d.id == want), None)
                if target is not None:
                    from spark_rapids_tpu.parallel import (
                        placement as _placement,
                    )

                    batch = _placement.adopt_batch(batch, target)
    return batch


class _HostFrame:
    """A HOST-tier entry held as one compressed serde frame instead of
    a raw array dict (spill.compressHostTier): the host tier then
    stores what the disk tier would write, so host->disk spill is a
    plain file write and host occupancy accounts compressed bytes."""

    __slots__ = ("frame",)

    def __init__(self, frame: bytes):
        self.frame = frame


def _host_arrays(held) -> dict:
    """A HOST-tier entry's payload as a raw array dict (decompressing
    a _HostFrame through the serde/codec registry)."""
    if isinstance(held, _HostFrame):
        from spark_rapids_tpu.columnar.serde import deserialize_arrays

        return deserialize_arrays(held.frame)
    return held


def _host_bytes(held) -> int:
    if isinstance(held, _HostFrame):
        return len(held.frame)
    return int(sum(a.nbytes for a in held.values()))


@dataclasses.dataclass
class _Entry:
    buffer_id: int
    priority: int
    nbytes: int
    tier: StorageTier
    batch: Optional[ColumnarBatch]  # DEVICE tier
    host: Optional[dict]  # HOST tier
    path: Optional[str]  # DISK tier
    schema: T.Schema
    #: pin COUNT: entries in active use must not be evicted — an
    #: acquire() that spills an already-acquired sibling would delete
    #: device arrays the caller still holds.  A count (not a flag)
    #: because shared entries (broadcast builds) are acquired by many
    #: stream partitions concurrently; the first unpin must not make
    #: the entry evictable under the others.
    pins: int = 0
    #: host-bytes equivalent parked on the DISK tier (what disk_used
    #: credits back when the entry is restored or removed)
    disk_bytes: int = 0

    @property
    def pinned(self) -> bool:
        return self.pins > 0


class SpillableBatch:
    """Handle registering a device batch with the store so it may spill
    while not in active use.  `get()` returns a device-resident batch,
    re-materializing (and re-registering at DEVICE) if spilled.

    `mark_consumed()` is the donation seam (docs/fusion.md): a caller
    that donates the batch's device arrays into a fused XLA program
    must un-register them FIRST — a donated-then-spilled buffer is a
    use-after-free (`_batch_to_host` would device_get freed HBM).
    Consumed handles stay valid objects: `unpin`/`close` become
    no-ops (so retry-ladder rollbacks that sweep handle lists never
    re-park a donated batch) and `get()` fails fast."""

    def __init__(self, store: "BufferStore", buffer_id: int):
        self._store = store
        self.buffer_id = buffer_id
        self._consumed = False

    def get(self) -> ColumnarBatch:
        """Acquire device-resident (pins the buffer until unpin/close)."""
        if self._consumed:
            from spark_rapids_tpu.columnar.transfer import (
                ConsumedBatchError,
            )

            raise ConsumedBatchError(
                f"buffer {self.buffer_id} was donated into a fused "
                "program and cannot be re-materialized")
        return self._store.acquire(self.buffer_id)

    def mark_consumed(self) -> None:
        """Un-register: the device arrays are being donated into a
        fused program (XLA reuses their HBM for the outputs), so the
        store must never spill or account them again.  Idempotent;
        the entry is dropped WITHOUT deleting the arrays (XLA now
        owns that memory)."""
        if self._consumed:
            return
        self._consumed = True
        self._store.remove(self.buffer_id)

    @property
    def consumed(self) -> bool:
        return self._consumed

    def _raise_consumed(self, what: str) -> None:
        from spark_rapids_tpu.columnar.transfer import (
            ConsumedBatchError,
        )

        raise ConsumedBatchError(
            f"buffer {self.buffer_id} was donated into a fused "
            f"program; {what} is gone")

    def get_host(self) -> dict:
        """Read the batch as host arrays without materializing on device
        (pins; the out-of-core sort assembles buckets host-side)."""
        if self._consumed:
            self._raise_consumed("its host view")
        return self._store.acquire_host(self.buffer_id)

    def unpin(self) -> None:
        """Make the buffer spillable again (caller dropped its batch
        reference).  No-op on a consumed handle — a rollback sweep
        must never make a donated buffer spillable."""
        if self._consumed:
            return
        with self._store._lock:
            e = self._store._entries.get(self.buffer_id)
            if e is not None:
                e.pins = max(0, e.pins - 1)

    @property
    def tier(self) -> StorageTier:
        if self._consumed:
            self._raise_consumed("its storage tier")
        return self._store._entries[self.buffer_id].tier

    @property
    def nbytes(self) -> int:
        if self._consumed:
            self._raise_consumed("its byte accounting")
        return self._store._entries[self.buffer_id].nbytes

    def close(self) -> None:
        """No-op on a consumed handle (mark_consumed already dropped
        the entry; the arrays belong to XLA now)."""
        if self._consumed:
            return
        self._store.remove(self.buffer_id)


class BufferStore:
    def __init__(self, device_budget: Optional[int] = None,
                 host_budget: Optional[int] = None,
                 spill_dir: Optional[str] = None):
        from spark_rapids_tpu.config import get_conf

        conf = get_conf()
        self.device_budget = device_budget if device_budget is not None \
            else conf.get(HBM_BUDGET_BYTES)
        self.host_budget = host_budget if host_budget is not None \
            else conf.get(HOST_SPILL_BYTES)
        self._spill_dir = spill_dir or conf.get(SPILL_DIR) or None
        # snapshot at construction: spills run on worker threads whose
        # thread-local conf is not the user's session conf
        from spark_rapids_tpu.columnar.serde import spill_codec

        self._spill_codec = spill_codec()
        self._host_compress = conf.get_bool(SPILL_HOST_COMPRESS.key) \
            and self._spill_codec != "none"
        self._tmpdir: Optional[tempfile.TemporaryDirectory] = None
        self._entries: dict[int, _Entry] = {}  # guard: _lock
        self._next_id = 0           # guard: _lock
        self._lock = threading.RLock()
        self.device_used = 0        # guard: _lock
        self.host_used = 0          # guard: _lock
        #: observability (ref: spill metrics + memoryBytesSpilled)
        self.spilled_device_to_host = 0  # guard: _lock
        self.spilled_host_to_disk = 0    # guard: _lock
        #: gauge: host-bytes equivalent currently parked on disk (the
        #: telemetry sampler's third storage tier)
        self.disk_used = 0          # guard: _lock

    def spill_stats(self) -> dict[str, int]:
        """Point-in-time spill/occupancy accounting — the store's
        contribution to the event log's counter surface (the two
        ``spilled_*`` totals are monotonic; the ``*_used`` figures are
        gauges).  One locked read so the four values are mutually
        consistent."""
        with self._lock:
            return {
                "device_used": self.device_used,
                "host_used": self.host_used,
                "disk_used": self.disk_used,
                "spilled_device_to_host": self.spilled_device_to_host,
                "spilled_host_to_disk": self.spilled_host_to_disk,
            }

    # -- registration --------------------------------------------------- #

    def register(self, batch: ColumnarBatch,
                 priority: int = SpillPriorities.ACTIVE_ON_DECK
                 ) -> SpillableBatch:
        nbytes = batch_device_bytes(batch)
        with self._lock:
            self.reserve(nbytes)
            bid = self._next_id
            self._next_id += 1
            self._entries[bid] = _Entry(
                bid, priority, nbytes, StorageTier.DEVICE, batch, None,
                None, batch.schema)
            self.device_used += nbytes
            return SpillableBatch(self, bid)

    def register_host(self, arrays: dict, schema: T.Schema,
                      priority: int = SpillPriorities.ACTIVE_ON_DECK
                      ) -> SpillableBatch:
        """Register a batch already materialized as host arrays (the
        out-of-core sort's run storage: data that by design does not live
        on device).  Enters at HOST tier and participates in host->disk
        spill; `get()` re-materializes on device as usual."""
        with self._lock:
            bid = self._next_id
            self._next_id += 1
            # device-size estimate for when it is re-materialized
            nbytes = _host_bytes(arrays)
            self._entries[bid] = _Entry(
                bid, priority, nbytes, StorageTier.HOST, None, arrays,
                None, schema)
            self.host_used += nbytes
            while self.host_used > self.host_budget:
                if not self._spill_one_host_locked():
                    break
            return SpillableBatch(self, bid)

    def acquire(self, buffer_id: int) -> ColumnarBatch:
        with self._lock:
            e = self._entries[buffer_id]
            e.pins += 1  # before reserve(): a cascaded spill must
            # never select the entry being acquired (it could write a
            # disk file acquire would then orphan)
            try:
                if e.tier == StorageTier.DEVICE:
                    return e.batch  # type: ignore[return-value]
                with _trace.span("spill.restore", tier=e.tier.name,
                                 bytes=e.nbytes, buffer=e.buffer_id):
                    if e.tier == StorageTier.HOST:
                        arrays = _host_arrays(e.host)
                    else:
                        from spark_rapids_tpu.columnar.serde import (
                            read_spill_file,
                        )

                        arrays = read_spill_file(e.path)  # type: ignore
                    self.reserve(e.nbytes)
                    batch = _host_to_batch(arrays, e.schema)  # H2D
            except BaseException:
                # a failed acquire must not leak its pin (the entry
                # would be unevictable forever)
                e.pins = max(0, e.pins - 1)
                raise
            if e.tier == StorageTier.HOST:
                self.host_used -= _host_bytes(e.host)
            elif e.path:
                # unlink only after the upload succeeded: an exception
                # mid-acquire (cascaded spill, H2D failure) must not lose
                # the only copy while the entry still claims DISK tier
                try:
                    os.unlink(e.path)
                except OSError:
                    pass
                self.disk_used -= e.disk_bytes
                e.disk_bytes = 0
            e.batch, e.host, e.path = batch, None, None
            e.tier = StorageTier.DEVICE
            self.device_used += e.nbytes
            return batch

    def acquire_host(self, buffer_id: int) -> dict:
        """Host-array view of an entry at any tier (pins the entry; a
        DEVICE-tier entry is pulled D2H without changing tiers)."""
        with self._lock:
            e = self._entries[buffer_id]
            e.pins += 1
            try:
                if e.tier == StorageTier.HOST:
                    return _host_arrays(e.host)
                if e.tier == StorageTier.DISK:
                    from spark_rapids_tpu.columnar.serde import (
                        read_spill_file,
                    )

                    return read_spill_file(e.path)  # type: ignore
                # DEVICE: pull without deleting
                return _batch_to_host(e.batch, delete=False)
            except BaseException:
                e.pins = max(0, e.pins - 1)  # failed acquire: no leak
                raise

    def remove(self, buffer_id: int) -> None:
        with self._lock:
            e = self._entries.pop(buffer_id, None)
            if e is None:
                return
            if e.tier == StorageTier.DEVICE:
                self.device_used -= e.nbytes
            elif e.tier == StorageTier.HOST:
                self.host_used -= _host_bytes(e.host)  # type: ignore
            elif e.path:
                try:
                    os.unlink(e.path)
                except OSError:
                    pass
                self.disk_used -= e.disk_bytes
                e.disk_bytes = 0

    # -- budget / spill -------------------------------------------------- #

    def reserve(self, nbytes: int) -> None:
        """Make room for an nbytes device allocation, spilling if needed
        (the proactive analog of DeviceMemoryEventHandler.onAllocFailure
        -> synchronousSpill).  The alloc.device fault checkpoint sits in
        front: a (injected or real) RESOURCE_EXHAUSTED from admission is
        absorbed once by spilling EVERYTHING unpinned and re-admitting —
        the onAllocFailure -> synchronousSpill -> retry-the-alloc loop;
        a second failure propagates to the batch split-and-retry
        ladder."""
        from spark_rapids_tpu.memory.device_manager import (
            device_alloc_checkpoint,
        )

        with self._lock:
            try:
                device_alloc_checkpoint(nbytes)
            except BaseException as e:  # noqa: BLE001 - classified below
                from spark_rapids_tpu.execs.retry import is_retryable
                from spark_rapids_tpu.robustness import faults as _faults

                if not is_retryable(e):
                    raise
                while self._spill_one_device_locked():
                    pass
                device_alloc_checkpoint(nbytes)  # 2nd failure escalates
                _faults.note_recovered(e, action="alloc_spill_retry")
            while self.device_used + nbytes > self.device_budget:
                if not self._spill_one_device_locked():
                    break  # nothing spillable left; let XLA try anyway

    def leak_report(self) -> list[str]:
        """Still-registered buffers (the all-buffers-released invariant
        check SURVEY.md §5.2 calls for; the reference leans on cudf's
        RefCount debugging — here the store itself is the registry, so
        leak detection is a dictionary walk).  Healthy shutdown (and
        end-of-test) state: empty."""
        with self._lock:
            return [
                f"buffer {bid}: tier={e.tier.name} pins={e.pins} "
                f"bytes={e.nbytes}"
                for bid, e in self._entries.items()]

    def assert_all_released(self) -> None:
        leaks = self.leak_report()
        assert not leaks, (
            f"{len(leaks)} buffer(s) never released:\n  "
            + "\n  ".join(leaks))

    def spill_all_unpinned(self) -> int:
        """Evict every unpinned DEVICE buffer to host — the
        release-everything step between task retry attempts (ref:
        RmmRapidsRetryIterator's spill-before-retry).  Returns the
        number of buffers spilled."""
        n = 0
        with self._lock:
            while self._spill_one_device_locked():
                n += 1
        return n

    def _spill_one_device_locked(self) -> bool:
        candidates = [e for e in self._entries.values()
                      if e.tier == StorageTier.DEVICE and not e.pinned]
        if not candidates:
            return False
        victim = min(candidates, key=lambda e: (e.priority, e.buffer_id))
        self._spill_to_host_locked(victim)
        return True

    def _spill_to_host_locked(self, e: _Entry) -> None:
        with _trace.span("spill.device_to_host", tier="DEVICE",
                         bytes=e.nbytes, buffer=e.buffer_id):
            arrays = _batch_to_host(e.batch)  # type: ignore[arg-type]
            held: object = arrays
            if self._host_compress:
                from spark_rapids_tpu.columnar.serde import (
                    serialize_arrays,
                )

                held = _HostFrame(serialize_arrays(
                    arrays, self._spill_codec))
        e.batch = None
        e.tier = StorageTier.HOST
        e.host = held  # type: ignore[assignment]
        self.device_used -= e.nbytes
        hb = _host_bytes(held)
        self.host_used += hb
        self.spilled_device_to_host += e.nbytes
        while self.host_used > self.host_budget:
            if not self._spill_one_host_locked():
                break

    def _spill_one_host_locked(self) -> bool:
        candidates = [e for e in self._entries.values()
                      if e.tier == StorageTier.HOST and not e.pinned]
        if not candidates:
            return False
        victim = min(candidates, key=lambda e: (e.priority, e.buffer_id))
        held = victim.host
        path = os.path.join(self._dir(), f"spill-{victim.buffer_id}.tpub")
        from spark_rapids_tpu.columnar.serde import write_spill_file

        hb = _host_bytes(held)  # type: ignore[arg-type]
        with _trace.span("spill.host_to_disk", tier="HOST", bytes=hb,
                         buffer=victim.buffer_id):
            if isinstance(held, _HostFrame):
                # the host tier already holds the serde frame: write
                # it as-is — no recompression on the way to disk
                with open(path, "wb") as f:
                    f.write(held.frame)
            else:
                write_spill_file(path, held,  # type: ignore[arg-type]
                                 self._spill_codec)
        victim.host = None
        victim.path = path
        victim.tier = StorageTier.DISK
        victim.disk_bytes = hb
        self.host_used -= hb
        self.disk_used += hb
        self.spilled_host_to_disk += hb
        return True

    def _dir(self) -> str:
        if self._spill_dir:
            os.makedirs(self._spill_dir, exist_ok=True)
            return self._spill_dir
        if self._tmpdir is None:
            self._tmpdir = tempfile.TemporaryDirectory(
                prefix="spark_rapids_tpu_spill_")
        return self._tmpdir.name

    def close(self) -> None:
        with self._lock:
            for bid in list(self._entries):
                self.remove(bid)
            if self._tmpdir is not None:
                self._tmpdir.cleanup()
                self._tmpdir = None


_STORE: Optional[BufferStore] = None
_STORE_LOCK = threading.Lock()


def get_store() -> BufferStore:
    global _STORE
    with _STORE_LOCK:
        if _STORE is None:
            _STORE = BufferStore()
        return _STORE


def peek_store() -> Optional[BufferStore]:
    """The live store WITHOUT creating one.  A background probe (the
    telemetry sampler) must never construct the process singleton from
    its own thread's conf — the store snapshots budgets and the spill
    codec at __init__, and a sampler-thread default conf would pin
    them for the process lifetime."""
    with _STORE_LOCK:
        return _STORE


def reset_store(store: Optional[BufferStore] = None) -> None:
    global _STORE
    with _STORE_LOCK:
        if _STORE is not None:
            _STORE.close()
        _STORE = store
