"""Device discovery, selection, and memory-budget initialization.

Analog of GpuDeviceManager (ref: GpuDeviceManager.scala:125
`initializeGpuAndMemory` — one accelerator per executor, pool sizes
computed from the device's physical memory, pinned-host pool setup).
The TPU version asks the PJRT client instead of CUDA:

- `discover()` enumerates `jax.devices()` with kind/ordinal/memory;
- `select_device(conf)` picks this process's chip
  (`spark.rapids.tpu.deviceOrdinal`, -1 = first of the preferred
  platform) — the 1-accelerator-per-executor model;
- `initialize(conf)` sizes the spill store's HBM budget as a FRACTION
  of the selected chip's actual memory when the runtime reports it
  (memory_stats()['bytes_limit']), falling back to the static conf —
  the computeRmmInitSizes analog — and installs a BufferStore wired to
  that budget;
- `HostBufferPool` is the pinned-host-pool analog: recycled numpy
  staging buffers for SYNCHRONOUS host paths (the spill serializer,
  columnar/serde.py).  jax exposes no true pinned allocations and its
  H2D transfers complete asynchronously (a recycled source buffer
  would race the wire), so the win is alloc/zeroing churn on the
  spill path, not DMA pinning — documented divergence.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional

import numpy as np

from spark_rapids_tpu.config import register, get_conf

DEVICE_ORDINAL = register(
    "spark.rapids.tpu.deviceOrdinal", -1,
    "Which local device this process owns (the 1-accelerator-per-"
    "executor model, ref: GpuDeviceManager); -1 picks the first "
    "device of the preferred platform.")

MEMORY_FRACTION = register(
    "spark.rapids.tpu.memory.fraction", 0.8,
    "Fraction of the selected device's reported memory given to the "
    "spill store's HBM budget when the runtime reports a limit (the "
    "spark.rapids.memory.gpu.allocFraction analog).")

HOST_POOL_BYTES = register(
    "spark.rapids.tpu.memory.hostPool.maxBytes", 256 << 20,
    "Upper bound on recycled host staging buffers held by the "
    "HostBufferPool (the pinned-host pool analog).")

BATCH_ROWS_AUTO = register(
    "spark.rapids.tpu.sql.batchSizeRows.auto", False,
    "Scale the DEFAULT batchSizeRows with the selected device's HBM: "
    "rows = pow2 floor of memory.fraction * HBM / 2KiB-per-row working "
    "set (≈32 live copies of a 64B row: the batch, its program "
    "temporaries and double-buffered successors), clamped to "
    "maxBatchCapacity — bigger chips run denser batches without "
    "retuning (the computeRmmInitSizes idea applied to batch sizing).  "
    "An EXPLICITLY set batchSizeRows always wins, and backends that "
    "report no real chip memory (the CPU test backend) keep the static "
    "default (docs/occupancy.md).")

#: HBM bytes budgeted per batch row under batchSizeRows.auto — ~32
#: concurrent live copies of a ~64-byte row (inputs, fused-program
#: temporaries, double-buffered successors, spill headroom)
_AUTO_ROW_BYTES = 2048


def device_alloc_checkpoint(nbytes: int) -> None:
    """The ``alloc.device`` fault-injection seam (robustness/faults.py):
    BufferStore.reserve consults it before admitting a device
    reservation, standing in for the alloc-failure hook XLA does not
    expose (the reference's DeviceMemoryEventHandler.onAllocFailure).
    Disarmed it is one global read; armed, an injected
    RESOURCE_EXHAUSTED here drives the store's spill-and-retry path and,
    past that, the batch split-and-retry ladder (execs/retry.py)."""
    from spark_rapids_tpu.robustness import faults as _faults

    _faults.fault_point("alloc.device", nbytes=nbytes)


@dataclasses.dataclass(frozen=True)
class DeviceInfo:
    ordinal: int
    platform: str
    kind: str
    memory_bytes: Optional[int]


def discover() -> list[DeviceInfo]:
    """All PJRT devices visible to this process."""
    import jax

    out = []
    for i, d in enumerate(jax.devices()):
        mem = None
        try:
            stats = d.memory_stats()
            if stats:
                mem = stats.get("bytes_limit") or stats.get(
                    "bytes_reservable_limit")
        except Exception:
            pass
        out.append(DeviceInfo(i, d.platform, getattr(d, "device_kind",
                                                     d.platform), mem))
    return out


def select_device(conf=None):
    """This process's device (jax device object)."""
    import jax

    conf = conf or get_conf()
    devs = jax.devices()
    ordinal = conf.get(DEVICE_ORDINAL)
    if 0 <= ordinal < len(devs):
        return devs[ordinal]
    return devs[0]


def effective_batch_size_rows(conf=None) -> int:
    """batchSizeRows after HBM scaling: the conf value verbatim unless
    batchSizeRows.auto is on AND the conf sits at its default AND the
    selected device reports real chip memory — then the default scales
    with the HBM budget (pow2 floor of fraction * HBM / _AUTO_ROW_BYTES,
    clamped to [default, maxBatchCapacity]).  Every consumer of
    BATCH_SIZE_ROWS that sizes device batches routes through here."""
    from spark_rapids_tpu.config import BATCH_SIZE_ROWS, MAX_CAPACITY

    conf = conf or get_conf()
    rows = int(conf.get(BATCH_SIZE_ROWS))
    if not conf.get(BATCH_ROWS_AUTO) or rows != BATCH_SIZE_ROWS.default:
        return rows
    try:
        import jax

        dev = select_device(conf)
        info = discover()[jax.devices().index(dev)]
    except Exception:
        return rows
    if not info.memory_bytes or info.platform == "cpu":
        # CPU test backends report host RAM as "device" memory
        return rows
    budget = int(info.memory_bytes * conf.get(MEMORY_FRACTION))
    scaled = max(1, budget // _AUTO_ROW_BYTES)
    scaled = 1 << (scaled.bit_length() - 1)
    return int(min(max(scaled, rows), conf.get(MAX_CAPACITY)))


def initialize(conf=None) -> "DeviceInfo":
    """Size and install the process BufferStore from the selected
    device's reported memory; returns the chosen device's info."""
    from spark_rapids_tpu.memory.store import (
        BufferStore,
        HBM_BUDGET_BYTES,
        reset_store,
    )

    conf = conf or get_conf()
    dev = select_device(conf)
    import jax

    ordinal = jax.devices().index(dev)
    info = discover()[ordinal]
    budget = conf.get(HBM_BUDGET_BYTES)
    if info.memory_bytes and info.platform != "cpu":
        # CPU test backends report host RAM as "device" memory — the
        # fraction sizing only makes sense against a real chip's HBM
        budget = int(info.memory_bytes * conf.get(MEMORY_FRACTION))
    reset_store(BufferStore(device_budget=budget))
    return info


class HostBufferPool:
    """Recycled host staging buffers, bucketed by rounded size (the
    pinned-host-pool shape without real page pinning)."""

    _instance: Optional["HostBufferPool"] = None
    _ilock = threading.Lock()

    def __init__(self, max_bytes: Optional[int] = None):
        self._free: dict[int, list[np.ndarray]] = {}
        self._lock = threading.Lock()
        self._held = 0
        self.max_bytes = max_bytes if max_bytes is not None \
            else get_conf().get(HOST_POOL_BYTES)

    @classmethod
    def get(cls) -> "HostBufferPool":
        with cls._ilock:
            if cls._instance is None:
                cls._instance = HostBufferPool()
            return cls._instance

    @staticmethod
    def _bucket(nbytes: int) -> int:
        b = 4096
        while b < nbytes:
            b <<= 1
        return b

    def take(self, nbytes: int) -> np.ndarray:
        """A uint8 buffer of >= nbytes (first nbytes NOT zeroed)."""
        b = self._bucket(nbytes)
        with self._lock:
            lst = self._free.get(b)
            if lst:
                buf = lst.pop()
                self._held -= buf.nbytes
                return buf
        return np.empty(b, np.uint8)

    def give(self, buf: np.ndarray) -> None:
        """Return a buffer taken from the pool (callers must not keep
        references)."""
        if buf.dtype != np.uint8 or buf.ndim != 1:
            return
        b = buf.nbytes
        if (b & (b - 1)) or b < 4096:
            return  # not a pool bucket
        with self._lock:
            if self._held + b > self.max_bytes:
                return  # over budget: let it be collected
            self._free.setdefault(b, []).append(buf)
            self._held += b
