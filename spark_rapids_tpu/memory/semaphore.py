"""Task admission semaphore.

Counterpart of GpuSemaphore (ref: sql-plugin/.../GpuSemaphore.scala:27,
74): caps how many concurrent tasks may hold device batches, preventing
HBM oversubscription when the scheduler runs partitions on a thread
pool.  On TPU a core runs one program at a time anyway, so the semaphore
guards *memory residency*, not kernel concurrency — acquired on first
batch materialization, released at task end (same protocol as the
reference)."""

from __future__ import annotations

import threading
from typing import Optional


class TpuSemaphore:
    _instance: Optional["TpuSemaphore"] = None
    _lock = threading.Lock()

    def __init__(self, permits: int):
        self.permits = permits
        self._available = permits
        self._cv = threading.Condition()
        self._holders: set[int] = set()

    @classmethod
    def get(cls) -> "TpuSemaphore":
        with cls._lock:
            if cls._instance is None:
                from spark_rapids_tpu.config import (
                    CONCURRENT_TPU_TASKS,
                    get_conf,
                )

                cls._instance = TpuSemaphore(
                    get_conf().get(CONCURRENT_TPU_TASKS))
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls._instance = None

    def acquire_if_necessary(self, task_id: int) -> None:
        """Idempotent per task (ref: GpuSemaphore.acquireIfNecessary).

        Membership check, permit take, and holder registration happen in
        one critical section, so two threads presenting the same task_id
        cannot both take a permit (the set add would dedupe and leak a
        permit on release).  notify_all after a grant wakes same-task
        waiters so they observe membership and return without a permit."""
        with self._cv:
            while True:
                if task_id in self._holders:
                    return
                if self._available > 0:
                    self._available -= 1
                    self._holders.add(task_id)
                    self._cv.notify_all()
                    return
                self._cv.wait()

    def release_if_necessary(self, task_id: int) -> None:
        with self._cv:
            if task_id not in self._holders:
                return
            self._holders.discard(task_id)
            self._available += 1
            self._cv.notify_all()
