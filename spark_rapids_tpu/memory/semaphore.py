"""Task admission semaphore.

Counterpart of GpuSemaphore (ref: sql-plugin/.../GpuSemaphore.scala:27,
74): caps how many concurrent tasks may hold device batches, preventing
HBM oversubscription when the scheduler runs partitions on a thread
pool.  On TPU a core runs one program at a time anyway, so the semaphore
guards *memory residency*, not kernel concurrency — acquired on first
batch materialization, released at task end (same protocol as the
reference).

The permit count is conf-driven (spark.rapids.tpu.sql.concurrentTpuTasks)
but the instance is process-global: :meth:`sync_conf` aligns the two at
each query boundary with the same ownership rule as the tracer and the
fault registry — a conf asking for a NON-default size resizes the live
semaphore and becomes its owner; a conf that merely carries the default
never shrinks another session's explicit resize; only the owner (or a
new explicit setting) moves it again.  Resizing wakes waiters, so tests
and per-session conf changes take effect without a process restart.
The serving tier's admission control (serving/scheduler.py) reads
:attr:`permits` as the device-side concurrency cap, so a resize here
re-sizes query admission too (docs/serving.md)."""

from __future__ import annotations

import threading
import weakref
from typing import Optional


class TpuSemaphore:
    _instance: Optional["TpuSemaphore"] = None
    _lock = threading.Lock()
    #: weakref to the conf that last resized the live instance to a
    #: non-default permit count (None = sized at the registry default)
    _owner: Optional["weakref.ref"] = None

    def __init__(self, permits: int):
        # `permits` itself is deliberately unguarded: scheduler._limit
        # reads it as a config-tier value on the admission hot path
        self.permits = permits
        self._available = permits   # guard: _cv
        self._cv = threading.Condition()
        self._holders: set = set()  # guard: _cv

    @classmethod
    def get(cls) -> "TpuSemaphore":
        with cls._lock:
            if cls._instance is None:
                from spark_rapids_tpu.config import (
                    CONCURRENT_TPU_TASKS,
                    get_conf,
                )

                cls._instance = TpuSemaphore(
                    get_conf().get(CONCURRENT_TPU_TASKS))
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls._instance = None
            cls._owner = None

    @classmethod
    def sync_conf(cls, conf=None) -> None:
        """Align the process semaphore with the session conf at a query
        boundary (the conf is a thread-local snapshot; the semaphore is
        process-global).  Ownership mirrors trace/faults.sync_conf: an
        explicit (non-default) size resizes the live instance and owns
        it; a conf carrying the registry default only resizes back if
        it IS the owner — another session's default conf must not
        shrink a concurrently resized semaphore mid-query."""
        from spark_rapids_tpu.config import CONCURRENT_TPU_TASKS, get_conf

        conf = conf or get_conf()
        want = int(conf.get(CONCURRENT_TPU_TASKS))
        default = int(CONCURRENT_TPU_TASKS.default)
        with cls._lock:
            inst = cls._instance
            if inst is None:
                return  # the next get() reads this conf's value anyway
            if want == inst.permits:
                if want != default:
                    cls._owner = weakref.ref(conf)
                return
            if want == default:
                owner = cls._owner() if cls._owner is not None else None
                if owner is not conf:
                    return
                cls._owner = None
            else:
                cls._owner = weakref.ref(conf)
        inst.resize(want)

    def resize(self, permits: int) -> None:
        """Change the permit count of a LIVE semaphore.  Growing wakes
        waiters immediately; shrinking lets in-flight holders finish —
        `_available` may go transiently negative and new acquisitions
        block until enough holders release (the acquire loop only
        admits while `_available > 0`)."""
        if permits < 1:
            raise ValueError(f"semaphore permits must be >= 1, "
                             f"got {permits}")
        with self._cv:
            delta = permits - self.permits
            self.permits = permits
            self._available += delta
            if delta > 0:
                self._cv.notify_all()

    def usage(self) -> dict:
        """Point-in-time permit occupancy — the telemetry sampler's
        device-residency gauge.  ``in_use`` may transiently exceed
        ``permits`` right after a shrink (holders finish out; see
        :meth:`resize`)."""
        with self._cv:
            return {"permits": self.permits,
                    "in_use": self.permits - self._available}

    @classmethod
    def usage_now(cls) -> dict:
        """Usage of the live instance WITHOUT creating one (a sampler
        probing an idle process must not instantiate the semaphore
        from whatever conf its thread happens to hold)."""
        with cls._lock:
            inst = cls._instance
        if inst is None:
            return {"permits": 0, "in_use": 0}
        return inst.usage()

    def acquire_if_necessary(self, task_id) -> None:
        """Idempotent per task (ref: GpuSemaphore.acquireIfNecessary).

        Membership check, permit take, and holder registration happen in
        one critical section, so two threads presenting the same task_id
        cannot both take a permit (the set add would dedupe and leak a
        permit on release).  notify_all after a grant wakes same-task
        waiters so they observe membership and return without a permit."""
        with self._cv:
            while True:
                if task_id in self._holders:
                    return
                if self._available > 0:
                    self._available -= 1
                    self._holders.add(task_id)
                    self._cv.notify_all()
                    return
                self._cv.wait()

    def release_if_necessary(self, task_id) -> None:
        with self._cv:
            if task_id not in self._holders:
                return
            self._holders.discard(task_id)
            self._available += 1
            self._cv.notify_all()
