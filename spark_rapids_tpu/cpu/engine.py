"""Pyarrow-based executor for logical plans.

Deliberately an *independent implementation* of the SQL semantics (built
on pyarrow.compute kernels + numpy for the gaps), so a TPU kernel bug
cannot be masked by sharing code with the device path.  Where Spark
semantics differ from pyarrow defaults (Kleene logic, NULL on zero
divisors, NaN ordering, IN-list NULLs, If's NULL predicate), the Spark
behavior is implemented here explicitly — mirroring the compatibility
contract the reference documents in docs/compatibility.md."""

from __future__ import annotations

from typing import Optional

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.arrow import schema_to_arrow
from spark_rapids_tpu.exprs import arithmetic as A
from spark_rapids_tpu.exprs import bitwise as BW
from spark_rapids_tpu.exprs import datetime as DT
from spark_rapids_tpu.exprs import decimal as DEC
from spark_rapids_tpu.exprs import math as M
from spark_rapids_tpu.exprs import predicates as P
from spark_rapids_tpu.exprs import strings as S
from spark_rapids_tpu.exprs import base as B
from spark_rapids_tpu.exprs.cast import Cast
from spark_rapids_tpu.exprs.hashing import Md5, Murmur3Hash
from spark_rapids_tpu.plan import logical as L

_PC_UNARY = {
    M.Sqrt: pc.sqrt, M.Exp: pc.exp, M.Sin: pc.sin, M.Cos: pc.cos,
    M.Tan: pc.tan, M.Asin: pc.asin, M.Acos: pc.acos, M.Atan: pc.atan,
    M.Signum: pc.sign,
}
_NP_UNARY = {
    M.Cbrt: np.cbrt, M.Expm1: np.expm1, M.Sinh: np.sinh,
    M.Cosh: np.cosh, M.Tanh: np.tanh, M.Asinh: np.arcsinh,
    M.Acosh: np.arccosh, M.Atanh: np.arctanh, M.Rint: np.rint,
    M.ToDegrees: np.degrees, M.ToRadians: np.radians,
    M.Cot: lambda d: 1.0 / np.tan(d),
}


# ---------------------------------------------------------------------- #
# Expression evaluation
# ---------------------------------------------------------------------- #

def _arr(x, n: int, atype=None) -> pa.Array:
    if isinstance(x, pa.ChunkedArray):
        return x.combine_chunks()
    return x


def cpu_eval(e: B.Expression, table: pa.Table) -> pa.Array:
    n = table.num_rows
    out = _dispatch(e, table, n)
    return _arr(out, n)


def _widen_type(e: B.Expression) -> pa.DataType:
    return T.to_arrow_type(e.dtype)


def _plain(arr):
    """Decode dictionary encodings at the engine boundary: the CPU
    oracle computes over plain arrays (fastpar ships scan columns as
    pa.DictionaryArray to keep the wire cheap)."""
    t = arr.type
    if pa.types.is_dictionary(t):
        return arr.cast(t.value_type)
    return arr


def _binary_operands(e, table, n):
    l = cpu_eval(e.left, table)
    r = cpu_eval(e.right, table)
    return l, r


def _np_vals(arr: pa.Array, dtype) -> tuple[np.ndarray, np.ndarray]:
    valid = np.asarray(arr.is_valid())
    filled = arr.fill_null(0).cast(dtype) if arr.null_count else \
        arr.cast(dtype)
    return filled.to_numpy(zero_copy_only=False), valid


def _from_np(vals: np.ndarray, valid: np.ndarray, atype) -> pa.Array:
    mask = ~valid if (~valid).any() else None
    return pa.array(vals, type=atype, mask=mask)


def _string_batch3(e, table, n):
    """Python-string reference semantics for the batch-3 string ops
    (the oracle definitions; java.lang.String behavior where Spark
    delegates there)."""
    import re

    if isinstance(e, S.ConcatWs):
        sep = e.sep.value
        cols = [cpu_eval(c, table).to_pylist() for c in e.exprs]
        if sep is None:
            return pa.array([None] * n, pa.string())
        out = []
        for i in range(n):
            parts = [c[i] for c in cols if c[i] is not None]
            out.append(sep.join(parts))
        return pa.array(out, pa.string())

    vals = cpu_eval(e.child, table).to_pylist()

    def mapped(fn):
        return pa.array([None if v is None else fn(v) for v in vals],
                        pa.string())

    if isinstance(e, S.RegExpReplace):
        pat, rep = e.search.value, e.replacement.value or ""
        return mapped(lambda s: re.sub(pat, rep, s))
    if isinstance(e, S.StringReplace):
        search, rep = e.search.value or "", e.replacement.value or ""
        if not search:
            return mapped(lambda s: s)
        return mapped(lambda s: s.replace(search, rep))
    if isinstance(e, S.StringLPad):
        tgt = int(e.length.value)
        p = e.pad.value or ""
        left = e._left

        def padfn(s):
            if tgt <= 0:
                return ""
            if len(s) >= tgt:
                return s[:tgt]
            if not p:
                return s
            fill = (p * tgt)[: tgt - len(s)]
            return fill + s if left else s + fill

        return mapped(padfn)
    if isinstance(e, S.StringLocate):
        sub = e.substr.value or ""
        start = int(e.start.value)

        def locfn(s):
            if start <= 0:
                return 0
            if sub == "":
                return min(start, len(s) + 1)
            return s.find(sub, start - 1) + 1

        return pa.array([None if v is None else locfn(v) for v in vals],
                        pa.int32())
    if isinstance(e, S.SubstringIndex):
        d = e.delim.value or ""
        cnt = int(e.count.value)

        def sifn(s):
            if cnt == 0 or not d:
                return ""
            pos, hits = 0, []
            while True:
                j = s.find(d, pos)
                if j < 0:
                    break
                hits.append(j)
                pos = j + len(d)
            if cnt > 0:
                return s if len(hits) < cnt else s[: hits[cnt - 1]]
            k = len(hits) + cnt
            return s if k < 0 else s[hits[k] + len(d):]

        return mapped(sifn)
    if isinstance(e, S.InitCap):
        def icfn(s):
            out, prev = [], " "
            for ch in s:
                out.append(ch.upper() if prev == " " else ch.lower())
                prev = ch
            return "".join(out)

        return mapped(icfn)
    raise AssertionError(type(e))


def _dispatch(e, table, n):  # noqa: C901 - a dispatcher is a big switch
    from spark_rapids_tpu.exprs import collections as COLL

    if isinstance(e, B.Alias):
        return cpu_eval(e.child, table)
    from spark_rapids_tpu.udf.exprs import JaxScalarUDF, OpaquePythonUDF

    if isinstance(e, OpaquePythonUDF):
        # row-wise in-process evaluation (the python-worker analog);
        # NULLs pass through to the function, as Spark's python UDFs do
        cols = [cpu_eval(a, table).to_pylist() for a in e.args]
        out = [e.fn(*vals) for vals in zip(*cols)] if cols \
            else [e.fn() for _ in range(n)]
        return pa.array(out, T.to_arrow_type(e.dtype))
    if isinstance(e, JaxScalarUDF):
        # mirror the device eval: fn over data arrays (NULL slots hold
        # fill values), result NULL iff any input NULL
        arrs = [cpu_eval(a, table) for a in e.args]
        datas, valid = [], np.ones(n, bool)
        for a, ax in zip(e.args, arrs):
            atype = T.to_arrow_type(a.dtype)
            v, ok = _np_vals(ax, atype)
            datas.append(v)
            valid &= ok
        res = np.asarray(e.fn(*datas))
        if res.shape != (n,):
            raise ValueError(
                f"jax UDF {e.fn_name!r} returned shape {res.shape}, "
                f"expected ({n},)")
        return _from_np(res.astype(T.to_numpy_dtype(e.dtype)), valid,
                       T.to_arrow_type(e.dtype))
    if isinstance(e, COLL.Size):
        c = cpu_eval(e.child, table)
        return pc.list_value_length(c).cast(pa.int32())
    if isinstance(e, COLL.GetArrayItem):
        c = cpu_eval(e.child, table)
        k = int(e.index.value)
        out = [None if (v is None or k < 0 or k >= len(v)) else v[k]
               for v in c.to_pylist()]
        return pa.array(out, T.to_arrow_type(e.dtype))
    from spark_rapids_tpu.exprs import complex as CX

    if isinstance(e, CX.GetStructField):
        c = cpu_eval(e.child, table)
        dt = e.child.dtype
        idx = dt.field_index(e.field_name)
        field = pc.struct_field(c, [idx])
        if c.null_count:
            # null parent rows must surface as null fields
            field = pc.if_else(pc.is_valid(c), field,
                               pa.scalar(None, field.type))
        return field
    if isinstance(e, CX.CreateNamedStruct):
        kids = [cpu_eval(v, table) for v in e.values]
        return pc.make_struct(*kids, field_names=list(e.names))
    if isinstance(e, (CX.GetMapValue, CX.ElementAt)) and isinstance(
            e.child.dtype, T.MapType):
        c = cpu_eval(e.child, table)
        key = e.key.value if isinstance(e, CX.GetMapValue) \
            else e.index.value
        out = []
        for row in c.to_pylist():
            if row is None:
                out.append(None)
            else:
                d = dict(row) if not isinstance(row, dict) else row
                out.append(d.get(key))
        return pa.array(out, T.to_arrow_type(e.dtype))
    if isinstance(e, CX.ElementAt):
        c = cpu_eval(e.child, table)
        k = int(e.index.value)
        if k == 0:
            # Spark contract: index 0 is an error in EVERY mode
            raise ValueError("SQL array indices start at 1")
        out = []
        for row in c.to_pylist():
            if row is None:
                out.append(None)
            else:
                pos = k - 1 if k > 0 else len(row) + k
                out.append(row[pos] if 0 <= pos < len(row) else None)
        return pa.array(out, T.to_arrow_type(e.dtype))
    if isinstance(e, COLL.ArrayContains):
        c = cpu_eval(e.child, table)
        v = e.value.value
        out = []
        for row in c.to_pylist():
            if row is None:
                out.append(None)
            elif v in row:
                out.append(True)
            elif None in row:
                out.append(None)
            else:
                out.append(False)
        return pa.array(out, pa.bool_())
    from spark_rapids_tpu.exprs import nondeterministic as ND

    if isinstance(e, ND.InputFileName):
        # no file context on this path: Spark's documented defaults
        return pa.array([e.DEFAULT] * n, T.to_arrow_type(e.dtype))
    if isinstance(e, B.BoundReference):
        return _plain(table.column(e.ordinal).combine_chunks())
    if isinstance(e, B.ColumnReference):
        return _plain(table.column(e.col_name).combine_chunks())
    if isinstance(e, B.Literal):
        if e.value is None:
            return pa.nulls(n, type=T.to_arrow_type(e.dtype)
                            if not isinstance(e.dtype, T.NullType)
                            else pa.bool_())
        return pa.array([e.value] * n, type=T.to_arrow_type(e.dtype))

    # arithmetic --------------------------------------------------------- #
    if isinstance(e, (A.Add, A.Subtract, A.Multiply)):
        l, r = _binary_operands(e, table, n)
        at = _widen_type(e)
        if isinstance(e.dtype, T.DecimalType):
            # exact python-Decimal reference for decimal arithmetic
            # (arrow's own promotion rules differ from Spark's).  The
            # declared type is capped at this engine's MAX_PRECISION;
            # exact results that cannot fit become NULL — the
            # nullOnOverflow contract for precision the engine cannot
            # represent (Spark with p<=38 would hold them; documented
            # 18-digit divergence)
            import decimal as _dec
            import operator as _op

            dt = e.dtype
            q = _dec.Decimal(1).scaleb(-dt.scale)
            bound = _dec.Decimal(10) ** (dt.precision - dt.scale)
            lv, rv = l.to_pylist(), r.to_pylist()
            op = {A.Add: _op.add, A.Subtract: _op.sub,
                  A.Multiply: _op.mul}[type(e)]
            out = []
            # wide context: the default 28-digit context would RAISE
            # (or double-round) on products wider than 28 digits —
            # exactly the values the overflow contract must NULL
            with _dec.localcontext() as ctx:
                ctx.prec = 76
                for a, b in zip(lv, rv):
                    if a is None or b is None:
                        out.append(None)
                        continue
                    v = op(a, b).quantize(q,
                                          rounding=_dec.ROUND_HALF_UP)
                    out.append(None if abs(v) >= bound else v)
            return pa.array(out, at)
        from spark_rapids_tpu.exprs.base import AnsiError, ansi_enabled

        if ansi_enabled() and pa.types.is_integer(at):
            fn = {A.Add: pc.add_checked, A.Subtract: pc.subtract_checked,
                  A.Multiply: pc.multiply_checked}[type(e)]
            try:
                return fn(l.cast(at), r.cast(at))
            except pa.ArrowInvalid as exc:
                msg = "long overflow" if pa.types.is_int64(at) \
                    else "integer overflow"
                raise AnsiError(
                    msg + ". If necessary set "
                    "spark.rapids.tpu.sql.ansi.enabled to false to "
                    "bypass this error.") from exc
        fn = {A.Add: pc.add, A.Subtract: pc.subtract,
              A.Multiply: pc.multiply}[type(e)]
        return fn(l.cast(at), r.cast(at))
    if isinstance(e, A.Divide):
        l, r = _binary_operands(e, table, n)
        l = l.cast(pa.float64())
        r = r.cast(pa.float64())
        zero = pc.equal(r, 0.0)
        # both-valid gating matches the device check (a NULL operand
        # row never raises; Spark's right-only gating differs on the
        # (NULL, 0) corner — documented engine behavior)
        _cpu_ansi_div_check(l, pc.and_kleene(
            pc.fill_null(zero, False), pc.is_valid(l)))
        safe = pc.if_else(pc.fill_null(zero, False), pa.scalar(1.0), r)
        out = pc.divide(l, safe)
        return pc.if_else(pc.fill_null(zero, True), pa.nulls(
            n, pa.float64()), out)
    if isinstance(e, (A.IntegralDivide, A.Remainder, A.Pmod)):
        l, r = _binary_operands(e, table, n)
        at = _widen_type(e)
        npdt = at.to_pandas_dtype()
        lv, lva = _np_vals(l, at)
        rv, rva = _np_vals(r, at)
        valid = lva & rva
        _cpu_ansi_div_check(None, pa.array((rv == 0) & valid))
        if np.issubdtype(npdt, np.floating):
            zero = rv == 0.0
            rv = np.where(zero, 1.0, rv)
            rem = np.fmod(lv, rv)
            if isinstance(e, A.Pmod):
                rem = np.where(rem < 0, np.fmod(rem + rv, rv), rem)
            out = rem
        else:
            zero = rv == 0
            rv = np.where(zero, 1, rv)
            q = np.where((lv < 0) != (rv < 0),
                         -(np.abs(lv) // np.abs(rv)), lv // rv)
            rem = lv - q * rv
            if isinstance(e, A.IntegralDivide):
                out = q
            elif isinstance(e, A.Pmod):
                out = np.where(rem < 0, (rem + rv) % rv if False else
                               _np_java_mod(rem + rv, rv), rem)
            else:
                out = rem
        return _from_np(out.astype(npdt), valid & ~zero, at)
    if isinstance(e, A.UnaryMinus):
        return pc.negate(cpu_eval(e.child, table))
    if isinstance(e, A.UnaryPositive):
        return cpu_eval(e.child, table)
    if isinstance(e, A.Abs):
        return pc.abs(cpu_eval(e.child, table))
    if isinstance(e, (A.Least, A.Greatest)):
        return _least_greatest(e, table, n)

    # predicates --------------------------------------------------------- #
    if isinstance(e, P.BinaryComparison):
        l, r = _binary_operands(e, table, n)
        # the engine's physical view lets dates compare against their
        # day counts (int literals); pyarrow has no date-vs-int kernel
        for a, b in ((l, r), (r, l)):
            if pa.types.is_date32(a.type) and pa.types.is_integer(b.type):
                if a is l:
                    l = a.cast(pa.int32()).cast(b.type)
                else:
                    r = a.cast(pa.int32()).cast(b.type)
        if isinstance(e, P.EqualNullSafe):
            ln, rn = pc.is_null(l), pc.is_null(r)
            eq = pc.fill_null(pc.equal(l, r), False)
            both_null = pc.and_(ln, rn)
            one_null = pc.xor(ln, rn)
            return pc.if_else(one_null, pa.scalar(False),
                              pc.or_(both_null, eq))
        fn = {P.EqualTo: pc.equal, P.LessThan: pc.less,
              P.LessThanOrEqual: pc.less_equal, P.GreaterThan: pc.greater,
              P.GreaterThanOrEqual: pc.greater_equal}[type(e)]
        out = fn(l, r)
        # Spark NaN comparison semantics (docs/compatibility.md: NaN is
        # larger than any other value and NaN = NaN) — raw IEEE from
        # pyarrow says the opposite for every NaN operand
        if pa.types.is_floating(l.type) or pa.types.is_floating(r.type):
            fl = l.cast(pa.float64()) if not pa.types.is_floating(l.type) \
                else l
            fr = r.cast(pa.float64()) if not pa.types.is_floating(r.type) \
                else r
            lnan = pc.fill_null(pc.is_nan(fl), False)
            rnan = pc.fill_null(pc.is_nan(fr), False)
            either = pc.or_(lnan, rnan)
            if pc.any(either).as_py():
                nan_lt = pc.and_(pc.invert(lnan), rnan)   # l < r
                nan_eq = pc.and_(lnan, rnan)              # l == r
                repl = {
                    P.EqualTo: nan_eq,
                    P.LessThan: nan_lt,
                    P.LessThanOrEqual: pc.or_(nan_lt, nan_eq),
                    P.GreaterThan: pc.and_(lnan, pc.invert(rnan)),
                    P.GreaterThanOrEqual: pc.or_(
                        pc.and_(lnan, pc.invert(rnan)), nan_eq),
                }[type(e)]
                valid = pc.and_(pc.is_valid(l), pc.is_valid(r))
                out = pc.if_else(pc.and_(either, valid), repl, out)
        return out
    if isinstance(e, P.And):
        l, r = _binary_operands(e, table, n)
        return pc.and_kleene(l, r)
    if isinstance(e, P.Or):
        l, r = _binary_operands(e, table, n)
        return pc.or_kleene(l, r)
    if isinstance(e, P.Not):
        return pc.invert(cpu_eval(e.child, table))
    if isinstance(e, P.IsNull):
        return pc.is_null(cpu_eval(e.child, table))
    if isinstance(e, P.IsNotNull):
        return pc.is_valid(cpu_eval(e.child, table))
    if isinstance(e, P.IsNaN):
        c = cpu_eval(e.child, table)
        return pc.fill_null(pc.is_nan(c), False)
    if isinstance(e, P.In):
        c = cpu_eval(e.child, table)
        has_null = any(v is None for v in e.values)
        vals = [v for v in e.values if v is not None]
        match = pc.is_in(c, value_set=pa.array(vals, type=c.type))
        if has_null:
            # no match + NULL in list -> NULL
            match = pc.if_else(match, pa.scalar(True),
                               pa.nulls(n, pa.bool_()))
        return pc.if_else(pc.is_valid(c), match, pa.nulls(n, pa.bool_()))
    if isinstance(e, P.Coalesce):
        arrs = [cpu_eval(x, table) for x in e.exprs]
        at = _widen_type(e)
        return pc.coalesce(*[a.cast(at) for a in arrs])
    if isinstance(e, P.If):
        p = pc.fill_null(cpu_eval(e.pred, table), False)
        at = _widen_type(e)
        return pc.if_else(p, cpu_eval(e.then, table).cast(at),
                          cpu_eval(e.otherwise, table).cast(at))
    if isinstance(e, P.CaseWhen):
        at = _widen_type(e)
        out = cpu_eval(e.else_value, table).cast(at)
        for cond, val in reversed(e.branches):
            p = pc.fill_null(cpu_eval(cond, table), False)
            out = pc.if_else(p, cpu_eval(val, table).cast(at), out)
        return out
    from spark_rapids_tpu.exprs.subquery import ScalarSubquery

    if isinstance(e, ScalarSubquery):
        sub = execute_cpu(e.plan)
        if sub.num_rows != 1 or sub.num_columns != 1:
            raise ValueError(
                f"scalar subquery must return 1x1, got "
                f"{sub.num_rows}x{sub.num_columns}")
        v = sub.column(0)[0].as_py()
        return pa.array([v] * n, T.to_arrow_type(e.dtype))
    if isinstance(e, COLL.CreateArray):
        arrs = [cpu_eval(x, table) for x in e.exprs]
        et = T.to_arrow_type(e.dtype.element)
        rows = list(zip(*[a.cast(et).to_pylist() for a in arrs]))
        return pa.array([list(r) for r in rows], pa.list_(et))
    if isinstance(e, (DT.FromUnixTime, DT.DateFormatClass)):
        import datetime as _dt

        c = cpu_eval(e.child, table)
        py_fmt = e.fmt.replace("yyyy", "%Y").replace(
            "MM", "%m").replace("dd", "%d").replace(
            "HH", "%H").replace("mm", "%M").replace("ss", "%S")
        out = []
        for v in c.to_pylist():
            if v is None:
                out.append(None)
                continue
            if isinstance(e, DT.FromUnixTime):
                t = _dt.datetime.fromtimestamp(int(v), _dt.timezone.utc)
            elif isinstance(v, _dt.datetime):
                t = v
            elif isinstance(v, _dt.date):
                t = _dt.datetime(v.year, v.month, v.day,
                                 tzinfo=_dt.timezone.utc)
            else:
                t = _dt.datetime.fromtimestamp(int(v) / 1e6,
                                               _dt.timezone.utc)
            out.append(t.strftime(py_fmt))
        return pa.array(out, pa.string())
    from spark_rapids_tpu.exprs import nondeterministic as ND

    if isinstance(e, ND.SparkPartitionID):
        # the CPU engine is a single partition
        return pa.array(np.zeros(n, np.int32))
    if isinstance(e, ND.MonotonicallyIncreasingID):
        return pa.array(np.arange(n, dtype=np.int64))
    if isinstance(e, ND.Rand):
        import jax

        from spark_rapids_tpu.exprs.nondeterministic import _rand_uniform

        with jax.default_device(jax.devices("cpu")[0]):
            vals = np.asarray(_rand_uniform(
                e.seed, 0, np.arange(n, dtype=np.int64)))
        return pa.array(vals)
    if isinstance(e, M.NaNvl):
        at = T.to_arrow_type(e.dtype)
        a = cpu_eval(e.left, table).cast(at)
        b = cpu_eval(e.right, table).cast(at)
        take_b = pc.fill_null(pc.is_nan(a), False)
        return pc.if_else(take_b, b, a)
    if isinstance(e, M.NormalizeNaNAndZero):
        a = cpu_eval(e.child, table)
        v, ok = _np_vals(a, a.type)
        v = np.where(np.isnan(v), np.nan, v) + 0.0
        return _from_np(v, ok, a.type)
    if isinstance(e, M.KnownFloatingPointNormalized):
        return cpu_eval(e.child, table)
    if isinstance(e, P.AtLeastNNonNulls):
        count = np.zeros(n, np.int32)
        for x in e.exprs:
            a = cpu_eval(x, table)
            ok = np.asarray(a.is_valid())
            if pa.types.is_floating(a.type):
                ok = ok & ~np.asarray(
                    pc.fill_null(pc.is_nan(a), False))
            count += ok.astype(np.int32)
        return pa.array(count >= e.n)

    if isinstance(e, Murmur3Hash):
        return _murmur3_cpu(e, table, n)
    from spark_rapids_tpu.exprs.decimal import CheckOverflow, PromotePrecision

    if isinstance(e, PromotePrecision):
        import decimal as _dec

        vals = cpu_eval(e.child, table).to_pylist()
        q = _dec.Decimal(1).scaleb(-e.target.scale)
        return pa.array(
            [None if v is None else v.quantize(q) for v in vals],
            pa.decimal128(e.target.precision, e.target.scale))
    if isinstance(e, CheckOverflow):
        import decimal as _dec

        vals = cpu_eval(e.child, table).to_pylist()
        q = _dec.Decimal(1).scaleb(-e.target.scale)
        bound = _dec.Decimal(10) ** (e.target.precision - e.target.scale)
        out = []
        with _dec.localcontext() as ctx:
            ctx.prec = 76  # wide children must NULL, not raise
            for v in vals:
                if v is None:
                    out.append(None)
                    continue
                r = v.quantize(q, rounding=_dec.ROUND_HALF_UP)
                out.append(None if abs(r) >= bound else r)
        return pa.array(out, pa.decimal128(e.target.precision,
                                           e.target.scale))
    if isinstance(e, Md5):
        import hashlib

        vals = cpu_eval(e.child, table).to_pylist()
        return pa.array(
            [None if v is None
             else hashlib.md5(str(v).encode()).hexdigest()
             for v in vals], pa.string())

    out = _dispatch_extended(e, table, n)
    if out is NotImplemented:
        raise NotImplementedError(
            f"CPU engine: unsupported expression {type(e).__name__}")
    return out


def _dispatch_extended(e, table, n):  # noqa: C901
    # math ---------------------------------------------------------------- #
    if type(e) in _PC_UNARY:
        c = cpu_eval(e.child, table).cast(pa.float64())
        return pc.cast(_PC_UNARY[type(e)](c), pa.float64())
    if type(e) in _NP_UNARY:
        c = cpu_eval(e.child, table).cast(pa.float64())
        v, ok = _np_vals(c, pa.float64())
        with np.errstate(all="ignore"):
            return _from_np(_NP_UNARY[type(e)](v), ok, pa.float64())
    if isinstance(e, M._LogBase):
        c = cpu_eval(e.child, table).cast(pa.float64())
        v, ok = _np_vals(c, pa.float64())
        bad = v <= (-1.0 if isinstance(e, M.Log1p) else 0.0)
        fn = {M.Log: np.log, M.Log10: np.log10, M.Log2: np.log2,
              M.Log1p: np.log1p}[type(e)]
        with np.errstate(all="ignore"):
            return _from_np(fn(np.where(bad, 1.0, v)), ok & ~bad,
                            pa.float64())
    if isinstance(e, M.Logarithm):
        b = cpu_eval(e.base, table).cast(pa.float64())
        c = cpu_eval(e.child, table).cast(pa.float64())
        bv, bok = _np_vals(b, pa.float64())
        cv, cok = _np_vals(c, pa.float64())
        bad = (cv <= 0) | (bv <= 0)
        with np.errstate(all="ignore"):
            out = np.log(np.where(cv <= 0, 1.0, cv)) / \
                np.log(np.where(bv <= 0, 2.0, bv))
        return _from_np(out, bok & cok & ~bad, pa.float64())
    if isinstance(e, M.Pow):
        l = cpu_eval(e.left, table).cast(pa.float64())
        r = cpu_eval(e.right, table).cast(pa.float64())
        return pc.power(l, r)
    if isinstance(e, M.Ceil):  # Floor subclasses Ceil
        c = cpu_eval(e.child, table)
        if not pa.types.is_floating(c.type):
            return c
        # Spark: ceil/floor(double) -> LONG via the Java (long) cast:
        # NaN -> 0, +/-inf and out-of-range saturate at Long.MIN/MAX
        v, ok = _np_vals(c.cast(pa.float64()), pa.float64())
        r = np.floor(v) if isinstance(e, M.Floor) else np.ceil(v)
        r = np.where(np.isnan(r), 0.0, r)
        i64 = np.iinfo(np.int64)
        hi_f, lo_f = float(i64.max) + 1.0, float(i64.min)
        out = np.where((r > lo_f) & (r < hi_f), r, 0.0).astype(np.int64)
        out = np.where(r >= hi_f, i64.max, out)
        out = np.where(r <= lo_f, i64.min, out)
        return _from_np(out, ok, pa.int64())
    if isinstance(e, M.Round):  # BRound subclasses Round
        c = cpu_eval(e.child, table)
        # Spark HALF_UP rounds half away from zero
        mode = "half_to_even" if e.half_even else "half_towards_infinity"
        if pa.types.is_floating(c.type):
            return pc.round(c, ndigits=e.scale, round_mode=mode).cast(
                c.type)
        if e.scale >= 0:
            return c
        return pc.round(c, ndigits=e.scale, round_mode=mode).cast(c.type)

    # bitwise ------------------------------------------------------------- #
    if isinstance(e, BW.BitwiseBinary):
        l, r = cpu_eval(e.left, table), cpu_eval(e.right, table)
        at = T.to_arrow_type(e.dtype)
        fn = {BW.BitwiseAnd: pc.bit_wise_and, BW.BitwiseOr: pc.bit_wise_or,
              BW.BitwiseXor: pc.bit_wise_xor}[type(e)]
        return fn(l.cast(at), r.cast(at))
    if isinstance(e, BW.BitwiseNot):
        return pc.bit_wise_not(cpu_eval(e.child, table))
    if isinstance(e, BW.ShiftLeft):  # covers Right/RightUnsigned
        l = cpu_eval(e.left, table)
        r = cpu_eval(e.right, table)
        bits = 64 if pa.types.is_int64(l.type) else 32
        npdt = np.int64 if bits == 64 else np.int32
        lv, lok = _np_vals(l, l.type)
        rv, rok = _np_vals(r.cast(pa.int32()), pa.int32())
        amount = rv.astype(npdt) & (bits - 1)
        lv = lv.astype(npdt)
        if isinstance(e, BW.ShiftRightUnsigned):
            u = np.uint64 if bits == 64 else np.uint32
            out = (lv.view(u) >> amount.astype(u)).view(npdt)
        elif isinstance(e, BW.ShiftRight):
            out = lv >> amount
        else:
            with np.errstate(over="ignore"):
                out = lv << amount
        return _from_np(out, lok & rok, l.type)

    # datetime ------------------------------------------------------------ #
    if isinstance(e, DT._DateField):
        c = cpu_eval(e.child, table)
        fns = {DT.Year: pc.year, DT.Month: pc.month,
               DT.DayOfMonth: pc.day, DT.Quarter: pc.quarter,
               DT.DayOfYear: pc.day_of_year}
        if type(e) in fns:
            return fns[type(e)](c).cast(pa.int32())
        if isinstance(e, DT.DayOfWeek):
            # Spark: Sunday=1..Saturday=7
            return pc.add(pc.day_of_week(c, count_from_zero=True,
                                         week_start=7), 1).cast(pa.int32())
        if isinstance(e, DT.WeekDay):
            return pc.day_of_week(c, count_from_zero=True,
                                  week_start=1).cast(pa.int32())
        return NotImplemented
    if isinstance(e, DT.LastDay):
        c = cpu_eval(e.child, table)
        v, ok = _np_vals(c.cast(pa.int32()), pa.int32())
        d = v.astype("datetime64[D]")
        m = d.astype("datetime64[M]")
        last = (m + 1).astype("datetime64[D]") - 1
        return _from_np(last.astype(np.int32), ok,
                        pa.int32()).cast(pa.date32())
    if isinstance(e, DT.TimeAdd):  # TimeSub subclasses TimeAdd
        c = cpu_eval(e.child, table)
        v, ok = _np_vals(c.cast(pa.int64()), pa.int64())
        if e.interval.months:
            # calendar month arithmetic (day-of-month clamped to the
            # target month's end, Spark's add_months rule) — the case
            # the device path rejects and this fallback exists for
            out = np.array([_add_interval_us(
                int(x), e.interval.months * e._sign,
                e.interval.days * e._sign,
                e.interval.microseconds * e._sign) for x in v],
                np.int64)
            return _from_np(out, ok, pa.int64()).cast(
                T.to_arrow_type(T.TIMESTAMP))
        delta = (e.interval.days * 86_400_000_000
                 + e.interval.microseconds) * e._sign
        return _from_np(v + delta, ok, pa.int64()).cast(
            T.to_arrow_type(T.TIMESTAMP))
    if isinstance(e, DT.DateAddInterval):
        c = cpu_eval(e.child, table)
        v, ok = _np_vals(c.cast(pa.int32()), pa.int32())
        if e.interval.months:
            us_day = 86_400_000_000
            out = np.array([
                _add_interval_us(int(x) * us_day, e.interval.months,
                                 e.interval.days,
                                 e.interval.microseconds) // us_day
                for x in v], np.int32)
            return _from_np(out, ok, pa.int32()).cast(pa.date32())
        days = e.interval.days + int(
            e.interval.microseconds / 86_400_000_000)
        return _from_np((v + days).astype(np.int32), ok,
                        pa.int32()).cast(pa.date32())
    if isinstance(e, DEC.UnscaledValue):
        import decimal as _dec

        c = cpu_eval(e.child, table)
        scale = e.child.dtype.scale
        out = [None if v is None else int(v.scaleb(scale))
               for v in c.to_pylist()]
        return pa.array(out, pa.int64())
    if isinstance(e, DEC.MakeDecimal):
        import decimal as _dec

        c = cpu_eval(e.child, table)
        bound = 10 ** e.precision
        out = [None if (v is None or not (-bound < v < bound))
               else _dec.Decimal(int(v)).scaleb(-e.scale)
               for v in c.cast(pa.int64()).to_pylist()]
        return pa.array(out, T.to_arrow_type(e.dtype))
    if isinstance(e, DT.AddMonths):
        import calendar as _cal
        import datetime as _pydt

        c = cpu_eval(e.child, table)
        v, ok = _np_vals(c.cast(pa.int32()), pa.int32())
        epoch = _pydt.date(1970, 1, 1)

        def _shift(x: int) -> int:
            d = epoch + _pydt.timedelta(days=int(x))
            mi = d.year * 12 + (d.month - 1) + e.months
            y, m = divmod(mi, 12)
            day = min(d.day, _cal.monthrange(y, m + 1)[1])
            return (_pydt.date(y, m + 1, day) - epoch).days

        out = np.array([_shift(x) for x in v], np.int32)
        return _from_np(out, ok, pa.int32()).cast(pa.date32())
    if isinstance(e, (DT.DateAdd, DT.DateSub)):
        l = cpu_eval(e.left, table).cast(pa.int32())
        r = cpu_eval(e.right, table).cast(pa.int32())
        sign = -1 if isinstance(e, DT.DateSub) else 1
        out = pc.add(l, pc.multiply(r, sign))
        return out.cast(pa.int32()).view(pa.date32())
    if isinstance(e, DT.DateDiff):
        l = cpu_eval(e.left, table).cast(pa.int32())
        r = cpu_eval(e.right, table).cast(pa.int32())
        return pc.subtract(l, r)
    if isinstance(e, DT._TimeField):
        c = cpu_eval(e.child, table)
        fn = {DT.Hour: pc.hour, DT.Minute: pc.minute,
              DT.Second: pc.second}[type(e)]
        return fn(c).cast(pa.int32())
    if isinstance(e, DT.UnixTimestampFromTs):
        c = cpu_eval(e.child, table).cast(pa.int64())
        v, ok = _np_vals(c, pa.int64())
        return _from_np(v // 1_000_000, ok, pa.int64())

    # cast ---------------------------------------------------------------- #
    if isinstance(e, Cast):
        return _cast_cpu(e, table, n)

    # strings -------------------------------------------------------------- #
    if isinstance(e, (S.StringReplace, S.RegExpReplace, S.StringLPad,
                      S.StringLocate, S.SubstringIndex, S.InitCap,
                      S.ConcatWs)):
        return _string_batch3(e, table, n)
    if isinstance(e, S.Length):
        return pc.utf8_length(cpu_eval(e.child, table)).cast(pa.int32())
    if isinstance(e, S.Upper):  # Lower subclasses Upper
        c = cpu_eval(e.child, table)
        return pc.utf8_lower(c) if isinstance(e, S.Lower) else \
            pc.utf8_upper(c)
    if isinstance(e, S.StartsWith):  # EndsWith/Contains subclass it
        c = cpu_eval(e.left, table)
        needle = e.right.value or ""
        fn = {S.StartsWith: pc.starts_with, S.EndsWith: pc.ends_with,
              S.Contains: pc.match_substring}[type(e)]
        out = fn(c, pattern=needle)
        rnull = e.right.value is None
        if rnull:
            return pa.nulls(n, pa.bool_())
        return out
    if isinstance(e, S.Like):
        c = cpu_eval(e.left, table)
        return pc.match_like(c, pattern=e.pattern)
    if isinstance(e, S.Substring):
        c = cpu_eval(e.child, table)
        if e.pos > 0:
            start = e.pos - 1
            stop = None if e.length is None else start + max(e.length, 0)
            return pc.utf8_slice_codeunits(c, start=start, stop=stop)
        if e.pos == 0:
            stop = None if e.length is None else max(e.length, 0)
            return pc.utf8_slice_codeunits(c, start=0, stop=stop)
        # negative pos: python oracle path.  Spark counts the length
        # window from the UNCLAMPED start (substring('abc',-5,3)=='a')
        out = []
        for v in c.to_pylist():
            if v is None:
                out.append(None)
                continue
            start = len(v) + e.pos
            end = len(v) if e.length is None else start + max(e.length, 0)
            out.append(v[max(start, 0):max(end, 0)])
        return pa.array(out, pa.string())
    if isinstance(e, S.GetJsonObject):
        import json as _json

        c = cpu_eval(e.child, table)
        path = e.path.value
        if any(tok in path for tok in ("*", "..")):
            raise NotImplementedError(
                f"get_json_object path {path!r}: wildcard/recursive "
                "descent is not implemented (simple $.a.b[i] paths "
                "only) — refusing rather than returning wrong NULLs")
        steps = S.GetJsonObject.parse_path(path)
        out = []
        for v in c.to_pylist():
            if v is None or steps is None:
                out.append(None)
                continue
            try:
                cur = _json.loads(v)
                for st in steps:
                    if isinstance(st, int):
                        cur = cur[st] if isinstance(cur, list) \
                            and 0 <= st < len(cur) else None
                    else:
                        cur = cur.get(st) if isinstance(cur, dict) \
                            else None
                    if cur is None:
                        break
                if cur is None:
                    out.append(None)
                elif isinstance(cur, str):
                    out.append(cur)  # Spark strips quotes on scalars
                elif isinstance(cur, bool):
                    out.append("true" if cur else "false")
                else:
                    out.append(_json.dumps(
                        cur, separators=(",", ":"),
                        ensure_ascii=False))
            except (ValueError, TypeError):
                out.append(None)
        return pa.array(out, pa.string())
    if isinstance(e, S.SplitPart):
        import re as _re

        c = cpu_eval(e.child, table)
        d = e.delim.value
        out = [None if v is None else
               (lambda parts: parts[e.index]
                if 0 <= e.index < len(parts) else None)(
                   _java_split(_re.escape(d), v, -1))
               for v in c.to_pylist()]
        return pa.array(out, pa.string())
    if isinstance(e, S.StringSplit):
        c = cpu_eval(e.child, table)
        d = e.delim.value
        if d is None:
            return pa.nulls(n, pa.list_(pa.string()))
        out = [None if v is None else _java_split(d, v, e.limit)
               for v in c.to_pylist()]
        return pa.array(out, pa.list_(pa.string()))
    if isinstance(e, S.StringTrim):
        c = cpu_eval(e.child, table)
        if isinstance(e, S.StringTrimLeft):
            return pc.utf8_ltrim(c, characters=" ")
        if isinstance(e, S.StringTrimRight):
            return pc.utf8_rtrim(c, characters=" ")
        return pc.utf8_trim(c, characters=" ")
    if isinstance(e, S.Concat):
        arrs = [cpu_eval(x, table) for x in e.exprs]
        return pc.binary_join_element_wise(
            *arrs, "", null_handling="emit_null")

    return NotImplemented


def _cpu_ansi_div_check(_l, zero_mask) -> None:
    """Raise the ANSI division-by-zero error when the conf is on."""
    from spark_rapids_tpu.exprs.base import AnsiError, ansi_enabled

    if not ansi_enabled():
        return
    z = zero_mask
    any_zero = bool(pc.any(pc.fill_null(z, False)).as_py()) \
        if isinstance(z, (pa.Array, pa.ChunkedArray)) \
        else bool(np.asarray(z).any())
    if any_zero:
        raise AnsiError(
            "Division by zero. If necessary set "
            "spark.rapids.tpu.sql.ansi.enabled to false to bypass "
            "this error.")


def _cast_cpu(e, table, n):
    from spark_rapids_tpu.exprs.base import AnsiError, ansi_enabled
    from spark_rapids_tpu.exprs.cast import Cast  # noqa: F401

    src = e.child.dtype
    dst = e.to
    c = cpu_eval(e.child, table)
    if src == dst:
        return c
    at = T.to_arrow_type(dst)
    ansi = ansi_enabled()
    if isinstance(src, T.StringType):
        out = _cast_cpu_from_string(c, dst, at)
        if ansi and out.null_count > c.null_count:
            raise AnsiError(
                f"invalid input syntax for type {dst.name} (ANSI "
                "cast). If necessary set "
                "spark.rapids.tpu.sql.ansi.enabled to false to "
                "bypass this error.")
        return out
    if ansi and isinstance(dst, T.IntegralType):
        info = np.iinfo(T.to_numpy_dtype(dst))
        bad = None
        if isinstance(src, (T.FloatType, T.DoubleType)):
            v, ok = _np_vals(c.cast(pa.float64()), pa.float64())
            t = np.trunc(v)
            bad = ok & (np.isnan(v) | (t > float(info.max))
                        | (t < float(info.min)))
        elif isinstance(src, T.IntegralType):
            # integer-space compare: a float64 round-trip would lose
            # precision past 2^53 (and pyarrow's safe cast would raise
            # its own non-ANSI error first)
            v, ok = _np_vals(c, T.to_arrow_type(src))
            bad = ok & ((v > info.max) | (v < info.min))
        if bad is not None and bad.any():
            raise AnsiError(
                f"value out of range for {dst.name} (ANSI cast "
                "overflow). If necessary set "
                "spark.rapids.tpu.sql.ansi.enabled to false to "
                "bypass this error.")
    if isinstance(dst, T.StringType):
        return pc.cast(c, pa.string())
    if isinstance(dst, T.BooleanType):
        return pc.not_equal(c, pa.scalar(0).cast(c.type))
    if isinstance(src, T.BooleanType):
        return pc.cast(c, at)
    if isinstance(src, T.DateType) and isinstance(dst, T.TimestampType):
        v, ok = _np_vals(c.cast(pa.int32()), pa.int32())
        return _from_np(v.astype(np.int64) * 86_400_000_000, ok,
                        pa.int64()).cast(at)
    if isinstance(src, T.TimestampType) and isinstance(dst, T.DateType):
        v, ok = _np_vals(c.cast(pa.int64()), pa.int64())
        return _from_np((v // 86_400_000_000).astype(np.int32), ok,
                        pa.int32()).cast(at)
    if isinstance(src, T.TimestampType) and isinstance(dst, T.LongType):
        v, ok = _np_vals(c.cast(pa.int64()), pa.int64())
        return _from_np(v // 1_000_000, ok, pa.int64())
    if isinstance(src, T.LongType) and isinstance(dst, T.TimestampType):
        v, ok = _np_vals(c, pa.int64())
        return _from_np(v * 1_000_000, ok, pa.int64()).cast(at)
    npdt = T.to_numpy_dtype(dst)
    if isinstance(src, (T.FloatType, T.DoubleType)) and \
            isinstance(dst, T.IntegralType):
        v, ok = _np_vals(c.cast(pa.float64()), pa.float64())
        info = np.iinfo(npdt)
        # float64 cannot represent int64 MAX exactly: saturate by
        # threshold compare, never by clip-then-cast (which overflows)
        hi_f = float(info.max) + 1.0  # exact power of two
        lo_f = float(info.min)
        t = np.trunc(np.where(np.isnan(v), 0.0, v))
        interior = (t > lo_f) & (t < hi_f)
        with np.errstate(invalid="ignore"):
            res = np.where(interior, t, 0.0).astype(npdt)
        res = np.where(t >= hi_f, info.max, res)
        res = np.where(t <= lo_f, info.min, res)
        return _from_np(res.astype(npdt), ok, at)
    v, ok = _np_vals(c, c.type)
    with np.errstate(over="ignore"):
        return _from_np(v.astype(npdt), ok, at)


def _np_java_mod(l, r):
    q = np.where((l < 0) != (r < 0), -(np.abs(l) // np.abs(r)), l // r)
    return l - q * r


def _least_greatest(e, table, n):
    is_least = isinstance(e, A.Least)
    at = _widen_type(e)
    npdt = at.to_pandas_dtype()
    acc_v = acc_ok = None
    for x in e.exprs:
        a = cpu_eval(x, table).cast(at)
        v, ok = _np_vals(a, at)
        if acc_v is None:
            acc_v, acc_ok = v.copy(), ok.copy()
            continue
        if np.issubdtype(npdt, np.floating):
            # NaN counts as the greatest value (Spark ordering)
            a_nan = np.isnan(acc_v)
            b_nan = np.isnan(v)
            if is_least:
                cmp = np.where(a_nan, True, np.where(b_nan, False,
                                                     v < acc_v))
            else:
                cmp = np.where(b_nan, True, np.where(a_nan, False,
                                                     v > acc_v))
        else:
            cmp = (v < acc_v) if is_least else (v > acc_v)
        take = ok & (~acc_ok | cmp)
        acc_v = np.where(take, v, acc_v)
        acc_ok = acc_ok | ok
    return _from_np(acc_v.astype(npdt), acc_ok, at)


def _murmur3_cpu(e: Murmur3Hash, table, n):
    """Numpy Spark murmur3 (independent of the XLA implementation; the
    scalar-python oracle in tests/test_hashing.py checks both)."""
    h = np.full(n, e.seed, np.uint32)
    with np.errstate(over="ignore"):
        for x in e.exprs:
            a = cpu_eval(x, table)
            h = _np_hash_col(a, h)
    return pa.array(h.astype(np.int32))


def _np_rotl(x, r):
    return (x << np.uint32(r)) | (x >> np.uint32(32 - r))


def _np_mix_k1(k1):
    k1 = k1 * np.uint32(0xCC9E2D51)
    k1 = _np_rotl(k1, 15)
    return k1 * np.uint32(0x1B873593)


def _np_mix_h1(h1, k1):
    h1 = h1 ^ k1
    h1 = _np_rotl(h1, 13)
    return h1 * np.uint32(5) + np.uint32(0xE6546B64)


def _np_fmix(h1, length):
    h1 = h1 ^ np.uint32(length) if np.isscalar(length) else \
        h1 ^ length.astype(np.uint32)
    h1 ^= h1 >> np.uint32(16)
    h1 = h1 * np.uint32(0x85EBCA6B)
    h1 ^= h1 >> np.uint32(13)
    h1 = h1 * np.uint32(0xC2B2AE35)
    h1 ^= h1 >> np.uint32(16)
    return h1


def _np_hash_col(a: pa.Array, seed: np.ndarray) -> np.ndarray:
    t = a.type
    valid = np.asarray(a.is_valid())
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        out = seed.copy()
        for i, v in enumerate(a.to_pylist()):
            if v is None:
                continue
            bs = v.encode("utf-8")
            h1 = np.uint32(seed[i])
            aligned = len(bs) - len(bs) % 4
            for j in range(0, aligned, 4):
                word = np.uint32(int.from_bytes(bs[j:j + 4], "little"))
                h1 = _np_mix_h1(h1, _np_mix_k1(word))
            for j in range(aligned, len(bs)):
                b = bs[j] - 256 if bs[j] >= 128 else bs[j]
                h1 = _np_mix_h1(h1, _np_mix_k1(np.uint32(b)))
            out[i] = _np_fmix(h1, len(bs))
        return out
    if pa.types.is_floating(t) and t.bit_width == 64:
        v, _ = _np_vals(a, pa.float64())
        v = np.where(v == 0.0, 0.0, v)
        bits = v.view(np.int64)
        bits = np.where(np.isnan(v), np.int64(0x7FF8000000000000), bits)
        h = _np_hash_i64(bits, seed)
    elif pa.types.is_floating(t):
        v, _ = _np_vals(a, pa.float32())
        v = np.where(v == 0.0, np.float32(0.0), v)
        bits = v.view(np.int32)
        bits = np.where(np.isnan(v), np.int32(0x7FC00000), bits)
        h = _np_fmix(_np_mix_h1(seed, _np_mix_k1(bits.astype(np.uint32))), 4)
    elif pa.types.is_int64(t) or pa.types.is_timestamp(t):
        v, _ = _np_vals(a.cast(pa.int64()) if not pa.types.is_int64(t)
                        else a, pa.int64())
        h = _np_hash_i64(v, seed)
    else:
        v, _ = _np_vals(a.cast(pa.int32()), pa.int32())
        h = _np_fmix(_np_mix_h1(seed, _np_mix_k1(v.astype(np.uint32))), 4)
    return np.where(valid, h, seed)


def _np_hash_i64(v: np.ndarray, seed: np.ndarray) -> np.ndarray:
    low = (v & np.int64(0xFFFFFFFF)).astype(np.uint32)
    high = ((v >> np.int64(32)) & np.int64(0xFFFFFFFF)).astype(np.uint32)
    h1 = _np_mix_h1(seed, _np_mix_k1(low))
    h1 = _np_mix_h1(h1, _np_mix_k1(high))
    return _np_fmix(h1, 8)


# ---------------------------------------------------------------------- #
# Plan execution
# ---------------------------------------------------------------------- #

_AGG_MAP = {
    "sum": "sum", "count": "count", "count_star": "count_all",
    "min": "min", "max": "max", "first": "first", "last": "last",
}


def _read_scan_file(plan: L.LogicalPlan, path: str) -> pa.Table:
    """One file's (projected) columns as a host table; preserves row
    counts even for an empty projection."""
    if isinstance(plan, L.ParquetRelation):
        import pyarrow.parquet as pq

        return pq.read_table(path, columns=plan.columns)
    if isinstance(plan, L.OrcRelation):
        import pyarrow.orc as paorc

        f = paorc.ORCFile(path)
        if plan.columns == []:
            # ORC read(columns=[]) loses num_rows (unlike parquet):
            # read one column and drop it to keep the row count
            names = [fl.name for fl in f.schema]
            t = f.read(columns=names[:1]) if names else f.read()
            return t.select([])
        return f.read(columns=plan.columns)
    import pyarrow.csv as pacsv

    return pacsv.read_csv(path).cast(schema_to_arrow(plan.file_schema))


def _scan_cpu(plan: L.LogicalPlan) -> pa.Table:
    """File-relation leaf on the CPU engine, with trailing Hive
    partition-value columns (same layout as the TPU scan's appender)."""
    aschema = schema_to_arrow(plan.schema)
    tables = []
    for i, p in enumerate(plan.paths):
        t = _read_scan_file(plan, p)
        for f in plan.partition_fields:
            v = plan.partition_values[i].get(f.name) \
                if i < len(plan.partition_values) else None
            if v is not None and isinstance(f.dtype, T.LongType):
                v = int(v)
            t = t.append_column(
                pa.field(f.name, aschema.field(f.name).type, True),
                pa.array([v] * t.num_rows,
                         aschema.field(f.name).type))
        tables.append(t)
    return pa.concat_tables(tables).cast(aschema)


def execute_cpu(plan: L.LogicalPlan) -> pa.Table:
    if isinstance(plan, L.InMemoryRelation):
        return plan.table
    if isinstance(plan, (L.ParquetRelation, L.OrcRelation,
                         L.CsvRelation)):
        return _scan_cpu(plan)
    if isinstance(plan, L.RangeRel):
        total = max(0, -(-(plan.end - plan.start) // plan.step))
        ids = plan.start + np.arange(total, dtype=np.int64) * plan.step
        return pa.table({"id": ids})
    if isinstance(plan, L.Project):
        child = execute_cpu(plan.children[0])
        arrays = [cpu_eval(e, child) for e in plan.exprs]
        return pa.Table.from_arrays(arrays,
                                    schema=schema_to_arrow(plan.schema))
    if isinstance(plan, L.Cached):
        # CPU engine caches the materialized table in the same slot
        with plan.slot.lock:
            if plan.slot.cpu_table is not None:
                return plan.slot.cpu_table
        t = execute_cpu(plan.children[0])
        with plan.slot.lock:
            if plan.slot.cpu_table is None:
                plan.slot.cpu_table = t
        return t
    if isinstance(plan, L.Filter):
        child = execute_cpu(plan.children[0])
        mask = pc.fill_null(cpu_eval(plan.condition, child), False)
        return child.filter(mask)
    if isinstance(plan, L.MapInArrow):
        child = execute_cpu(plan.children[0])
        if getattr(plan, "pandas", False):
            from spark_rapids_tpu.execs.python_exec import (
                _map_in_pandas_wrapper,
            )

            aschema = schema_to_arrow(plan.schema)
            return _map_in_pandas_wrapper(
                child, fn=plan.fn, aschema=aschema).cast(aschema)
        out = plan.fn(child)
        if isinstance(out, pa.RecordBatch):
            out = pa.Table.from_batches([out])
        return out.cast(schema_to_arrow(plan.schema))
    if isinstance(plan, L.CoGroupedPandas):
        import functools

        from spark_rapids_tpu.execs import python_exec as PE

        lt = execute_cpu(plan.children[0])
        rt = execute_cpu(plan.children[1])
        aschema = schema_to_arrow(plan.schema)
        side = pa.array(np.concatenate(
            [np.zeros(lt.num_rows, np.int8),
             np.ones(rt.num_rows, np.int8)]))
        arrays = [side]
        names = ["__side"]
        for i, f in enumerate(lt.schema):
            arrays.append(pa.concat_arrays(
                [lt.column(i).combine_chunks(),
                 pa.nulls(rt.num_rows, f.type)]))
            names.append(f"__l_{f.name}")
        for i, f in enumerate(rt.schema):
            arrays.append(pa.concat_arrays(
                [pa.nulls(lt.num_rows, f.type),
                 rt.column(i).combine_chunks()]))
            names.append(f"__r_{f.name}")
        combined = pa.Table.from_arrays(arrays, names)
        fn = functools.partial(
            PE._cogroup_wrapper, fn=plan.fn,
            left_keys=plan.left_key_names,
            right_keys=plan.right_key_names,
            aschema=aschema, n_left_cols=lt.num_columns,
            left_names=lt.column_names, right_names=rt.column_names)
        return fn(combined).cast(aschema)
    if isinstance(plan, L.GroupedPandas):
        import functools

        from spark_rapids_tpu.execs import python_exec as PE

        child = execute_cpu(plan.children[0])
        aschema = schema_to_arrow(plan.schema)
        if plan.kind == "flatmap":
            fn = functools.partial(PE._grouped_apply_wrapper,
                                   fn=plan.payload,
                                   key_names=plan.key_names,
                                   aschema=aschema)
        elif plan.kind == "agg":
            fn = functools.partial(PE._grouped_agg_wrapper,
                                   aggs=plan.payload,
                                   key_names=plan.key_names,
                                   aschema=aschema)
        else:
            fn = functools.partial(PE._window_in_pandas_wrapper,
                                   fns=plan.payload,
                                   key_names=plan.key_names,
                                   aschema=aschema)
        return fn(child).cast(aschema)
    if isinstance(plan, L.Generate):
        child = execute_cpu(plan.children[0])
        gen = plan.generator
        aschema = schema_to_arrow(plan.schema)
        arr = cpu_eval(gen.child, child)
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        lens = pc.fill_null(pc.list_value_length(arr), 0).to_numpy(
            zero_copy_only=False).astype(np.int64)
        n = len(arr)
        if gen.outer:
            rep = np.maximum(lens, 1)
        else:
            rep = lens
        parent = np.repeat(np.arange(n), rep)
        pos_list = []
        elems = []
        py = arr.to_pylist()
        for i in range(n):
            vals = py[i]
            if vals:
                for j, v in enumerate(vals):
                    pos_list.append(j)
                    elems.append(v)
            elif gen.outer:
                pos_list.append(None)
                elems.append(None)
        arrays = [child.column(cname).take(pa.array(parent))
                  for cname in child.schema.names]
        if gen.pos:
            arrays.append(pa.array(pos_list, pa.int32()))
        arrays.append(pa.array(
            elems, aschema.field(plan.out_name).type))
        return pa.Table.from_arrays(arrays, schema=aschema)
    if isinstance(plan, L.Expand):
        child = execute_cpu(plan.children[0])
        aschema = schema_to_arrow(plan.schema)
        parts = []
        for proj in plan.projections:
            arrays = []
            for e, f in zip(proj, aschema):
                a = cpu_eval(e, child)
                if a.type != f.type:
                    a = a.cast(f.type)
                arrays.append(a)
            parts.append(pa.Table.from_arrays(arrays, schema=aschema))
        return pa.concat_tables(parts)
    if isinstance(plan, L.Aggregate):
        return _aggregate_cpu(plan)
    if isinstance(plan, L.Sort):
        return _sort_cpu(plan)
    if isinstance(plan, L.Limit):
        return execute_cpu(plan.children[0]).slice(0, plan.n)
    if isinstance(plan, L.Union):
        tables = [execute_cpu(c) for c in plan.children]
        schema = tables[0].schema
        tables = [t.rename_columns(schema.names) for t in tables]
        return pa.concat_tables(tables)
    if isinstance(plan, L.Join):
        return _join_cpu(plan)
    if isinstance(plan, L.Window):
        return _window_cpu(plan)
    raise NotImplementedError(f"CPU engine: {plan.name}")


class _RevCmp:
    """Reverses comparison order for descending sort keys (works for any
    comparable payload, unlike numeric negation)."""

    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v

    def __lt__(self, o):
        return o.v < self.v

    def __eq__(self, o):
        return self.v == o.v


def _canon_key(v):
    """Canonicalize a value for grouping/peers: NULL==NULL, NaN==NaN."""
    if isinstance(v, float) and np.isnan(v):
        return ("nan",)
    return v


def _sort_entry(v, descending, nulls_last):
    null_flag = (1 if nulls_last else 0) if v is None else \
        (0 if nulls_last else 1)
    if v is None:
        return (null_flag, 0)
    if isinstance(v, float) and np.isnan(v):
        # Spark sorts NaN greatest among values
        v = _NaNGreatest()
    return (null_flag, _RevCmp(v) if descending else v)


class _NaNGreatest:
    __slots__ = ()

    def __lt__(self, o):
        return False  # nothing is greater than NaN

    def __gt__(self, o):
        return not isinstance(o, _NaNGreatest)

    def __eq__(self, o):
        return isinstance(o, _NaNGreatest)


def _window_cpu(plan: L.Window) -> pa.Table:
    """Reference implementation with explicit per-group python loops —
    deliberately simple and independent of the TPU kernels (the oracle
    role of 'CPU Spark' in the differential harness)."""
    from spark_rapids_tpu.exprs import window as WX

    child = execute_cpu(plan.children[0])
    n = child.num_rows
    spec = plan.window_exprs[0][0].spec
    pvals = [cpu_eval(e, child).to_pylist() for e in spec.partition_by]
    ovals = [cpu_eval(k.expr, child).to_pylist() for k in spec.order_by]

    def sort_key(i):
        parts = [_sort_entry(c[i], False, False) for c in pvals]
        parts += [_sort_entry(c[i], k.descending, k.nulls_last)
                  for c, k in zip(ovals, spec.order_by)]
        return tuple(parts)

    order = sorted(range(n), key=sort_key)
    pkey = [tuple(_canon_key(c[i]) for c in pvals) for i in range(n)]
    okey = [tuple(_canon_key(c[i]) for c in ovals) for i in range(n)]

    # group boundaries over the sorted order
    groups: list[list[int]] = []
    for pos, i in enumerate(order):
        if pos == 0 or pkey[i] != pkey[order[pos - 1]]:
            groups.append([])
        groups[-1].append(i)

    out_cols: dict[str, list] = {name: [None] * n
                                 for _we, name in plan.window_exprs}
    for we, name in plan.window_exprs:
        fn = we.fn
        vals = None
        dvals = None
        if fn.inputs():
            vals = cpu_eval(fn.inputs()[0], child).to_pylist()
        if isinstance(fn, WX.Lead) and fn.default is not None:
            dvals = cpu_eval(fn.default, child).to_pylist()
        col = out_cols[name]
        for g in groups:
            m = len(g)
            gok = [okey[i] for i in g]
            for pos, i in enumerate(g):
                if isinstance(fn, WX.RowNumber):
                    col[i] = pos + 1
                elif isinstance(fn, WX.Rank):
                    col[i] = gok.index(gok[pos]) + 1
                elif isinstance(fn, WX.DenseRank):
                    seen, dr = None, 0
                    for q in range(pos + 1):
                        if gok[q] != seen:
                            dr += 1
                            seen = gok[q]
                    col[i] = dr
                elif isinstance(fn, WX.Lead):  # Lag subclasses Lead
                    j = pos + fn.shift
                    if 0 <= j < m:
                        col[i] = vals[g[j]]
                    elif dvals is not None:
                        col[i] = dvals[i]
                elif isinstance(fn, WX.WindowAgg):
                    frame = we.spec.resolved_frame()
                    if frame.mode == "rows":
                        lo = 0 if frame.start is None else max(
                            0, pos + frame.start)
                        hi = m - 1 if frame.end is None else min(
                            m - 1, pos + frame.end)
                        if hi < lo or hi < 0:  # empty frame (e.g. end
                            lo, hi = 0, -1  # still before the partition)
                    elif frame.start is None and frame.end in (0, None):
                        lo = 0
                        if frame.end is None:
                            hi = m - 1
                        else:  # current peer group's last row
                            hi = pos
                            while hi + 1 < m and gok[hi + 1] == gok[pos]:
                                hi += 1
                    else:
                        # bounded value-based RANGE frame: one numeric
                        # order key; descending measures the offset the
                        # other way; a null-key row's frame is its null
                        # peer block (Spark RangeFrame semantics)
                        sval = ovals[0]
                        desc = spec.order_by[0].descending
                        v = sval[g[pos]]

                        def _ordnum(x):
                            import datetime

                            if isinstance(x, datetime.datetime):
                                if x.tzinfo is None:
                                    # Arrow hands back naive UTC; a
                                    # bare .timestamp() would apply
                                    # the machine's local timezone/DST
                                    x = x.replace(
                                        tzinfo=datetime.timezone.utc)
                                return int(x.timestamp() * 1e6)
                            if isinstance(x, datetime.date):
                                return x.toordinal()
                            return x

                        def in_frame(q):
                            import math as _m

                            u = sval[g[q]]
                            if v is None or u is None:
                                return v is None and u is None
                            v_nan = isinstance(v, float) and _m.isnan(v)
                            u_nan = isinstance(u, float) and _m.isnan(u)
                            if v_nan or u_nan:
                                # Spark total order: all NaN are equal
                                # and greatest — a NaN row's frame is
                                # the NaN peer block, nothing else
                                return v_nan and u_nan
                            un, vn = _ordnum(u), _ordnum(v)
                            d = (un - vn) if not desc else (vn - un)
                            if frame.start is not None and d < frame.start:
                                return False
                            if frame.end is not None and d > frame.end:
                                return False
                            return True

                        members = [q for q in range(m) if in_frame(q)]
                        if members:
                            lo, hi = members[0], members[-1]
                        else:
                            lo, hi = 0, -1
                    col[i] = _frame_agg(fn.agg, vals, g, lo, hi)
        # order within ties of the TPU sort may differ; that is fine — the
        # differential harness compares row sets, and ranking fns only
        # depend on key values
    arrays = [child.column(j) for j in range(child.num_columns)]
    names = list(child.schema.names)
    aschema = schema_to_arrow(plan.schema)
    for we, name in plan.window_exprs:
        arrays.append(pa.array(out_cols[name],
                               type=aschema.field(name).type))
        names.append(name)
    return pa.Table.from_arrays(arrays, names=names).cast(aschema)


def _frame_agg(agg, vals, g, lo, hi):
    from spark_rapids_tpu.exprs import aggregates as AGG

    window_rows = g[lo:hi + 1] if hi >= lo >= 0 else []
    if isinstance(agg, AGG.CountStar):
        return len(window_rows)
    xs = [vals[i] for i in window_rows if vals[i] is not None]
    if isinstance(agg, AGG.Count):
        return len(xs)
    if not xs:
        return None
    import math as _math

    def _nan(x):
        return isinstance(x, float) and _math.isnan(x)

    if isinstance(agg, AGG.Sum):
        return sum(xs)
    if isinstance(agg, AGG.Min):
        # Spark float total order: NaN greatest — min ignores NaN
        # unless the whole frame is NaN
        non_nan = [x for x in xs if not _nan(x)]
        return min(non_nan) if non_nan else float("nan")
    if isinstance(agg, AGG.Max):
        if any(_nan(x) for x in xs):
            return float("nan")
        return max(xs)
    if isinstance(agg, AGG.Average):
        return sum(float(x) for x in xs) / len(xs)
    raise NotImplementedError(type(agg).__name__)


def _aggregate_cpu(plan: L.Aggregate) -> pa.Table:
    child = execute_cpu(plan.children[0])
    n_keys = len(plan.groups)
    # project keys + agg inputs with partial-dtype casts applied
    cols, names, agg_specs = [], [], []
    for i, g in enumerate(plan.groups):
        arr = cpu_eval(g, child)
        if pa.types.is_floating(arr.type):
            # Spark's NormalizeFloatingNumbers under grouping keys:
            # -0.0 groups (and reports) as 0.0; NaNs as one canonical
            # NaN (pyarrow already groups NaNs together)
            zero = pa.scalar(0.0, arr.type)
            arr = pc.if_else(pc.equal(arr, zero), zero, arr)
        cols.append(arr)
        names.append(plan.schema.fields[i].name)
    seen = 0
    for na in plan.aggs:
        fn = na.fn
        ins = fn.inputs()
        if not ins:
            agg_specs.append(([], "count_all", na.out_name, fn))
            continue
        in_name = f"__a{seen}"
        seen += 1
        arr = cpu_eval(ins[0], child)
        op = fn.update_ops()[0]
        if op == "sum":
            arr = arr.cast(T.to_arrow_type(fn.partial_dtypes()[0]))
        if fn.name == "average":
            arr = arr.cast(pa.float64())
        cols.append(arr)
        names.append(in_name)
        agg_specs.append(([in_name], fn.name, na.out_name, fn))

    if cols:
        proj = pa.Table.from_arrays(cols, names=names)
    else:
        # COUNT(*)-only grand aggregate: a zero-column table would
        # report zero rows; count against the child's row count (the
        # TPU exec pads with a constant column for the same reason)
        proj = child
    if n_keys == 0:
        out_cols, out_names = [], []
        for in_names, fname, out_name, fn in agg_specs:
            out_cols.append(_grand_agg(proj, in_names, fname, fn))
            out_names.append(out_name)
        return pa.Table.from_arrays(
            [pa.array([v.as_py()], type=v.type) for v in out_cols],
            names=out_names).cast(schema_to_arrow(plan.schema))

    aggs = []
    nan_fix: dict[int, str] = {}  # spec index -> '__aK__nan' source
    for si, (in_names, fname, out_name, fn) in enumerate(agg_specs):
        if fname == "count_all":
            aggs.append(([], "count_all"))
        elif fname == "count":
            aggs.append((in_names[0], "count"))
        elif fname == "average":
            aggs.append((in_names[0], "mean"))
        elif fname in ("first", "last"):
            # Spark defaults ignoreNulls=false; pyarrow defaults skip
            aggs.append((in_names[0], fname, pc.ScalarAggregateOptions(
                skip_nulls=fn.ignore_nulls, min_count=0)))
        elif fname in ("collectlist", "collectset"):
            aggs.append((in_names[0], "list"))
            nan_fix[si] = ("collect", in_names[0], fname)
        elif fname in ("min", "max") and pa.types.is_floating(
                proj.column(in_names[0]).type):
            # Spark float total order: NaN greatest.  Aggregate the
            # NaN-cleaned values plus a per-group any-NaN flag, then
            # recompose (max: NaN if any NaN; min: NaN only when every
            # non-null value is NaN).
            src = in_names[0]
            x = proj.column(src)
            xnan = pc.fill_null(pc.is_nan(x), False)
            clean = pc.if_else(xnan, pa.scalar(None, x.type), x)
            proj = proj.append_column(f"{src}__clean", clean)
            proj = proj.append_column(f"{src}__nan", xnan)
            aggs.append((f"{src}__clean", fname))
            aggs.append((f"{src}__nan", "any"))
            nan_fix[si] = src
        else:
            aggs.append((in_names[0], fname))
    gb = proj.group_by(names[:n_keys], use_threads=False)
    res = gb.aggregate(aggs)
    # rename to output schema order: keys first in our schema, aggregates
    # come back named '<col>_<agg>'
    out_arrays = []
    aschema = schema_to_arrow(plan.schema)
    for i in range(n_keys):
        out_arrays.append(res.column(names[i]))
    ai = 0
    for si, (in_names, fname, out_name, fn) in enumerate(agg_specs):
        spec = aggs[ai]
        src, op = (spec[0], spec[1]) if spec[0] else ("", spec[1])
        if isinstance(nan_fix.get(si), tuple):
            _tag, base, fname2 = nan_fix[si]
            lists = res.column(f"{base}_list").to_pylist()
            out = []
            for lv in lists:
                xs = [x for x in (lv or []) if x is not None]
                if fname2 == "collectset":
                    xs = _dedup_total_order(xs)
                out.append(xs)
            out_arrays.append(pa.array(
                out, type=aschema.field(n_keys + si).type))
            ai += 1
            continue
        if si in nan_fix:
            base = nan_fix[si]
            vals = res.column(f"{base}__clean_{fname}")
            anynan = res.column(f"{base}__nan_any")
            nan_scalar = pa.scalar(float("nan"), vals.type)
            if fname == "max":
                out = pc.if_else(pc.fill_null(anynan, False),
                                 nan_scalar, vals)
            else:  # min: NaN only when no non-NaN value existed
                out = pc.if_else(
                    pc.and_(pc.is_null(vals),
                            pc.fill_null(anynan, False)),
                    nan_scalar, vals)
            out_arrays.append(out)
            ai += 2
            continue
        col_name = f"{src}_{op}" if src else f"{op}"
        if col_name not in res.column_names:
            col_name = f"{'_'.join(in_names)}_{op}" if in_names else op
        out_arrays.append(res.column(col_name))
        ai += 1
    return pa.Table.from_arrays(out_arrays,
                                names=aschema.names).cast(aschema)


def _grand_agg(proj: pa.Table, in_names, fname, fn=None) -> pa.Scalar:
    if fname == "count_all":
        return pa.scalar(proj.num_rows, pa.int64())
    col = proj.column(in_names[0])
    if fname == "count":
        return pa.scalar(len(col) - col.null_count, pa.int64())
    if fname == "average":
        return pc.mean(col)
    if fname == "sum":
        return pc.sum(col)
    if fname in ("min", "max") and pa.types.is_floating(col.type):
        # Spark float total order: NaN greatest (see _aggregate_cpu)
        xnan = pc.fill_null(pc.is_nan(col), False)
        any_nan = pc.any(xnan).as_py()
        clean = pc.if_else(xnan, pa.scalar(None, col.type), col)
        v = pc.min(clean) if fname == "min" else pc.max(clean)
        if fname == "max" and any_nan:
            return pa.scalar(float("nan"), col.type)
        if fname == "min" and v.as_py() is None and any_nan:
            return pa.scalar(float("nan"), col.type)
        return v
    if fname == "min":
        return pc.min(col)
    if fname == "max":
        return pc.max(col)
    if fname in ("first", "last"):
        src = col if (fn is None or fn.ignore_nulls) else None
        vals = col.drop_null() if src is not None else col.combine_chunks()
        if len(vals) == 0:
            return pa.scalar(None, col.type)
        return vals[0] if fname == "first" else vals[-1]
    if fname in ("collectlist", "collectset"):
        xs = [x for x in col.to_pylist() if x is not None]
        if fname == "collectset":
            xs = _dedup_total_order(xs)
        return pa.scalar(xs, pa.list_(col.type))
    raise NotImplementedError(fname)


def _dedup_total_order(xs: list) -> list:
    """Keep-first dedup under Spark's total-order equality (NaN == NaN)
    — ONE implementation for grouped and grand collect_set."""
    import math as _math

    kept: list = []
    for x in xs:
        dup = any(
            (isinstance(x, float) and isinstance(y, float)
             and _math.isnan(x) and _math.isnan(y)) or x == y
            for y in kept)
        if not dup:
            kept.append(x)
    return kept


def _spark_sortable(arr: pa.Array) -> pa.Array:
    """pyarrow sorts NaN alongside nulls; Spark sorts NaN as the greatest
    value.  Encode floats as IEEE total-order int64 keys (nulls kept)."""
    if not pa.types.is_floating(arr.type):
        return arr
    v, valid = _np_vals(arr, pa.float64())
    bits = v.view(np.int64)
    bits = np.where(np.isnan(v), np.int64(0x7FF8000000000000), bits)
    keys = np.where(bits < 0, bits ^ np.int64(2**63 - 1), bits)
    return _from_np(keys, valid, pa.int64())


_INT_RE = None


def _cast_cpu_from_string(c: pa.Array, dst, at) -> pa.Array:
    """Spark non-ANSI string casts: trim whitespace, NULL on malformed.
    Strict ASCII-digit integer syntax (Python int() would accept '1_2'
    and Unicode digits that Spark rejects)."""
    global _INT_RE
    import re

    if _INT_RE is None:
        # Spark accepts a fractional tail and truncates toward zero
        # (cast('3.5' as int) = 3); exponents stay rejected
        _INT_RE = re.compile(r"^([+-]?)([0-9]*)(?:\.([0-9]*))?$")
    out = []
    if isinstance(dst, T.IntegralType):
        lo = np.iinfo(T.to_numpy_dtype(dst)).min
        hi = np.iinfo(T.to_numpy_dtype(dst)).max
        for v in c.to_pylist():
            if v is None:
                out.append(None)
                continue
            s = v.strip()
            m = _INT_RE.match(s)
            if not m or not (m.group(2) or m.group(3)):
                out.append(None)
                continue
            iv = int((m.group(1) or "") + (m.group(2) or "0"))
            out.append(iv if lo <= iv <= hi else None)
        return pa.array(out, at)
    if isinstance(dst, (T.FloatType, T.DoubleType)):
        for v in c.to_pylist():
            if v is None:
                out.append(None)
                continue
            s = v.strip()
            try:
                out.append(float(s))
            except ValueError:
                out.append(None)
        return pa.array(out, at)
    if isinstance(dst, T.BooleanType):
        true_set = {"true", "t", "yes", "y", "1"}
        false_set = {"false", "f", "no", "n", "0"}
        for v in c.to_pylist():
            if v is None:
                out.append(None)
                continue
            s = v.strip().lower()
            out.append(True if s in true_set
                       else False if s in false_set else None)
        return pa.array(out, at)
    if isinstance(dst, T.DateType):
        import datetime as _dt

        for v in c.to_pylist():
            if v is None:
                out.append(None)
                continue
            s = v.strip()
            try:
                out.append(_dt.date.fromisoformat(s))
            except ValueError:
                out.append(None)
        return pa.array(out, at)
    raise NotImplementedError(f"CPU cast string -> {dst}")


_SORT_KEY_PLACEMENT: list = []  # lazy probe: [] unknown, [bool] known


def _sort_indices(data, sort_keys, null_placement: str):
    """pyarrow >= 25 deprecates SortOptions-level ``null_placement``
    (FutureWarning on every call) in favor of per-sort-key placement
    passed as (name, order, null_placement) triples; older pyarrow
    rejects the triple form.  Probe once, then stick to whichever form
    this runtime supports."""
    if not _SORT_KEY_PLACEMENT:
        try:
            probe = pa.table({"__p": [1]})
            pc.sort_indices(
                probe,
                sort_keys=[("__p", "ascending", null_placement)])
            _SORT_KEY_PLACEMENT.append(True)
        except Exception:
            _SORT_KEY_PLACEMENT.append(False)
    if _SORT_KEY_PLACEMENT[0]:
        return pc.sort_indices(
            data, sort_keys=[(n, o, null_placement)
                             for n, o in sort_keys])
    return pc.sort_indices(data, sort_keys=sort_keys,
                           null_placement=null_placement)


def _sort_cpu(plan: L.Sort) -> pa.Table:
    child = execute_cpu(plan.children[0])
    # project sort keys as temp columns
    tmp = child
    keys = []
    for i, k in enumerate(plan.keys):
        name = f"__s{i}"
        tmp = tmp.append_column(
            name, _spark_sortable(cpu_eval(k.expr, child)))
        keys.append((name, "descending" if k.descending else "ascending"))
    placements = {k.nulls_last for k in plan.keys}
    if len(placements) == 1:
        idx = _sort_indices(
            tmp, keys,
            "at_end" if placements.pop() else "at_start")
    else:
        # mixed per-key null placement: stable multi-pass sort from the
        # least significant key (python fallback, oracle-grade only)
        idx_np = np.arange(tmp.num_rows)
        for (name, order), k in reversed(list(zip(keys, plan.keys))):
            col = tmp.column(name).combine_chunks().take(
                pa.array(idx_np, pa.int64()))
            sidx = _sort_indices(
                col, [("", order)],
                "at_end" if k.nulls_last else "at_start")
            idx_np = idx_np[np.asarray(sidx)]
        idx = pa.array(idx_np, pa.int64())
    return child.take(idx)


def _join_cpu(plan: L.Join) -> pa.Table:
    left = execute_cpu(plan.children[0])
    right = execute_cpu(plan.children[1])
    jt = plan.join_type
    if jt == "cross" or (jt == "inner" and not plan.left_keys):
        # cross product / keyless conditional inner join (nested loop)
        left = left.append_column("__ck", pa.array([1] * left.num_rows))
        right = right.append_column("__ck", pa.array([1] * right.num_rows))
        lkeys, rkeys = ["__ck"], ["__ck"]
        jt = "inner"
    else:
        tmpl, tmpr = left, right
        lkeys, rkeys = [], []
        for i, (lk, rk) in enumerate(zip(plan.left_keys, plan.right_keys)):
            ln, rn = f"__lk{i}", f"__rk{i}"
            tmpl = tmpl.append_column(ln, cpu_eval(lk, left))
            tmpr = tmpr.append_column(rn, cpu_eval(rk, right))
            lkeys.append(ln)
            rkeys.append(rn)
        left, right = tmpl, tmpr
    pa_type = {"inner": "inner", "left_outer": "left outer",
               "right_outer": "right outer", "full_outer": "full outer",
               "left_semi": "left semi", "left_anti": "left anti"}[jt]
    res = left.join(right, keys=lkeys, right_keys=rkeys, join_type=pa_type,
                    left_suffix="", right_suffix="__r",
                    coalesce_keys=False)
    out_names = [f.name for f in plan.schema.fields]
    res_names = res.column_names
    arrays = []
    used = []
    for name in out_names:
        # account for pa.join suffixing duplicate names
        if name in res_names and name not in used:
            pick = name
        else:
            pick = f"{name}__r"
        used.append(pick)
        arrays.append(res.column(pick))
    out = pa.Table.from_arrays(arrays, names=out_names)
    if plan.condition is not None:
        mask = pc.fill_null(cpu_eval(plan.condition, out), False)
        out = out.filter(mask)
    return out.cast(schema_to_arrow(plan.schema))


def _add_interval_us(us: int, months: int, days: int,
                     microseconds: int) -> int:
    """Epoch-us + calendar interval with Spark's add_months rule:
    month arithmetic clamps day-of-month to the target month's end;
    days/microseconds add after."""
    import calendar
    import datetime

    utc = datetime.timezone.utc
    dt = (datetime.datetime(1970, 1, 1, tzinfo=utc)
          + datetime.timedelta(microseconds=us))
    m0 = dt.month - 1 + months
    y = dt.year + m0 // 12
    m = m0 % 12 + 1
    day = min(dt.day, calendar.monthrange(y, m)[1])
    dt = dt.replace(year=y, month=m, day=day)
    dt += datetime.timedelta(days=days, microseconds=microseconds)
    return int((dt - datetime.datetime(1970, 1, 1, tzinfo=utc))
               / datetime.timedelta(microseconds=1))


def _java_split(pattern: str, s: str, limit: int) -> list[str]:
    """java.lang.String.split semantics: captured groups never leak
    into the result (unlike re.split), a leading zero-width match is
    skipped, limit > 0 caps the piece count, and limit == 0 drops
    trailing empty pieces."""
    import re

    out = []
    last = 0
    pieces = 0
    for m in re.finditer(pattern, s):
        if limit > 0 and pieces >= limit - 1:
            break
        if m.start() == m.end():
            if m.start() == 0 or m.start() == len(s):
                continue  # Java skips boundary zero-width matches
            if m.start() < last:
                continue
        out.append(s[last:m.start()])
        last = m.end()
        pieces += 1
    out.append(s[last:])
    if limit == 0:
        while out and out[-1] == "":
            out.pop()
    return out
