"""CPU reference engine (pyarrow-backed).

Plays the role "CPU Spark" plays for the reference: the independent
implementation every TPU operator is differentially tested against
(ref: integration_tests/src/main/python/asserts.py
assert_gpu_and_cpu_are_equal_collect), and the fallback executor for
plan nodes the TPU planner cannot replace (ref: RapidsMeta
willNotWorkOnGpu -> original Spark operator keeps running).
"""

from spark_rapids_tpu.cpu.engine import execute_cpu  # noqa: F401
