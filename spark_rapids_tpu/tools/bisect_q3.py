"""BENCH_r05 -> r06 q3 regression bisect: A/B the suspect layers.

BENCH_r06 ran q3 at 0.117x vs CPU where BENCH_r05 ran 0.248x — a 2.3x
wall-clock regression on the join+groupby milestone.  The layers that
landed between the rounds (fusion + buffer donation in PR11, SPMD
stage execution in PR14) each ship a kill switch, so the regression is
bisectable by CONF, not by checkout: every arm below re-runs the exact
bench.py q3 shape (same fixture generator, same timed-iteration
protocol, wire compression + device ledger + event log on, matching
the committed rounds) in a FRESH subprocess (no shared jit cache —
each arm pays its own compiles, exactly like a bench round) with one
suspect toggled off.

Run:  python -m spark_rapids_tpu.tools.bisect_q3 [out.json]

Writes a committed artifact (BISECT_q3_r07.json by default): per-arm
timings + dispatch/ledger fields, the wall-clock delta of each arm
against the r06 baseline arm, and the `tools/history compare` matrix
across the per-arm event logs (per-query and per-operator deltas, the
CompareApplications analog).  The arm set also includes the r07
mitigation config (batch coalescing on, docs/occupancy.md) so the
artifact shows the regression AND the shipped answer side by side.
"""

from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_DONATE = "spark.rapids.tpu.sql.fusion.donation.enabled"
_FUSION = "spark.rapids.tpu.sql.fusion.enabled"
_SPMD = "spark.rapids.tpu.shuffle.collective.spmd.enabled"
_SPEC = "spark.rapids.tpu.sql.speculation.enabled"
_RF = "spark.rapids.tpu.sql.runtimeFilter.enabled"
_COALESCE = "spark.rapids.tpu.sql.coalesce.enabled"

#: each arm = the r06 bench config with ONE suspect toggled (plus the
#: r05-equivalent "all suspects off" floor and the r07 mitigation).
ARMS = [
    ("r06_base", {_DONATE: True}),
    ("no_donation", {_DONATE: False}),
    ("no_fusion", {_DONATE: True, _FUSION: False}),
    ("no_fusion_no_donation", {_DONATE: False, _FUSION: False}),
    ("no_spmd", {_DONATE: True, _SPMD: False}),
    ("no_speculation", {_DONATE: True, _SPEC: False}),
    ("no_runtime_filter", {_DONATE: True, _RF: False}),
    ("r07_coalesce", {_DONATE: True, _COALESCE: True}),
]


def run_arm(fixture_dir: str, ev_dir: str, overrides: dict) -> dict:
    """Child-process body: one bench-equivalent q3 round under the
    arm's conf.  Digest-gated against the CPU engine like bench.py's
    _bench_q3 (a fast wrong answer is not a data point)."""
    sys.path.insert(0, REPO)
    import bench

    from spark_rapids_tpu.config import get_conf
    from spark_rapids_tpu.session import TpuSession

    conf = get_conf()
    conf.set("spark.rapids.tpu.sql.wireCompression.enabled", True)
    conf.set("spark.rapids.tpu.trace.ledger.enabled", True)
    conf.set("spark.rapids.tpu.eventLog.enabled", True)
    conf.set("spark.rapids.tpu.eventLog.dir", ev_dir)
    for k, v in overrides.items():
        conf.set(k, v)

    session = TpuSession()
    li = [os.path.join(fixture_dir, f"lineitem-{i}.parquet")
          for i in range(2)]
    orders = os.path.join(fixture_dir, "orders.parquet")
    df = bench.q3_dataframe(session, li, orders)

    df.collect(engine="tpu")  # warmup: compile + page cache
    bench.reset_all_counters()
    tpu_ts, tpu_r = bench._time_collect(df, "tpu", 3)
    out = {"q3_tpu_s_median": round(statistics.median(tpu_ts), 4)}
    out.update(bench._stats(tpu_ts, "q3_tpu"))
    out.update(bench._ledger_fields("q3", 3))
    out.update(bench._fusion_fields("q3", 3))
    out.update(bench._rf_fields(df, 3))
    out.update(bench._stage_breakdown(df, "q3"))
    cpu_ts, cpu_r = bench._time_collect(df, "cpu", 2)
    got = sorted(tpu_r.to_pydict()["revenue"], reverse=True)
    want = sorted(cpu_r.to_pydict()["revenue"], reverse=True)
    assert len(got) == len(want) == 10, (len(got), len(want))
    for gv, wv in zip(got, want):
        assert abs(gv - wv) <= 1e-6 * max(1.0, abs(wv)), (gv, wv)
    cpu_t = statistics.median(cpu_ts)
    out["q3_cpu_s_per_query"] = round(cpu_t, 4)
    out["q3_vs_cpu"] = round(cpu_t / out["q3_tpu_s_median"], 3)
    return out


def _make_fixture(d: str) -> None:
    sys.path.insert(0, REPO)
    import bench

    bench.make_lineitem(d, n_files=2, with_orderkey=True)
    bench.make_orders(d)


def _compare_md(ev_dirs: dict) -> str:
    """history compare across the per-arm event logs (baseline first)."""
    from spark_rapids_tpu.tools import history

    apps = []
    for label, d in ev_dirs.items():
        logs = sorted(os.path.join(d, f) for f in os.listdir(d))
        if not logs:
            continue
        # label the app by ARM (compare renders basenames)
        named = os.path.join(d, f"{label}.jsonl")
        os.rename(logs[0], named)
        apps.append(history.load_application(named))
    if len(apps) < 2:
        return "(compare skipped: <2 event logs)"
    return history.render_compare_md(history.compare_applications(
        apps, history.DEFAULT_REGRESSION_THRESHOLD))


def main(out_path: str = "BISECT_q3_r07.json") -> int:
    results: dict = {"protocol": {
        "fixture": "bench.make_lineitem(n_files=2, with_orderkey) + "
                   "make_orders (q3_rows=3145728), warmup + median of "
                   "3 timed tpu collects, cpu median of 2, fresh "
                   "subprocess per arm",
        "arms": {label: ov for label, ov in ARMS},
    }, "arms": {}}
    tmp = tempfile.mkdtemp(prefix="q3bisect_")
    fixture = os.path.join(tmp, "fixture")
    os.makedirs(fixture)
    _make_fixture(fixture)
    ev_dirs = {}
    for label, overrides in ARMS:
        ev_dir = os.path.join(tmp, f"ev_{label}")
        os.makedirs(ev_dir)
        ev_dirs[label] = ev_dir
        child = (
            "import json,sys; sys.path.insert(0, %r); "
            "from spark_rapids_tpu.tools.bisect_q3 import run_arm; "
            "print('ARM_RESULT ' + json.dumps(run_arm(%r, %r, "
            "json.loads(sys.argv[1]))))"
            % (REPO, fixture, ev_dir))
        proc = subprocess.run(
            [sys.executable, "-c", child, json.dumps(overrides)],
            capture_output=True, text=True, cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS":
                 os.environ.get("JAX_PLATFORMS", "cpu")})
        line = next((ln for ln in proc.stdout.splitlines()
                     if ln.startswith("ARM_RESULT ")), None)
        if line is None:
            results["arms"][label] = {
                "error": (proc.stderr or proc.stdout)[-2000:]}
            print(f"{label}: FAILED", file=sys.stderr)
            continue
        results["arms"][label] = json.loads(line[len("ARM_RESULT "):])
        print(f"{label}: q3_tpu_s_median="
              f"{results['arms'][label]['q3_tpu_s_median']} "
              f"vs_cpu={results['arms'][label]['q3_vs_cpu']}")
    base = results["arms"].get("r06_base", {}).get("q3_tpu_s_median")
    if base:
        results["delta_vs_r06_base"] = {
            label: round(base / a["q3_tpu_s_median"], 3)
            for label, a in results["arms"].items()
            if a.get("q3_tpu_s_median")}
    results["history_compare_md"] = _compare_md(ev_dirs)
    with open(out_path, "w") as f:
        json.dump(results, f, indent=1, sort_keys=True)
    print(f"wrote {out_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else
                  "BISECT_q3_r07.json"))
