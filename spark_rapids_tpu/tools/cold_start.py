"""Cold-process measurement harness for the warm-start cache.

A process restart is the one cost the in-process caches cannot see:
every jit wrapper, prepared plan and cached result dies with the
process, and the next process re-pays trace + XLA compile for the
whole working set (docs/warm_start.md).  This module is the measured
unit for that cost — ONE fresh process executing the fusion-smoke
query (the same q1-shaped scan->filter->agg fixture
tools/bench_smoke.run_fusion_smoke gates on) against a given persist
directory, reporting wall time, result digest, jit miss/compile
counts, ledger dispatch count and the persist.* counter snapshot as
one JSON line on stdout.

Drivers fork it:

- ``bench.py --cold-start N``: N children against a WARM persist dir
  vs N against EMPTY dirs -> cold_p50_ms / cold_p99_ms /
  cold_jit_misses / persist_hit_rate both ways (the rollout-cost
  artifact).
- ``tools/bench_smoke.run_warm_start_smoke`` (tier-1): one
  populate-and-prime pass, then a measured child asserting ZERO
  compiles and a digest bit-identical to the in-process run.

Run: python -m spark_rapids_tpu.tools.cold_start --data DIR \\
         [--persist DIR]
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Optional

#: fixture constants — shared with run_fusion_smoke's shape so the
#: warm-start numbers describe the same program population the fusion
#: gates describe
FIXTURE_SEED = 0xF05E
FIXTURE_ROWS = 1 << 14


def make_fixture(dir_: str) -> str:
    """Write the fusion-smoke parquet fixture (4 row groups) into
    `dir_` and return its path.  Deterministic: every process seeds
    the same rng, so parent and children agree on content digests."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(FIXTURE_SEED)
    n = FIXTURE_ROWS
    t = pa.table({
        "l_shipdate": rng.integers(8766, 10957, n).astype(np.int32),
        "l_key": rng.integers(0, 4, n).astype(np.int64),
        "l_quantity": rng.integers(1, 51, n).astype(np.int64),
        "l_price": rng.integers(900, 105000, n).astype(np.int64),
    })
    path = os.path.join(dir_, "li.parquet")
    pq.write_table(t, path, row_group_size=n // 4)
    return path


def run_once(data_dir: str,
             persist_dir: Optional[str] = None) -> dict:
    """Execute the fixture query once in THIS process and return the
    measurement record.  With `persist_dir` set, persistence is
    enabled against it BEFORE any compile (so the XLA compilation
    cache attaches in time) and the background writer is drained
    before returning (so a later process sees every entry).

    wall_ms times session construction + collect only — the portion
    a restart re-pays per query; interpreter/jax import time is paid
    before this function runs and is the same for warm and empty."""
    from spark_rapids_tpu import persist as P
    from spark_rapids_tpu.config import get_conf
    from spark_rapids_tpu.eventlog import table_digest
    from spark_rapids_tpu.execs.base import _budget_conf, _fusion_conf
    from spark_rapids_tpu.execs.jit_cache import cache_stats
    from spark_rapids_tpu.exprs.base import lit
    from spark_rapids_tpu.session import (
        TpuSession,
        col,
        count_star,
        sum_,
    )
    from spark_rapids_tpu.trace import ledger

    _fusion_conf()
    _budget_conf()
    conf = get_conf()
    n = FIXTURE_ROWS
    # pinned like run_fusion_smoke: deterministic dispatch pattern,
    # 4 row groups -> 4 wire batches, fused chain on
    conf.set("spark.rapids.tpu.sql.pipeline.enabled", False)
    conf.set("spark.rapids.tpu.sql.speculation.enabled", False)
    conf.set("spark.rapids.tpu.sql.batchSizeRows", n // 4)
    conf.set("spark.rapids.tpu.sql.shuffle.partitions", 1)
    conf.set("spark.rapids.tpu.sql.fusion.enabled", True)
    conf.set("spark.rapids.tpu.sql.fusion.donation.enabled", False)
    if persist_dir is not None:
        conf.set("spark.rapids.tpu.persist.enabled", True)
        conf.set("spark.rapids.tpu.persist.dir", persist_dir)
        # activate NOW, before the first compile: the XLA persistent
        # compilation cache only captures compiles that happen after
        # jax_compilation_cache_dir is set
        P.active()
    ledger.enable()

    path = os.path.join(data_dir, "li.parquet")
    t0 = time.perf_counter()
    session = TpuSession()
    r = (session.read_parquet(path)
         .where(col("l_shipdate") <= lit(10471))
         .group_by(col("l_key"))
         .agg((sum_(col("l_quantity")), "sum_qty"),
              (sum_(col("l_price")), "sum_price"),
              (count_star(), "n"))
         .order_by(col("l_key"))
         .collect(engine="tpu"))
    wall_ms = (time.perf_counter() - t0) * 1e3

    ledger.LEDGER.flush(timeout=30.0)
    summary = ledger.summarize(ledger.snapshot())
    jc = cache_stats()
    if persist_dir is not None:
        P.flush(timeout=30.0)
    return {
        "wall_ms": round(wall_ms, 3),
        "digest": table_digest(r),
        "rows": r.num_rows,
        "jit_misses": jc["misses"],
        "compiles": jc["compiles"],
        "dispatches": summary["totals"]["dispatches"],
        "persist": P.stats(),
    }


def run_subprocess(data_dir: str, persist_dir: Optional[str] = None,
                   timeout: float = 300.0) -> dict:
    """Fork one fresh interpreter running this module's CLI and parse
    its JSON record.  The child inherits the environment (so a
    JAX_PLATFORMS pin applies) with the repo root prepended to
    PYTHONPATH."""
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env = dict(os.environ)
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [sys.executable, "-m", "spark_rapids_tpu.tools.cold_start",
           "--data", data_dir]
    if persist_dir is not None:
        cmd += ["--persist", persist_dir]
    proc = subprocess.run(cmd, env=env, capture_output=True,
                          text=True, timeout=timeout)
    if proc.returncode != 0:
        raise RuntimeError(
            f"cold-start child failed ({proc.returncode}):\n"
            f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}")
    # the record is the LAST stdout line (backends may chat above it)
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    return json.loads(lines[-1])


def main(argv: Optional[list] = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    data_dir = persist_dir = None
    i = 0
    while i < len(args):
        if args[i] == "--data" and i + 1 < len(args):
            data_dir = args[i + 1]
            i += 2
        elif args[i] == "--persist" and i + 1 < len(args):
            persist_dir = args[i + 1]
            i += 2
        else:
            print(f"unknown arg: {args[i]}", file=sys.stderr)
            return 2
    if not data_dir:
        print("usage: python -m spark_rapids_tpu.tools.cold_start "
              "--data DIR [--persist DIR]", file=sys.stderr)
        return 2
    if not os.path.exists(os.path.join(data_dir, "li.parquet")):
        make_fixture(data_dir)
    print(json.dumps(run_once(data_dir, persist_dir)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
