"""API validation: diff this engine's registries against the reference's
operator checklist.

TPU analog of the reference's api_validation module
(api_validation/src/main/scala/.../ApiValidation.scala:27-46 — a
reflection tool diffing each Gpu*Exec against its CPU counterpart to
catch drift).  Here the authoritative checklist is the reference's
replacement-rule inventory (SURVEY.md Appendix A, from
GpuOverrides.scala:773-3041), and the diff is against the LIVE
registries: SUPPORTED_EXPRS, SUPPORTED_AGGS, the exec conf table and
the session surface.  Run `python -m spark_rapids_tpu.tools.gen_docs`
to refresh docs/api_coverage.md; the coverage test keeps the count
honest per commit.
"""

from __future__ import annotations

#: reference expression rules (GpuOverrides.scala:773-2669 + shims)
REFERENCE_EXPRESSIONS = """
Abs Acos Acosh Add AddMonths AggregateExpression Alias And ArrayContains
Asin Asinh
AtLeastNNonNulls Atan Atanh AttributeReference Average BRound BitwiseAnd
BitwiseNot BitwiseOr BitwiseXor CaseWhen Cbrt Ceil CheckOverflow Coalesce
CollectList Concat ConcatWs Contains Cos Cosh Cot Count CreateArray
CreateNamedStruct CurrentRow DateAdd DateAddInterval DateDiff
DateFormatClass DateSub DayOfMonth DayOfWeek DayOfYear Divide ElementAt
EndsWith EqualNullSafe EqualTo Exp Explode Expm1 First Floor FromUnixTime
GetArrayItem GetJsonObject GetMapValue GetStructField GreaterThan
GreaterThanOrEqual Greatest Hour If In InSet InitCap InputFileBlockLength
InputFileBlockStart InputFileName IntegralDivide IsNaN IsNotNull IsNull
KnownFloatingPointNormalized Lag Last LastDay Lead Least Length LessThan
LessThanOrEqual Like Literal Log Log10 Log1p Log2 Logarithm Lower
MakeDecimal Max Md5 Min Minute MonotonicallyIncreasingID Month Multiply
Murmur3Hash NaNvl NormalizeNaNAndZero Not Or PivotFirst Pmod PosExplode
Pow PromotePrecision PythonUDF Quarter Rand Remainder Rint Round RowNumber
ScalarSubquery Second ShiftLeft ShiftRight ShiftRightUnsigned Signum Sin
Sinh Size SortOrder SparkPartitionID SpecifiedWindowFrame Sqrt StartsWith
StringLPad StringLocate StringRPad StringReplace StringSplit StringTrim
StringTrimLeft StringTrimRight Substring SubstringIndex Subtract Sum Tan
Tanh TimeAdd ToDegrees ToRadians ToUnixTimestamp UnaryMinus UnaryPositive
UnboundedFollowing UnboundedPreceding UnixTimestamp UnscaledValue Upper
WeekDay WindowExpression WindowSpecDefinition Year Cast RegExpReplace
AnsiCast TimeSub
""".split()

#: reference exec rules (GpuOverrides.scala:2774-3041 + shims)
REFERENCE_EXECS = """
BatchScanExec BroadcastExchangeExec BroadcastNestedLoopJoinExec
CartesianProductExec CoalesceExec CollectLimitExec CustomShuffleReaderExec
DataWritingCommandExec ExpandExec FilterExec GenerateExec GlobalLimitExec
HashAggregateExec LocalLimitExec ProjectExec RangeExec ShuffleExchangeExec
SortAggregateExec SortExec TakeOrderedAndProjectExec UnionExec WindowExec
BroadcastHashJoinExec FileSourceScanExec ShuffledHashJoinExec
SortMergeJoinExec ArrowEvalPythonExec MapInPandasExec
FlatMapGroupsInPandasExec AggregateInPandasExec WindowInPandasExec
FlatMapCoGroupsInPandasExec
""".split()

REFERENCE_SCANS = ["CSVScan", "ParquetScan", "OrcScan"]
REFERENCE_PARTITIONINGS = ["Hash", "Range", "RoundRobin", "Single"]

#: reference-name -> (module, attribute) implementing the same concept
#: under a TPU-idiomatic spelling.  Each entry is PROBED at validate()
#: time — a dropped implementation flips the doc back to missing.
_RENAMES = {
    "AttributeReference": ("spark_rapids_tpu.exprs.base",
                           "ColumnReference"),
    "PythonUDF": ("spark_rapids_tpu.udf.exprs", "OpaquePythonUDF"),
    "AggregateExpression": ("spark_rapids_tpu.exprs.aggregates",
                            "NamedAgg"),
    "SortOrder": ("spark_rapids_tpu.execs.sort", "SortKey"),
    "WindowSpecDefinition": ("spark_rapids_tpu.exprs.window",
                             "WindowSpec"),
    "SpecifiedWindowFrame": ("spark_rapids_tpu.exprs.window",
                             "WindowFrame"),
    "CurrentRow": ("spark_rapids_tpu.exprs.window", "CURRENT_ROW"),
    "UnboundedPreceding": ("spark_rapids_tpu.exprs.window", "UNBOUNDED"),
    "UnboundedFollowing": ("spark_rapids_tpu.exprs.window", "UNBOUNDED"),
    "Explode": ("spark_rapids_tpu.exprs.collections", "Explode"),
    "PosExplode": ("spark_rapids_tpu.exprs.collections", "Explode"),
    "InSet": ("spark_rapids_tpu.exprs.predicates", "In"),
    "CountDistinct": ("spark_rapids_tpu.exprs.aggregates",
                      "CountDistinct"),
    "UnixTimestamp": ("spark_rapids_tpu.exprs.datetime",
                      "UnixTimestampFromTs"),
    "ToUnixTimestamp": ("spark_rapids_tpu.exprs.datetime",
                        "UnixTimestampFromTs"),
    "ScalarSubquery": ("spark_rapids_tpu.exprs.subquery",
                       "ScalarSubquery"),
    # ANSI cast is the same Cast evaluator under the ansi.enabled conf
    # (the GpuCast.scala:166 ANSI matrix lives in exprs/cast.py)
    "AnsiCast": ("spark_rapids_tpu.exprs.cast", "Cast"),
}


def _known_expression_names() -> set:
    """Every expression/aggregate/window concept the engine implements,
    by reference name — live registries plus probed renames."""
    import importlib

    from spark_rapids_tpu.plan import planner as PL

    names = {c.__name__ for c in PL.SUPPORTED_EXPRS}
    names |= {c.__name__ for c in PL.SUPPORTED_AGGS}
    # window machinery is spec-based rather than per-rule
    from spark_rapids_tpu.exprs import window as W

    for cls in (W.WindowExpression, W.RowNumber, W.Rank, W.DenseRank,
                W.Lead, W.Lag):
        names.add(cls.__name__)
    for ref, (mod, attr) in _RENAMES.items():
        try:
            if hasattr(importlib.import_module(mod), attr):
                names.add(ref)
        except ImportError:
            pass
    return names


#: reference exec -> (module, class-name, note).  The class is resolved
#: via importlib at validate() time, exactly like the expression path —
#: a renamed or deleted implementation flips the entry to DRIFT instead
#: of silently reporting phantom coverage.  None = known-missing.
_EXEC_MAP: dict = {
    "BatchScanExec": ("spark_rapids_tpu.io.scan", "ParquetScanExec",
                      "+OrcScanExec/CsvScanExec"),
    "FileSourceScanExec": ("spark_rapids_tpu.io.scan", "ParquetScanExec",
                           "+pushdown, coalescing"),
    "BroadcastExchangeExec": ("spark_rapids_tpu.execs.join",
                              "TpuBroadcastHashJoinExec",
                              "broadcast build collection inside"),
    "BroadcastHashJoinExec": ("spark_rapids_tpu.execs.join",
                              "TpuBroadcastHashJoinExec", ""),
    "BroadcastNestedLoopJoinExec": ("spark_rapids_tpu.execs.join",
                                    "TpuBroadcastHashJoinExec",
                                    "cross/keyless-conditional path"),
    "CartesianProductExec": ("spark_rapids_tpu.execs.join",
                             "TpuShuffledHashJoinExec", "cross path"),
    "CoalesceExec": ("spark_rapids_tpu.execs.coalesce",
                     "TpuCoalescePartitionsExec", ""),
    "CollectLimitExec": ("spark_rapids_tpu.execs.limit",
                         "TpuCollectLimitExec", ""),
    "CustomShuffleReaderExec": ("spark_rapids_tpu.execs.adaptive",
                                "CoalescedShuffleReaderExec",
                                "AQE coalesced partition specs"),
    "DataWritingCommandExec": ("spark_rapids_tpu.io.write",
                               "FileWriteExec", "+Parquet/Csv/Orc"),
    "ExpandExec": ("spark_rapids_tpu.execs.expand", "TpuExpandExec", ""),
    "FilterExec": ("spark_rapids_tpu.execs.basic", "TpuFilterExec", ""),
    "GenerateExec": ("spark_rapids_tpu.execs.generate",
                     "TpuGenerateExec", ""),
    "GlobalLimitExec": ("spark_rapids_tpu.execs.limit",
                        "TpuGlobalLimitExec", ""),
    "LocalLimitExec": ("spark_rapids_tpu.execs.limit",
                       "TpuLocalLimitExec", ""),
    "HashAggregateExec": ("spark_rapids_tpu.execs.aggregate",
                          "TpuHashAggregateExec", ""),
    "SortAggregateExec": ("spark_rapids_tpu.execs.aggregate",
                          "TpuHashAggregateExec", "sort-agnostic"),
    "ProjectExec": ("spark_rapids_tpu.execs.basic", "TpuProjectExec", ""),
    "RangeExec": ("spark_rapids_tpu.execs.basic", "TpuRangeExec", ""),
    "ShuffleExchangeExec": ("spark_rapids_tpu.execs.exchange",
                            "TpuShuffleExchangeExec", "+collective"),
    "ShuffledHashJoinExec": ("spark_rapids_tpu.execs.join",
                             "TpuShuffledHashJoinExec", ""),
    "SortMergeJoinExec": ("spark_rapids_tpu.execs.join",
                          "TpuShuffledHashJoinExec",
                          "hash join instead, like the reference"),
    "SortExec": ("spark_rapids_tpu.execs.sort", "TpuSortExec",
                 "out-of-core"),
    "TakeOrderedAndProjectExec": ("spark_rapids_tpu.execs.sort",
                                  "TpuTakeOrderedAndProjectExec", ""),
    "UnionExec": ("spark_rapids_tpu.execs.basic", "TpuUnionExec", ""),
    "WindowExec": ("spark_rapids_tpu.execs.window", "TpuWindowExec", ""),
    "ArrowEvalPythonExec": ("spark_rapids_tpu.execs.python_exec",
                            "TpuMapInArrowExec",
                            "arrow-batch python eval"),
    "MapInPandasExec": ("spark_rapids_tpu.execs.python_exec",
                        "TpuMapInPandasExec", ""),
    "FlatMapGroupsInPandasExec": ("spark_rapids_tpu.execs.python_exec",
                                  "TpuFlatMapGroupsInPandasExec", ""),
    "AggregateInPandasExec": ("spark_rapids_tpu.execs.python_exec",
                              "TpuAggregateInPandasExec", ""),
    "WindowInPandasExec": ("spark_rapids_tpu.execs.python_exec",
                           "TpuWindowInPandasExec",
                           "unbounded frames"),
    "FlatMapCoGroupsInPandasExec": (
        "spark_rapids_tpu.execs.python_exec",
        "TpuFlatMapCoGroupsInPandasExec", ""),
}


def _resolve_execs():
    """Probe every _EXEC_MAP entry against the live modules.  Returns
    (resolved {ref: display}, missing [ref], drift [ref]) where drift
    means the map names a module/class that does not exist."""
    import importlib

    resolved: dict = {}
    missing: list = []
    drift: list = []
    for ref, entry in _EXEC_MAP.items():
        if entry is None:
            missing.append(ref)
            continue
        mod, cls, note = entry
        try:
            ok = hasattr(importlib.import_module(mod), cls)
        except ImportError:
            ok = False
        if ok:
            resolved[ref] = f"{cls}" + (f" ({note})" if note else "")
        else:
            drift.append(ref)
    return resolved, sorted(missing), sorted(drift)


def validate() -> dict:
    """Return {'expressions': (supported, missing), 'execs': ...} by
    diffing the live registries against the reference checklist."""
    have = _known_expression_names()
    exprs_ok = sorted(n for n in REFERENCE_EXPRESSIONS if n in have)
    exprs_missing = sorted(n for n in set(REFERENCE_EXPRESSIONS) - have)

    resolved, missing, drift = _resolve_execs()
    exec_map = dict(resolved)
    for ref in missing:
        exec_map[ref] = None
    for ref in drift:
        exec_map[ref] = None

    return {
        "expressions": (exprs_ok, exprs_missing),
        "execs": (sorted(resolved), missing + drift, exec_map),
        "exec_drift": drift,
        "scans": (list(REFERENCE_SCANS), []),
        "partitionings": (list(REFERENCE_PARTITIONINGS), []),
    }


def assert_no_drift() -> None:
    """Hard pass: raise when the exec map names implementations that no
    longer resolve (the lint REG005 rule; tpulint calls this module the
    same way).  Missing-by-design entries (None) are fine — only DRIFT
    (a named module/class that vanished) fails."""
    drift = validate()["exec_drift"]
    if drift:
        raise AssertionError(
            "api_validation exec map drift (implementation vanished): "
            + ", ".join(drift)
            + " — update _EXEC_MAP in tools/api_validation.py")


def coverage_md() -> str:
    v = validate()
    eo, em = v["expressions"]
    xo, xm, xmap = v["execs"]
    lines = [
        "# API coverage vs the reference checklist",
        "",
        "Generated by `python -m spark_rapids_tpu.tools.gen_docs` from "
        "the live registries diffed against the reference's replacement "
        "rules (SURVEY.md Appendix A / GpuOverrides.scala) — do not "
        "edit.",
        "",
        f"## Expressions: {len(eo)}/{len(set(REFERENCE_EXPRESSIONS))}",
        "",
        "Missing: " + (", ".join(em) if em else "none"),
        "",
        f"## Execs: {len(xo)}/{len(xmap)}",
        "",
        "| reference exec | this engine |",
        "|---|---|",
    ]
    for k in sorted(xmap):
        lines.append(f"| {k} | {xmap[k] or '**missing**'} |")
    lines += [
        "",
        f"## Scans: {len(v['scans'][0])}/{len(REFERENCE_SCANS)} — "
        + ", ".join(v["scans"][0]),
        f"## Partitionings: {len(v['partitionings'][0])}"
        f"/{len(REFERENCE_PARTITIONINGS)} — "
        + ", ".join(v["partitionings"][0]),
        "",
    ]
    return "\n".join(lines)


if __name__ == "__main__":
    assert_no_drift()
    v = validate()
    eo, em = v["expressions"]
    xo, xm, _ = v["execs"]
    print(f"expressions {len(eo)} supported / {len(em)} missing; "
          f"execs {len(xo)} supported / {len(xm)} missing; no drift")
