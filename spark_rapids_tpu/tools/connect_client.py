"""Stand-alone connect client CLI (docs/connect.md).

    python -m spark_rapids_tpu.tools.connect_client \\
        --host 127.0.0.1 --port 15002 --plan plan.json [--tenant t1] \\
        [--deadline-ms 5000] [--conf k=v ...] [--digest-only]

    python -m spark_rapids_tpu.tools.connect_client \\
        --port 15002 --sql "select count(*) as n from t"

Submits one serialized plan (Substrait JSON file / ``-`` for stdin) or
one SQL text over the wire and prints the result — the whole run stays
engine-free: only ``connect/client.py`` (stdlib + pyarrow) is
imported, never the session/planner/device runtime.  ``--digest-only``
prints the 16-hex Arrow IPC content digest, the value the wire-parity
tests compare against an in-process collect.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="spark_rapids_tpu.tools.connect_client",
        description="Submit a Substrait plan or SQL text to a "
                    "spark-rapids-tpu connect server.")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--plan", help="Substrait plan JSON file "
                                    "('-' reads stdin)")
    src.add_argument("--sql", help="SQL text")
    ap.add_argument("--tenant", default="default")
    ap.add_argument("--deadline-ms", type=float, default=None)
    ap.add_argument("--batch-rows", type=int, default=None)
    ap.add_argument("--conf", action="append", default=[],
                    metavar="K=V", help="session conf override "
                                        "(repeatable)")
    ap.add_argument("--params", default=None,
                    help="SQL :name bindings as a JSON object")
    ap.add_argument("--digest-only", action="store_true",
                    help="print only the Arrow IPC content digest")
    args = ap.parse_args(argv)

    from spark_rapids_tpu.connect.client import (
        ConnectClient,
        ConnectError,
        table_digest,
    )

    conf = {}
    for item in args.conf:
        k, sep, v = item.partition("=")
        if not sep:
            ap.error(f"--conf needs K=V, got {item!r}")
        conf[k] = v
    plan = None
    if args.plan is not None:
        text = (sys.stdin.read() if args.plan == "-"
                else open(args.plan).read())
        plan = json.loads(text)
    params = json.loads(args.params) if args.params else None

    try:
        with ConnectClient(args.host, args.port,
                           tenant=args.tenant) as cli:
            tbl = cli.execute_plan(
                plan, sql=args.sql, conf=conf or None, params=params,
                deadline_ms=args.deadline_ms,
                batch_rows=args.batch_rows)
    except ConnectError as e:
        print(f"error [{e.kind}]: {e}", file=sys.stderr)
        return 1
    if args.digest_only:
        print(table_digest(tbl))
    else:
        print(tbl.to_pandas().to_string(index=False)
              if tbl.num_rows else "(0 rows)")
        print(f"-- {tbl.num_rows} rows, digest {table_digest(tbl)}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
