"""tpulint CLI: static analysis for plans, registries, and engine
source.

Usage::

    python -m spark_rapids_tpu.tools.lint [options]

    --strict            fail on NEW warnings too (default: new errors)
    --baseline PATH     accepted-findings file
                        (default: spark_rapids_tpu/lint/baseline.json)
    --update-baseline   accept all current findings and rewrite the
                        baseline file
    --json              machine-readable output
    --no-source / --no-registry / --no-plans / --no-metrics
                        skip individual analyzers

Exit status: 0 when every finding at/above the failing severity is in
the baseline; 1 otherwise.  Rule ids and examples: docs/lint.md.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m spark_rapids_tpu.tools.lint",
        description="tpulint: static analysis for plans, registries, "
                    "and engine source (rules: docs/lint.md)")
    ap.add_argument("--strict", action="store_true",
                    help="fail on new warnings too")
    ap.add_argument("--baseline", default=None,
                    help="accepted-findings file")
    ap.add_argument("--update-baseline", action="store_true",
                    help="accept all current findings")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--no-source", action="store_true")
    ap.add_argument("--no-registry", action="store_true")
    ap.add_argument("--no-plans", action="store_true")
    ap.add_argument("--no-metrics", action="store_true")
    args = ap.parse_args(argv)

    from spark_rapids_tpu.lint import (
        evaluate,
        run_lint,
        save_baseline,
    )

    diags = run_lint(source=not args.no_source,
                     registry=not args.no_registry,
                     plans=not args.no_plans,
                     metrics=not args.no_metrics)

    if args.update_baseline:
        path = save_baseline(diags, args.baseline)
        print(f"baseline updated: {path} ({len(diags)} accepted)")
        return 0

    new, accepted, code = evaluate(diags, strict=args.strict,
                                   baseline_path=args.baseline)
    if args.json:
        print(json.dumps({
            "new": [d.to_json() for d in new],
            "accepted": [d.to_json() for d in accepted],
            "exit": code,
        }, indent=1))
        return code
    for d in new:
        print(d.render())
    if accepted:
        print(f"[{len(accepted)} baselined finding(s) suppressed]")
    if new:
        print(f"{len(new)} new finding(s)")
    if code:
        print("tpulint: FAIL (new findings at failing severity; fix "
              "them or --update-baseline)")
    else:
        print("tpulint: OK")
    return code


if __name__ == "__main__":
    sys.exit(main())
