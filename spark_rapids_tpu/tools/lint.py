"""tpulint CLI: static analysis for plans, registries, and engine
source.

Usage::

    python -m spark_rapids_tpu.tools.lint [options]

    --strict            fail on NEW warnings too (default: new errors)
    --baseline PATH     accepted-findings file
                        (default: spark_rapids_tpu/lint/baseline.json)
    --update-baseline   accept all current findings and rewrite the
                        baseline file
    --json              machine-readable output
    --no-source / --no-registry / --no-plans / --no-metrics /
    --no-concurrency    skip individual analyzers
    --baseline-diff     audit the baseline file against HEAD: print
                        added (firing, not baselined) and stale
                        (baselined, no longer firing) entries; stale
                        entries are an ERROR — a suppression whose
                        site is gone must be deleted, or it will
                        silently mask the next regression at that key

Exit status: 0 when every finding at/above the failing severity is in
the baseline; 1 otherwise.  Rule ids and examples: docs/lint.md.
"""

from __future__ import annotations

import argparse
import json
import sys


def _baseline_diff(diags, baseline_path, as_json: bool) -> int:
    """Audit the suppression file against what HEAD actually fires:
    `added` = findings not yet baselined (informational — the normal
    strict gate owns failing on those); `stale` = baseline keys whose
    site no longer fires, which is an ERROR: a dead suppression sits
    ready to mask the next real regression that lands on its key."""
    from spark_rapids_tpu.lint import load_baseline

    current = {d.key for d in diags}
    accepted = load_baseline(baseline_path)
    added = sorted(current - accepted)
    stale = sorted(accepted - current)
    if as_json:
        print(json.dumps({"added": added, "stale": stale,
                          "exit": 1 if stale else 0}, indent=1))
        return 1 if stale else 0
    for key in added:
        print(f"added (firing, not baselined): {key}")
    for key in stale:
        print(f"STALE (baselined, no longer firing): {key}")
    print(f"baseline-diff: {len(added)} added, {len(stale)} stale")
    if stale:
        print("tpulint: FAIL (stale baseline entries; delete them "
              "from baseline.json or run --update-baseline)")
        return 1
    print("tpulint: OK")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m spark_rapids_tpu.tools.lint",
        description="tpulint: static analysis for plans, registries, "
                    "and engine source (rules: docs/lint.md)")
    ap.add_argument("--strict", action="store_true",
                    help="fail on new warnings too")
    ap.add_argument("--baseline", default=None,
                    help="accepted-findings file")
    ap.add_argument("--update-baseline", action="store_true",
                    help="accept all current findings")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--no-source", action="store_true")
    ap.add_argument("--no-registry", action="store_true")
    ap.add_argument("--no-plans", action="store_true")
    ap.add_argument("--no-metrics", action="store_true")
    ap.add_argument("--no-concurrency", action="store_true")
    ap.add_argument("--baseline-diff", action="store_true",
                    help="audit baseline vs HEAD findings; stale "
                         "entries fail")
    args = ap.parse_args(argv)

    from spark_rapids_tpu.lint import (
        evaluate,
        run_lint,
        save_baseline,
    )

    diags = run_lint(source=not args.no_source,
                     registry=not args.no_registry,
                     plans=not args.no_plans,
                     metrics=not args.no_metrics,
                     concurrency=not args.no_concurrency)

    if args.baseline_diff:
        return _baseline_diff(diags, args.baseline, args.json)

    if args.update_baseline:
        path = save_baseline(diags, args.baseline)
        print(f"baseline updated: {path} ({len(diags)} accepted)")
        return 0

    new, accepted, code = evaluate(diags, strict=args.strict,
                                   baseline_path=args.baseline)
    if args.json:
        print(json.dumps({
            "new": [d.to_json() for d in new],
            "accepted": [d.to_json() for d in accepted],
            "exit": code,
        }, indent=1))
        return code
    for d in new:
        print(d.render())
    if accepted:
        print(f"[{len(accepted)} baselined finding(s) suppressed]")
    if new:
        print(f"{len(new)} new finding(s)")
    if code:
        print("tpulint: FAIL (new findings at failing severity; fix "
              "them or --update-baseline)")
    else:
        print("tpulint: OK")
    return code


if __name__ == "__main__":
    sys.exit(main())
