"""Profiling tool: per-query operator reports and device traces.

TPU analog of the reference's profiling tool (tools/src/main/scala/...
/tool/profiling/ProfileMain.scala — ApplicationInfo/Analysis over event
logs).  This engine is in-process, so the "event log" is the session's
query history: every TPU collect records its exec tree, whose metrics
(device-synced ns timers, row/batch counts, spill and prune counters)
the report aggregates.

For timeline-level work there is `device_trace(dir)`: a context manager
around jax.profiler.trace producing a Perfetto/XPlane trace (the
nvtx_profiling.md workflow analog, ref: SURVEY §5.1).
"""

from __future__ import annotations

import contextlib
import dataclasses
import itertools
import time
from typing import Iterator, Optional, Sequence

from spark_rapids_tpu.config import register
from spark_rapids_tpu.execs.base import TpuExec

HISTORY_CAPACITY = register(
    "spark.rapids.tpu.sql.queryHistory.capacity", 100,
    "How many collected queries the session's QueryHistory ring "
    "retains (operator snapshots + explain text per query; the oldest "
    "event is dropped past the cap).",
    check=lambda v: v >= 1)

#: PROCESS-global query-id source: the id doubles as the trace
#: subsystem's correlation key in a process-wide buffer, so two
#: sessions must never both hand out id 0 (their spans would merge in
#: span_stats / EXPLAIN ANALYZE).  itertools.count.__next__ is atomic
#: in CPython.
_QUERY_IDS = itertools.count()


@dataclasses.dataclass
class NodeSnapshot:
    """One operator's description + settled metric values.  History
    stores snapshots, NOT live exec trees — a live tree would pin the
    query's input data (e.g. ArrowSourceExec.table) for the session
    lifetime."""

    desc: str
    metrics: dict
    children: list


@dataclasses.dataclass
class QueryEvent:
    """One collected query (the ApplicationInfo analog).

    Beyond the id-keyed snapshot, events carry WHEN the query ran —
    ``start_ts``/``end_ts`` epoch seconds for human alignment and
    ``start_ns``/``end_ns`` monotonic (perf_counter_ns, same clock as
    the tracer) for in-process interval math — and ``conf_hash``, the
    active conf's fingerprint at collect time.  Event-log records and
    cross-run compares align runs on exactly these fields; ids alone
    are process-local and restart at 0 every run."""

    query_id: int
    explain: str
    root: NodeSnapshot
    wall_s: float
    ts: float
    start_ts: float = 0.0
    end_ts: float = 0.0
    start_ns: int = 0
    end_ns: int = 0
    conf_hash: str = ""


def snapshot_exec(node: TpuExec) -> NodeSnapshot:
    from spark_rapids_tpu.execs.base import TpuMetric, _MetricReaper

    _MetricReaper.get().flush()  # settle device-synced timers
    # settle ALL deferred device counts in one transfer: per-metric
    # flushes would pay one link round trip each
    mets: list = []

    def gather(n: TpuExec) -> None:
        mets.extend(n.metrics.values())
        for c in n.children:
            gather(c)

    gather(node)
    TpuMetric.flush_many(mets)
    return _snap(node)


def _snap(node: TpuExec) -> NodeSnapshot:
    return NodeSnapshot(
        node.node_desc(),
        {name: m.value for name, m in node.metrics.items()},
        [_snap(c) for c in node.children])


def snapshot_delta(after: NodeSnapshot,
                   before: Optional[NodeSnapshot]) -> NodeSnapshot:
    """Positional per-metric subtraction of two snapshots of ONE exec
    tree (same shape by construction): the per-execution attribution
    for re-drained cached plan trees, whose live metrics accumulate
    across executions.  Numeric metrics subtract (clamped at 0 — a
    concurrent settle between the snapshots must never read as
    negative work); anything else reports the after value."""
    if before is None:
        return after
    mets: dict = {}
    for k, v in after.metrics.items():
        b = before.metrics.get(k)
        if isinstance(v, (int, float)) and isinstance(b, (int, float)) \
            and not isinstance(v, bool):
            mets[k] = max(0, v - b)
        else:
            mets[k] = v
    kids = [snapshot_delta(c, before.children[i]
                           if i < len(before.children) else None)
            for i, c in enumerate(after.children)]
    return NodeSnapshot(after.desc, mets, kids)


class QueryHistory:
    """Session-attached ring of recent QueryEvents.

    `record` snapshots on a background worker: settling device-synced
    timers means waiting for completion notifications, which on remote
    PJRT links can lag the actual result by over a second — that wait
    must not sit on collect()'s critical path.  Every reader drains the
    worker first, so observable history is always consistent."""

    #: ONE process-wide snapshot worker (daemon): per-session pools
    #: would leak a thread per TpuSession for the process lifetime
    _pool = None
    _pool_lock = None

    @classmethod
    def _worker(cls):
        import concurrent.futures
        import threading

        if cls._pool_lock is None:
            cls._pool_lock = threading.Lock()
        with cls._pool_lock:
            if cls._pool is None:
                cls._pool = concurrent.futures.ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="query-history")
            return cls._pool

    def __init__(self, capacity: Optional[int] = None):
        import threading

        if capacity is None:
            from spark_rapids_tpu.config import get_conf

            capacity = int(get_conf().get(HISTORY_CAPACITY))
        self.capacity = capacity
        self._events: list[QueryEvent] = []
        self._pending: list = []
        # guards _pending/_events against caller-thread vs
        # worker/reader races (a reader swapping _pending mid-append
        # would drop a just-recorded snapshot future)
        self._mu = threading.Lock()

    def allocate_id(self) -> int:
        """Claim the next query id BEFORE execution, so trace spans and
        the eventual history event share one correlation key.  Ids are
        process-global: the trace buffer is shared by every session."""
        return next(_QUERY_IDS)

    def record(self, explain: str, exec_tree: Optional[TpuExec],
               wall_s: float, query_id: Optional[int] = None,
               start_ts: float = 0.0, end_ts: float = 0.0,
               start_ns: int = 0, end_ns: int = 0,
               conf_hash: str = "",
               on_event=None, baseline=None) -> None:
        """`on_event(ev)` (optional) runs on the snapshot worker AFTER
        the settled event is appended — the event-log writer's hook:
        it sees device-settled metrics without adding a second settle
        wait to collect()'s critical path.  `baseline` (a settled
        pre-drain NodeSnapshot of the same tree) turns the recorded
        metrics into per-execution deltas — the cached-plan re-drain
        contract; `exec_tree` may be None for queries that executed no
        operators (a result-cache hit), which record a placeholder
        operator node."""
        ts = time.time()
        if query_id is None:
            query_id = next(_QUERY_IDS)

        def snap(qid):
            if exec_tree is None:
                root = NodeSnapshot(
                    "ResultCacheHit [no operators executed]", {}, [])
            else:
                root = snapshot_delta(snapshot_exec(exec_tree),
                                      baseline)
            ev = QueryEvent(qid, explain, root,
                            wall_s, ts, start_ts=start_ts,
                            end_ts=end_ts, start_ns=start_ns,
                            end_ns=end_ns, conf_hash=conf_hash)
            with self._mu:
                self._events.append(ev)
                if len(self._events) > self.capacity:
                    self._events.pop(0)
            if on_event is not None:
                try:
                    on_event(ev)
                except Exception as exc:
                    # a failed event-log append (disk full, revoked
                    # dir) must not poison this future: _drain()
                    # re-raises worker exceptions into EVERY later
                    # history read — explain("analyze"), bench's
                    # final drain — after the query itself succeeded
                    import warnings

                    warnings.warn(
                        f"query-history on_event hook failed for "
                        f"query {qid}: {exc!r}", RuntimeWarning)
        with self._mu:
            # drop settled futures so a never-inspected history stays O(1)
            self._pending = [f for f in self._pending if not f.done()]
            self._pending.append(self._worker().submit(snap, query_id))

    def _drain(self) -> None:
        with self._mu:
            pending, self._pending = self._pending, []
        for f in pending:
            f.result()

    @property
    def events(self) -> list[QueryEvent]:
        self._drain()
        with self._mu:
            return list(self._events)


def _walk_snap(s: NodeSnapshot):
    yield s
    for c in s.children:
        yield from _walk_snap(c)


def _op_key(desc: str) -> str:
    """The exec class name a snapshot desc starts with — the join key
    against trace spans' `op` attribute."""
    return desc.split(" ", 1)[0].split("[", 1)[0]


def _jit_cache_line(cache_stats: Optional[dict]) -> Optional[str]:
    """One-line compile-cache summary (callers pass a PER-QUERY delta
    of jit_cache.cache_stats(), next to the per-miss jit.cache_miss
    trace events)."""
    if cache_stats is None:
        return None
    hits = cache_stats.get("hits", 0)
    misses = cache_stats.get("misses", 0)
    total = hits + misses
    rate = f"{hits / total:.2f}" if total else "n/a"
    return (f"jit cache: hits={hits} misses={misses} "
            f"hit_rate={rate}")


def _counter_footer(counters: Optional[dict]) -> list[str]:
    """Recovery + runtime-filter footer lines (callers pass PER-QUERY
    deltas of execs/retry.retry_stats, robustness/faults recovered
    counts and plan/runtime_filter.stats) — the in-process view of
    exactly the counters the event log persists, so explain("analyze")
    and tools/history can never tell a different story."""
    if not counters:
        return []
    lines = []
    r = counters.get("retry")
    if r is not None:
        line = (f"retry: splits={r.get('splits', 0)} "
                f"spill_retries={r.get('spill_retries', 0)} "
                f"task_retries={r.get('task_retries', 0)} "
                f"cpu_fallbacks={r.get('cpu_fallbacks', 0)}")
        if "faults_recovered" in counters:
            line += (f"; recovered_faults="
                     f"{counters['faults_recovered']}")
        lines.append(line)
    rf = counters.get("rf")
    if rf is not None:
        lines.append(
            f"runtime filters: built={rf.get('filters_built', 0)} "
            f"pruned_rows={rf.get('pruned_rows', 0)} "
            f"row_groups_pruned={rf.get('row_groups_pruned', 0)}")
    pc = counters.get("plan_cache")
    if pc is not None:
        lines.append(
            f"plan cache: hits={pc.get('hits', 0)} "
            f"misses={pc.get('misses', 0)} "
            f"evictions={pc.get('evictions', 0)}")
    return lines


def _ledger_footer(ledger: Optional[dict]) -> list[str]:
    """Device-ledger footer: totals + the top programs by device time
    (callers pass a per-query `trace.ledger.summarize(delta)` — the
    same section the event log persists, so explain("analyze") and
    tools/history read one story)."""
    if not ledger:
        return []
    t = ledger.get("totals") or {}
    roof = t.get("roofline")
    lcr = t.get("live_capacity_ratio")
    lines = [
        f"device ledger: programs={t.get('programs', 0)} "
        f"dispatches={t.get('dispatches', 0)} "
        f"device_ms={t.get('device_ms', 0.0):.2f} "
        f"dispatch_ms={t.get('dispatch_ms', 0.0):.2f} "
        + (f"roofline={roof:.6f}" if roof is not None
           else "roofline=n/a")
        + (f" live/cap={lcr:.2f}" if lcr is not None else "")]
    progs = ledger.get("programs") or {}
    for p in t.get("top") or []:
        # per-program efficiency: cost-model bytes x dispatches over
        # settled busy time, against the HBM peak — plus the occupancy
        # ratio saying how much of that traffic was live rows
        e = progs.get(p["key"]) or {}
        eff = e.get("roofline")
        plcr = p.get("live_capacity_ratio")
        lines.append(
            f"  top: {p['key']} op={p['op'] or '-'} "
            f"dispatches={p['dispatches']} "
            f"device_ms={p['device_ms']:.2f} share={p['share']:.0%}"
            + (f" eff={eff:.6f}" if eff is not None else " eff=n/a")
            + (f" live/cap={plcr:.2f}" if plcr is not None else ""))
    return lines


def profile_query(ev: QueryEvent,
                  trace_events: Optional[Sequence] = None,
                  cache_stats: Optional[dict] = None,
                  counters: Optional[dict] = None,
                  ledger: Optional[dict] = None) -> str:
    """Per-operator metrics table for one query (the Analysis /
    ClassWarehouse per-SQL metrics view).  With `trace_events` (a
    spark_rapids_tpu.trace snapshot), a `self_ms` column reports each
    operator's span-derived self-time: the union of its trace spans for
    this query — time the operator was actively running on SOME thread,
    as opposed to summed per-thread busy time.  With `cache_stats` (a
    per-query jit_cache.cache_stats() delta), a compile-cache hit-rate
    footer rides along."""
    stats: dict = {}
    if trace_events is not None:
        from spark_rapids_tpu.trace.export import span_stats

        stats = span_stats(trace_events, query_id=ev.query_id)
    self_col = " self_ms |" if stats else ""
    lines = [
        f"== Query {ev.query_id} ({ev.wall_s:.3f}s wall) ==",
        "",
        f"| operator | rows | batches | time_ms |{self_col}"
        " other metrics |",
        f"|---|---|---|---|{'---|' if stats else ''}---|",
    ]
    for n in _walk_snap(ev.root):
        m = dict(n.metrics)
        rows = m.pop("numOutputRows", "")
        batches = m.pop("numOutputBatches", "")
        t = m.pop("totalTime", None)
        others = [f"{k}={v}" for k, v in sorted(m.items()) if v]
        t_ms = f"{t / 1e6:.2f}" if t is not None else ""
        extra = ""
        if stats:
            st = stats.get(_op_key(n.desc))
            extra = (f" {st['wall_ns'] / 1e6:.2f} |" if st
                     else "  |")
        lines.append(
            f"| {n.desc[:60]} | {rows} | {batches} | {t_ms} |{extra}"
            f" {' '.join(others)} |")
    footer = ([] if cache_stats is None
              else [_jit_cache_line(cache_stats)])
    footer += _counter_footer(counters)
    footer += _ledger_footer(ledger)
    if footer:
        lines += [""] + footer
    return "\n".join(lines) + "\n"


def render_analyze(ev: QueryEvent,
                   trace_events: Optional[Sequence] = None,
                   cache_stats: Optional[dict] = None,
                   counters: Optional[dict] = None,
                   ledger: Optional[dict] = None) -> str:
    """EXPLAIN ANALYZE: the post-run plan tree, each operator annotated
    with its SETTLED metrics (wall time per device-synced totalTime,
    rows, batches) and — when a trace is available — span-derived
    busy/self/overlap: busy sums this operator's span time across all
    threads, self is the union of those intervals, and overlap =
    busy - self (concurrent execution the aggregate timers hide).
    Span figures aggregate per operator CLASS (spans carry the exec
    name), so two instances of one class — a partial and a final
    aggregate — show the class total on each.  Speculative-sizing
    operators surface their `specHits`/`specOverflows` counters through
    the regular metric annotations — a join showing only specHits ran
    its stream loop sync-free.  `cache_stats` (a per-query
    jit_cache.cache_stats() delta) appends the compile-cache hit
    rate.  `ledger` (a per-query `trace.ledger.summarize(delta)`,
    present when the device ledger is on) adds a per-operator
    ``roofline=`` column — that operator's ATTRIBUTED roofline
    fraction: cost-model bytes x dispatches of the programs it
    compiled, over their settled device time, against the HBM peak —
    plus a top-programs footer (docs/device_ledger.md)."""
    stats: dict = {}
    if trace_events is not None:
        from spark_rapids_tpu.trace.export import span_stats

        stats = span_stats(trace_events, query_id=ev.query_id)
    op_roof: dict = {}
    if ledger:
        from spark_rapids_tpu.trace.ledger import per_op

        op_roof = per_op(ledger.get("programs") or {})
    lines = [f"== Physical Plan (ANALYZE, query {ev.query_id}, "
             f"{ev.wall_s:.3f}s wall) =="]

    def walk(n: NodeSnapshot, indent: int) -> None:
        m = n.metrics
        ann = []
        t = m.get("totalTime")
        if t is not None:
            ann.append(f"time={t / 1e6:.2f}ms")
        ann.append(f"rows={m.get('numOutputRows', 0)}")
        ann.append(f"batches={m.get('numOutputBatches', 0)}")
        st = stats.get(_op_key(n.desc))
        if st:
            ann.append(
                f"span(busy={st['busy_ns'] / 1e6:.2f}ms "
                f"self={st['wall_ns'] / 1e6:.2f}ms "
                f"overlap={st['overlap_ns'] / 1e6:.2f}ms)")
        lr = op_roof.get(_op_key(n.desc))
        if lr:
            # the ledger's attributed per-operator roofline (the
            # column ROADMAP #2's fusion work is judged against)
            ann.append(
                "roofline=" + (f"{lr['roofline']:.6f}"
                               if lr["roofline"] is not None
                               else "n/a")
                + f" device={lr['device_ms']:.2f}ms"
                  f" dispatches={lr['dispatches']}"
                + (f" live/cap={lr['live_capacity_ratio']:.2f}"
                   if lr.get("live_capacity_ratio") is not None
                   else ""))
        extras = {k: v for k, v in m.items()
                  if k not in ("totalTime", "numOutputRows",
                               "numOutputBatches") and v}
        if extras:
            ann.append(" ".join(f"{k}={v}"
                                for k, v in sorted(extras.items())))
        lines.append("  " * indent + "+- " + n.desc
                     + "  [" + " ".join(ann) + "]")
        for c in n.children:
            walk(c, indent + 1)

    walk(ev.root, 0)
    jc = _jit_cache_line(cache_stats)
    if jc is not None:
        lines.append(jc)
    lines.extend(_counter_footer(counters))
    lines.extend(_ledger_footer(ledger))
    return "\n".join(lines) + "\n"


def profile_report(history: QueryHistory) -> str:
    """Whole-session report: store/spill health plus per-query operator
    tables (ProfileMain's aggregate + per-app sections)."""
    from spark_rapids_tpu.memory import get_store

    store = get_store()
    lines = [
        "# Profile report",
        "",
        f"queries: {len(history.events)}",
        "",
        "## Memory / spill health (HealthCheck analog)",
        "",
        f"- device bytes in store: {store.device_used}",
        f"- host bytes in store: {store.host_used}",
        f"- spilled device->host: {store.spilled_device_to_host}",
        f"- spilled host->disk: {store.spilled_host_to_disk}",
        "",
        "## Queries",
        "",
    ]
    for ev in history.events:
        lines.append(profile_query(ev))
    return "\n".join(lines)


@contextlib.contextmanager
def device_trace(trace_dir: str) -> Iterator[None]:
    """Capture an XLA device trace viewable in Perfetto/TensorBoard
    (jax.profiler.trace), the nsys/NVTX workflow analog."""
    import jax

    with jax.profiler.trace(trace_dir):
        yield


def generate_dot(ev: QueryEvent) -> str:
    """SQL-plan DOT graph (GenerateDot.scala analog)."""
    lines = ["digraph plan {", "  node [shape=box fontname=monospace];"]
    ids: dict[int, int] = {}

    def nid(n) -> int:
        if id(n) not in ids:
            ids[id(n)] = len(ids)
        return ids[id(n)]

    for n in _walk_snap(ev.root):
        rows = n.metrics.get("numOutputRows")
        label = n.desc.replace("\\", "\\\\").replace('"', "'")[:80]
        if rows:
            label += f"\\nrows={rows}"
        lines.append(f'  n{nid(n)} [label="{label}"];')
        for c in n.children:
            lines.append(f"  n{nid(c)} -> n{nid(n)};")
    lines.append("}")
    return "\n".join(lines)
