"""Qualification tool: score queries for TPU-acceleration fit.

TPU analog of the reference's qualification tool (tools/src/main/scala/
.../tool/qualification/QualificationMain.scala — scores CPU event logs
for GPU fit without needing a GPU).  Here the input is a DataFrame (or
several): the tool runs ONLY the planner's tagging walk — no execution,
no device — and reports which operators would run on TPU, which fall
back and why, and an eligible-fraction score.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence


@dataclasses.dataclass
class QualReport:
    total_ops: int
    tpu_ops: int
    fallback_ops: int
    reasons: dict[str, int]          # reason -> occurrence count
    explain: str

    @property
    def eligible_fraction(self) -> float:
        return self.tpu_ops / self.total_ops if self.total_ops else 0.0

    @property
    def recommendation(self) -> str:
        f = self.eligible_fraction
        if f >= 0.75:
            return "strongly recommended"
        if f >= 0.5:
            return "recommended"
        if f > 0.0:
            return "partial"
        return "not recommended"


def qualify(df, conf=None) -> QualReport:
    """Tag one DataFrame's plan and score it (plan-only, no execution)."""
    from spark_rapids_tpu.plan.planner import PlanMeta

    if conf is None:
        conf = getattr(getattr(df, "_session", None), "conf", None)
    if conf is None:
        from spark_rapids_tpu.config import get_conf

        conf = get_conf()
    meta = PlanMeta(df._plan, conf)
    meta.tag()
    total = tpu = fb = 0
    reasons: dict[str, int] = {}

    def walk(m):
        nonlocal total, tpu, fb
        total += 1
        if m.can_replace:
            tpu += 1
        else:
            fb += 1
            for r in m.reasons:
                reasons[r] = reasons.get(r, 0) + 1
        for c in m.children:
            walk(c)

    walk(meta)
    return QualReport(total, tpu, fb, reasons, meta.explain())


def qualification_report(dfs: Sequence, names: Optional[Sequence[str]]
                         = None) -> str:
    """Multi-query report (the per-application qualification summary)."""
    names = list(names or [f"query-{i}" for i in range(len(dfs))])
    reports = [qualify(df) for df in dfs]
    lines = [
        "# Qualification report",
        "",
        "| query | operators | on TPU | fallback | eligible | "
        "recommendation |",
        "|---|---|---|---|---|---|",
    ]
    for name, r in zip(names, reports):
        lines.append(
            f"| {name} | {r.total_ops} | {r.tpu_ops} | {r.fallback_ops} "
            f"| {r.eligible_fraction:.0%} | {r.recommendation} |")
    all_reasons: dict[str, int] = {}
    for r in reports:
        for k, v in r.reasons.items():
            all_reasons[k] = all_reasons.get(k, 0) + v
    if all_reasons:
        lines += ["", "## Fallback reasons", ""]
        for k, v in sorted(all_reasons.items(), key=lambda kv: -kv[1]):
            lines.append(f"- {v}x {k}")
    return "\n".join(lines) + "\n"
