"""Aux tooling (ref: the reference's tools/ + doc generation from
registries: RapidsConf.help -> docs/configs.md, TypeChecks ->
docs/supported_ops.md)."""
