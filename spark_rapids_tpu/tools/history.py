"""Cross-run query-history analysis over persisted event logs.

TPU analog of the reference profiling tool's offline side
(tools/.../profiling/ProfileMain.scala): ``ApplicationInfo`` loads one
run's event log (spark_rapids_tpu/eventlog/) into a typed model, and
four analyses operate on one or many of them:

- ``compare``  — CompareApplications: per-query wall-clock and
  per-operator deltas across runs, with a configurable regression
  threshold.  Queries match across runs by *plan fingerprint*
  (normalized-plan hash), so the same query template lines up even
  when query ids and temp paths differ.  Committed ``BENCH_r0*.json``
  and ``SWEEP_r0*.json`` round artifacts load as pseudo-applications,
  so the whole perf trajectory is diffable with one command.
- ``health``   — HealthCheck: a rule registry flagging unhealthy runs
  (CPU fallbacks, retry storms, spill thrash, jit-cache miss-budget
  blowouts, steady-state blocking readbacks, starved pipelines,
  runtime filters that pruned nothing, serving-tier admission waits
  past the conf budget, dispatch-overhead-dominated queries,
  attributed rooflines below budget — those two fed from the device
  ledger's per-query ``programs`` section — cross-tenant
  result-cache thrash from the work-sharing counter deltas, and SLO
  budget breaches recorded by the live ops plane's watchdog, HC016).
- ``report``   — the fleet-style regression report: one markdown
  document with run fingerprints, the compare matrix, the
  work-sharing rollup (when any run engaged the sharing tier), and
  per-run health findings.
- ``dot``      — GenerateDot: the recorded plan as annotated graphviz.

CLI::

    python -m spark_rapids_tpu.tools.history compare  LOG LOG... \
        [--threshold 1.25] [--json] [-o FILE]
    python -m spark_rapids_tpu.tools.history health   LOG... [--json]
    python -m spark_rapids_tpu.tools.history report   LOG LOG... \
        [--threshold 1.25] [-o FILE]
    python -m spark_rapids_tpu.tools.history dot      LOG \
        [--query ID] [-o FILE]

Docs: docs/eventlog.md.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Callable, Optional, Sequence

# -- thresholds (health-rule defaults; compare takes --threshold) ----- #

#: wall-clock ratio at/above which compare flags a per-query regression
DEFAULT_REGRESSION_THRESHOLD = 1.25
#: ladder activity per query that reads as a retry STORM, not a blip
RETRY_STORM_FLOOR = 3
#: per-query device->host spill volume that reads as thrash
SPILL_THRASH_BYTES = 32 << 20
#: per-query compile-cache miss budget (a steady-state query should
#: re-use programs; sustained misses mean shape-bucketing is broken)
JIT_MISS_BUDGET = 16
#: per-query blocking-readback budget (speculative sizing exists to
#: drive the STEADY-STATE count to ~0; warm-up syncs, sort sample
#: fetches and the final result fetch are legitimate, hence the slack)
BLOCKING_READBACK_BUDGET = 32
#: pipeline occupancy below this, with real traffic, means stages ran
#: starved/serial (the items floor keeps tiny unit-test-sized queries
#: from reading as starvation)
OCCUPANCY_FLOOR = 0.05
OCCUPANCY_MIN_ITEMS = 32
#: HC010 (dispatch-overhead-dominated): at/above this many program
#: dispatches in one query AND device time under the share below, the
#: chip idled between launches — fuse chains / bucket shapes instead
DISPATCH_OVERHEAD_FLOOR = 64
DISPATCH_DEVICE_SHARE = 0.2
#: HC011 (roofline below budget) only engages past this much settled
#: device time — a 3ms unit query tells you nothing about the roofline
ROOFLINE_MIN_DEVICE_MS = 50.0
#: HC015 (pad-waste) likewise only engages past this much settled
#: device time — tiny queries legitimately ride part-full buckets
PAD_WASTE_MIN_DEVICE_MS = 50.0


# ------------------------------------------------------------------ #
# Model (the ApplicationInfo analog)
# ------------------------------------------------------------------ #


@dataclasses.dataclass
class OpNode:
    """One recorded operator: desc + settled metrics."""

    desc: str
    metrics: dict
    children: list

    @property
    def op(self) -> str:
        return self.desc.split(" ", 1)[0].split("[", 1)[0]

    def walk(self):
        yield self
        for c in self.children:
            yield from c.walk()


@dataclasses.dataclass
class QueryRecord:
    """One collected query, loaded from a log record."""

    query_id: object
    plan: str
    plan_hash: str
    engine: str
    wall_s: float
    start_ts: float
    end_ts: float
    conf_hash: str
    counters: dict
    operators: Optional[OpNode]
    spans: Optional[dict]
    pipeline: Optional[dict]
    faults: Optional[dict]
    result_digest: Optional[str]
    rows: Optional[int]
    raw: dict
    #: device-ledger attribution ({"programs": {...}, "totals": {...}},
    #: trace/ledger.py) — None when the ledger was off for this query
    programs: Optional[dict] = None
    #: cross-tenant work sharing ({"result_cache": verdict,
    #: "counters": {...}}, serving/work_share.py) — None when the
    #: sharing tier never engaged for this query
    sharing: Optional[dict] = None

    def counter(self, key: str, default: float = 0) -> float:
        return self.counters.get(key, default) or 0

    def program_totals(self) -> dict:
        """The ledger totals for this query ({} when unrecorded)."""
        return (self.programs or {}).get("totals") or {}

    def occupancy(self) -> Optional[float]:
        """Item-weighted pipeline occupancy (bench.py's formula), or
        None when the record carries no pipeline surface."""
        if not self.pipeline:
            return None
        weighted = items = 0.0
        for s in self.pipeline.values():
            n = s.get("items", 0)
            if n:
                weighted += s.get("occupancy_fraction", 0.0) * n
                items += n
        return round(weighted / items, 3) if items else None


@dataclasses.dataclass
class ApplicationInfo:
    """One run: header fingerprint + its query records."""

    path: str
    kind: str  # "eventlog" | "bench" | "sweep"
    header: dict
    queries: list
    #: live-telemetry gauge samples (trace/telemetry.py records), in
    #: file order; empty for bench pseudo-apps and sampler-off runs
    telemetry: list = dataclasses.field(default_factory=list)
    #: SLO breach records (obs/slo.py watchdog emissions), in file
    #: order; HC016's input — empty for watchdog-off runs
    slo: list = dataclasses.field(default_factory=list)

    @property
    def label(self) -> str:
        return os.path.basename(self.path)

    @property
    def conf_hash(self) -> str:
        return self.header.get("conf_hash", "")

    def by_plan(self) -> dict[str, list]:
        out: dict[str, list] = {}
        for q in self.queries:
            out.setdefault(q.plan_hash, []).append(q)
        return out


def _op_from_dict(d: Optional[dict]) -> Optional[OpNode]:
    if not d:
        return None
    return OpNode(d.get("desc", "?"), dict(d.get("metrics", {})),
                  [_op_from_dict(c) for c in d.get("children", [])])


def _query_from_record(rec: dict) -> QueryRecord:
    return QueryRecord(
        query_id=rec.get("query_id"),
        plan=rec.get("plan", ""),
        plan_hash=rec.get("plan_hash", ""),
        engine=rec.get("engine", "tpu"),
        wall_s=float(rec.get("wall_s", 0.0)),
        start_ts=float(rec.get("start_ts", 0.0)),
        end_ts=float(rec.get("end_ts", 0.0)),
        conf_hash=rec.get("conf_hash", ""),
        counters=dict(rec.get("counters", {}) or {}),
        operators=_op_from_dict(rec.get("operators")),
        spans=rec.get("spans"),
        pipeline=rec.get("pipeline"),
        faults=rec.get("faults"),
        result_digest=rec.get("result_digest"),
        rows=rec.get("rows"),
        raw=rec,
        programs=rec.get("programs"),
        sharing=rec.get("sharing"),
    )


# ------------------------------------------------------------------ #
# Loading (event logs + committed bench rounds)
# ------------------------------------------------------------------ #

#: bench queries a BENCH_r0*.json round reports, with their wall field
_BENCH_QUERIES = (("q6", "tpu_s_per_query"),
                  ("q1", "q1_tpu_s_per_query"),
                  ("q3", "q3_tpu_s_per_query"),
                  ("q67", "q67_tpu_s_per_query"))


def load_bench_round(path: str) -> ApplicationInfo:
    """Adapt one committed BENCH_rNN.json round artifact into a
    pseudo-application: one QueryRecord per benchmark query (q6/q1/
    q3/q67) keyed ``bench:<q>`` so rounds line up with each other (and
    never accidentally with real event-log queries)."""
    with open(path) as f:
        data = json.load(f)
    # rounds are stored as the driver's wrapper {"tail": "...json..."}
    # OR as the bare bench.py output line
    if "metric" not in data and isinstance(data.get("tail"), str):
        for line in reversed(data["tail"].splitlines()):
            line = line.strip()
            if line.startswith("{") and '"metric"' in line:
                data = json.loads(line)
                break
    queries = []
    for q, wall_field in _BENCH_QUERIES:
        wall = data.get(wall_field)
        if wall is None:
            continue
        counters = {
            "retry.splits": data.get(f"{q}_retry_splits", 0),
            "retry.cpu_fallbacks": 0,
            "faults.recovered": data.get(f"{q}_recovered_faults", 0),
            "spill.device_to_host_bytes":
                data.get(f"{q}_spills_under_pressure", 0),
            "pipeline.readbacks": data.get(f"{q}_host_sync_count", 0),
        }
        queries.append(QueryRecord(
            query_id=q, plan=f"bench:{q}", plan_hash=f"bench:{q}",
            engine="tpu", wall_s=float(wall),
            start_ts=0.0, end_ts=0.0, conf_hash="",
            counters=counters, operators=None, spans=None,
            pipeline=None, faults=None, result_digest=None,
            rows=data.get(f"{q}_rows") or data.get("rows"),
            raw={k: v for k, v in data.items()
                 if k == "metric" or k.startswith(q)}))
    header = {"session": os.path.basename(path), "conf_hash": "",
              "env": {"link_rtt_ms_median":
                      data.get("link_rtt_ms_median"),
                      "link_upload_mb_s": data.get("link_upload_mb_s")}}
    return ApplicationInfo(path, "bench", header, queries)


def load_sweep_round(path: str) -> ApplicationInfo:
    """Adapt one committed SWEEP_rNN.json artifact (tools/sweep.py)
    into a pseudo-application: one QueryRecord per swept query keyed
    ``sweep:<q>`` (plan fingerprints line rounds up with each other
    and never with real event logs), wall from the verdict's
    ``wall_ms`` — so ``history compare SWEEP_r01.json SWEEP_r02.json``
    diffs sweep rounds exactly like bench rounds.  Old artifacts
    without per-query wall load with wall 0 (they predate the
    field)."""
    with open(path) as f:
        data = json.load(f)
    queries = []
    for name, v in sorted(data.get("queries", {}).items(),
                          key=lambda kv: int(kv[0][1:])):
        queries.append(QueryRecord(
            query_id=name, plan=f"sweep:{name}",
            plan_hash=f"sweep:{name}",
            engine=v.get("status", "unknown"),
            wall_s=float(v.get("wall_ms", 0.0)) / 1e3,
            start_ts=0.0, end_ts=0.0, conf_hash="",
            counters={}, operators=None, spans=None, pipeline=None,
            faults=None, result_digest=None, rows=v.get("rows"),
            raw=v))
    header = {"session": os.path.basename(path), "conf_hash": "",
              "env": {"round": data.get("round"),
                      "scale": data.get("scale"),
                      "totals": data.get("totals")}}
    return ApplicationInfo(path, "sweep", header, queries)


def _is_eventlog_head(head: str) -> bool:
    """True when the sniffed file prefix is an event log: its first
    line is a typed record (the header).  Checked BEFORE the bench/
    sweep keyword sniffs — an `slo` record carries a "metric" field,
    so keyword order alone would misroute a breached run's log into
    the bench-round loader."""
    from spark_rapids_tpu.eventlog.schema import RECORD_TYPES

    try:
        first = json.loads(head.splitlines()[0])
    except (json.JSONDecodeError, IndexError):
        return False
    return isinstance(first, dict) and first.get("type") in RECORD_TYPES


def load_application(path: str) -> ApplicationInfo:
    """Load one run: an event log (.jsonl[.gz]), a committed bench
    round JSON, or a committed sweep round JSON (detected by content,
    not extension)."""
    from spark_rapids_tpu.eventlog.reader import read_log_all

    if not path.endswith(".gz"):
        try:
            with open(path) as f:
                head = f.read(1 << 16).lstrip()
            if head.startswith("{") and not _is_eventlog_head(head):
                if "\"failure_taxonomy\"" in head \
                        or "\"satellite_advances\"" in head:
                    return load_sweep_round(path)
                if "\"metric\"" in head or "\"tail\"" in head:
                    return load_bench_round(path)
        except UnicodeDecodeError:
            pass
    header, recs, telemetry, slo = read_log_all(path)
    return ApplicationInfo(path, "eventlog", header or {},
                           [_query_from_record(r) for r in recs],
                           telemetry=telemetry, slo=slo)


# ------------------------------------------------------------------ #
# compare (the CompareApplications analog)
# ------------------------------------------------------------------ #


def _median_query(qs: Sequence[QueryRecord]) -> QueryRecord:
    """Representative record for repeated runs of one plan: the one
    with the median wall clock (a real record, so operator trees and
    counters stay attached)."""
    qs = sorted(qs, key=lambda q: q.wall_s)
    return qs[len(qs) // 2]


def _query_label(q: QueryRecord) -> str:
    if isinstance(q.query_id, str):
        return q.query_id
    root = q.operators.desc if q.operators else ""
    return f"q{q.query_id} [{root[:40]}]" if root \
        else f"q{q.query_id}"


def _operator_deltas(base: OpNode, run: OpNode,
                     threshold: float) -> list[dict]:
    """Positional walk of two recorded operator trees (same plan hash
    => same shape; a mismatch just truncates), reporting per-operator
    totalTime ratios past the threshold."""
    out: list[dict] = []

    def walk(a: Optional[OpNode], b: Optional[OpNode]) -> None:
        if a is None or b is None or a.op != b.op:
            return
        ta = a.metrics.get("totalTime") or 0
        tb = b.metrics.get("totalTime") or 0
        if ta >= 1e6 and tb >= 1e6:  # ignore sub-ms noise
            ratio = tb / ta
            if ratio >= threshold or ratio <= 1.0 / threshold:
                out.append({
                    "operator": a.desc[:60],
                    "base_ms": round(ta / 1e6, 2),
                    "run_ms": round(tb / 1e6, 2),
                    "ratio": round(ratio, 3),
                })
        for ca, cb in zip(a.children, b.children):
            walk(ca, cb)

    walk(base, run)
    return sorted(out, key=lambda d: -d["ratio"])


def _program_deltas(base: dict, run: dict,
                    threshold: float) -> list[dict]:
    """Per-PROGRAM device-time deltas between two recorded ledger
    sections (the `programs` query-record field): programs match by
    their structural key hash (stable across runs — the key is built
    from expression trees and capacities, never addresses), so a
    regression is pinned to the compiled program that slowed down, not
    just the operator class.  Programs present on only one side are
    reported as appeared/vanished — a changed fusion/bucketing
    decision shows up as churn here before it shows up as wall
    time."""
    bp = (base or {}).get("programs") or {}
    rp = (run or {}).get("programs") or {}
    out: list[dict] = []
    for key in sorted(set(bp) | set(rp)):
        b, r = bp.get(key), rp.get(key)
        if b is None or r is None:
            side = "appeared" if b is None else "vanished"
            p = r or b
            out.append({"program": key, "op": p.get("op"),
                        "change": side,
                        "device_ms": p.get("device_ms", 0.0),
                        "dispatches": p.get("dispatches", 0)})
            continue
        tb, tr = b.get("device_ms", 0.0), r.get("device_ms", 0.0)
        if tb >= 1.0 and tr >= 1.0:  # ignore sub-ms noise
            ratio = tr / tb
            if ratio >= threshold or ratio <= 1.0 / threshold:
                out.append({
                    "program": key, "op": r.get("op"),
                    "change": "ratio",
                    "base_ms": round(tb, 2), "run_ms": round(tr, 2),
                    "ratio": round(ratio, 3),
                    "base_dispatches": b.get("dispatches", 0),
                    "run_dispatches": r.get("dispatches", 0),
                })
    return sorted(out, key=lambda d: -d.get("ratio", 0.0))


def compare_applications(apps: Sequence[ApplicationInfo],
                         threshold: float =
                         DEFAULT_REGRESSION_THRESHOLD) -> dict:
    """Per-query wall-clock (and per-operator) deltas of every app
    against the FIRST (the baseline).  Queries match by plan
    fingerprint; repeated collects of one plan collapse to the
    median-wall record.  Returns a JSON-able result dict."""
    assert len(apps) >= 2, "compare needs a baseline and 1+ runs"
    base = apps[0]
    base_by_plan = {h: _median_query(qs)
                    for h, qs in base.by_plan().items()}
    rows: list[dict] = []
    regressions: list[dict] = []
    unmatched: list[dict] = []
    for app in apps[1:]:
        for h, qs in app.by_plan().items():
            rq = _median_query(qs)
            bq = base_by_plan.get(h)
            if bq is None or bq.wall_s <= 0:
                unmatched.append({"run": app.label,
                                  "query": _query_label(rq),
                                  "plan_hash": h,
                                  "wall_s": round(rq.wall_s, 4)})
                continue
            ratio = rq.wall_s / bq.wall_s
            flag = ("regression" if ratio >= threshold
                    else "improvement" if ratio <= 1.0 / threshold
                    else "ok")
            row = {
                "run": app.label,
                "query": _query_label(rq),
                "plan_hash": h,
                "base_wall_s": round(bq.wall_s, 4),
                "wall_s": round(rq.wall_s, 4),
                "ratio": round(ratio, 3),
                "flag": flag,
                "conf_changed": (bq.conf_hash != rq.conf_hash
                                 and bool(bq.conf_hash)
                                 and bool(rq.conf_hash)),
            }
            if bq.operators and rq.operators:
                row["operator_deltas"] = _operator_deltas(
                    bq.operators, rq.operators, threshold)
            if bq.programs and rq.programs:
                pd = _program_deltas(bq.programs, rq.programs,
                                     threshold)
                if pd:
                    row["program_deltas"] = pd
            rows.append(row)
            if flag == "regression":
                regressions.append(row)
        seen = set(app.by_plan())
        for h, bq in base_by_plan.items():
            if h not in seen:
                unmatched.append({"run": base.label,
                                  "query": _query_label(bq),
                                  "plan_hash": h,
                                  "wall_s": round(bq.wall_s, 4),
                                  "missing_in": app.label})
    return {"baseline": base.label, "threshold": threshold,
            "rows": rows, "regressions": regressions,
            "unmatched": unmatched}


# ------------------------------------------------------------------ #
# health (the HealthCheck analog)
# ------------------------------------------------------------------ #


@dataclasses.dataclass(frozen=True)
class HealthFinding:
    rule: str
    severity: str  # "info" | "warning" | "error"
    query: str
    message: str

    def render(self) -> str:
        return f"{self.severity:7s} {self.rule} {self.query} — " \
               f"{self.message}"


#: rule registry: (rule_id, severity, check(QueryRecord) -> msg|None).
#: Register additional rules with :func:`register_health_rule`.
HEALTH_RULES: list[tuple[str, str,
                         Callable[[QueryRecord], Optional[str]]]] = []


def register_health_rule(rule_id: str, severity: str,
                         check: Callable[[QueryRecord], Optional[str]]
                         ) -> None:
    HEALTH_RULES.append((rule_id, severity, check))


def _hc_cpu_fallback(q: QueryRecord) -> Optional[str]:
    # engine + plan marker only: retry.cpu_fallbacks is a
    # process-global delta, and a CONCURRENT session's fallback
    # bleeding into this query's window must not flag a healthy run
    if q.engine != "tpu" or "[degraded to CPU engine" in q.plan:
        return ("query degraded to the CPU engine — the last ladder "
                "rung fired (docs/robustness.md)")
    return None


def _hc_retry_storm(q: QueryRecord) -> Optional[str]:
    n = q.counter("retry.splits") + q.counter("retry.task_retries")
    if n >= RETRY_STORM_FLOOR:
        return (f"retry storm: {int(q.counter('retry.splits'))} splits"
                f" + {int(q.counter('retry.task_retries'))} task "
                f"retries in one query (floor {RETRY_STORM_FLOOR}) — "
                "the device budget is undersized for this plan")
    return None


def _hc_spill_thrash(q: QueryRecord) -> Optional[str]:
    b = q.counter("spill.device_to_host_bytes")
    if b >= SPILL_THRASH_BYTES:
        disk = q.counter("spill.host_to_disk_bytes")
        msg = (f"spill thrash: {int(b)} device->host bytes in one "
               f"query (floor {SPILL_THRASH_BYTES})")
        if disk:
            msg += f", {int(disk)} of it on to disk"
        return msg
    return None


def _hc_jit_blowout(q: QueryRecord) -> Optional[str]:
    m = q.counter("jit.misses")
    if m > JIT_MISS_BUDGET:
        return (f"jit-cache miss budget blown: {int(m)} compiles in "
                f"one query (budget {JIT_MISS_BUDGET}) — shape "
                "bucketing / fuse keys are not stabilizing")
    return None


def _hc_blocking_readbacks(q: QueryRecord) -> Optional[str]:
    r = q.counter("pipeline.readbacks")
    if r > BLOCKING_READBACK_BUDGET:
        return (f"{int(r)} blocking device->host readbacks (budget "
                f"{BLOCKING_READBACK_BUDGET}) — speculative sizing is "
                "not engaging (docs/speculation.md)")
    return None


def _hc_starved_pipeline(q: QueryRecord) -> Optional[str]:
    occ = q.occupancy()
    if occ is None or not q.pipeline:
        return None
    items = sum(s.get("items", 0) for s in q.pipeline.values())
    if items >= OCCUPANCY_MIN_ITEMS and occ < OCCUPANCY_FLOOR:
        return (f"pipeline occupancy {occ} over {items} items — "
                "stages ran starved/serial (docs/pipeline.md)")
    return None


def _hc_rf_no_prune(q: QueryRecord) -> Optional[str]:
    if q.counter("rf.filters_built") > 0 \
            and q.counter("rf.pruned_rows") == 0 \
            and q.counter("rf.row_groups_pruned") == 0:
        return ("runtime filter built but pruned nothing — build cost "
                "paid for zero wire savings (docs/runtime_filters.md)")
    return None


def _hc_recovered_faults(q: QueryRecord) -> Optional[str]:
    n = q.counter("faults.recovered")
    if n > 0:
        return (f"{int(n)} injected fault(s) recovered in this query "
                "(chaos mode)")
    return None


def _hc_admission_wait(q: QueryRecord) -> Optional[str]:
    """HC009: this query's serving-tier admission wait blew the
    conf budget (spark.rapids.tpu.serving.health.admitWaitBudgetMs) —
    the serving tier is saturated for its traffic.  Fed from the
    serve.admit_wait_ms event-log counter the scheduler deposits per
    query; queries that never passed admission carry no counter and
    stay silent.  bench.py --sessions reports the fleet-level
    admission_wait_p99_ms next to this per-query flag."""
    w = q.counter("serve.admit_wait_ms")
    if w <= 0:
        return None
    from spark_rapids_tpu.config import get_conf
    from spark_rapids_tpu.serving import ADMIT_WAIT_BUDGET_MS

    budget = float(get_conf().get(ADMIT_WAIT_BUDGET_MS))
    if w > budget:
        tenant = ""
        serving = q.raw.get("serving") or {}
        if serving.get("tenant"):
            tenant = f" (tenant {serving['tenant']!r})"
        return (f"admission wait {w:.0f}ms above the "
                f"{budget:.0f}ms budget{tenant} — the serving tier "
                "is saturated; raise serving.maxConcurrent, shed "
                "load, or add replicas (docs/serving.md)")
    return None


def _hc_dispatch_overhead(q: QueryRecord) -> Optional[str]:
    """HC010: dispatch-overhead-dominated query — the ledger recorded
    many program launches but the chip was busy for only a small
    share of the wall, so per-dispatch overhead (trace/compile-cache
    lookup, host argument marshalling, link round trips on tunneled
    backends) dominated.  The fusion/bucketing work of ROADMAP #2
    exists to collapse exactly this shape."""
    totals = q.program_totals()
    disp = totals.get("dispatches") or 0
    device_ms = totals.get("device_ms") or 0.0
    if disp < DISPATCH_OVERHEAD_FLOOR or q.wall_s <= 0:
        return None
    if device_ms < DISPATCH_DEVICE_SHARE * q.wall_s * 1e3:
        return (f"dispatch-overhead-dominated: {int(disp)} program "
                f"dispatches but only {device_ms:.0f}ms device time "
                f"in {q.wall_s * 1e3:.0f}ms wall "
                f"(< {DISPATCH_DEVICE_SHARE:.0%}) — fuse chains / "
                "bucket shapes to cut launches "
                "(docs/device_ledger.md)")
    return None


def _hc_roofline_budget(q: QueryRecord) -> Optional[str]:
    """HC011: attributed roofline below budget — the query's programs
    burned real device time at a device-time-weighted roofline
    fraction under spark.rapids.tpu.trace.ledger.health.rooflineFloor.
    Only fires past ROOFLINE_MIN_DEVICE_MS of settled device time, so
    unit-test-sized queries stay silent."""
    totals = q.program_totals()
    device_ms = totals.get("device_ms") or 0.0
    roofline = totals.get("roofline")
    if roofline is None or device_ms < ROOFLINE_MIN_DEVICE_MS:
        return None
    from spark_rapids_tpu.config import get_conf
    from spark_rapids_tpu.trace.ledger import LEDGER_ROOFLINE_FLOOR

    floor = float(get_conf().get(LEDGER_ROOFLINE_FLOOR))
    if roofline < floor:
        return (f"attributed roofline {roofline:.6f} below the "
                f"{floor} budget over {device_ms:.0f}ms device time — "
                "the chip ran far under its bandwidth roofline for "
                "this plan (docs/device_ledger.md; ROADMAP #2)")
    return None


def _hc_result_cache_thrash(q: QueryRecord) -> Optional[str]:
    """HC012: cross-tenant result-cache thrash — this query's window
    evicted more cached results than it served while the hit rate sat
    under spark.rapids.tpu.serving.resultCache.health.minHitRate: the
    cache budget is too small for the fleet's working set, so entries
    churn host/disk bytes without ever amortizing device work.  Fed
    from the per-query share.* counter deltas the event log records
    (docs/work_sharing.md); sharing-off fleets carry no deltas and
    stay silent."""
    ev = q.counter("share.result_evictions")
    hits = q.counter("share.result_hits")
    misses = q.counter("share.result_misses")
    window = hits + misses
    if ev <= hits or window <= 0:
        return None
    from spark_rapids_tpu.config import get_conf
    from spark_rapids_tpu.serving.work_share import RESULT_MIN_HIT_RATE

    floor = float(get_conf().get(RESULT_MIN_HIT_RATE))
    rate = hits / window
    if rate < floor:
        return (f"result-cache thrash: {int(ev)} eviction(s) against "
                f"{int(hits)} hit(s) at a {rate:.2f} hit rate "
                f"(< {floor}) — the cache budget "
                "(serving.resultCache.budgetBytes) is too small for "
                "the fleet's working set (docs/work_sharing.md)")
    return None


def _hc_cancellation_leak(q: QueryRecord) -> Optional[str]:
    """HC013: cancellation-storm health.  Two triggers:

    (a) a CANCELLED query record (engine "cancelled" /
    "deadline_exceeded") whose end-of-query residency gauges —
    semaphore permits in use, live pipeline stage threads, in-flight
    shared-scan entries — did not return to zero: the unwind leaked.
    The gauges are process-wide, so a concurrent fleet may carry
    another query's residency here (warning severity for that
    reason); in a serialized storm replay a nonzero reading is a real
    leak (docs/robustness.md).

    (b) any query window whose cancel.breaker_trips counter delta
    exceeds spark.rapids.tpu.serving.breaker.health.maxTrips —
    tenants are crash-looping into quarantine faster than the fleet
    should tolerate (docs/serving.md)."""
    if q.engine in ("cancelled", "deadline_exceeded"):
        leaked = {g: int(q.counter(g)) for g in
                  ("semaphore.in_use", "pipeline.stage_threads",
                   "scan.inflight")
                  if q.counter(g) > 0}
        if leaked:
            return (f"{q.engine} query left nonzero residency gauges "
                    f"{leaked} at query end — the cooperative unwind "
                    "leaked (or a concurrent query held residency); "
                    "permits/stage threads/scan shares must return "
                    "to baseline (docs/robustness.md)")
    trips = q.counter("cancel.breaker_trips")
    if trips > 0:
        from spark_rapids_tpu.config import get_conf
        from spark_rapids_tpu.serving.cancel import BREAKER_MAX_TRIPS

        budget = int(get_conf().get(BREAKER_MAX_TRIPS))
        if trips > budget:
            return (f"{int(trips)} circuit-breaker trip(s) in this "
                    f"query window (> {budget} budget, "
                    "serving.breaker.health.maxTrips) — a tenant is "
                    "crash-looping into quarantine "
                    "(docs/serving.md)")
    return None


def _hc_lock_hold(q: QueryRecord) -> Optional[str]:
    """HC014: tracked-lock hold over budget.  Only queries run with
    the lock tracker armed (robustness.lockTracker.enabled) carry a
    nonzero lock.max_hold_ms gauge; a reading over
    spark.rapids.tpu.robustness.lockTracker.holdBudgetMs means some
    engine registry mutex (plan cache, scan-share registry, breaker
    table, ...) was held long enough to serialize every thread
    population behind it during this query (docs/concurrency.md)."""
    hold_ms = q.counter("lock.max_hold_ms")
    if hold_ms <= 0:
        return None
    from spark_rapids_tpu.config import get_conf
    from spark_rapids_tpu.robustness.lock_tracker import (
        LOCK_HOLD_BUDGET_MS,
    )

    budget = float(get_conf().get(LOCK_HOLD_BUDGET_MS))
    if hold_ms > budget:
        extra = ""
        cycles = q.counter("lock.cycles")
        if cycles > 0:
            extra = (f"; {int(cycles)} lock-order cycle(s) were also "
                     "detected in this window")
        return (f"a tracked engine lock was held for {hold_ms:.1f}ms "
                f"(> {budget:g}ms budget, "
                "robustness.lockTracker.holdBudgetMs) — long registry "
                "holds serialize the fleet behind one mutex"
                f"{extra} (docs/concurrency.md)")
    return None


def _hc_pad_waste(q: QueryRecord) -> Optional[str]:
    """HC015: pad-waste — the query's dispatches carried live rows
    for under spark.rapids.tpu.trace.ledger.health.occupancyFloor of
    their padded capacity while burning real device time (>=
    PAD_WASTE_MIN_DEVICE_MS settled): most of what the chip read was
    padding.  Coalesce small batches or switch the capacity policy to
    densify (docs/occupancy.md)."""
    totals = q.program_totals()
    device_ms = totals.get("device_ms") or 0.0
    ratio = totals.get("live_capacity_ratio")
    if ratio is None or device_ms < PAD_WASTE_MIN_DEVICE_MS:
        return None
    from spark_rapids_tpu.config import get_conf
    from spark_rapids_tpu.trace.ledger import LEDGER_OCCUPANCY_FLOOR

    floor = float(get_conf().get(LEDGER_OCCUPANCY_FLOOR))
    if ratio < floor:
        return (f"pad-waste: live/capacity ratio {ratio:.2f} below "
                f"the {floor:g} floor over {device_ms:.0f}ms device "
                "time — programs mostly processed padding; enable "
                "sql.coalesce.enabled or capacity.policy=pow2x3 "
                "(docs/occupancy.md)")
    return None


def _hc_persist_low_hit(q: QueryRecord) -> Optional[str]:
    """HC017: cold process, warm disk cache, but the warm-start
    program store mostly missed — this query's window probed the
    persist tier (persist.hits + persist.misses > 0), still paid real
    XLA compiles (jit.compiles > 0), and its persist hit rate sat
    under spark.rapids.tpu.persist.health.minHitRate.  The serialized
    artifacts did not match this process: stale entries (jax/jaxlib
    upgrade, different device fingerprint, conf drift splitting the
    fingerprint) or a wrong persist.dir (docs/warm_start.md).
    Persist-off fleets carry no persist.* deltas and stay silent."""
    hits = q.counter("persist.hits")
    misses = q.counter("persist.misses")
    window = hits + misses
    compiles = q.counter("jit.compiles")
    if window <= 0 or compiles <= 0:
        return None
    from spark_rapids_tpu.config import get_conf
    from spark_rapids_tpu.persist import PERSIST_MIN_HIT_RATE

    floor = float(get_conf().get(PERSIST_MIN_HIT_RATE))
    rate = hits / window
    if rate < floor:
        return (f"warm-start cache mostly missed: persist hit rate "
                f"{rate:.2f} (< {floor}) with {int(compiles)} real "
                "compile(s) in this window — disk entries are stale "
                "(jax/device/conf drift) or persist.dir is wrong "
                "(docs/warm_start.md)")
    return None


for _id, _sev, _fn in (
        ("HC001", "error", _hc_cpu_fallback),
        ("HC002", "warning", _hc_retry_storm),
        ("HC003", "warning", _hc_spill_thrash),
        ("HC004", "warning", _hc_jit_blowout),
        ("HC005", "warning", _hc_blocking_readbacks),
        ("HC006", "warning", _hc_starved_pipeline),
        ("HC007", "warning", _hc_rf_no_prune),
        ("HC008", "info", _hc_recovered_faults),
        ("HC009", "warning", _hc_admission_wait),
        ("HC010", "warning", _hc_dispatch_overhead),
        ("HC011", "warning", _hc_roofline_budget),
        ("HC012", "warning", _hc_result_cache_thrash),
        ("HC013", "warning", _hc_cancellation_leak),
        ("HC014", "warning", _hc_lock_hold),
        ("HC015", "warning", _hc_pad_waste),
        ("HC017", "warning", _hc_persist_low_hit)):
    register_health_rule(_id, _sev, _fn)


def _hc016_slo_breaches(app: ApplicationInfo) -> list[HealthFinding]:
    """HC016: SLO budget breach — the obs watchdog (obs/slo.py)
    recorded a tenant's rolling percentile over its
    spark.rapids.tpu.obs.slo.* budget during this run.  Unlike
    HC001-HC015 this rule reads the run-level ``slo`` records, not a
    QueryRecord: one finding per (tenant, metric) pair summarizing the
    worst observed value, so a sustained breach doesn't flood the
    report with one line per watchdog tick (docs/ops_plane.md)."""
    worst: dict[tuple[str, str], dict] = {}
    count: dict[tuple[str, str], int] = {}
    for rec in app.slo:
        key = (rec.get("tenant") or "", rec.get("metric") or "")
        count[key] = count.get(key, 0) + 1
        prev = worst.get(key)
        if prev is None or rec.get("observed_ms", 0.0) \
                > prev.get("observed_ms", 0.0):
            worst[key] = rec
    out = []
    for (tenant, metric), rec in sorted(worst.items()):
        n = count[(tenant, metric)]
        out.append(HealthFinding(
            "HC016", "warning", f"tenant:{tenant or 'default'}",
            f"SLO breach: {metric} reached "
            f"{rec.get('observed_ms', 0.0):.0f}ms against a "
            f"{rec.get('budget_ms', 0.0):.0f}ms budget "
            f"({n} breach record(s) over a "
            f"{rec.get('window', 0)}-observation window) — "
            "the tenant ran over its obs.slo.* budget "
            "(docs/ops_plane.md)"))
    return out


def health_check(app: ApplicationInfo) -> list[HealthFinding]:
    """Run every registered rule over every query of one run, plus
    the run-level rules (HC016, fed from the SLO breach records)."""
    out: list[HealthFinding] = []
    for q in app.queries:
        for rule_id, severity, check in HEALTH_RULES:
            msg = check(q)
            if msg is not None:
                out.append(HealthFinding(rule_id, severity,
                                         _query_label(q), msg))
    out.extend(_hc016_slo_breaches(app))
    return out


# ------------------------------------------------------------------ #
# report (the fleet-style regression report)
# ------------------------------------------------------------------ #


def _fmt_ratio(row: dict) -> str:
    mark = {"regression": " ⚠ REGRESSION", "improvement": " ✓",
            "ok": ""}[row["flag"]]
    extra = " (conf changed)" if row.get("conf_changed") else ""
    return f"{row['ratio']:.3f}x{mark}{extra}"


def render_compare_md(result: dict) -> str:
    lines = [
        f"## Compare (baseline: {result['baseline']}, "
        f"threshold {result['threshold']}x)",
        "",
        "| run | query | base_s | run_s | ratio |",
        "|---|---|---|---|---|",
    ]
    for row in result["rows"]:
        lines.append(
            f"| {row['run']} | {row['query']} | {row['base_wall_s']} "
            f"| {row['wall_s']} | {_fmt_ratio(row)} |")
    for row in result["rows"]:
        for od in row.get("operator_deltas", []):
            lines.append(
                f"- {row['run']} / {row['query']}: "
                f"`{od['operator']}` {od['base_ms']}ms -> "
                f"{od['run_ms']}ms ({od['ratio']}x)")
        for pd in row.get("program_deltas", []):
            if pd["change"] == "ratio":
                lines.append(
                    f"- {row['run']} / {row['query']}: program "
                    f"`{pd['program']}` ({pd['op']}) "
                    f"{pd['base_ms']}ms -> {pd['run_ms']}ms "
                    f"({pd['ratio']}x, "
                    f"{pd['base_dispatches']}->"
                    f"{pd['run_dispatches']} dispatches)")
            else:
                lines.append(
                    f"- {row['run']} / {row['query']}: program "
                    f"`{pd['program']}` ({pd['op']}) {pd['change']} "
                    f"({pd['device_ms']}ms, "
                    f"{pd['dispatches']} dispatches)")
    if result["unmatched"]:
        lines += ["", "Unmatched queries (no counterpart run):"]
        for u in result["unmatched"]:
            lines.append(f"- {u['run']}: {u['query']} "
                         f"({u['wall_s']}s)")
    n = len(result["regressions"])
    lines += ["", f"**{n} regression(s) at >= "
                  f"{result['threshold']}x**" if n else
              "No regressions at the threshold."]
    return "\n".join(lines) + "\n"


def render_health_md(apps: Sequence[ApplicationInfo]) -> str:
    lines = ["## Health"]
    for app in apps:
        findings = health_check(app)
        lines += ["", f"### {app.label}", ""]
        if not findings:
            lines.append("no findings — run is healthy")
            continue
        for f in findings:
            lines.append(f"- **{f.rule}** ({f.severity}) {f.query}: "
                         f"{f.message}")
    return "\n".join(lines) + "\n"


def render_sharing_md(apps: Sequence[ApplicationInfo]) -> str:
    """The cross-tenant work-sharing section (docs/work_sharing.md):
    per run, the result-cache verdict mix and the shared-scan dedup
    evidence aggregated from each query's share.* counter deltas.
    Empty string when no run ever engaged the sharing tier, so
    sharing-off fleets see no section at all."""
    rows = []
    for app in apps:
        agg = {"hits": 0, "misses": 0, "evictions": 0,
               "invalidations": 0, "units_shared": 0,
               "units_decoded": 0, "rows_decoded": 0}
        served = 0
        for q in app.queries:
            if q.sharing is not None:
                served += 1
            agg["hits"] += int(q.counter("share.result_hits"))
            agg["misses"] += int(q.counter("share.result_misses"))
            agg["evictions"] += int(
                q.counter("share.result_evictions"))
            agg["invalidations"] += int(
                q.counter("share.result_invalidations"))
            agg["units_shared"] += int(
                q.counter("share.scan_units_shared"))
            agg["units_decoded"] += int(
                q.counter("share.scan_units_decoded"))
            agg["rows_decoded"] += int(
                q.counter("share.scan_rows_decoded"))
        if served or any(agg.values()):
            rows.append((app.label, served, agg))
    if not rows:
        return ""
    lines = ["## Work sharing", "",
             "| run | shared queries | hits | misses | hit rate | "
             "evictions | invalidations | scan units shared | "
             "scan units decoded |",
             "|---|---|---|---|---|---|---|---|---|"]
    for label, served, a in rows:
        total = a["hits"] + a["misses"]
        rate = f"{a['hits'] / total:.2f}" if total else "-"
        lines.append(
            f"| {label} | {served} | {a['hits']} | {a['misses']} | "
            f"{rate} | {a['evictions']} | {a['invalidations']} | "
            f"{a['units_shared']} | {a['units_decoded']} |")
    return "\n".join(lines) + "\n"


def render_report(apps: Sequence[ApplicationInfo],
                  threshold: float = DEFAULT_REGRESSION_THRESHOLD
                  ) -> str:
    """The full fleet-style markdown report: run fingerprints, the
    cross-run compare, the work-sharing rollup (when any run engaged
    the sharing tier), per-run health."""
    lines = ["# Fleet regression report", "",
             "| run | kind | queries | conf hash | jax | devices |",
             "|---|---|---|---|---|---|"]
    for app in apps:
        env = app.header.get("env", {}) or {}
        devs = env.get("devices") or []
        dev = f"{len(devs)}x {devs[0]['platform']}" if devs else ""
        lines.append(
            f"| {app.label} | {app.kind} | {len(app.queries)} | "
            f"{app.conf_hash or '-'} | {env.get('jax') or '-'} | "
            f"{dev or '-'} |")
    lines.append("")
    if len(apps) >= 2:
        lines.append(render_compare_md(
            compare_applications(apps, threshold)))
    sharing = render_sharing_md(apps)
    if sharing:
        lines.append(sharing)
    lines.append(render_health_md(apps))
    return "\n".join(lines)


# ------------------------------------------------------------------ #
# dot (the GenerateDot analog)
# ------------------------------------------------------------------ #


def generate_dot(q: QueryRecord) -> str:
    """Annotated plan graph for one recorded query (rows + wall time
    per operator, health-relevant counters in the graph label)."""
    lines = ["digraph plan {",
             "  node [shape=box fontname=monospace];",
             f'  label="query {q.query_id} — {q.wall_s:.3f}s wall '
             f'({q.engine})";']
    if q.operators is None:
        lines.append('  n0 [label="(no operator snapshot recorded)"];')
        lines.append("}")
        return "\n".join(lines)
    ids: dict[int, int] = {}

    def nid(n: OpNode) -> int:
        if id(n) not in ids:
            ids[id(n)] = len(ids)
        return ids[id(n)]

    for n in q.operators.walk():
        label = n.desc.replace("\\", "\\\\").replace('"', "'")[:80]
        rows = n.metrics.get("numOutputRows")
        t = n.metrics.get("totalTime")
        if rows:
            label += f"\\nrows={rows}"
        if t:
            label += f"\\ntime={t / 1e6:.2f}ms"
        lines.append(f'  n{nid(n)} [label="{label}"];')
        for c in n.children:
            lines.append(f"  n{nid(c)} -> n{nid(n)};")
    lines.append("}")
    return "\n".join(lines)


# ------------------------------------------------------------------ #
# CLI
# ------------------------------------------------------------------ #


def _write_out(text: str, out: Optional[str]) -> None:
    if out:
        with open(out, "w") as f:
            f.write(text)
        print(f"wrote {out}")
    else:
        print(text)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m spark_rapids_tpu.tools.history",
        description="event-log analysis: compare / health / report / "
                    "dot (docs/eventlog.md)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("compare", help="per-query deltas across runs")
    p.add_argument("logs", nargs="+",
                   help="event logs or BENCH_r*.json (first = baseline)")
    p.add_argument("--threshold", type=float,
                   default=DEFAULT_REGRESSION_THRESHOLD)
    p.add_argument("--json", action="store_true")
    p.add_argument("-o", "--out", default=None)

    p = sub.add_parser("health", help="flag unhealthy runs")
    p.add_argument("logs", nargs="+")
    p.add_argument("--json", action="store_true")
    p.add_argument("-o", "--out", default=None)

    p = sub.add_parser("report",
                       help="markdown fleet regression report")
    p.add_argument("logs", nargs="+")
    p.add_argument("--threshold", type=float,
                   default=DEFAULT_REGRESSION_THRESHOLD)
    p.add_argument("-o", "--out", default=None)

    p = sub.add_parser("dot", help="annotated plan graphviz")
    p.add_argument("logs", nargs=1)
    p.add_argument("--query", type=int, default=None,
                   help="query id (default: the slowest query)")
    p.add_argument("-o", "--out", default=None)

    args = ap.parse_args(argv)
    apps = [load_application(p) for p in args.logs]

    if args.cmd == "compare":
        if len(apps) < 2:
            ap.error("compare needs >= 2 logs")
        result = compare_applications(apps, args.threshold)
        text = json.dumps(result, indent=1) if args.json \
            else render_compare_md(result)
        _write_out(text, args.out)
        return 1 if result["regressions"] else 0
    if args.cmd == "health":
        findings = {app.label: health_check(app) for app in apps}
        if args.json:
            text = json.dumps(
                {k: [dataclasses.asdict(f) for f in v]
                 for k, v in findings.items()}, indent=1)
        else:
            text = render_health_md(apps)
        _write_out(text, args.out)
        return 1 if any(f.severity == "error"
                        for v in findings.values() for f in v) else 0
    if args.cmd == "report":
        _write_out(render_report(apps, args.threshold), args.out)
        return 0
    # dot
    app = apps[0]
    if not app.queries:
        ap.error(f"{app.label} holds no query records")
    if args.query is not None:
        q = next((q for q in app.queries
                  if q.query_id == args.query), None)
        if q is None:
            ap.error(f"query id {args.query} not in {app.label}")
    else:
        q = max(app.queries, key=lambda q: q.wall_s)
    _write_out(generate_dot(q), args.out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
