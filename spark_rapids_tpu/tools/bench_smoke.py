"""Bench smoke: one tiny query per hot exec (join, aggregate,
exchange), each collected with speculative sizing ON and OFF, asserting
result equality.

The acceptance contract of the speculation layer is that it is a pure
latency optimization — `speculation.enabled=false` must reproduce the
same results bit-for-bit.  This driver is the cheap CI hook for that
contract: `scripts/bench_smoke.sh` runs it standalone, and
`tests/test_speculation.py::test_bench_smoke_queries_match` runs the
same function inside the tier-1 `not slow` suite.

`run_rf_smoke` holds the twin contract for runtime join filters
(plan/runtime_filter.py): a parquet-backed q3-shaped join must return
identical rows with `runtimeFilter.enabled` on and off, AND must have
actually pruned probe rows when on (tier-1 via
tests/test_runtime_filter.py).

`run_eventlog_smoke` holds the persistence contract for the event log
(spark_rapids_tpu/eventlog/): a query collected with
`eventLog.enabled` must reload through tools/history with per-operator
metrics identical to the session's settled QueryHistory snapshot
(tier-1 via tests/test_eventlog.py).

`run_ledger_smoke` holds the device-ledger contract
(spark_rapids_tpu/trace/ledger.py, docs/device_ledger.md): a tiny
query collected with `trace.ledger.enabled` must attribute at least
one program with nonzero cost-model bytes and dispatch count, and the
attributed device time must stay within the query wall (tier-1 via
tests/test_ledger.py).

`run_serving_smoke` holds the serving-tier contract
(spark_rapids_tpu/serving/, docs/serving.md): a prepared template's
second execution is a plan-cache hit that never re-enters plan_query,
a streamed fetch equals collect() to the bit, and two sessions under
maxConcurrent=1 admission both complete with identical digests
(tier-1 via tests/test_serving.py).

`run_ops_smoke` holds the live ops-plane contract
(spark_rapids_tpu/obs/, docs/ops_plane.md): with `obs.enabled` a real
HTTP scrape of /metrics must parse as OpenMetrics and EQUAL the
in-process counters_snapshot (the registry-adapter parity gate), the
live query registry must empty back to zero after the query, and
turning the conf off must leave no ops thread and no listening socket
(tier-1 via tests/test_obs.py).

`run_sharing_smoke` holds the cross-tenant work-sharing contract
(serving/work_share.py, docs/work_sharing.md): a second session's
identical parquet-backed template performs ZERO scan decodes (tapped
counter), its digest is bit-identical to sharing-off and to serial,
and rewriting the input file invalidates the cached result on the
content-digest change (tier-1 via tests/test_work_share.py).

Run: python -m spark_rapids_tpu.tools.bench_smoke
"""

from __future__ import annotations


def _queries(session):
    """(name, DataFrame) per hot exec, tiny enough for seconds-scale
    CPU runs but multi-batch so the stream loops actually stream."""
    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.session import col, sum_

    rng = np.random.default_rng(0x5BEC)
    n = 4096
    lineitem = pa.table({
        "k": rng.integers(0, 64, n).astype(np.int64),
        "v": rng.random(n),
    })
    dim = pa.table({
        "k": np.arange(64, dtype=np.int64),
        "w": rng.integers(0, 9, 64).astype(np.int64),
    })
    li = session.create_dataframe(lineitem)
    joined = li.join(session.create_dataframe(dim),
                     left_on=[col("k")], right_on=[col("k")])
    yield "join", joined
    yield "aggregate", li.group_by(col("k")).agg((sum_(col("v")), "sv"))
    # the grouped aggregate above plans partial -> exchange -> final;
    # an ORDER BY adds the range-partitioned exchange shape too
    yield "exchange", (li.group_by(col("k"))
                       .agg((sum_(col("v")), "sv"))
                       .order_by(col("k")))


def _assert_rows_match(name: str, on, off) -> None:
    """Row-set equality with float tolerance: the engine documents
    run-to-run float aggregation order variability
    (spark.rapids.tpu.sql.variableFloatAgg.enabled), so exact float
    equality would flake at the ULP level regardless of speculation."""
    assert on.num_rows == off.num_rows, (name, on.num_rows,
                                         off.num_rows)
    on_rows = sorted(map(tuple, zip(*on.to_pydict().values())))
    off_rows = sorted(map(tuple, zip(*off.to_pydict().values())))
    for a, b in zip(on_rows, off_rows):
        for x, y in zip(a, b):
            if isinstance(x, float):
                assert abs(x - y) <= 1e-9 * max(1.0, abs(y)), \
                    f"{name}: speculation on/off results differ: {a} {b}"
            else:
                assert x == y, \
                    f"{name}: speculation on/off results differ: {a} {b}"


def count_upload_rows(df) -> int:
    """One TPU collect with ParquetScanExec._upload tapped: total rows
    actually crossing the host->device wire — the number runtime join
    filters exist to shrink.  Shared by bench.py's q3_upload_rows
    fields and the test-suite acceptance assertions."""
    import spark_rapids_tpu.io.scan as scan_mod

    counted = [0]
    orig = scan_mod.ParquetScanExec._upload

    def upload(inner_self, tables):
        counted[0] += sum(t.num_rows for t in tables
                          if not isinstance(t, int))
        return orig(inner_self, tables)

    scan_mod.ParquetScanExec._upload = upload
    try:
        df.collect(engine="tpu")
    finally:
        scan_mod.ParquetScanExec._upload = orig
    return counted[0]


def count_upload_bytes(df) -> int:
    """One TPU collect over the tapped batched-upload counter
    (columnar/transfer.upload_stats): total bytes actually crossing
    the H2D wire — compressed components count their packed size, so
    the wire-codec on/off delta IS the bytes the codec kept off the
    slow link.  Shared by bench.py's q*_upload_bytes_wire /
    q*_upload_ratio fields and the wire-codec acceptance tests."""
    from spark_rapids_tpu.columnar import transfer

    transfer.reset_upload_stats()
    df.collect(engine="tpu")
    return transfer.upload_stats()["wire_bytes"]


def run_rf_smoke() -> dict:
    """Runtime-filter acceptance contract, cheap CI form: a q3-shaped
    parquet join (date-filtered build side, larger probe side)
    collected with spark.rapids.tpu.sql.runtimeFilter.enabled on and
    off must return identical rows — the filter is a pure IO
    optimization.  With filters on, the probe scan must actually have
    pruned rows (asserted via the runtime_filter stats registry), so
    the q3 win this subsystem targets stays measurable."""
    import os
    import tempfile

    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_tpu.config import get_conf
    from spark_rapids_tpu.exprs.base import lit
    from spark_rapids_tpu.plan import runtime_filter
    from spark_rapids_tpu.session import TpuSession, col, sum_

    key = "spark.rapids.tpu.sql.runtimeFilter.enabled"
    conf = get_conf()
    saved = conf.get(key)
    session = TpuSession()
    out: dict = {}
    rng = np.random.default_rng(0xF11)
    with tempfile.TemporaryDirectory(prefix="rf_smoke_") as d:
        n = 8192
        li = pa.table({
            "l_orderkey": rng.integers(0, 512, n).astype(np.int64),
            "l_price": rng.random(n),
        })
        li_path = os.path.join(d, "li.parquet")
        pq.write_table(li, li_path, row_group_size=2048)
        orders = pa.table({
            "o_orderkey": np.arange(512, dtype=np.int64),
            "o_date": rng.integers(0, 100, 512).astype(np.int32),
        })
        o_path = os.path.join(d, "orders.parquet")
        pq.write_table(orders, o_path)

        def q():
            lidf = session.read_parquet(li_path)
            odf = (session.read_parquet(o_path)
                   .where(col("o_date") < lit(20)))
            return (lidf.join(odf, left_on=[col("l_orderkey")],
                              right_on=[col("o_orderkey")])
                    .group_by(col("l_orderkey"))
                    .agg((sum_(col("l_price")), "rev")))

        try:
            conf.set(key, True)
            runtime_filter.reset_stats()
            on = q().collect(engine="tpu")
            st = runtime_filter.stats()
            assert st["filters_built"] >= 1, \
                "runtime filter did not build on the q3-shaped join"
            assert st["pruned_rows"] > 0, \
                "runtime filter pruned nothing on a selective build"
            conf.set(key, False)
            off = q().collect(engine="tpu")
            _assert_rows_match("runtime_filter", on, off)
            out["runtime_filter"] = on.num_rows
            out["runtime_filter_pruned_rows"] = st["pruned_rows"]
        finally:
            conf.set(key, saved)
    return out


def run_eventlog_smoke() -> dict:
    """Event-log acceptance contract, cheap CI form (tier-1 via
    tests/test_eventlog.py): a tiny grouped aggregate collected with
    ``spark.rapids.tpu.eventLog.enabled`` must produce a log that
    reloads through tools/history into an ApplicationInfo whose
    per-operator metric tree EQUALS the session's settled QueryHistory
    snapshot — what the file says must be what the process measured."""
    import os
    import tempfile

    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.config import get_conf
    from spark_rapids_tpu.session import TpuSession, col, sum_
    from spark_rapids_tpu.tools.history import load_application

    conf = get_conf()
    keys = ("spark.rapids.tpu.eventLog.enabled",
            "spark.rapids.tpu.eventLog.dir")
    saved = {k: conf.get(k) for k in keys}
    out: dict = {}
    with tempfile.TemporaryDirectory(prefix="eventlog_smoke_") as d:
        try:
            conf.set(keys[0], True)
            conf.set(keys[1], os.path.join(d, "log"))
            session = TpuSession()
            rng = np.random.default_rng(0xE7)
            n = 2048
            t = pa.table({
                "k": rng.integers(0, 32, n).astype(np.int64),
                "v": rng.random(n),
            })
            df = (session.create_dataframe(t)
                  .group_by(col("k"))
                  .agg((sum_(col("v")), "sv")))
            result = df.collect(engine="tpu")
            # reading events DRAINS the snapshot worker, which also
            # appends the event-log record — the file is complete now
            ev = session.history.events[-1]
            app = load_application(session.event_log_path)
            assert app.header, "event log is missing its header record"
            assert len(app.queries) == 1, len(app.queries)
            q = app.queries[0]
            assert q.query_id == ev.query_id, (q.query_id, ev.query_id)
            assert q.rows == result.num_rows, (q.rows, result.num_rows)
            assert q.conf_hash == ev.conf_hash and q.conf_hash

            def check(node, snap):
                assert node.desc == snap.desc, (node.desc, snap.desc)
                assert node.metrics == snap.metrics, \
                    (node.desc, node.metrics, snap.metrics)
                assert len(node.children) == len(snap.children)
                for c, sc in zip(node.children, snap.children):
                    check(c, sc)

            check(q.operators, ev.root)
            out["eventlog"] = q.rows
            out["eventlog_operators"] = sum(
                1 for _ in q.operators.walk())
        finally:
            for k, v in saved.items():
                conf.set(k, v)
    return out


def run_serving_smoke() -> dict:
    """Serving-tier acceptance contract, cheap CI form (tier-1 via
    tests/test_serving.py): two concurrent sessions under admission
    control (maxConcurrent=1, so one of them measurably waits), a
    prepared SQL template whose SECOND execution is a plan-cache hit
    that performs no plan/tag/lower work, and a streamed fetch whose
    concatenation equals collect() to the bit."""
    import threading

    import pyarrow as pa

    import numpy as np

    from spark_rapids_tpu.config import TpuConf, get_conf, set_conf
    from spark_rapids_tpu.eventlog import table_digest
    from spark_rapids_tpu.frontends.sql import SqlSession
    from spark_rapids_tpu.serving import plan_cache as plan_cache_mod
    from spark_rapids_tpu.serving import scheduler as scheduler_mod
    from spark_rapids_tpu.plan import planner as planner_mod

    rng = np.random.default_rng(0x5E17)
    n = 4096
    t = pa.table({
        "k": rng.integers(0, 32, n).astype(np.int64),
        "v": rng.integers(0, 1000, n).astype(np.int64),
    })
    out: dict = {}
    base = dict(get_conf()._values)
    scheduler_mod.reset()
    plan_cache_mod.reset_stats()
    try:
        # -- prepared SQL template: second execution must be a HIT
        # that never re-enters plan_query -------------------------- #
        conf = TpuConf(base)
        set_conf(conf)
        ss = SqlSession(conf)
        ss.register_table("t", t)
        pq = ss.prepare("select k, sum(v) as sv, count(*) as n from t "
                        "where k < :kmax group by k order by k")
        first = pq.execute(params={"kmax": 16})
        calls = [0]
        orig_plan_query = planner_mod.plan_query

        def counting_plan_query(*a, **kw):
            calls[0] += 1
            return orig_plan_query(*a, **kw)

        # patch EVERY import binding: session.py binds plan_query at
        # module level, so patching only the planner module would let
        # a hit path that regressed to re-lowering pass unobserved
        import spark_rapids_tpu.session as session_mod

        planner_mod.plan_query = counting_plan_query
        session_mod.plan_query = counting_plan_query
        try:
            second = pq.execute(params={"kmax": 16})
        finally:
            planner_mod.plan_query = orig_plan_query
            session_mod.plan_query = orig_plan_query
        assert calls[0] == 0, \
            f"plan-cache hit re-lowered the template ({calls[0]}x)"
        assert table_digest(first) == table_digest(second)
        pc = plan_cache_mod.stats()
        assert pc["hits"] >= 1, pc
        out["serving_plan_cache_hits"] = pc["hits"]

        # -- stream == collect, to the bit ------------------------- #
        batches = list(pq.execute_stream(params={"kmax": 16}))
        stream_tbl = pa.Table.from_batches(batches,
                                           schema=first.schema)
        assert table_digest(stream_tbl) == table_digest(first), \
            "streamed result != collected result"
        out["serving_stream_rows"] = stream_tbl.num_rows

        # -- two sessions, one admission slot ---------------------- #
        over = dict(base)
        over["spark.rapids.tpu.serving.maxConcurrent"] = 1
        over["spark.rapids.tpu.serving.queueDepth"] = 8
        scheduler_mod.reset()
        results: list = []
        errors: list = []

        def run(i: int) -> None:
            try:
                c = TpuConf(over)
                set_conf(c)
                from spark_rapids_tpu.session import TpuSession, col
                from spark_rapids_tpu.session import sum_ as _sum

                sess = TpuSession(c, tenant=f"tenant{i}")
                df = (sess.create_dataframe(t)
                      .group_by(col("k"))
                      .agg((_sum(col("v")), "sv"))
                      .order_by(col("k")))
                spq = sess.prepare(df)
                for _ in range(3):
                    results.append(table_digest(spq.execute()))
            except BaseException as e:  # noqa: BLE001 — reported below
                errors.append(e)

        ths = [threading.Thread(target=run, args=(i,))
               for i in range(2)]
        for th in ths:
            th.start()
        for th in ths:
            th.join()
        assert not errors, errors
        assert len(set(results)) == 1, \
            "concurrent sessions produced diverging results"
        st = scheduler_mod.scheduler_stats()
        assert st["admitted"] >= 6, st
        assert st["rejected"] == 0, st
        out["serving_admitted"] = st["admitted"]
    finally:
        conf = get_conf()
        conf._values.clear()
        conf._values.update(base)
        set_conf(conf)
        scheduler_mod.reset()
    return out


def run_sharing_smoke() -> dict:
    """Cross-tenant work-sharing acceptance contract, cheap CI form
    (tier-1 via tests/test_work_share.py; docs/work_sharing.md): two
    sessions execute the same parquet-backed golden template —

    - the second execution performs ZERO scan decodes (the tapped
      scan_units_decoded counter stays flat: it is served from the
      process-wide result cache);
    - its digest is bit-identical to the sharing-off run and to the
      serial reference (sharing must be invisible in the bytes);
    - a content-mutation probe rewrites the input file and proves the
      cache INVALIDATES on digest change: the next execution decodes
      again and returns the new file's answer."""
    import os
    import tempfile

    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq_mod

    from spark_rapids_tpu.config import TpuConf, get_conf, set_conf
    from spark_rapids_tpu.eventlog import table_digest
    from spark_rapids_tpu.serving import work_share as ws
    from spark_rapids_tpu.session import TpuSession, col, count_star
    from spark_rapids_tpu.session import sum_ as _sum

    def _template(session, path):
        return (session.read_parquet(path)
                .group_by(col("k"))
                .agg((_sum(col("v")), "sv"), (count_star(), "n"))
                .order_by(col("k")))

    def _write(path, seed: int) -> None:
        rng = np.random.default_rng(seed)
        n = 8192
        pq_mod.write_table(pa.table({
            "k": rng.integers(0, 16, n).astype(np.int64),
            "v": rng.integers(0, 1000, n).astype(np.int64),
        }), path)

    out: dict = {}
    base = dict(get_conf()._values)
    ws.reset()
    try:
        with tempfile.TemporaryDirectory(prefix="share_smoke_") as d:
            path = os.path.join(d, "t.parquet")
            _write(path, seed=0x5A5A)

            # serial sharing-off reference: THE ground truth
            off_conf = TpuConf(base)
            set_conf(off_conf)
            d_serial = table_digest(
                _template(TpuSession(off_conf), path)
                .collect(engine="tpu"))

            on = dict(base)
            on["spark.rapids.tpu.serving.sharing.enabled"] = True

            # session 1 (sharing on): decodes + populates the cache
            c1 = TpuConf(on)
            set_conf(c1)
            d1 = table_digest(
                _template(TpuSession(c1, tenant="a"), path)
                .collect(engine="tpu"))
            assert d1 == d_serial, \
                "sharing-on digest != serial sharing-off digest"
            st1 = ws.stats()
            assert st1["scan_units_decoded"] >= 1, st1
            assert st1["result_inserts"] >= 1, st1

            # session 2, same template: served from the result cache
            # with ZERO scan decodes (the tapped counter stays flat)
            c2 = TpuConf(on)
            set_conf(c2)
            d2 = table_digest(
                _template(TpuSession(c2, tenant="b"), path)
                .collect(engine="tpu"))
            st2 = ws.stats()
            assert d2 == d_serial, \
                "second session's digest != serial digest"
            assert st2["result_hits"] == st1["result_hits"] + 1, \
                (st1, st2)
            assert st2["scan_units_decoded"] == \
                st1["scan_units_decoded"], (
                    "result-cache hit decoded scan units", st1, st2)
            out["sharing_second_exec_decodes"] = (
                st2["scan_units_decoded"]
                - st1["scan_units_decoded"])
            out["sharing_result_hits"] = st2["result_hits"]

            # content-mutation probe: rewrite the file — the stale
            # entry must invalidate on the digest change, and the
            # fresh execution must answer for the NEW content
            _write(path, seed=0xB0B0)
            set_conf(off_conf)
            d_serial2 = table_digest(
                _template(TpuSession(off_conf), path)
                .collect(engine="tpu"))
            assert d_serial2 != d_serial, \
                "mutation probe wrote identical content"
            set_conf(c2)
            d3 = table_digest(
                _template(TpuSession(c2, tenant="b"), path)
                .collect(engine="tpu"))
            st3 = ws.stats()
            assert d3 == d_serial2, \
                "post-mutation digest != fresh serial digest"
            assert st3["result_invalidations"] >= 1, st3
            assert st3["scan_units_decoded"] > \
                st2["scan_units_decoded"], (
                    "post-mutation execution did not re-decode", st3)
            out["sharing_invalidations"] = st3["result_invalidations"]
    finally:
        conf = get_conf()
        conf._values.clear()
        conf._values.update(base)
        set_conf(conf)
        ws.reset()
    return out


def run_ledger_smoke() -> dict:
    """Device-ledger acceptance contract, cheap CI form (tier-1 via
    tests/test_ledger.py): a tiny grouped aggregate collected with the
    ledger on must attribute >=1 program with a nonzero cost-model
    byte count AND a nonzero dispatch count, and the sum of attributed
    device time must not exceed the query's wall clock (attribution
    may under-count — dispatch gaps are real — but it must never
    invent device time; the ledger credits EXCLUSIVE busy intervals,
    so overlapping async-dispatch windows cannot double-count the one
    chip).  Pipelining/speculation are pinned OFF so the stream loop
    stays serial, and the wall is measured through the settle flush —
    every credited interval lies inside the measured window."""
    import time

    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.config import get_conf
    from spark_rapids_tpu.session import TpuSession, col, sum_
    from spark_rapids_tpu.trace import ledger

    conf = get_conf()
    keys = ("spark.rapids.tpu.trace.ledger.enabled",
            "spark.rapids.tpu.sql.pipeline.enabled",
            "spark.rapids.tpu.sql.speculation.enabled")
    saved = {k: conf.get(k) for k in keys}
    out: dict = {}
    try:
        conf.set(keys[0], True)
        conf.set(keys[1], False)
        conf.set(keys[2], False)
        ledger.reset_stats()
        session = TpuSession()
        rng = np.random.default_rng(0x1ED6)
        n = 4096
        t = pa.table({
            "k": rng.integers(0, 32, n).astype(np.int64),
            "v": rng.random(n),
        })
        df = (session.create_dataframe(t)
              .group_by(col("k"))
              .agg((sum_(col("v")), "sv")))
        t0 = time.perf_counter()
        result = df.collect(engine="tpu")
        assert ledger.LEDGER.flush(timeout=30.0), \
            "ledger settlement did not drain"
        wall_ms = (time.perf_counter() - t0) * 1e3
        s = ledger.summarize(ledger.snapshot())
        progs = s["programs"]
        assert progs, "ledger recorded no programs"
        assert any(p["dispatches"] > 0 and p["bytes_accessed"] > 0
                   for p in progs.values()), \
            f"no program has cost-model bytes + dispatches: {progs}"
        total = s["totals"]
        assert total["device_ms"] <= wall_ms, (
            f"attributed device time {total['device_ms']}ms exceeds "
            f"the query wall {wall_ms:.1f}ms")
        out["ledger_programs"] = total["programs"]
        out["ledger_dispatches"] = total["dispatches"]
        out["ledger_rows"] = result.num_rows
    finally:
        for k, v in saved.items():
            conf.set(k, v)
        ledger.reset_stats()
        if not ledger.LEDGER.forced:
            # conf-owned enable from this smoke: drop it now instead
            # of waiting for the next query boundary (a FORCED enable
            # belongs to someone else — leave it alone)
            ledger.disable()
    return out


def run_wire_codec_smoke() -> dict:
    """Wire-compression acceptance contract, cheap CI form (tier-1 via
    tests/test_wire_compression.py): a q3-shaped scan->join->aggregate
    over a COMPRESSIBLE parquet fixture must return bit-identical rows
    with spark.rapids.tpu.sql.wireCompression on and off (the codec is
    lossless re-encoding, never approximation), and with compression
    on the tapped upload counter must show ratio > 1 — fewer bytes
    actually crossed the H2D wire.  Aggregates are integer-exact
    (sums of integers, counts) with pinned output order, so the
    equality gate is bit-for-bit, not tolerance-based."""
    import os
    import tempfile

    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_tpu.config import get_conf
    from spark_rapids_tpu.exprs.base import lit
    from spark_rapids_tpu.session import TpuSession, col, count_star, sum_

    key = "spark.rapids.tpu.sql.wireCompression.enabled"
    conf = get_conf()
    saved = conf.get(key)
    session = TpuSession()
    out: dict = {}
    rng = np.random.default_rng(0xC0DEC)
    with tempfile.TemporaryDirectory(prefix="wire_codec_smoke_") as d:
        n = 1 << 15
        # q3 shape, deliberately compressible the way real fact tables
        # are: clustered keys, sorted dates, small-range quantities
        li = pa.table({
            "l_orderkey": np.sort(rng.integers(0, 2048, n)).astype(
                np.int64),
            "l_shipdate": np.sort(rng.integers(8766, 10957, n)).astype(
                np.int32),
            "l_quantity": rng.integers(1, 51, n).astype(np.int64),
        })
        li_path = os.path.join(d, "li.parquet")
        pq.write_table(li, li_path, row_group_size=n)
        orders = pa.table({
            "o_orderkey": np.arange(2048, dtype=np.int64),
            "o_priority": rng.integers(0, 5, 2048).astype(np.int32),
        })
        o_path = os.path.join(d, "orders.parquet")
        pq.write_table(orders, o_path)

        def q():
            lidf = (session.read_parquet(li_path)
                    .where(col("l_shipdate") > lit(9000)))
            odf = session.read_parquet(o_path)
            return (lidf.join(odf, left_on=[col("l_orderkey")],
                              right_on=[col("o_orderkey")])
                    .group_by(col("o_priority"))
                    .agg((sum_(col("l_quantity")), "qty"),
                         (count_star(), "cnt"))
                    .order_by(col("o_priority")))

        try:
            conf.set(key, True)
            on_bytes = count_upload_bytes(q())
            on = q().collect(engine="tpu")
            conf.set(key, False)
            off_bytes = count_upload_bytes(q())
            off = q().collect(engine="tpu")
        finally:
            conf.set(key, saved)
    assert on.to_pydict() == off.to_pydict(), (
        "wire compression changed query results: "
        f"{on.to_pydict()} != {off.to_pydict()}")
    ratio = off_bytes / max(on_bytes, 1)
    assert ratio > 1.0, (
        f"wire compression saved nothing on a compressible fixture: "
        f"{off_bytes} raw vs {on_bytes} compressed")
    out["wire_codec_rows"] = on.num_rows
    out["wire_codec_upload_ratio"] = round(ratio, 2)
    return out


def run_fusion_smoke() -> dict:
    """Whole-stage fusion acceptance contract, cheap CI form (tier-1
    via tests/test_fusion.py, docs/fusion.md): a q1-shaped
    scan->filter->agg parquet query, multi-batch, run with the device
    ledger on.

    - the WARM pass (second fusion-enabled collect) compiles nothing:
      0 jit-cache misses in its window;
    - the warm pass dispatches STRICTLY fewer ledger programs than the
      unfused baseline (`spark.rapids.tpu.sql.fusion.enabled=false`) —
      decode+filter+agg-update collapse into one program per batch;
    - results are bit-identical across fusion on, fusion off, and
      donation on (the three-way digest gate);
    - the warm dispatch count respects the conf budget
      (`spark.rapids.tpu.sql.fusion.warmDispatchBudget`) — the
      regression gate ROADMAP #2's dispatch-soup diagnosis asked for.

    Returns the warm/unfused dispatch counts, the warm roofline
    fraction and the top-programs footer so callers (and the committed
    smoke artifact) can show WHERE the device time went."""
    import os
    import tempfile

    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_tpu.config import get_conf
    from spark_rapids_tpu.eventlog import table_digest
    from spark_rapids_tpu.execs.base import fusion_stats, \
        reset_fusion_stats
    from spark_rapids_tpu.execs.jit_cache import cache_stats
    from spark_rapids_tpu.exprs.base import lit
    from spark_rapids_tpu.session import TpuSession, col, count_star, sum_
    from spark_rapids_tpu.trace import ledger

    # force-register the lazily-registered fusion confs BEFORE the
    # save/restore snapshot: saving an unregistered key reads None,
    # and restoring that None would permanently shadow the registered
    # default for the rest of the process
    from spark_rapids_tpu.execs.base import _budget_conf, _fusion_conf

    _fusion_conf()
    _budget_conf()
    conf = get_conf()
    keys = ("spark.rapids.tpu.sql.fusion.enabled",
            "spark.rapids.tpu.sql.fusion.donation.enabled",
            "spark.rapids.tpu.sql.pipeline.enabled",
            "spark.rapids.tpu.sql.speculation.enabled",
            "spark.rapids.tpu.sql.batchSizeRows",
            "spark.rapids.tpu.sql.shuffle.partitions")
    saved = {k: conf.get(k) for k in keys}
    out: dict = {}
    ledger_was_on = ledger.LEDGER.enabled
    rng = np.random.default_rng(0xF05E)
    with tempfile.TemporaryDirectory(prefix="fusion_smoke_") as d:
        n = 1 << 14
        t = pa.table({
            # q1 shape: date filter, string-ish group keys (small int
            # domain stands in — keeps the fixture seconds-scale),
            # summed measures
            "l_shipdate": rng.integers(8766, 10957, n).astype(np.int32),
            "l_key": rng.integers(0, 4, n).astype(np.int64),
            "l_quantity": rng.integers(1, 51, n).astype(np.int64),
            "l_price": rng.integers(900, 105000, n).astype(np.int64),
        })
        path = os.path.join(d, "li.parquet")
        pq.write_table(t, path, row_group_size=n // 4)

        def q(session):
            return (session.read_parquet(path)
                    .where(col("l_shipdate") <= lit(10471))
                    .group_by(col("l_key"))
                    .agg((sum_(col("l_quantity")), "sum_qty"),
                         (sum_(col("l_price")), "sum_price"),
                         (count_star(), "n"))
                    .order_by(col("l_key")))

        def collect_counted(session):
            """(digest, ledger dispatch count, jit misses) for one
            collect, ledger window isolated."""
            ledger.reset_stats()
            j0 = cache_stats()
            r = q(session).collect(engine="tpu")
            assert ledger.LEDGER.flush(timeout=30.0), \
                "ledger settlement did not drain"
            s = ledger.summarize(ledger.snapshot())
            j1 = cache_stats()
            return (table_digest(r), s, j1["misses"] - j0["misses"])

        try:
            # pipelining/speculation pinned off so dispatch counts are
            # deterministic; small batches so the stream actually
            # streams (4 row groups -> 4 wire batches)
            conf.set(keys[2], False)
            conf.set(keys[3], False)
            conf.set(keys[4], n // 4)
            conf.set(keys[5], 1)
            conf.set(keys[0], True)
            conf.set(keys[1], False)
            ledger.enable()
            reset_fusion_stats()
            session = TpuSession()
            cold_digest, cold_sum, _ = collect_counted(session)
            # isolate the warm window: chains/saved_dispatches below
            # describe ONE collect, same semantics as bench.py's
            # per-query q*_fusion_chains fields
            reset_fusion_stats()
            warm_digest, warm_sum, warm_misses = \
                collect_counted(session)
            fstats = fusion_stats()
            assert warm_misses == 0, (
                f"warm pass re-compiled {warm_misses} program(s): "
                "jit keys are unstable across identical collects")
            assert warm_digest == cold_digest
            warm_d = warm_sum["totals"]["dispatches"]

            # unfused baseline: fresh session, fusion off
            conf.set(keys[0], False)
            unfused_digest, unfused_sum, _ = \
                collect_counted(TpuSession())
            unfused_d = unfused_sum["totals"]["dispatches"]
            assert unfused_digest == warm_digest, \
                "fusion.enabled changed query results"
            assert warm_d < unfused_d, (
                f"fusion saved no dispatches: warm {warm_d} vs "
                f"unfused {unfused_d}")

            # donation on: digest identical, consumed-state bookkeeping
            # exercised end to end
            conf.set(keys[0], True)
            conf.set(keys[1], True)
            donated_digest, _ds, _ = collect_counted(TpuSession())
            assert donated_digest == warm_digest, \
                "donation.enabled changed query results"

            # the dispatch-budget regression gate
            from spark_rapids_tpu.execs.base import (
                warm_dispatch_budget,
            )

            budget = warm_dispatch_budget()
            if budget > 0:
                assert warm_d <= budget, (
                    f"warm dispatch count {warm_d} exceeds the "
                    f"budget {budget} "
                    "(spark.rapids.tpu.sql.fusion.warmDispatchBudget)")

            top = warm_sum["totals"].get("top") or []
            out["fusion_warm_dispatches"] = warm_d
            out["fusion_unfused_dispatches"] = unfused_d
            out["fusion_dispatch_savings_ratio"] = round(
                unfused_d / max(warm_d, 1), 2)
            out["fusion_warm_jit_misses"] = warm_misses
            out["fusion_chains"] = fstats["chains"]
            out["fusion_saved_dispatches"] = fstats["saved_dispatches"]
            out["fusion_warm_roofline"] = \
                warm_sum["totals"]["roofline"]
            out["fusion_warm_device_ms"] = \
                warm_sum["totals"]["device_ms"]
            out["fusion_top_programs"] = [
                {"key": p["key"], "op": p["op"],
                 "dispatches": p["dispatches"],
                 "device_ms": p["device_ms"], "share": p["share"]}
                for p in top]
        finally:
            for k, v in saved.items():
                conf.set(k, v)
            ledger.reset_stats()
            if not ledger_was_on:
                # this smoke's own force-enable: release it (an outer
                # caller's enable — bench, a wrapping test — survives)
                ledger.disable()
    return out


def run_warm_start_smoke() -> dict:
    """Warm-start acceptance contract, cheap CI form (tier-1 via
    tests/test_persist.py, docs/warm_start.md): one child process
    populates a persist directory with the fusion-smoke query's AOT
    programs, then a second FRESH child runs the same query against
    the warm directory and must

    - compile NOTHING: the jit cache's `compiles` counter stays 0 in
      the child (restored programs dispatch deserialized jax.export
      artifacts; the counter bumps only at a fresh wrapper's first
      real invocation);
    - restore from disk: `persist.hits` > 0;
    - agree bit-for-bit: the child's digest equals both the
      populating child's and an in-process reference run with
      persistence OFF;
    - keep ledger attribution: the warm child's dispatch count equals
      the populating child's (restored programs still meter)."""
    import os
    import tempfile

    from spark_rapids_tpu.config import get_conf
    from spark_rapids_tpu.execs.base import _budget_conf, _fusion_conf
    from spark_rapids_tpu.tools import cold_start as cs
    from spark_rapids_tpu.trace import ledger

    # force-register lazily-registered confs BEFORE the snapshot (the
    # fusion smoke's save/restore caveat applies here too)
    _fusion_conf()
    _budget_conf()
    conf = get_conf()
    keys = ("spark.rapids.tpu.sql.pipeline.enabled",
            "spark.rapids.tpu.sql.speculation.enabled",
            "spark.rapids.tpu.sql.batchSizeRows",
            "spark.rapids.tpu.sql.shuffle.partitions",
            "spark.rapids.tpu.sql.fusion.enabled",
            "spark.rapids.tpu.sql.fusion.donation.enabled")
    saved = {k: conf.get(k) for k in keys}
    ledger_was_on = ledger.LEDGER.enabled
    with tempfile.TemporaryDirectory(prefix="warm_smoke_") as d:
        data = os.path.join(d, "data")
        warm = os.path.join(d, "persist")
        os.makedirs(data)
        os.makedirs(warm)
        cs.make_fixture(data)
        try:
            ledger.reset_stats()
            ref = cs.run_once(data, None)  # in-process, persist OFF
        finally:
            for k, v in saved.items():
                conf.set(k, v)
            ledger.reset_stats()
            if not ledger_was_on:
                ledger.disable()
        populate = cs.run_subprocess(data, warm)
        child = cs.run_subprocess(data, warm)
    assert child["compiles"] == 0, (
        f"warm child compiled {child['compiles']} programs; a warm "
        "disk cache must restore every invoked program")
    assert child["persist"]["hits"] > 0, (
        "warm child restored nothing from the persist directory")
    assert child["digest"] == populate["digest"] == ref["digest"], (
        f"digest drift across persist modes: in-process "
        f"{ref['digest']}, populate {populate['digest']}, warm child "
        f"{child['digest']}")
    assert child["dispatches"] == populate["dispatches"], (
        f"restored programs lost ledger attribution: warm child "
        f"dispatched {child['dispatches']} vs populate "
        f"{populate['dispatches']}")
    return {
        "warm_start_child_compiles": child["compiles"],
        "warm_start_persist_hits": child["persist"]["hits"],
        "warm_start_dispatches": child["dispatches"],
        "warm_start_digest_ok": True,
    }


def run_coalesce_smoke() -> dict:
    """Batch-coalescing acceptance contract, cheap CI form (tier-1 via
    tests/test_coalesce.py, docs/occupancy.md): many tiny cached
    batches through a q1-shaped filter->group-by->agg chain.

    - results digest bit-identical with sql.coalesce.enabled on vs off
      (coalescing only re-buckets rows);
    - the coalesced run dispatches STRICTLY fewer ledger programs —
      the fused chain runs once over one dense block instead of once
      per starved input batch;
    - the coalesced window's aggregate live/capacity ratio sits at or
      above the HC015 occupancy floor
      (trace.ledger.health.occupancyFloor): the chip ran dense;
    - under a SHRUNK device budget the retry ladder bisects a
      coalesced batch back along its input seams (`coalesce_seams`),
      so recovery dispatches land on the producer's original batch
      granularity, with row order preserved."""
    import os
    import tempfile

    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.config import get_conf
    from spark_rapids_tpu.eventlog import table_digest
    from spark_rapids_tpu.execs import retry as R
    from spark_rapids_tpu.execs.basic import TpuBatchSourceExec
    from spark_rapids_tpu.execs.coalesce import TpuCoalesceBatchesExec
    from spark_rapids_tpu.exprs.base import lit
    from spark_rapids_tpu.session import TpuSession, col, count_star, \
        sum_
    from spark_rapids_tpu.trace import ledger
    from spark_rapids_tpu.trace.ledger import LEDGER_OCCUPANCY_FLOOR

    conf = get_conf()
    keys = ("spark.rapids.tpu.sql.coalesce.enabled",
            "spark.rapids.tpu.sql.coalesce.targetRows",
            "spark.rapids.tpu.sql.batchSizeRows",
            "spark.rapids.tpu.sql.shuffle.partitions",
            "spark.rapids.tpu.sql.pipeline.enabled",
            "spark.rapids.tpu.sql.speculation.enabled",
            R.SPLIT_MIN_ROWS.key)
    saved = {k: conf.get(k) for k in keys}
    out: dict = {}
    ledger_was_on = ledger.LEDGER.enabled
    rng = np.random.default_rng(0xC0A1)
    with tempfile.TemporaryDirectory(prefix="coalesce_smoke_") as d:
        # 16 part-full batches: 384 live rows each ride a 512 bucket
        # (live/cap 0.75 uncoalesced); coalesced they pack one dense
        # 6144-row block in the 8192 bucket
        group, n_batches = 384, 16
        n = group * n_batches
        t = pa.table({
            "l_shipdate": rng.integers(8766, 10957, n).astype(np.int32),
            "l_key": rng.integers(0, 4, n).astype(np.int64),
            "l_quantity": rng.integers(1, 51, n).astype(np.int64),
        })
        path = os.path.join(d, "li.parquet")
        pq.write_table(t, path, row_group_size=group)

        def q(cached):
            return (cached
                    .where(col("l_shipdate") <= lit(10471))
                    .group_by(col("l_key"))
                    .agg((sum_(col("l_quantity")), "sum_qty"),
                         (count_star(), "cnt"))
                    .order_by(col("l_key")))

        def collect_counted(enabled: bool):
            """(digest, ledger summary) for one warm collect against a
            device-resident cache, coalesce as given.  A fresh session
            per config: the planner decides insertion at plan time."""
            conf.set(keys[0], enabled)
            session = TpuSession()
            cached = session.read_parquet(path).cache()
            df = q(cached)
            try:
                df.collect(engine="tpu")  # fill the cache + compile
                ledger.reset_stats()
                r = df.collect(engine="tpu")
                assert ledger.LEDGER.flush(timeout=30.0), \
                    "ledger settlement did not drain"
                s = ledger.summarize(ledger.snapshot())
            finally:
                cached.unpersist()
            return table_digest(r), s

        try:
            # pipelining/speculation pinned off so dispatch counts are
            # deterministic; tiny batches so the chain actually starves
            conf.set(keys[2], group)
            conf.set(keys[3], 1)
            conf.set(keys[4], False)
            conf.set(keys[5], False)
            conf.set(keys[1], 1 << 20)  # one flush per partition
            ledger.enable()
            off_digest, off_sum = collect_counted(False)
            on_digest, on_sum = collect_counted(True)
            assert on_digest == off_digest, \
                "sql.coalesce.enabled changed query results"
            off_d = off_sum["totals"]["dispatches"]
            on_d = on_sum["totals"]["dispatches"]
            assert on_d < off_d, (
                f"coalescing saved no dispatches: on {on_d} vs "
                f"off {off_d}")
            ratio = on_sum["totals"].get("live_capacity_ratio")
            floor = float(conf.get(LEDGER_OCCUPANCY_FLOOR))
            assert ratio is not None and ratio >= floor, (
                f"coalesced live/capacity ratio {ratio} below the "
                f"{floor} occupancy floor")
            out["coalesce_off_dispatches"] = off_d
            out["coalesce_on_dispatches"] = on_d
            out["coalesce_dispatch_savings_ratio"] = round(
                off_d / max(on_d, 1), 2)
            out["coalesce_live_capacity_ratio"] = ratio
            out["coalesce_off_live_capacity_ratio"] = \
                off_sum["totals"].get("live_capacity_ratio")

            # shrunk-budget split: the coalesced block must bisect
            # back along its input seams, not at the arbitrary midpoint
            schema = T.Schema([T.Field("x", T.LONG)])
            sizes = (300, 500, 200, 400)  # midpoint 700; seam cut 800
            offs = np.cumsum((0,) + sizes)
            parts = [ColumnarBatch.from_numpy(
                {"x": np.arange(offs[i], offs[i + 1],
                                dtype=np.int64)}, schema)
                for i in range(len(sizes))]
            co = TpuCoalesceBatchesExec(
                TpuBatchSourceExec(parts, schema))
            outs = list(co.execute())
            assert len(outs) == 1 and \
                outs[0].coalesce_seams == sizes
            conf.set(R.SPLIT_MIN_ROWS.key, 64)

            class _ShrunkBudget(RuntimeError):
                def __str__(self):
                    return ("RESOURCE_EXHAUSTED: shrunk device "
                            "budget (coalesce smoke)")

            budget_rows, seen, got = 900, [], []

            def run(batch):
                nr = batch.concrete_num_rows()
                if nr > budget_rows:
                    raise _ShrunkBudget()
                seen.append(nr)
                yield batch

            for b in R.with_split_retry(run, outs[0],
                                        desc="coalesce_smoke"):
                got.extend(b.to_pydict()["x"])
            # seam-aligned halves (300+500 | 200+400), not 700/700
            assert seen == [800, 600], seen
            assert got == list(range(sum(sizes))), \
                "seam split lost or reordered rows"
            out["coalesce_split_chunks"] = seen
        finally:
            for k, v in saved.items():
                conf.set(k, v)
            ledger.reset_stats()
            if not ledger_was_on:
                ledger.disable()
    return out


def run_ops_smoke() -> dict:
    """Live ops-plane acceptance contract, cheap CI form (tier-1 via
    tests/test_obs.py; docs/ops_plane.md):

    - `spark.rapids.tpu.obs.enabled` starts the endpoint at the next
      query boundary; after the query the LIVE registry is empty again
      (/queries serves []);
    - a real HTTP scrape of /metrics parses as OpenMetrics (terminated
      by `# EOF`) and every eventlog counters_snapshot family equals
      the in-process snapshot value — asserted only for counters that
      are QUIESCENT across the scrape (bracketing snapshots on both
      sides), so a background settle cannot flake the gate while a
      drifting scrape implementation still fails it;
    - the owning conf's off stops BOTH threads (http + slo watchdog)
      and releases the socket: no tpu-obs-* thread survives, and a
      fresh connect to the old port is refused."""
    import json as _json
    import socket
    import threading
    import urllib.request

    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu import obs
    from spark_rapids_tpu.config import get_conf
    from spark_rapids_tpu.eventlog import (
        MONOTONIC_COUNTERS,
        counters_snapshot,
    )
    from spark_rapids_tpu.obs import metrics as om
    from spark_rapids_tpu.session import TpuSession, col, sum_

    def _obs_threads():
        return [t.name for t in threading.enumerate()
                if t.name.startswith("tpu-obs")]

    conf = get_conf()
    keys = ("spark.rapids.tpu.obs.enabled",
            "spark.rapids.tpu.obs.port")
    saved = {k: conf.get(k) for k in keys}
    out: dict = {}
    try:
        conf.set(keys[0], True)
        conf.set(keys[1], 0)  # ephemeral: parallel CI runs never clash
        session = TpuSession()
        rng = np.random.default_rng(0x0B5)
        n = 2048
        t = pa.table({
            "k": rng.integers(0, 16, n).astype(np.int64),
            "v": rng.random(n),
        })
        df = (session.create_dataframe(t)
              .group_by(col("k"))
              .agg((sum_(col("v")), "sv")))
        result = df.collect(engine="tpu")
        assert obs.is_enabled(), "obs.enabled did not start the plane"
        port = obs.plane().port
        assert port, "ops endpoint bound no port"
        assert obs.REGISTRY.count() == 0, \
            "live query registry did not empty after the query"

        # -- scrape == snapshot parity ------------------------------ #
        base = f"http://127.0.0.1:{port}"
        before = counters_snapshot()
        body = urllib.request.urlopen(
            base + "/metrics", timeout=10).read().decode()
        after = counters_snapshot()
        assert body.rstrip().endswith("# EOF"), \
            "scrape is missing the OpenMetrics EOF marker"
        parsed = om.parse_openmetrics(body)
        mono = set(MONOTONIC_COUNTERS)
        checked = 0
        for key, val in before.items():
            name = om.counter_metric_name(key) if key in mono \
                else om.metric_name(key)
            got = om.scrape_value(parsed, name)
            assert got is not None, f"/metrics is missing {name}"
            if after.get(key) == val:  # quiescent across the scrape
                assert got == float(val), (
                    f"scrape parity broken for {key}: "
                    f"/metrics says {got}, snapshot says {val}")
                checked += 1
        assert checked > 0, "no quiescent counter to parity-check"

        # -- live registry JSON surface ----------------------------- #
        qbody = urllib.request.urlopen(
            base + "/queries", timeout=10).read().decode()
        assert _json.loads(qbody) == [], \
            "/queries is not empty between queries"
        out["ops_rows"] = result.num_rows
        out["ops_scrape_families"] = len(parsed)
        out["ops_parity_counters"] = checked

        # -- off: no thread, no socket ------------------------------ #
        conf.set(keys[0], False)
        obs.sync_conf(conf)
        assert not obs.is_enabled()
        assert _obs_threads() == [], \
            f"ops threads survived the off: {_obs_threads()}"
        with socket.socket() as probe:
            probe.settimeout(0.5)
            assert probe.connect_ex(("127.0.0.1", port)) != 0, \
                "ops socket still listening after stop"
        out["ops_stopped_clean"] = True
    finally:
        for k, v in saved.items():
            conf.set(k, v)
        obs.stop()
    return out


def run_connect_smoke() -> dict:
    """The wire front-door contract (spark_rapids_tpu/connect/,
    docs/connect.md): an in-process ConnectServer thread serves one
    wire query — a Substrait plan over real TCP framing — and the
    Arrow batches reassembled by the engine-free client must digest
    bit-identical to the SAME plan collected in-process, with the
    repeat request hitting the prepared-plan cache (tier-1 via
    tests/test_connect.py)."""
    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.connect.client import (
        ConnectClient,
        table_digest,
    )
    from spark_rapids_tpu.connect.server import ConnectServer
    from spark_rapids_tpu.frontends.substrait import SubstraitFrontend

    rng = np.random.default_rng(41)
    n = 4096
    t = pa.table({
        "k": (rng.integers(0, 9, n)).astype(np.int64),
        "v": rng.integers(0, 1000, n).astype(np.float64),
    })
    plan = {
        "extensions": [
            {"extensionFunction": {"functionAnchor": 1,
                                   "name": "gt:any_any"}},
            {"extensionFunction": {"functionAnchor": 2,
                                   "name": "sum:fp64"}},
        ],
        "relations": [{"root": {"names": ["k", "total"], "input": {
            "aggregate": {
                "input": {"filter": {
                    "input": {"read": {
                        "namedTable": {"names": ["t"]},
                        "baseSchema": {"names": ["k", "v"]}}},
                    "condition": {"scalarFunction": {
                        "functionReference": 1, "arguments": [
                            {"value": {"selection": {
                                "directReference": {
                                    "structField": {"field": 1}}}}},
                            {"value": {"literal": {"fp64": 10.0}}},
                        ]}}}},
                "groupings": [{"groupingExpressions": [
                    {"selection": {"directReference": {
                        "structField": {"field": 0}}}}]}],
                "measures": [{"measure": {
                    "functionReference": 2,
                    "arguments": [{"value": {"selection": {
                        "directReference": {
                            "structField": {"field": 1}}}}}]}}],
            }}}}],
    }
    srv = ConnectServer()
    srv.register_table("t", t)
    srv.start()
    try:
        host, port = srv.address
        with ConnectClient(host, port, tenant="smoke") as cli:
            assert cli.ping(), "connect ping failed"
            wire1 = cli.execute_plan(plan)
            wire2 = cli.execute_plan(plan)  # prepared-plan cache hit
        local = SubstraitFrontend()
        local.register_table("t", t)
        in_proc = local.execute_plan(plan).combine_chunks()
        d_wire, d_local = table_digest(wire1), table_digest(in_proc)
        assert d_wire == d_local, (
            f"wire digest {d_wire} != in-process {d_local}")
        assert table_digest(wire2) == d_local, "repeat wire mismatch"
    finally:
        srv.shutdown()
    return {"connect_smoke_rows": wire1.num_rows,
            "connect_smoke_digest": d_wire}


def run_smoke() -> dict:
    """Collect each smoke query with speculation on, then off, assert
    table equality, and return {query_name: rows}."""
    from spark_rapids_tpu.config import get_conf
    from spark_rapids_tpu.session import TpuSession

    key = "spark.rapids.tpu.sql.speculation.enabled"
    batch_key = "spark.rapids.tpu.sql.batchSizeRows"
    conf = get_conf()
    saved = {k: conf.get(k) for k in (key, batch_key)}
    session = TpuSession()
    # small batches so every stream loop sees several batches (the
    # warm-up -> steady-state transition is the interesting part)
    conf.set(batch_key, 512)
    out: dict = {}
    try:
        for name, df in _queries(session):
            conf.set(key, True)
            on = df.collect(engine="tpu")
            conf.set(key, False)
            off = df.collect(engine="tpu")
            _assert_rows_match(name, on, off)
            out[name] = on.num_rows
    finally:
        for k, v in saved.items():
            conf.set(k, v)
    return out


def run_mesh_serving_smoke() -> dict:
    """Pod-scale serving acceptance contract, cheap CI form (tier-1
    via tests/test_pod_serving.py; docs/pod_serving.md): two sessions
    on a virtual 4-device mesh with mesh-resident serving enabled —

    - SHARED PROGRAM SET: the second session's executions mint zero
      new partitioned programs (the jit-key census is flat between
      sessions: same templates, same conf fingerprint, same mesh key
      — one mesh-resident program set serves every tenant);
    - DEVICE-BORN steady state: the second session's window performs
      zero data-plane host uploads (tapped ``placement.host_uploads``
      counter; control-plane row-count uploads tallied separately);
    - a digest gate: every mesh-resident result hashes identical to
      the serial single-device reference.
    """
    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.config import TpuConf, get_conf, set_conf
    from spark_rapids_tpu.eventlog import table_digest
    from spark_rapids_tpu.execs.jit_cache import program_census
    from spark_rapids_tpu.parallel import make_mesh
    from spark_rapids_tpu.parallel import placement as placement_mod
    from spark_rapids_tpu.parallel.mesh import (
        active_mesh,
        set_active_mesh,
    )
    from spark_rapids_tpu.session import TpuSession, col, sum_
    from spark_rapids_tpu.shuffle.transport import SHUFFLE_TRANSPORT

    import jax

    if len(jax.devices()) < 4:
        raise AssertionError(
            "mesh serving smoke needs >= 4 virtual devices "
            "(tests/conftest.py pins 8)")
    rng = np.random.default_rng(0x90D)
    n = 4096
    t = pa.table({
        "k": rng.integers(0, 64, n).astype(np.int64),
        "v": rng.integers(0, 1000, n).astype(np.int64),
    })

    def templates(s):
        return [
            ("agg", s.create_dataframe(t)
             .group_by(col("k")).agg((sum_(col("v")), "sv"))),
            ("sort", s.create_dataframe(t).order_by(col("k"))),
        ]
    def canon(tbl) -> str:
        # row-order-insensitive: the collective exchange legitimately
        # lands agg groups in shard order, not the serial engine's —
        # canonical row sort first, THEN the content digest
        return table_digest(
            tbl.sort_by([(c, "ascending") for c in tbl.column_names]))


    def mesh_conf(base: dict) -> TpuConf:
        over = dict(base)
        over.update({
            SHUFFLE_TRANSPORT.key: "collective",
            "spark.rapids.tpu.shuffle.collective.spmd.enabled": True,
            "spark.rapids.tpu.shuffle.collective.roundRows": 512,
            "spark.rapids.tpu.sql.batchSizeRows": 512,
            "spark.rapids.tpu.serving.mesh.enabled": True,
        })
        return TpuConf(over)

    out: dict = {}
    base = dict(get_conf()._values)
    prev_mesh = active_mesh()
    mesh = make_mesh(4)
    set_active_mesh(mesh)
    try:
        # serial single-device reference (mesh serving off, local
        # transport): the ground truth digests
        serial_conf = TpuConf(base)
        serial_conf.set(SHUFFLE_TRANSPORT.key, "local")
        set_conf(serial_conf)
        s0 = TpuSession(serial_conf)
        digests = {name: canon(df.collect(engine="tpu"))
                   for name, df in templates(s0)}

        # session 1 on the mesh: mints the partitioned program set
        conf1 = mesh_conf(base)
        set_conf(conf1)
        s1 = TpuSession(conf1, tenant="t0")
        pqs1 = {name: s1.prepare(df) for name, df in templates(s1)}
        for name, pq in pqs1.items():
            assert canon(pq.execute()) == digests[name], \
                f"mesh-resident {name} diverged from serial reference"
        census1 = program_census()

        # session 2, same templates: must REUSE session 1's programs
        # (flat census) and move zero data-plane bytes host->device
        # in its executions (device-born stage inputs)
        conf2 = mesh_conf(base)
        set_conf(conf2)
        s2 = TpuSession(conf2, tenant="t1")
        pqs2 = {name: s2.prepare(df) for name, df in templates(s2)}
        placement_mod.reset_stats()
        for name, pq in pqs2.items():
            assert canon(pq.execute()) == digests[name], \
                f"second session's {name} diverged"
        census2 = program_census()
        pl = placement_mod.stats()
        grew = {tag: (census1.get(tag, 0), cnt)
                for tag, cnt in census2.items()
                if cnt > census1.get(tag, 0)}
        assert not grew, (
            f"second session minted new programs (census grew): {grew}")
        assert pl["host_uploads"] == 0, (
            f"mesh-resident steady state moved data-plane bytes "
            f"host->device: {pl}")
        out["mesh_serving_programs"] = sum(
            cnt for tag, cnt in census2.items()
            if tag.startswith("spmd"))
        out["mesh_serving_host_uploads"] = pl["host_uploads"]
        out["mesh_serving_device_born"] = pl["device_born"]
        out["mesh_serving_adoptions"] = pl["adoptions"]
    finally:
        set_active_mesh(prev_mesh)
        conf = get_conf()
        conf._values.clear()
        conf._values.update(base)
        set_conf(conf)
    return out


def main() -> int:
    import json

    # stand-alone runs ride the CPU backend: this is a correctness
    # smoke, and the container's sitecustomize would otherwise pin a
    # fragile remote-TPU tunnel (config.update beats the env var)
    import jax

    jax.config.update("jax_platforms", "cpu")
    results = run_smoke()
    results.update(run_rf_smoke())
    results.update(run_eventlog_smoke())
    results.update(run_serving_smoke())
    results.update(run_sharing_smoke())
    results.update(run_ledger_smoke())
    results.update(run_wire_codec_smoke())
    results.update(run_fusion_smoke())
    results.update(run_warm_start_smoke())
    results.update(run_coalesce_smoke())
    results.update(run_connect_smoke())
    results.update(run_ops_smoke())
    results.update(run_mesh_serving_smoke())
    print(json.dumps({"bench_smoke": results, "ok": True}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
