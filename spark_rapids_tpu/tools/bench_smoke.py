"""Bench smoke: one tiny query per hot exec (join, aggregate,
exchange), each collected with speculative sizing ON and OFF, asserting
result equality.

The acceptance contract of the speculation layer is that it is a pure
latency optimization — `speculation.enabled=false` must reproduce the
same results bit-for-bit.  This driver is the cheap CI hook for that
contract: `scripts/bench_smoke.sh` runs it standalone, and
`tests/test_speculation.py::test_bench_smoke_queries_match` runs the
same function inside the tier-1 `not slow` suite.

Run: python -m spark_rapids_tpu.tools.bench_smoke
"""

from __future__ import annotations


def _queries(session):
    """(name, DataFrame) per hot exec, tiny enough for seconds-scale
    CPU runs but multi-batch so the stream loops actually stream."""
    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.session import col, sum_

    rng = np.random.default_rng(0x5BEC)
    n = 4096
    lineitem = pa.table({
        "k": rng.integers(0, 64, n).astype(np.int64),
        "v": rng.random(n),
    })
    dim = pa.table({
        "k": np.arange(64, dtype=np.int64),
        "w": rng.integers(0, 9, 64).astype(np.int64),
    })
    li = session.create_dataframe(lineitem)
    joined = li.join(session.create_dataframe(dim),
                     left_on=[col("k")], right_on=[col("k")])
    yield "join", joined
    yield "aggregate", li.group_by(col("k")).agg((sum_(col("v")), "sv"))
    # the grouped aggregate above plans partial -> exchange -> final;
    # an ORDER BY adds the range-partitioned exchange shape too
    yield "exchange", (li.group_by(col("k"))
                       .agg((sum_(col("v")), "sv"))
                       .order_by(col("k")))


def _assert_rows_match(name: str, on, off) -> None:
    """Row-set equality with float tolerance: the engine documents
    run-to-run float aggregation order variability
    (spark.rapids.tpu.sql.variableFloatAgg.enabled), so exact float
    equality would flake at the ULP level regardless of speculation."""
    assert on.num_rows == off.num_rows, (name, on.num_rows,
                                         off.num_rows)
    on_rows = sorted(map(tuple, zip(*on.to_pydict().values())))
    off_rows = sorted(map(tuple, zip(*off.to_pydict().values())))
    for a, b in zip(on_rows, off_rows):
        for x, y in zip(a, b):
            if isinstance(x, float):
                assert abs(x - y) <= 1e-9 * max(1.0, abs(y)), \
                    f"{name}: speculation on/off results differ: {a} {b}"
            else:
                assert x == y, \
                    f"{name}: speculation on/off results differ: {a} {b}"


def run_smoke() -> dict:
    """Collect each smoke query with speculation on, then off, assert
    table equality, and return {query_name: rows}."""
    from spark_rapids_tpu.config import get_conf
    from spark_rapids_tpu.session import TpuSession

    key = "spark.rapids.tpu.sql.speculation.enabled"
    batch_key = "spark.rapids.tpu.sql.batchSizeRows"
    conf = get_conf()
    saved = {k: conf.get(k) for k in (key, batch_key)}
    session = TpuSession()
    # small batches so every stream loop sees several batches (the
    # warm-up -> steady-state transition is the interesting part)
    conf.set(batch_key, 512)
    out: dict = {}
    try:
        for name, df in _queries(session):
            conf.set(key, True)
            on = df.collect(engine="tpu")
            conf.set(key, False)
            off = df.collect(engine="tpu")
            _assert_rows_match(name, on, off)
            out[name] = on.num_rows
    finally:
        for k, v in saved.items():
            conf.set(k, v)
    return out


def main() -> int:
    import json

    # stand-alone runs ride the CPU backend: this is a correctness
    # smoke, and the container's sitecustomize would otherwise pin a
    # fragile remote-TPU tunnel (config.update beats the env var)
    import jax

    jax.config.update("jax_platforms", "cpu")
    print(json.dumps({"bench_smoke": run_smoke(), "ok": True}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
