"""The 99-query TPC-DS sweep: classify every query's fate.

BASELINE config #5's missing artifact (ROADMAP #5, VERDICT missing #2):
drive all 99 TPC-DS query texts (tools/tpcds_queries.py) through the
SQL frontend against the deterministic mini catalog
(tools/tpcds_schema.py) and classify each as

    parsed -> planned -> executed -> correct (vs the CPU oracle)

recording WHERE each one stops and WHY (the failure taxonomy: which
grammar production or operator rejected it) — turning "grow the SQL
surface" from guesswork into a ranked backlog.  On top:

- **fix probes**: re-run the parse/plan stages with each satellite
  grammar fix disabled (frontends.sql.DISABLED_FEATURES) and record
  exactly which queries each fix advances;
- **wire subset**: queries expressible as Substrait plans are ALSO
  driven through the connect front door (connect/server.py) and their
  Arrow results digest-checked against the in-process collect.

CLI:

    python -m spark_rapids_tpu.tools.sweep \\
        [--out SWEEP_r01.json] [--md docs/sweep_coverage.md]
        [--queries 3,27,37] [--scale 1.0] [--no-oracle] [--no-wire]

The committed SWEEP_r01.json is this tool's output at defaults.
"""

from __future__ import annotations

import json
import time
from typing import Optional

#: sweep round — bump when the corpus or classification changes shape
SWEEP_ROUND = 1

#: failure-taxonomy buckets, matched in order against the error text
_TAXONOMY = [
    ("intersect", "set-op INTERSECT not supported"),
    ("except", "set-op EXCEPT not supported"),
    ("cannot tokenize", "tokenizer"),
    ("not in (subquery)", "NOT IN (subquery)"),
    ("month/year interval", "month/year interval on date column"),
    ("grouping sets", "GROUPING SETS"),
    ("unknown function", "unknown function"),
    ("full outer join", "FULL OUTER JOIN shape"),
    ("exists over an aggregating", "EXISTS over aggregate"),
    ("exists correlation", "non-equality EXISTS correlation"),
    ("exists subquery must correlate", "uncorrelated EXISTS"),
    ("in/exists (subquery) is only supported",
     "IN/EXISTS below top-level AND"),
    ("in (subquery) is only supported", "IN-subquery placement"),
    ("scalar subquery must", "scalar subquery shape"),
    ("cartesian", "cartesian product"),
    ("join on needs at least one equality", "non-equi JOIN ON"),
    ("no join condition links", "join graph (comma-join order)"),
    ("derived table requires an alias", "derived-table alias"),
    ("must appear in group by", "group-by binding"),
    ("expected", "grammar (unexpected token)"),
    ("unexpected trailing", "grammar (trailing tokens)"),
    ("mixing count_distinct", "count(distinct) mix"),
    ("distinct unsupported", "DISTINCT aggregate"),
    ("unsupported cast type", "cast type"),
    ("unsupported interval unit", "interval unit"),
    ("unknown table alias", "alias resolution"),
    ("is not registered", "catalog resolution"),
    ("keyerror", "unresolved column (correlated subquery)"),
]


def _classify_reason(msg: str) -> str:
    low = msg.lower()
    for needle, bucket in _TAXONOMY:
        if needle in low:
            return bucket
    return "other"


def _first_line(e: BaseException) -> str:
    return f"{type(e).__name__}: {str(e).splitlines()[0][:200]}"


def build_session(scale: float = 1.0, seed: int = 7, conf=None):
    """A SqlSession with the full mini catalog registered."""
    from spark_rapids_tpu.frontends.sql import SqlSession
    from spark_rapids_tpu.tools.tpcds_schema import generate

    fe = SqlSession(conf)
    for name, tbl in generate(scale=scale, seed=seed).items():
        fe.register_table(name, tbl)
    return fe


def _row_key(row) -> str:
    """Order-insensitive matching key: floats round to fewer digits
    than the comparison tolerance, so ULP-level engine jitter cannot
    reorder near-equal rows into a false positional mismatch."""
    return repr(tuple(round(x, 3) if isinstance(x, float) else x
                      for x in row))


def _tables_equal(a, b, rel_tol: float = 1e-4) -> Optional[str]:
    """None when equal (unordered, float-tolerant); else a reason."""
    if a.num_columns != b.num_columns:
        return f"column count {a.num_columns} != {b.num_columns}"
    if a.num_rows != b.num_rows:
        return f"row count {a.num_rows} != {b.num_rows}"
    ra = sorted(zip(*[c.to_pylist() for c in a.columns]),
                key=_row_key) if a.num_columns else []
    rb = sorted(zip(*[c.to_pylist() for c in b.columns]),
                key=_row_key) if b.num_columns else []
    for x, y in zip(ra, rb):
        for u, v in zip(x, y):
            if isinstance(u, float) and isinstance(v, float):
                if abs(u - v) > rel_tol * max(1.0, abs(u), abs(v)):
                    return f"float mismatch {u} vs {v}"
            elif u != v:
                return f"value mismatch {u!r} vs {v!r}"
    return None


def classify_query(fe, text: str, oracle: bool = True) -> dict:
    """One query's verdict: {stage, status, reason?, rows?, wall_ms}."""
    from spark_rapids_tpu.frontends.sql import SqlError, _Parser

    t0 = time.perf_counter()
    out: dict = {}
    try:
        _Parser(text).parse_select()
    except SqlError as e:
        out.update(stage="parse", status="parse_error",
                   error=_first_line(e),
                   reason=_classify_reason(str(e)))
        return out
    finally:
        out["wall_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
    try:
        df = fe.sql(text)
    except Exception as e:  # noqa: BLE001 — the verdict IS the product
        out.update(stage="plan", status="plan_error",
                   error=_first_line(e),
                   reason=_classify_reason(str(e)))
        out["wall_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
        return out
    try:
        got = df.collect(engine="tpu")
    except Exception as e:  # noqa: BLE001
        out.update(stage="execute", status="exec_error",
                   error=_first_line(e),
                   reason=_classify_reason(str(e)))
        out["wall_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
        return out
    out.update(rows=got.num_rows)
    if not oracle:
        out.update(stage="execute", status="executed")
        out["wall_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
        return out
    try:
        want = df.collect(engine="cpu")
    except Exception as e:  # noqa: BLE001
        out.update(stage="oracle", status="oracle_error",
                   error=_first_line(e),
                   reason=_classify_reason(str(e)))
        out["wall_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
        return out
    diff = _tables_equal(got, want)
    if diff is None:
        out.update(stage="correct", status="correct")
    else:
        out.update(stage="correct", status="mismatch", error=diff,
                   reason="result mismatch vs CPU oracle")
    out["wall_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
    return out


# ------------------------------------------------------------------ #
# Satellite fix probes
# ------------------------------------------------------------------ #

_STAGE_ORDER = {"parse_error": 0, "plan_error": 1, "exec_error": 2,
                "oracle_error": 3, "mismatch": 3, "executed": 3,
                "correct": 4}

FIX_FEATURES = ("not_in_subquery", "month_year_interval",
                "grouping_sets")


def _parse_plan_stage(fe, text: str) -> int:
    """Cheap parse+plan-only stage rank (no execution)."""
    from spark_rapids_tpu.frontends.sql import SqlError, _Parser

    try:
        _Parser(text).parse_select()
    except SqlError:
        return 0
    try:
        fe.sql(text)
    except Exception:  # noqa: BLE001
        return 1
    return 2


def fix_probes(fe, queries: dict, results: dict) -> dict:
    """For each satellite grammar fix: which queries move FORWARD with
    the fix on (probed by disabling the fix and re-running the cheap
    parse/plan stages)."""
    from spark_rapids_tpu.frontends import sql as sql_mod

    out: dict = {}
    for feature in FIX_FEATURES:
        advanced = []
        sql_mod.DISABLED_FEATURES.add(feature)
        try:
            for qid, text in sorted(queries.items()):
                with_fix = results[f"q{qid}"]
                fixed_rank = min(
                    _STAGE_ORDER.get(with_fix["status"], 0), 2)
                disabled_rank = _parse_plan_stage(fe, text)
                if disabled_rank < fixed_rank:
                    advanced.append(f"q{qid}")
        finally:
            sql_mod.DISABLED_FEATURES.discard(feature)
        out[feature] = advanced
    return out


# ------------------------------------------------------------------ #
# Wire subset: Substrait plans through the connect front door
# ------------------------------------------------------------------ #


def _brand_sales_substrait(manager_id: int, moy: int,
                           year: Optional[int]) -> dict:
    """The q52/q55 family as a Substrait plan: date_dim x store_sales
    x item, filter (d_moy, i_manager_id [, d_year]), group by
    (i_brand_id, i_brand), sum(ss_ext_sales_price), sort by the sum
    desc, limit 100."""
    def field(i):
        return {"selection": {"directReference":
                              {"structField": {"field": i}}}}

    def fn(ref, *args):
        return {"scalarFunction": {"functionReference": ref,
                                   "arguments": [{"value": a}
                                                 for a in args]}}

    # store_sales(ss_sold_date_sk, ss_item_sk, ss_ext_sales_price) = 0..2
    # date_dim(d_date_sk, d_year, d_moy) = 3..5
    # item(i_item_sk, i_brand_id, i_brand, i_manager_id) = 6..9
    ss = {"read": {"namedTable": {"names": ["store_sales"]},
                   "baseSchema": {"names": ["ss_sold_date_sk",
                                            "ss_item_sk",
                                            "ss_ext_sales_price"]}}}
    dd = {"read": {"namedTable": {"names": ["date_dim"]},
                   "baseSchema": {"names": ["d_date_sk", "d_year",
                                            "d_moy"]}}}
    it = {"read": {"namedTable": {"names": ["item"]},
                   "baseSchema": {"names": ["i_item_sk", "i_brand_id",
                                            "i_brand",
                                            "i_manager_id"]}}}
    j1 = {"join": {"type": "JOIN_TYPE_INNER", "left": ss, "right": dd,
                   "expression": fn(1, field(0), field(3))}}
    j2 = {"join": {"type": "JOIN_TYPE_INNER", "left": j1, "right": it,
                   "expression": fn(1, field(1), field(6))}}
    conds = [fn(1, field(5), {"literal": {"i64": moy}}),
             fn(1, field(9), {"literal": {"i64": manager_id}})]
    if year is not None:
        conds.append(fn(1, field(4), {"literal": {"i64": year}}))
    cond = conds[0]
    for c in conds[1:]:
        cond = fn(2, cond, c)
    filt = {"filter": {"input": j2, "condition": cond}}
    agg = {"aggregate": {
        "input": filt,
        "groupings": [{"groupingExpressions": [field(7), field(8)]}],
        "measures": [{"measure": {"functionReference": 3,
                                  "arguments":
                                      [{"value": field(2)}]}}]}}
    # aggregate output: [i_brand_id, i_brand, m0]
    srt = {"sort": {"input": agg, "sorts": [
        {"expr": field(2),
         "direction": "SORT_DIRECTION_DESC_NULLS_LAST"},
        {"expr": field(0),
         "direction": "SORT_DIRECTION_ASC_NULLS_FIRST"}]}}
    fetch = {"fetch": {"input": srt, "count": 100}}
    return {
        "extensions": [
            {"extensionFunction": {"functionAnchor": 1,
                                   "name": "equal:any_any"}},
            {"extensionFunction": {"functionAnchor": 2,
                                   "name": "and:bool"}},
            {"extensionFunction": {"functionAnchor": 3,
                                   "name": "sum:fp64"}},
        ],
        "relations": [{"root": {
            "input": fetch,
            "names": ["brand_id", "brand", "ext_price"]}}],
    }


#: query id -> Substrait plan for the wire subset
WIRE_PLANS = {
    42: lambda: _brand_sales_substrait(1, 11, 2000),
    52: lambda: _brand_sales_substrait(1, 11, 2000),
    55: lambda: _brand_sales_substrait(28, 11, 1999),
    3: lambda: _brand_sales_substrait(1, 11, None),
}


def wire_sweep(scale: float = 1.0, seed: int = 7,
               query_ids=None) -> dict:
    """Drive the Substrait-expressible subset through the connect
    server (a real TCP round trip) and digest-check each result
    against the same plan collected in-process.  ``query_ids``
    restricts to that subset of WIRE_PLANS (None = all)."""
    from spark_rapids_tpu.connect.client import (
        ConnectClient,
        table_digest,
    )
    from spark_rapids_tpu.connect.server import ConnectServer
    from spark_rapids_tpu.frontends.substrait import SubstraitFrontend
    from spark_rapids_tpu.tools.tpcds_schema import generate

    catalog = generate(scale=scale, seed=seed)
    srv = ConnectServer()
    for name in ("store_sales", "date_dim", "item"):
        srv.register_table(name, catalog[name])
    srv.start()
    out: dict = {}
    try:
        local = SubstraitFrontend()
        for name in ("store_sales", "date_dim", "item"):
            local.register_table(name, catalog[name])
        host, port = srv.address
        with ConnectClient(host, port, tenant="sweep") as cli:
            for qid, mk in sorted(WIRE_PLANS.items()):
                if query_ids is not None and qid not in query_ids:
                    continue
                plan = mk()
                try:
                    wire_tbl = cli.execute_plan(plan)
                    local_tbl = local.execute_plan(plan)
                    match = (table_digest(wire_tbl)
                             == table_digest(local_tbl.combine_chunks()))
                    out[f"q{qid}"] = {
                        "status": "ok" if match else "digest_mismatch",
                        "rows": wire_tbl.num_rows,
                        "digest_match": match}
                except Exception as e:  # noqa: BLE001
                    out[f"q{qid}"] = {"status": "error",
                                      "error": _first_line(e)}
    finally:
        srv.shutdown()
    return out


# ------------------------------------------------------------------ #
# The sweep
# ------------------------------------------------------------------ #


def run_sweep(query_ids=None, scale: float = 1.0, seed: int = 7,
              oracle: bool = True, wire: bool = True,
              probes: bool = True, verbose: bool = False) -> dict:
    from spark_rapids_tpu.tools.tpcds_queries import QUERIES

    ids = sorted(query_ids) if query_ids else sorted(QUERIES)
    fe = build_session(scale=scale, seed=seed)
    results: dict = {}
    for qid in ids:
        verdict = classify_query(fe, QUERIES[qid], oracle=oracle)
        results[f"q{qid}"] = verdict
        if verbose:
            print(f"q{qid}: {verdict['status']}"
                  + (f" [{verdict.get('reason', '')}]"
                     if verdict.get("reason") else ""), flush=True)
    counts: dict = {}
    for v in results.values():
        counts[v["status"]] = counts.get(v["status"], 0) + 1
    rank = _STAGE_ORDER
    totals = {
        "queries": len(results),
        "parsed": sum(1 for v in results.values()
                      if rank.get(v["status"], 0) >= 1),
        "planned": sum(1 for v in results.values()
                       if rank.get(v["status"], 0) >= 2),
        "executed": sum(1 for v in results.values()
                        if rank.get(v["status"], 0) >= 3
                        and v["status"] != "oracle_error"),
        "correct": counts.get("correct", 0),
        "by_status": counts,
        # summed per-query wall (each verdict's wall_ms covers its
        # parse->oracle chain): the round-over-round perf trend that
        # `tools/history compare SWEEP_r01.json SWEEP_r02.json` diffs
        "wall_ms": round(sum(v.get("wall_ms", 0.0)
                             for v in results.values()), 1),
    }
    taxonomy: dict = {}
    for v in results.values():
        r = v.get("reason")
        if r:
            taxonomy[r] = taxonomy.get(r, 0) + 1
    report = {
        "round": SWEEP_ROUND,
        "scale": scale,
        "seed": seed,
        "totals": totals,
        "failure_taxonomy": dict(sorted(
            taxonomy.items(), key=lambda kv: -kv[1])),
        "queries": results,
    }
    if probes:
        qmap = {qid: QUERIES[qid] for qid in ids}
        report["satellite_advances"] = fix_probes(fe, qmap, results)
    if wire:
        wire_ids = [q for q in WIRE_PLANS
                    if query_ids is None or q in ids]
        if wire_ids:
            report["wire"] = wire_sweep(scale=scale, seed=seed,
                                        query_ids=set(wire_ids))
    return report


def render_markdown(report: dict) -> str:
    t = report["totals"]
    lines = [
        "# TPC-DS 99-query sweep coverage",
        "",
        f"Round r{report['round']:02d} — generated by "
        "`python -m spark_rapids_tpu.tools.sweep` against the "
        "deterministic mini catalog (tools/tpcds_schema.py, scale "
        f"{report['scale']}).  The committed artifact is "
        f"`SWEEP_r{report['round']:02d}.json`.",
        "",
        f"**{t['parsed']}/{t['queries']} parsed · "
        f"{t['planned']} planned · {t['executed']} executed · "
        f"{t['correct']} correct vs the CPU oracle.**",
        "",
        "Stage semantics: *parsed* = the SQL grammar accepts the "
        "text; *planned* = it lowers onto the engine's logical plan; "
        "*executed* = `collect(engine='tpu')` returns (CPU-fallback "
        "operators allowed, exactly like the reference plugin); "
        "*correct* = the result matches an independent CPU-engine "
        "run of the same plan (float-tolerant, order-insensitive).",
        "",
        "## Failure taxonomy (the ranked backlog)",
        "",
        "| Reason | Queries |",
        "|---|---|",
    ]
    tax = report.get("failure_taxonomy", {})
    by_reason: dict = {}
    for name, v in sorted(report["queries"].items(),
                          key=lambda kv: int(kv[0][1:])):
        r = v.get("reason")
        if r:
            by_reason.setdefault(r, []).append(name)
    for reason, _n in sorted(tax.items(), key=lambda kv: -kv[1]):
        qs = ", ".join(by_reason.get(reason, []))
        lines.append(f"| {reason} | {qs} |")
    adv = report.get("satellite_advances")
    if adv:
        lines += ["", "## Satellite grammar fixes (this PR)", "",
                  "| Fix | Queries advanced |", "|---|---|"]
        for feature, qs in adv.items():
            lines.append(f"| {feature} | {', '.join(qs) or '-'} |")
    wire = report.get("wire")
    if wire:
        lines += ["", "## Wire path (Substrait over the connect "
                      "front door)", "",
                  "| Query | Status | Digest == in-process |",
                  "|---|---|---|"]
        for name, v in sorted(wire.items(),
                              key=lambda kv: int(kv[0][1:])):
            lines.append(
                f"| {name} | {v['status']} | "
                f"{v.get('digest_match', '-')} |")
    lines += ["", "## Per-query status", "",
              "| Query | Status | Reason |", "|---|---|---|"]
    for name, v in sorted(report["queries"].items(),
                          key=lambda kv: int(kv[0][1:])):
        lines.append(
            f"| {name} | {v['status']} | {v.get('reason', '')} |")
    lines += [
        "",
        "Corpus dialect notes (tools/tpcds_queries.py): date "
        "arithmetic is spelled `interval 'N' day/month` (the Spark "
        "kit form of `+ N days`); q27 uses the spec-equivalent "
        "GROUPING SETS spelling of its rollup; q16's returns "
        "exclusion uses NOT IN (subquery) on the non-null order "
        "number; q37's 60-day window from 2000-02-01 is `+ interval "
        "'2' month` (identical dates for that anchor).",
        "",
    ]
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="spark_rapids_tpu.tools.sweep",
        description="Run the 99-query TPC-DS coverage sweep.")
    ap.add_argument("--out", default=None,
                    help="write the JSON artifact here "
                         "(default SWEEP_r01.json next to the repo "
                         "root when run from it)")
    ap.add_argument("--md", default=None,
                    help="write the markdown coverage table here")
    ap.add_argument("--queries", default=None,
                    help="comma-separated query numbers (default all)")
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--no-oracle", action="store_true",
                    help="skip the CPU-oracle comparison")
    ap.add_argument("--no-wire", action="store_true",
                    help="skip the connect wire subset")
    ap.add_argument("--no-probes", action="store_true",
                    help="skip the satellite fix probes")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    ids = ([int(x) for x in args.queries.split(",")]
           if args.queries else None)
    report = run_sweep(query_ids=ids, scale=args.scale, seed=args.seed,
                       oracle=not args.no_oracle,
                       wire=not args.no_wire,
                       probes=not args.no_probes,
                       verbose=args.verbose)
    text = json.dumps(report, indent=1, sort_keys=False)
    out = args.out or f"SWEEP_r{SWEEP_ROUND:02d}.json"
    with open(out, "w") as f:
        f.write(text + "\n")
    print(f"wrote {out}")
    if args.md:
        with open(args.md, "w") as f:
            f.write(render_markdown(report))
        print(f"wrote {args.md}")
    t = report["totals"]
    print(f"parsed {t['parsed']}/{t['queries']}, planned "
          f"{t['planned']}, executed {t['executed']}, correct "
          f"{t['correct']}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
