"""`top` for the serving tier: a terminal live view over the ops
plane's HTTP endpoints (docs/ops_plane.md).

Deliberately ENGINE-FREE (stdlib only, like connect/client.py): it
polls ``/queries``, ``/slo`` and ``/metrics`` over HTTP, so it runs
from any machine that can reach the endpoint — including against a
process it did not start.

Run::

    python -m spark_rapids_tpu.tools.top [--url http://127.0.0.1:PORT]
        [--interval 1.0] [--once]

`--once` prints a single frame and exits (the test mode); otherwise
the screen redraws every ``--interval`` seconds until Ctrl-C.
"""

from __future__ import annotations

import json
import sys
import time
import urllib.request

_GAUGES = (
    ("in flight", "tpu_queries_in_flight"),
    ("sem in use", "tpu_semaphore_in_use"),
    ("adm running", "tpu_telemetry_admission_running"),
    ("adm waiting", "tpu_telemetry_admission_waiting"),
    ("store dev B", "tpu_telemetry_store_device_bytes"),
    ("result $ B", "tpu_telemetry_result_cache_bytes"),
)


def _get(url: str, timeout: float = 2.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode("utf-8")


def _metric(parsed: dict, name: str) -> float:
    fam = parsed.get(name) or {"samples": {}}
    return fam["samples"].get("", 0.0)


def render_frame(base_url: str) -> str:
    """One frame of the live view (also the test surface): header
    gauges, the in-flight query table, per-tenant SLO percentiles."""
    from spark_rapids_tpu.obs.metrics import parse_openmetrics

    queries = json.loads(_get(base_url + "/queries"))
    slo = json.loads(_get(base_url + "/slo"))
    parsed = parse_openmetrics(_get(base_url + "/metrics"))
    lines = [f"tpu-top — {base_url}  "
             f"({time.strftime('%H:%M:%S')})", ""]
    lines.append("  ".join(
        f"{label}: {_metric(parsed, name):g}"
        for label, name in _GAUGES))
    lines += ["", f"in-flight queries ({len(queries)}):",
              f"{'qid':>6} {'tenant':<12} {'elapsed':>10} "
              f"{'batches':>8} {'rows':>10} {'cancel':<10} plan"]
    for q in queries:
        cancel = "-"
        if q.get("cancel"):
            c = q["cancel"]
            cancel = c.get("reason") or (
                "armed" if c.get("deadline_remaining_s") is not None
                else "token")
        lines.append(
            f"{q['query_id']:>6} {(q.get('tenant') or '-'):<12} "
            f"{q['elapsed_ms']:>8.1f}ms {q['batches']:>8} "
            f"{q['rows']:>10} {cancel:<10} "
            f"{(q.get('plan_hash') or '')[:12]}")
    tenants = slo.get("tenants", {})
    lines += ["", f"slo (window {slo['budgets']['window_s']:g}s, "
                  f"breaches {slo.get('breach_count', 0)}):",
              f"{'tenant':<12} {'n':>6} {'wall p50':>10} "
              f"{'wall p99':>10} {'wait p99':>10}"]
    for t, s in sorted(tenants.items()):
        lines.append(
            f"{(t or '-'):<12} {s['n']:>6} "
            f"{s['wall_p50_ms']:>8.1f}ms {s['wall_p99_ms']:>8.1f}ms "
            f"{s['admit_wait_p99_ms']:>8.1f}ms")
    for b in slo.get("breaches", [])[-3:]:
        lines.append(f"  BREACH {b['tenant']!r} {b['metric']} "
                     f"{b['observed_ms']:.1f}ms > "
                     f"{b['budget_ms']:g}ms (n={b['window']})")
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m spark_rapids_tpu.tools.top",
        description="terminal live view over the ops plane "
                    "(docs/ops_plane.md)")
    ap.add_argument("--url", default="http://127.0.0.1:9100",
                    help="ops-plane base URL (spark.rapids.tpu.obs.*)")
    ap.add_argument("--interval", type=float, default=1.0)
    ap.add_argument("--once", action="store_true",
                    help="print one frame and exit")
    args = ap.parse_args(argv)
    base = args.url.rstrip("/")
    try:
        while True:
            frame = render_frame(base)
            if args.once:
                print(frame)
                return 0
            # clear + home, then the frame: flicker-free enough for a
            # 1 Hz operator view without a curses dependency
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    except OSError as e:
        print(f"tpu-top: cannot reach {base}: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    raise SystemExit(main())
