"""Trace tool: run a workload with the unified tracer enabled and
export a Chrome-trace JSON timeline.

Usage::

    python -m spark_rapids_tpu.tools.trace [-o trace.json]
                                           [--buffer N]
                                           script.py [script args...]

Runs `script.py` in this process (so in-process engine state — compile
caches, the trace buffer — is shared) with tracing force-enabled,
then writes the collected spans/events as Chrome Trace Format JSON.
Open the output in Perfetto (ui.perfetto.dev) or chrome://tracing; to
line the engine timeline up against device activity, capture an XPlane
trace of the same run with ``tools.profiling.device_trace`` and load
both (docs/observability.md walks through the overlay).

In-process alternative: ``session.export_trace(path)`` after running
queries with ``spark.rapids.tpu.trace.enabled=true``.
"""

from __future__ import annotations

import argparse
import runpy
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m spark_rapids_tpu.tools.trace",
        description="run a python workload with engine tracing enabled "
                    "and export a Chrome-trace JSON (Perfetto-viewable)")
    ap.add_argument("-o", "--output", default="trace.json",
                    help="output Chrome-trace JSON path "
                         "(default: trace.json)")
    ap.add_argument("--buffer", type=int, default=None,
                    help="per-thread ring-buffer capacity "
                         "(default: spark.rapids.tpu.trace.bufferSize)")
    ap.add_argument("script", help="python script to run under tracing")
    ap.add_argument("args", nargs=argparse.REMAINDER,
                    help="arguments passed to the script")
    args = ap.parse_args(argv)

    from spark_rapids_tpu import trace
    from spark_rapids_tpu.trace.export import export_chrome_trace

    trace.enable(args.buffer)
    old_argv = sys.argv
    sys.argv = [args.script] + list(args.args)
    code = 0
    try:
        try:
            runpy.run_path(args.script, run_name="__main__")
        except SystemExit as e:  # still export what was traced
            code = int(e.code or 0) if not isinstance(e.code, str) else 1
    finally:
        sys.argv = old_argv
        events = trace.snapshot()
        path = export_chrome_trace(args.output, events)
        dropped = trace.TRACER.dropped()
        print(f"wrote {path}: {len(events)} events"
              + (f" ({dropped} evicted from full ring buffers)"
                 if dropped else "")
              + " — open in Perfetto (ui.perfetto.dev)")
    return code


if __name__ == "__main__":
    raise SystemExit(main())
