"""TPC-DS schema + deterministic mini catalog for the 99-query sweep.

All 24 benchmark tables with their standard column names, populated
with small, seeded, referentially-consistent data (FKs land inside
their dimension's key range; date_dim is a REAL calendar).  The sweep
harness (tools/sweep.py) registers these with the SQL frontend and
classifies every query's fate against the CPU oracle — the point is
grammar/operator coverage and correctness, not scale (bench.py owns
scale).

Conventions (aligned with the spec where queries depend on it):

- ``*_sk`` surrogate keys are int64; ``d_date_sk`` uses the spec's
  Julian-day numbering (1998-01-01 = 2450815) so literal sk windows in
  query texts land inside the data;
- ``d_month_seq`` counts months since 1900-01 (2000-01 = 1200),
  ``d_week_seq`` counts weeks since 1900-01-01 — the sequences the
  year-over-year queries join on;
- money columns are float64 rounded to cents; flag columns are
  'Y'/'N'; a few percent of non-key fact FKs are NULL.
"""

from __future__ import annotations

import datetime as _dt

import numpy as np
import pyarrow as pa

#: 1998-01-01 as a TPC-DS date_dim surrogate key (Julian day number)
DATE_SK_EPOCH = 2450815
_D0 = _dt.date(1998, 1, 1)
_DAYS = (_dt.date(2003, 12, 31) - _D0).days + 1

_CATEGORIES = ["Books", "Children", "Electronics", "Home", "Jewelry",
               "Men", "Music", "Shoes", "Sports", "Women"]
_CLASSES = ["accent", "bedding", "classical", "dresses", "fiction",
            "fragrances", "mens watch", "pants", "pop", "romance",
            "school-uniforms", "shirts"]
_COLORS = ["aquamarine", "azure", "beige", "black", "blue", "brown",
           "chocolate", "coral", "cream", "cyan", "gold", "green",
           "indigo", "ivory", "khaki", "lime", "magenta", "maroon",
           "navy", "olive", "orange", "pink", "plum", "purple", "red",
           "rose", "salmon", "silver", "snow", "tan", "violet", "white"]
_UNITS = ["Box", "Bunch", "Bundle", "Carton", "Case", "Dozen", "Each",
          "Gram", "Lb", "N/A", "Oz", "Pallet", "Pound", "Tbl", "Ton",
          "Unknown"]
_SIZES = ["economy", "extra large", "large", "medium", "N/A", "petite",
          "small"]
_STATES = ["AL", "CA", "GA", "IL", "IN", "KS", "KY", "LA", "MI", "MN",
           "MO", "MS", "NC", "NY", "OH", "OK", "SD", "TN", "TX", "VA",
           "WA", "WI"]
_CITIES = ["Antioch", "Bethel", "Centerville", "Fairview", "Five Points",
           "Friendship", "Glendale", "Greenville", "Liberty", "Midway",
           "Mount Olive", "Mount Zion", "Oak Grove", "Oak Ridge",
           "Oakland", "Pleasant Grove", "Pleasant Hill", "Riverdale",
           "Riverside", "Salem", "Shiloh", "Springfield", "Union",
           "Walnut Grove", "Wilson"]
_COUNTIES = ["Barrow County", "Daviess County", "Fairfield County",
             "Franklin Parish", "Luce County", "Mobile County",
             "Richland County", "Walker County", "Williamson County",
             "Ziebach County"]
_EDUCATION = ["Primary", "Secondary", "College", "2 yr Degree",
              "4 yr Degree", "Advanced Degree", "Unknown"]
_MARITAL = ["M", "S", "D", "W", "U"]
_CREDIT = ["Good", "High Risk", "Low Risk", "Unknown"]
_BUY_POTENTIAL = [">10000", "5001-10000", "1001-5000", "501-1000",
                  "0-500", "Unknown"]
_DAY_NAMES = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
              "Friday", "Saturday"]
_STORE_NAMES = ["ought", "able", "pri", "ese", "anti", "cally",
                "ation", "eing", "n st", "bar"]
_FIRST = ["James", "John", "Robert", "Michael", "William", "David",
          "Mary", "Patricia", "Linda", "Barbara", "Elizabeth",
          "Jennifer", "Maria", "Susan", "Margaret", "Dorothy"]
_LAST = ["Smith", "Johnson", "Williams", "Jones", "Brown", "Davis",
         "Miller", "Wilson", "Moore", "Taylor", "Anderson", "Thomas",
         "Jackson", "White", "Harris", "Martin"]
_COUNTRIES = ["United States", "Canada", "Mexico", "Germany", "Japan",
              "United Kingdom", "France", "Brazil", "India", "China"]

#: base row counts at scale=1 (kept deliberately small: the sweep's
#: job is coverage classification, not throughput)
ROWS = {
    "store_sales": 20_000, "catalog_sales": 12_000, "web_sales": 12_000,
    "store_returns": 3_000, "catalog_returns": 2_000,
    "web_returns": 2_000, "inventory": 12_000,
    "customer": 1_000, "customer_address": 800,
    "customer_demographics": 1_920, "household_demographics": 720,
    "item": 1_000, "time_dim": 1_440, "income_band": 20,
    "store": 12, "warehouse": 6, "promotion": 30, "reason": 10,
    "ship_mode": 5, "call_center": 4, "web_site": 6, "web_page": 20,
    "catalog_page": 40,
}


def _money(rng, n, lo=1.0, hi=300.0):
    return np.round(rng.uniform(lo, hi, n), 2)


def _flags(rng, n):
    return np.array(["Y", "N"])[rng.integers(0, 2, n)]


def _pick(rng, pool, n):
    return np.array(pool, dtype=object)[rng.integers(0, len(pool), n)]


def _null_some(rng, arr, frac=0.04, type_=None):
    """pa.array with ~frac of entries nulled (fact-table FK realism)."""
    mask = rng.random(len(arr)) < frac
    vals = [None if m else v for v, m in zip(arr.tolist(), mask)]
    return pa.array(vals, type=type_)


def _date_dim() -> pa.Table:
    n = _DAYS
    dates = [_D0 + _dt.timedelta(days=i) for i in range(n)]
    epoch = _dt.date(1970, 1, 1)
    base_1900 = (_D0 - _dt.date(1900, 1, 1)).days
    sk = np.arange(n, dtype=np.int64) + DATE_SK_EPOCH
    year = np.array([d.year for d in dates], np.int64)
    moy = np.array([d.month for d in dates], np.int64)
    dom = np.array([d.day for d in dates], np.int64)
    dow = np.array([(d.weekday() + 1) % 7 for d in dates], np.int64)
    month_seq = (year - 1900) * 12 + (moy - 1)
    week_seq = (base_1900 + np.arange(n)) // 7 + 1
    qoy = (moy - 1) // 3 + 1
    return pa.table({
        "d_date_sk": sk,
        "d_date_id": pa.array([f"AAAAAAAA{i:08d}" for i in range(n)]),
        "d_date": pa.array(
            np.array([(d - epoch).days for d in dates], np.int32),
            type=pa.date32()),
        "d_month_seq": month_seq,
        "d_week_seq": week_seq,
        "d_quarter_seq": (year - 1900) * 4 + (qoy - 1),
        "d_year": year,
        "d_dow": dow,
        "d_moy": moy,
        "d_dom": dom,
        "d_qoy": qoy,
        "d_fy_year": year,
        "d_fy_quarter_seq": (year - 1900) * 4 + (qoy - 1),
        "d_fy_week_seq": week_seq,
        "d_day_name": pa.array([_DAY_NAMES[x] for x in dow]),
        "d_quarter_name": pa.array(
            [f"{y}Q{q}" for y, q in zip(year, qoy)]),
        "d_holiday": pa.array(
            ["Y" if (m, dm) in ((7, 4), (12, 25), (1, 1)) else "N"
             for m, dm in zip(moy, dom)]),
        "d_weekend": pa.array(
            ["Y" if x in (0, 6) else "N" for x in dow]),
        "d_following_holiday": pa.array(
            ["Y" if (m, dm) in ((7, 5), (12, 26), (1, 2)) else "N"
             for m, dm in zip(moy, dom)]),
        "d_first_dom": sk - (dom - 1),
        "d_last_dom": sk + 27,
        "d_same_day_ly": sk - 365,
        "d_same_day_lq": sk - 91,
        "d_current_day": pa.array(["N"] * n),
        "d_current_week": pa.array(["N"] * n),
        "d_current_month": pa.array(["N"] * n),
        "d_current_quarter": pa.array(["N"] * n),
        "d_current_year": pa.array(["N"] * n),
    })


def _time_dim(n: int) -> pa.Table:
    # one row per minute of the day: t_time is the second-of-day at
    # the minute boundary, t_time_sk == t_time (the spec's identity)
    mins = np.arange(n, dtype=np.int64)
    secs = mins * (86400 // max(n, 1))
    hour = secs // 3600
    return pa.table({
        "t_time_sk": secs,
        "t_time_id": pa.array([f"AAAAAAAA{i:08d}" for i in mins]),
        "t_time": secs,
        "t_hour": hour,
        "t_minute": (secs % 3600) // 60,
        "t_second": secs % 60,
        "t_am_pm": pa.array(["AM" if h < 12 else "PM" for h in hour]),
        "t_shift": pa.array(
            ["first" if h < 8 else "second" if h < 16 else "third"
             for h in hour]),
        "t_sub_shift": pa.array(
            ["morning" if h < 12 else "afternoon" if h < 18
             else "evening" for h in hour]),
        "t_meal_time": pa.array(
            ["breakfast" if 6 <= h < 9 else
             "lunch" if 11 <= h < 13 else
             "dinner" if 17 <= h < 20 else None for h in hour]),
    })


def _item(rng, n: int) -> pa.Table:
    sk = np.arange(1, n + 1, dtype=np.int64)
    manu_id = rng.integers(1, 200, n)
    brand_id = (rng.integers(1, 10, n) * 1000000
                + rng.integers(1, 10, n) * 10000 + manu_id)
    cat_idx = rng.integers(0, len(_CATEGORIES), n)
    return pa.table({
        "i_item_sk": sk,
        # two sks share one item_id (the spec's SCD pairing the
        # distinct-buyer queries group on)
        "i_item_id": pa.array([f"AAAAAAAA{x // 2:08d}" for x in sk]),
        "i_rec_start_date": pa.array(
            np.full(n, 9131, np.int32), type=pa.date32()),
        "i_rec_end_date": pa.array([None] * n, type=pa.date32()),
        "i_item_desc": pa.array(
            [f"the promise of item {x} landed" for x in sk]),
        "i_current_price": _money(rng, n, 0.5, 100.0),
        "i_wholesale_cost": _money(rng, n, 0.2, 80.0),
        "i_brand_id": brand_id.astype(np.int64),
        "i_brand": pa.array(
            [f"brand#{b % 100}" for b in brand_id]),
        "i_class_id": rng.integers(1, 17, n).astype(np.int64),
        "i_class": _pick(rng, _CLASSES, n),
        "i_category_id": (cat_idx + 1).astype(np.int64),
        "i_category": pa.array([_CATEGORIES[c] for c in cat_idx]),
        "i_manufact_id": manu_id.astype(np.int64),
        "i_manufact": pa.array([f"manufact#{m}" for m in manu_id]),
        "i_size": _pick(rng, _SIZES, n),
        "i_formulation": pa.array(
            [f"form{x:05d}" for x in rng.integers(0, 1000, n)]),
        "i_color": _pick(rng, _COLORS, n),
        "i_units": _pick(rng, _UNITS, n),
        "i_container": pa.array(["Unknown"] * n),
        "i_manager_id": rng.integers(1, 100, n).astype(np.int64),
        "i_product_name": pa.array([f"product{x}" for x in sk]),
    })


def _customer(rng, n: int, n_addr: int, n_cd: int, n_hd: int,
              n_days: int) -> pa.Table:
    sk = np.arange(1, n + 1, dtype=np.int64)
    return pa.table({
        "c_customer_sk": sk,
        "c_customer_id": pa.array([f"AAAAAAAA{x:08d}" for x in sk]),
        "c_current_cdemo_sk": _null_some(
            rng, rng.integers(1, n_cd + 1, n).astype(np.int64)),
        "c_current_hdemo_sk": _null_some(
            rng, rng.integers(1, n_hd + 1, n).astype(np.int64)),
        "c_current_addr_sk": rng.integers(
            1, n_addr + 1, n).astype(np.int64),
        "c_first_shipto_date_sk": (
            DATE_SK_EPOCH + rng.integers(0, n_days, n)).astype(np.int64),
        "c_first_sales_date_sk": (
            DATE_SK_EPOCH + rng.integers(0, n_days, n)).astype(np.int64),
        "c_salutation": _pick(
            rng, ["Mr.", "Mrs.", "Ms.", "Dr.", "Sir"], n),
        "c_first_name": _pick(rng, _FIRST, n),
        "c_last_name": _pick(rng, _LAST, n),
        "c_preferred_cust_flag": pa.array(list(_flags(rng, n))),
        "c_birth_day": rng.integers(1, 29, n).astype(np.int64),
        "c_birth_month": rng.integers(1, 13, n).astype(np.int64),
        "c_birth_year": rng.integers(1930, 1995, n).astype(np.int64),
        "c_birth_country": _pick(rng, _COUNTRIES, n),
        "c_login": pa.array([f"login{x}" for x in sk]),
        "c_email_address": pa.array(
            [f"c{x}@example.com" for x in sk]),
        "c_last_review_date_sk": (
            DATE_SK_EPOCH + rng.integers(0, n_days, n)).astype(np.int64),
    })


def _customer_address(rng, n: int) -> pa.Table:
    sk = np.arange(1, n + 1, dtype=np.int64)
    return pa.table({
        "ca_address_sk": sk,
        "ca_address_id": pa.array([f"AAAAAAAA{x:08d}" for x in sk]),
        "ca_street_number": pa.array(
            [str(x) for x in rng.integers(1, 1000, n)]),
        "ca_street_name": _pick(
            rng, ["Main", "Oak", "Park", "First", "Elm", "Cedar",
                  "Maple", "Lake", "Hill", "Pine"], n),
        "ca_street_type": _pick(
            rng, ["Street", "Ave", "Blvd", "Ct.", "Dr.", "Lane",
                  "Pkwy", "Rd", "Way"], n),
        "ca_suite_number": pa.array(
            [f"Suite {x}" for x in rng.integers(0, 100, n)]),
        "ca_city": _pick(rng, _CITIES, n),
        "ca_county": _pick(rng, _COUNTIES, n),
        "ca_state": _pick(rng, _STATES, n),
        "ca_zip": pa.array(
            [f"{x:05d}" for x in rng.integers(10000, 99999, n)]),
        "ca_country": pa.array(["United States"] * n),
        "ca_gmt_offset": rng.choice(
            [-5.0, -6.0, -7.0, -8.0], n),
        "ca_location_type": _pick(
            rng, ["apartment", "condo", "single family"], n),
    })


def _customer_demographics(rng, n: int) -> pa.Table:
    sk = np.arange(1, n + 1, dtype=np.int64)
    return pa.table({
        "cd_demo_sk": sk,
        "cd_gender": _pick(rng, ["M", "F"], n),
        "cd_marital_status": _pick(rng, _MARITAL, n),
        "cd_education_status": _pick(rng, _EDUCATION, n),
        "cd_purchase_estimate": (
            rng.integers(1, 20, n) * 500).astype(np.int64),
        "cd_credit_rating": _pick(rng, _CREDIT, n),
        "cd_dep_count": rng.integers(0, 7, n).astype(np.int64),
        "cd_dep_employed_count": rng.integers(0, 7, n).astype(np.int64),
        "cd_dep_college_count": rng.integers(0, 7, n).astype(np.int64),
    })


def _household_demographics(rng, n: int, n_ib: int) -> pa.Table:
    sk = np.arange(1, n + 1, dtype=np.int64)
    return pa.table({
        "hd_demo_sk": sk,
        "hd_income_band_sk": rng.integers(
            1, n_ib + 1, n).astype(np.int64),
        "hd_buy_potential": _pick(rng, _BUY_POTENTIAL, n),
        "hd_dep_count": rng.integers(0, 10, n).astype(np.int64),
        "hd_vehicle_count": rng.integers(-1, 5, n).astype(np.int64),
    })


def _income_band(n: int) -> pa.Table:
    sk = np.arange(1, n + 1, dtype=np.int64)
    return pa.table({
        "ib_income_band_sk": sk,
        "ib_lower_bound": (sk - 1) * 10000,
        "ib_upper_bound": sk * 10000,
    })


def _store(rng, n: int) -> pa.Table:
    sk = np.arange(1, n + 1, dtype=np.int64)
    return pa.table({
        "s_store_sk": sk,
        "s_store_id": pa.array([f"AAAAAAAA{x:08d}" for x in sk]),
        "s_rec_start_date": pa.array(
            np.full(n, 9131, np.int32), type=pa.date32()),
        "s_rec_end_date": pa.array([None] * n, type=pa.date32()),
        "s_closed_date_sk": pa.array([None] * n, type=pa.int64()),
        "s_store_name": pa.array(
            [_STORE_NAMES[int(x) % len(_STORE_NAMES)] for x in sk]),
        "s_number_employees": rng.integers(
            200, 300, n).astype(np.int64),
        "s_floor_space": rng.integers(
            5000000, 9000000, n).astype(np.int64),
        "s_hours": _pick(rng, ["8AM-8AM", "8AM-4PM", "8AM-12AM"], n),
        "s_manager": _pick(rng, _FIRST, n),
        "s_market_id": rng.integers(1, 11, n).astype(np.int64),
        "s_geography_class": pa.array(["Unknown"] * n),
        "s_market_desc": pa.array(
            [f"market description {x}" for x in sk]),
        "s_market_manager": _pick(rng, _FIRST, n),
        "s_division_id": np.ones(n, np.int64),
        "s_division_name": pa.array(["Unknown"] * n),
        "s_company_id": np.ones(n, np.int64),
        "s_company_name": pa.array(["Unknown"] * n),
        "s_street_number": pa.array(
            [str(x) for x in rng.integers(1, 1000, n)]),
        "s_street_name": _pick(rng, ["Main", "Oak", "Park"], n),
        "s_street_type": _pick(rng, ["Street", "Ave", "Blvd"], n),
        "s_suite_number": pa.array(
            [f"Suite {x}" for x in rng.integers(0, 100, n)]),
        "s_city": _pick(rng, _CITIES[:6], n),
        "s_county": _pick(rng, _COUNTIES, n),
        "s_state": _pick(rng, _STATES[:8], n),
        "s_zip": pa.array(
            [f"{x:05d}" for x in rng.integers(10000, 99999, n)]),
        "s_country": pa.array(["United States"] * n),
        "s_gmt_offset": rng.choice([-5.0, -6.0], n),
        "s_tax_precentage": np.round(rng.uniform(0.0, 0.11, n), 2),
    })


def _warehouse(rng, n: int) -> pa.Table:
    sk = np.arange(1, n + 1, dtype=np.int64)
    return pa.table({
        "w_warehouse_sk": sk,
        "w_warehouse_id": pa.array([f"AAAAAAAA{x:08d}" for x in sk]),
        "w_warehouse_name": pa.array(
            [f"Warehouse number {x}" for x in sk]),
        "w_warehouse_sq_ft": rng.integers(
            50000, 1000000, n).astype(np.int64),
        "w_street_number": pa.array(
            [str(x) for x in rng.integers(1, 1000, n)]),
        "w_street_name": _pick(rng, ["Main", "Oak", "Park"], n),
        "w_street_type": _pick(rng, ["Street", "Ave"], n),
        "w_suite_number": pa.array(
            [f"Suite {x}" for x in rng.integers(0, 100, n)]),
        "w_city": _pick(rng, _CITIES[:6], n),
        "w_county": _pick(rng, _COUNTIES, n),
        "w_state": _pick(rng, _STATES[:8], n),
        "w_zip": pa.array(
            [f"{x:05d}" for x in rng.integers(10000, 99999, n)]),
        "w_country": pa.array(["United States"] * n),
        "w_gmt_offset": rng.choice([-5.0, -6.0], n),
    })


def _ship_mode(n: int) -> pa.Table:
    sk = np.arange(1, n + 1, dtype=np.int64)
    types = ["EXPRESS", "NEXT DAY", "OVERNIGHT", "REGULAR", "LIBRARY"]
    return pa.table({
        "sm_ship_mode_sk": sk,
        "sm_ship_mode_id": pa.array([f"AAAAAAAA{x:08d}" for x in sk]),
        "sm_type": pa.array([types[int(x - 1) % len(types)]
                             for x in sk]),
        "sm_code": pa.array(["AIR", "SURFACE", "SEA", "AIR", "SURFACE"
                             ][:n]),
        "sm_carrier": pa.array(["UPS", "FEDEX", "AIRBORNE", "USPS",
                                "DHL"][:n]),
        "sm_contract": pa.array([f"contract{x}" for x in sk]),
    })


def _reason(n: int) -> pa.Table:
    sk = np.arange(1, n + 1, dtype=np.int64)
    descs = ["Package was damaged", "Stopped working",
             "Did not get it on time", "Not the product that was "
             "ordred", "Parts missing", "Does not work with a product "
             "that I have", "Gift exchange", "Did not like the color",
             "Did not like the model", "Did not fit"]
    return pa.table({
        "r_reason_sk": sk,
        "r_reason_id": pa.array([f"AAAAAAAA{x:08d}" for x in sk]),
        "r_reason_desc": pa.array(descs[:n]),
    })


def _promotion(rng, n: int, n_item: int, n_days: int) -> pa.Table:
    sk = np.arange(1, n + 1, dtype=np.int64)
    return pa.table({
        "p_promo_sk": sk,
        "p_promo_id": pa.array([f"AAAAAAAA{x:08d}" for x in sk]),
        "p_start_date_sk": (DATE_SK_EPOCH + rng.integers(
            0, n_days, n)).astype(np.int64),
        "p_end_date_sk": (DATE_SK_EPOCH + rng.integers(
            0, n_days, n)).astype(np.int64),
        "p_item_sk": rng.integers(1, n_item + 1, n).astype(np.int64),
        "p_cost": np.round(rng.uniform(500.0, 2000.0, n), 2),
        "p_response_target": np.ones(n, np.int64),
        "p_promo_name": _pick(
            rng, ["anti", "bar", "cally", "ese", "ought"], n),
        "p_channel_dmail": pa.array(list(_flags(rng, n))),
        "p_channel_email": pa.array(list(_flags(rng, n))),
        "p_channel_catalog": pa.array(list(_flags(rng, n))),
        "p_channel_tv": pa.array(list(_flags(rng, n))),
        "p_channel_radio": pa.array(list(_flags(rng, n))),
        "p_channel_press": pa.array(list(_flags(rng, n))),
        "p_channel_event": pa.array(list(_flags(rng, n))),
        "p_channel_demo": pa.array(list(_flags(rng, n))),
        "p_channel_details": pa.array(
            [f"promo details {x}" for x in sk]),
        "p_purpose": pa.array(["Unknown"] * n),
        "p_discount_active": pa.array(["N"] * n),
    })


def _call_center(rng, n: int) -> pa.Table:
    sk = np.arange(1, n + 1, dtype=np.int64)
    return pa.table({
        "cc_call_center_sk": sk,
        "cc_call_center_id": pa.array(
            [f"AAAAAAAA{x:08d}" for x in sk]),
        "cc_rec_start_date": pa.array(
            np.full(n, 9131, np.int32), type=pa.date32()),
        "cc_rec_end_date": pa.array([None] * n, type=pa.date32()),
        "cc_name": pa.array(
            [f"call center {x}" for x in sk]),
        "cc_class": _pick(rng, ["small", "medium", "large"], n),
        "cc_employees": rng.integers(100, 700, n).astype(np.int64),
        "cc_sq_ft": rng.integers(10000, 50000, n).astype(np.int64),
        "cc_hours": _pick(rng, ["8AM-8AM", "8AM-4PM"], n),
        "cc_manager": _pick(rng, _FIRST, n),
        "cc_mkt_id": rng.integers(1, 7, n).astype(np.int64),
        "cc_mkt_class": pa.array([f"mkt class {x}" for x in sk]),
        "cc_mkt_desc": pa.array([f"mkt desc {x}" for x in sk]),
        "cc_market_manager": _pick(rng, _FIRST, n),
        "cc_division": np.ones(n, np.int64),
        "cc_division_name": pa.array(["Unknown"] * n),
        "cc_company": np.ones(n, np.int64),
        "cc_company_name": pa.array(["Unknown"] * n),
        "cc_county": _pick(rng, _COUNTIES, n),
        "cc_state": _pick(rng, _STATES[:8], n),
        "cc_country": pa.array(["United States"] * n),
        "cc_gmt_offset": rng.choice([-5.0, -6.0], n),
        "cc_tax_percentage": np.round(rng.uniform(0.0, 0.12, n), 2),
    })


def _web_site(rng, n: int) -> pa.Table:
    sk = np.arange(1, n + 1, dtype=np.int64)
    return pa.table({
        "web_site_sk": sk,
        "web_site_id": pa.array([f"AAAAAAAA{x:08d}" for x in sk]),
        "web_name": pa.array([f"site_{x}" for x in sk]),
        "web_mkt_id": rng.integers(1, 7, n).astype(np.int64),
        "web_company_name": _pick(
            rng, ["pri", "able", "ought", "ese", "anti"], n),
        "web_manager": _pick(rng, _FIRST, n),
        "web_county": _pick(rng, _COUNTIES, n),
        "web_state": _pick(rng, _STATES[:8], n),
        "web_country": pa.array(["United States"] * n),
        "web_gmt_offset": rng.choice([-5.0, -6.0], n),
        "web_tax_percentage": np.round(rng.uniform(0.0, 0.12, n), 2),
    })


def _web_page(rng, n: int, n_days: int) -> pa.Table:
    sk = np.arange(1, n + 1, dtype=np.int64)
    return pa.table({
        "wp_web_page_sk": sk,
        "wp_web_page_id": pa.array([f"AAAAAAAA{x:08d}" for x in sk]),
        "wp_creation_date_sk": (DATE_SK_EPOCH + rng.integers(
            0, n_days, n)).astype(np.int64),
        "wp_access_date_sk": (DATE_SK_EPOCH + rng.integers(
            0, n_days, n)).astype(np.int64),
        "wp_autogen_flag": pa.array(list(_flags(rng, n))),
        "wp_customer_sk": _null_some(
            rng, rng.integers(1, 100, n).astype(np.int64), 0.5,
            pa.int64()),
        "wp_url": pa.array(["http://www.foo.com"] * n),
        "wp_type": _pick(
            rng, ["ad", "dynamic", "feedback", "general", "order",
                  "protected", "welcome"], n),
        "wp_char_count": rng.integers(
            1000, 8000, n).astype(np.int64),
        "wp_link_count": rng.integers(2, 25, n).astype(np.int64),
        "wp_image_count": rng.integers(1, 7, n).astype(np.int64),
        "wp_max_ad_count": rng.integers(0, 5, n).astype(np.int64),
    })


def _catalog_page(rng, n: int) -> pa.Table:
    sk = np.arange(1, n + 1, dtype=np.int64)
    return pa.table({
        "cp_catalog_page_sk": sk,
        "cp_catalog_page_id": pa.array(
            [f"AAAAAAAA{x:08d}" for x in sk]),
        "cp_start_date_sk": np.full(n, DATE_SK_EPOCH, np.int64),
        "cp_end_date_sk": np.full(n, DATE_SK_EPOCH + 364, np.int64),
        "cp_department": pa.array(["DEPARTMENT"] * n),
        "cp_catalog_number": ((sk - 1) // 10 + 1),
        "cp_catalog_page_number": ((sk - 1) % 10 + 1),
        "cp_description": pa.array([f"catalog page {x}" for x in sk]),
        "cp_type": _pick(
            rng, ["bi-annual", "monthly", "quarterly"], n),
    })


def generate(scale: float = 1.0, seed: int = 7) -> dict:
    """The full mini catalog: {table_name: pa.Table}, deterministic in
    (scale, seed)."""
    rng = np.random.default_rng(seed)
    rows = {k: max(4, int(v * scale)) for k, v in ROWS.items()}
    n_days = _DAYS
    out: dict = {}
    out["date_dim"] = _date_dim()
    out["time_dim"] = _time_dim(rows["time_dim"])
    out["item"] = _item(rng, rows["item"])
    out["customer_address"] = _customer_address(
        rng, rows["customer_address"])
    out["customer_demographics"] = _customer_demographics(
        rng, rows["customer_demographics"])
    out["income_band"] = _income_band(rows["income_band"])
    out["household_demographics"] = _household_demographics(
        rng, rows["household_demographics"], rows["income_band"])
    out["customer"] = _customer(
        rng, rows["customer"], rows["customer_address"],
        rows["customer_demographics"],
        rows["household_demographics"], n_days)
    out["store"] = _store(rng, rows["store"])
    out["warehouse"] = _warehouse(rng, rows["warehouse"])
    out["ship_mode"] = _ship_mode(rows["ship_mode"])
    out["reason"] = _reason(rows["reason"])
    out["promotion"] = _promotion(
        rng, rows["promotion"], rows["item"], n_days)
    out["call_center"] = _call_center(rng, rows["call_center"])
    out["web_site"] = _web_site(rng, rows["web_site"])
    out["web_page"] = _web_page(rng, rows["web_page"], n_days)
    out["catalog_page"] = _catalog_page(rng, rows["catalog_page"])

    def dsk(n):
        # concentrate sales in 1998-2002 so year-filtered queries hit
        return (DATE_SK_EPOCH
                + rng.integers(0, min(n_days, 365 * 5), n)).astype(
                    np.int64)

    def tsk(n):
        return out["time_dim"].column("t_time_sk")[
            0].as_py() + (rng.integers(0, rows["time_dim"], n)
                          * (86400 // rows["time_dim"])).astype(np.int64)

    n = rows["store_sales"]
    qty = rng.integers(1, 101, n).astype(np.int64)
    wcost = _money(rng, n, 1, 100)
    lprice = np.round(wcost * rng.uniform(1.0, 2.0, n), 2)
    sprice = np.round(lprice * rng.uniform(0.3, 1.0, n), 2)
    ext_sales = np.round(sprice * qty, 2)
    ext_wcost = np.round(wcost * qty, 2)
    ext_list = np.round(lprice * qty, 2)
    discount = np.round(ext_list - ext_sales, 2)
    tax = np.round(ext_sales * 0.05, 2)
    coupon = np.round(ext_sales * (rng.random(n) < 0.1)
                      * rng.uniform(0, 0.5, n), 2)
    net_paid = np.round(ext_sales - coupon, 2)
    out["store_sales"] = pa.table({
        "ss_sold_date_sk": _null_some(rng, dsk(n), 0.02, pa.int64()),
        "ss_sold_time_sk": tsk(n),
        "ss_item_sk": rng.integers(
            1, rows["item"] + 1, n).astype(np.int64),
        "ss_customer_sk": _null_some(
            rng, rng.integers(1, rows["customer"] + 1, n)
            .astype(np.int64), 0.03, pa.int64()),
        "ss_cdemo_sk": rng.integers(
            1, rows["customer_demographics"] + 1, n).astype(np.int64),
        "ss_hdemo_sk": rng.integers(
            1, rows["household_demographics"] + 1, n).astype(np.int64),
        "ss_addr_sk": rng.integers(
            1, rows["customer_address"] + 1, n).astype(np.int64),
        "ss_store_sk": rng.integers(
            1, rows["store"] + 1, n).astype(np.int64),
        "ss_promo_sk": rng.integers(
            1, rows["promotion"] + 1, n).astype(np.int64),
        "ss_ticket_number": (np.arange(n, dtype=np.int64) // 4 + 1),
        "ss_quantity": qty,
        "ss_wholesale_cost": wcost,
        "ss_list_price": lprice,
        "ss_sales_price": sprice,
        "ss_ext_discount_amt": discount,
        "ss_ext_sales_price": ext_sales,
        "ss_ext_wholesale_cost": ext_wcost,
        "ss_ext_list_price": ext_list,
        "ss_ext_tax": tax,
        "ss_coupon_amt": coupon,
        "ss_net_paid": net_paid,
        "ss_net_paid_inc_tax": np.round(net_paid + tax, 2),
        "ss_net_profit": np.round(net_paid - ext_wcost, 2),
    })

    n = rows["store_returns"]
    ridx = rng.integers(0, rows["store_sales"], n)
    ss = out["store_sales"]
    ret_amt = _money(rng, n, 1, 300)
    out["store_returns"] = pa.table({
        "sr_returned_date_sk": dsk(n),
        "sr_return_time_sk": tsk(n),
        "sr_item_sk": pa.array(
            [ss.column("ss_item_sk")[i].as_py() for i in ridx],
            pa.int64()),
        "sr_customer_sk": pa.array(
            [ss.column("ss_customer_sk")[i].as_py() for i in ridx],
            pa.int64()),
        "sr_cdemo_sk": rng.integers(
            1, rows["customer_demographics"] + 1, n).astype(np.int64),
        "sr_hdemo_sk": rng.integers(
            1, rows["household_demographics"] + 1, n).astype(np.int64),
        "sr_addr_sk": rng.integers(
            1, rows["customer_address"] + 1, n).astype(np.int64),
        "sr_store_sk": pa.array(
            [ss.column("ss_store_sk")[i].as_py() for i in ridx],
            pa.int64()),
        "sr_reason_sk": rng.integers(
            1, rows["reason"] + 1, n).astype(np.int64),
        "sr_ticket_number": pa.array(
            [ss.column("ss_ticket_number")[i].as_py() for i in ridx],
            pa.int64()),
        "sr_return_quantity": rng.integers(1, 20, n).astype(np.int64),
        "sr_return_amt": ret_amt,
        "sr_return_tax": np.round(ret_amt * 0.05, 2),
        "sr_return_amt_inc_tax": np.round(ret_amt * 1.05, 2),
        "sr_fee": _money(rng, n, 0.5, 100),
        "sr_return_ship_cost": _money(rng, n, 0, 50),
        "sr_refunded_cash": np.round(ret_amt * 0.7, 2),
        "sr_reversed_charge": np.round(ret_amt * 0.2, 2),
        "sr_store_credit": np.round(ret_amt * 0.1, 2),
        "sr_net_loss": _money(rng, n, 0.5, 200),
    })

    def _sales(prefix: str, n: int, order_div: int) -> pa.Table:
        qty = rng.integers(1, 101, n).astype(np.int64)
        wcost = _money(rng, n, 1, 100)
        lprice = np.round(wcost * rng.uniform(1.0, 2.0, n), 2)
        sprice = np.round(lprice * rng.uniform(0.3, 1.0, n), 2)
        ext_sales = np.round(sprice * qty, 2)
        ext_wcost = np.round(wcost * qty, 2)
        ext_list = np.round(lprice * qty, 2)
        tax = np.round(ext_sales * 0.05, 2)
        ship = _money(rng, n, 0, 150)
        coupon = np.round(ext_sales * (rng.random(n) < 0.1)
                          * rng.uniform(0, 0.5, n), 2)
        net_paid = np.round(ext_sales - coupon, 2)
        sold = dsk(n)
        cols = {
            "sold_date_sk": sold,
            "sold_time_sk": tsk(n),
            "ship_date_sk": sold + rng.integers(2, 90, n),
            "bill_customer_sk": rng.integers(
                1, rows["customer"] + 1, n).astype(np.int64),
            "bill_cdemo_sk": rng.integers(
                1, rows["customer_demographics"] + 1,
                n).astype(np.int64),
            "bill_hdemo_sk": rng.integers(
                1, rows["household_demographics"] + 1,
                n).astype(np.int64),
            "bill_addr_sk": rng.integers(
                1, rows["customer_address"] + 1, n).astype(np.int64),
            "ship_customer_sk": rng.integers(
                1, rows["customer"] + 1, n).astype(np.int64),
            "ship_cdemo_sk": rng.integers(
                1, rows["customer_demographics"] + 1,
                n).astype(np.int64),
            "ship_hdemo_sk": rng.integers(
                1, rows["household_demographics"] + 1,
                n).astype(np.int64),
            "ship_addr_sk": rng.integers(
                1, rows["customer_address"] + 1, n).astype(np.int64),
            "ship_mode_sk": rng.integers(
                1, rows["ship_mode"] + 1, n).astype(np.int64),
            "warehouse_sk": rng.integers(
                1, rows["warehouse"] + 1, n).astype(np.int64),
            "item_sk": rng.integers(
                1, rows["item"] + 1, n).astype(np.int64),
            "promo_sk": rng.integers(
                1, rows["promotion"] + 1, n).astype(np.int64),
            "order_number": (np.arange(n, dtype=np.int64)
                             // order_div + 1),
            "quantity": qty,
            "wholesale_cost": wcost,
            "list_price": lprice,
            "sales_price": sprice,
            "ext_discount_amt": np.round(ext_list - ext_sales, 2),
            "ext_sales_price": ext_sales,
            "ext_wholesale_cost": ext_wcost,
            "ext_list_price": ext_list,
            "ext_tax": tax,
            "coupon_amt": coupon,
            "ext_ship_cost": ship,
            "net_paid": net_paid,
            "net_paid_inc_tax": np.round(net_paid + tax, 2),
            "net_paid_inc_ship": np.round(net_paid + ship, 2),
            "net_paid_inc_ship_tax": np.round(net_paid + ship + tax, 2),
            "net_profit": np.round(net_paid - ext_wcost, 2),
        }
        return cols

    cs = _sales("cs", rows["catalog_sales"], 3)
    out["catalog_sales"] = pa.table({
        "cs_sold_date_sk": _null_some(rng, cs["sold_date_sk"], 0.02,
                                      pa.int64()),
        "cs_sold_time_sk": cs["sold_time_sk"],
        "cs_ship_date_sk": cs["ship_date_sk"],
        "cs_bill_customer_sk": cs["bill_customer_sk"],
        "cs_bill_cdemo_sk": cs["bill_cdemo_sk"],
        "cs_bill_hdemo_sk": cs["bill_hdemo_sk"],
        "cs_bill_addr_sk": cs["bill_addr_sk"],
        "cs_ship_customer_sk": cs["ship_customer_sk"],
        "cs_ship_cdemo_sk": cs["ship_cdemo_sk"],
        "cs_ship_hdemo_sk": cs["ship_hdemo_sk"],
        "cs_ship_addr_sk": cs["ship_addr_sk"],
        "cs_call_center_sk": rng.integers(
            1, rows["call_center"] + 1,
            rows["catalog_sales"]).astype(np.int64),
        "cs_catalog_page_sk": rng.integers(
            1, rows["catalog_page"] + 1,
            rows["catalog_sales"]).astype(np.int64),
        "cs_ship_mode_sk": cs["ship_mode_sk"],
        "cs_warehouse_sk": cs["warehouse_sk"],
        "cs_item_sk": cs["item_sk"],
        "cs_promo_sk": cs["promo_sk"],
        "cs_order_number": cs["order_number"],
        "cs_quantity": cs["quantity"],
        "cs_wholesale_cost": cs["wholesale_cost"],
        "cs_list_price": cs["list_price"],
        "cs_sales_price": cs["sales_price"],
        "cs_ext_discount_amt": cs["ext_discount_amt"],
        "cs_ext_sales_price": cs["ext_sales_price"],
        "cs_ext_wholesale_cost": cs["ext_wholesale_cost"],
        "cs_ext_list_price": cs["ext_list_price"],
        "cs_ext_tax": cs["ext_tax"],
        "cs_coupon_amt": cs["coupon_amt"],
        "cs_ext_ship_cost": cs["ext_ship_cost"],
        "cs_net_paid": cs["net_paid"],
        "cs_net_paid_inc_tax": cs["net_paid_inc_tax"],
        "cs_net_paid_inc_ship": cs["net_paid_inc_ship"],
        "cs_net_paid_inc_ship_tax": cs["net_paid_inc_ship_tax"],
        "cs_net_profit": cs["net_profit"],
    })

    ws = _sales("ws", rows["web_sales"], 3)
    out["web_sales"] = pa.table({
        "ws_sold_date_sk": _null_some(rng, ws["sold_date_sk"], 0.02,
                                      pa.int64()),
        "ws_sold_time_sk": ws["sold_time_sk"],
        "ws_ship_date_sk": ws["ship_date_sk"],
        "ws_item_sk": ws["item_sk"],
        "ws_bill_customer_sk": ws["bill_customer_sk"],
        "ws_bill_cdemo_sk": ws["bill_cdemo_sk"],
        "ws_bill_hdemo_sk": ws["bill_hdemo_sk"],
        "ws_bill_addr_sk": ws["bill_addr_sk"],
        "ws_ship_customer_sk": ws["ship_customer_sk"],
        "ws_ship_cdemo_sk": ws["ship_cdemo_sk"],
        "ws_ship_hdemo_sk": ws["ship_hdemo_sk"],
        "ws_ship_addr_sk": ws["ship_addr_sk"],
        "ws_web_page_sk": rng.integers(
            1, rows["web_page"] + 1, rows["web_sales"]).astype(np.int64),
        "ws_web_site_sk": rng.integers(
            1, rows["web_site"] + 1, rows["web_sales"]).astype(np.int64),
        "ws_ship_mode_sk": ws["ship_mode_sk"],
        "ws_warehouse_sk": ws["warehouse_sk"],
        "ws_promo_sk": ws["promo_sk"],
        "ws_order_number": ws["order_number"],
        "ws_quantity": ws["quantity"],
        "ws_wholesale_cost": ws["wholesale_cost"],
        "ws_list_price": ws["list_price"],
        "ws_sales_price": ws["sales_price"],
        "ws_ext_discount_amt": ws["ext_discount_amt"],
        "ws_ext_sales_price": ws["ext_sales_price"],
        "ws_ext_wholesale_cost": ws["ext_wholesale_cost"],
        "ws_ext_list_price": ws["ext_list_price"],
        "ws_ext_tax": ws["ext_tax"],
        "ws_coupon_amt": ws["coupon_amt"],
        "ws_ext_ship_cost": ws["ext_ship_cost"],
        "ws_net_paid": ws["net_paid"],
        "ws_net_paid_inc_tax": ws["net_paid_inc_tax"],
        "ws_net_paid_inc_ship": ws["net_paid_inc_ship"],
        "ws_net_paid_inc_ship_tax": ws["net_paid_inc_ship_tax"],
        "ws_net_profit": ws["net_profit"],
    })

    def _returns(sales: pa.Table, pfx: str, n: int,
                 item_col: str, order_col: str, cust_col: str) -> dict:
        ridx = rng.integers(0, sales.num_rows, n)
        amt = _money(rng, n, 1, 300)
        return {
            "returned_date_sk": dsk(n),
            "returned_time_sk": tsk(n),
            "item_sk": pa.array(
                [sales.column(item_col)[i].as_py() for i in ridx],
                pa.int64()),
            "order_number": pa.array(
                [sales.column(order_col)[i].as_py() for i in ridx],
                pa.int64()),
            "customer_sk": pa.array(
                [sales.column(cust_col)[i].as_py() for i in ridx],
                pa.int64()),
            "quantity": rng.integers(1, 20, n).astype(np.int64),
            "amt": amt,
            "tax": np.round(amt * 0.05, 2),
            "amt_inc_tax": np.round(amt * 1.05, 2),
            "fee": _money(rng, n, 0.5, 100),
            "ship_cost": _money(rng, n, 0, 50),
            "refunded_cash": np.round(amt * 0.7, 2),
            "reversed_charge": np.round(amt * 0.2, 2),
            "credit": np.round(amt * 0.1, 2),
            "net_loss": _money(rng, n, 0.5, 200),
        }

    n = rows["catalog_returns"]
    cr = _returns(out["catalog_sales"], "cr", n, "cs_item_sk",
                  "cs_order_number", "cs_bill_customer_sk")
    out["catalog_returns"] = pa.table({
        "cr_returned_date_sk": cr["returned_date_sk"],
        "cr_returned_time_sk": cr["returned_time_sk"],
        "cr_item_sk": cr["item_sk"],
        "cr_refunded_customer_sk": cr["customer_sk"],
        "cr_refunded_cdemo_sk": rng.integers(
            1, rows["customer_demographics"] + 1, n).astype(np.int64),
        "cr_refunded_hdemo_sk": rng.integers(
            1, rows["household_demographics"] + 1, n).astype(np.int64),
        "cr_refunded_addr_sk": rng.integers(
            1, rows["customer_address"] + 1, n).astype(np.int64),
        "cr_returning_customer_sk": rng.integers(
            1, rows["customer"] + 1, n).astype(np.int64),
        "cr_returning_cdemo_sk": rng.integers(
            1, rows["customer_demographics"] + 1, n).astype(np.int64),
        "cr_returning_hdemo_sk": rng.integers(
            1, rows["household_demographics"] + 1, n).astype(np.int64),
        "cr_returning_addr_sk": rng.integers(
            1, rows["customer_address"] + 1, n).astype(np.int64),
        "cr_call_center_sk": rng.integers(
            1, rows["call_center"] + 1, n).astype(np.int64),
        "cr_catalog_page_sk": rng.integers(
            1, rows["catalog_page"] + 1, n).astype(np.int64),
        "cr_ship_mode_sk": rng.integers(
            1, rows["ship_mode"] + 1, n).astype(np.int64),
        "cr_warehouse_sk": rng.integers(
            1, rows["warehouse"] + 1, n).astype(np.int64),
        "cr_reason_sk": rng.integers(
            1, rows["reason"] + 1, n).astype(np.int64),
        "cr_order_number": cr["order_number"],
        "cr_return_quantity": cr["quantity"],
        "cr_return_amount": cr["amt"],
        "cr_return_tax": cr["tax"],
        "cr_return_amt_inc_tax": cr["amt_inc_tax"],
        "cr_fee": cr["fee"],
        "cr_return_ship_cost": cr["ship_cost"],
        "cr_refunded_cash": cr["refunded_cash"],
        "cr_reversed_charge": cr["reversed_charge"],
        "cr_store_credit": cr["credit"],
        "cr_net_loss": cr["net_loss"],
    })

    n = rows["web_returns"]
    wr = _returns(out["web_sales"], "wr", n, "ws_item_sk",
                  "ws_order_number", "ws_bill_customer_sk")
    out["web_returns"] = pa.table({
        "wr_returned_date_sk": wr["returned_date_sk"],
        "wr_returned_time_sk": wr["returned_time_sk"],
        "wr_item_sk": wr["item_sk"],
        "wr_refunded_customer_sk": wr["customer_sk"],
        "wr_refunded_cdemo_sk": rng.integers(
            1, rows["customer_demographics"] + 1, n).astype(np.int64),
        "wr_refunded_hdemo_sk": rng.integers(
            1, rows["household_demographics"] + 1, n).astype(np.int64),
        "wr_refunded_addr_sk": rng.integers(
            1, rows["customer_address"] + 1, n).astype(np.int64),
        "wr_returning_customer_sk": rng.integers(
            1, rows["customer"] + 1, n).astype(np.int64),
        "wr_returning_cdemo_sk": rng.integers(
            1, rows["customer_demographics"] + 1, n).astype(np.int64),
        "wr_returning_hdemo_sk": rng.integers(
            1, rows["household_demographics"] + 1, n).astype(np.int64),
        "wr_returning_addr_sk": rng.integers(
            1, rows["customer_address"] + 1, n).astype(np.int64),
        "wr_web_page_sk": rng.integers(
            1, rows["web_page"] + 1, n).astype(np.int64),
        "wr_reason_sk": rng.integers(
            1, rows["reason"] + 1, n).astype(np.int64),
        "wr_order_number": wr["order_number"],
        "wr_return_quantity": wr["quantity"],
        "wr_return_amt": wr["amt"],
        "wr_return_tax": wr["tax"],
        "wr_return_amt_inc_tax": wr["amt_inc_tax"],
        "wr_fee": wr["fee"],
        "wr_return_ship_cost": wr["ship_cost"],
        "wr_refunded_cash": wr["refunded_cash"],
        "wr_reversed_charge": wr["reversed_charge"],
        "wr_account_credit": wr["credit"],
        "wr_net_loss": wr["net_loss"],
    })

    n = rows["inventory"]
    out["inventory"] = pa.table({
        "inv_date_sk": dsk(n),
        "inv_item_sk": rng.integers(
            1, rows["item"] + 1, n).astype(np.int64),
        "inv_warehouse_sk": rng.integers(
            1, rows["warehouse"] + 1, n).astype(np.int64),
        "inv_quantity_on_hand": rng.integers(
            0, 1000, n).astype(np.int64),
    })
    return out
