"""Native (C++) host-runtime components.

Compiled lazily with the system toolchain into a per-source-hash
shared object and loaded through ctypes (pybind11 is unavailable;
a plain C ABI keeps the binding dependency-free).  Every native entry
point has a numpy fallback in its caller, so a missing compiler only
costs performance, never correctness — the same posture the reference
takes toward its optional JNI acceleration libraries.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

_lib = None
_tried = False
_lock = threading.Lock()


def _build(src: str, out: str) -> bool:
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
           src, "-o", out]
    try:
        r = subprocess.run(cmd, capture_output=True, timeout=120)
        return r.returncode == 0 and os.path.exists(out)
    except Exception:
        return False


def load() -> Optional[ctypes.CDLL]:
    """The host codec library, building it on first use; None when no
    toolchain is available (callers fall back to numpy)."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        here = os.path.dirname(__file__)
        src = os.path.join(here, "hostcodec.cpp")
        try:
            with open(src, "rb") as f:
                tag = hashlib.sha256(f.read()).hexdigest()[:16]
        except OSError:
            return None
        build_dir = os.path.join(here, "_build")
        out = os.path.join(build_dir, f"hostcodec-{tag}.so")
        if not os.path.exists(out):
            try:
                os.makedirs(build_dir, exist_ok=True)
            except OSError:
                return None
            if not _build(src, out):
                return None
        try:
            lib = ctypes.CDLL(out)
        except OSError:
            return None
        _declare(lib)
        _lib = lib
        return _lib


def _declare(lib: ctypes.CDLL) -> None:
    c = ctypes
    lib.chars_fill.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p,
                               c.c_int64, c.c_int64, c.c_void_p]
    lib.chars_fill.restype = None
    for name in ("minmax_i64", "minmax_i32"):
        fn = getattr(lib, name)
        fn.argtypes = [c.c_void_p, c.c_int64, c.c_void_p, c.c_void_p]
        fn.restype = None
    for name in ("bias_encode8_i64", "bias_encode16_i64",
                 "bias_encode8_i32", "bias_encode16_i32"):
        fn = getattr(lib, name)
        fn.argtypes = [c.c_void_p, c.c_int64, c.c_int64, c.c_void_p]
        fn.restype = None
    lib.scaled_check_encode.argtypes = [c.c_void_p, c.c_int64, c.c_void_p]
    lib.scaled_check_encode.restype = ctypes.c_int
    lib.snappy_raw_decompress.argtypes = [c.c_void_p, c.c_int64,
                                          c.c_void_p, c.c_int64]
    lib.snappy_raw_decompress.restype = ctypes.c_int
    lib.rle_unpack_u32.argtypes = [c.c_void_p, c.c_int64, c.c_int,
                                   c.c_void_p, c.c_int64]
    lib.rle_unpack_u32.restype = ctypes.c_int
