// Native host codec for the wire-encoding hot path.
//
// The TPU compute path is XLA/Pallas; the HOST side of the transfer
// layer (columnar/transfer.py) is memory-bound C-style work — exactly
// the part the reference implements natively (ref: the JNI host-side
// copy/assembly helpers under sql-plugin's HostColumnarToGpu and the
// native table assembly in GpuParquetScan.scala:495-560).  These
// kernels replace the numpy fallbacks:
//
//   - chars_fill: ragged UTF-8 bytes + offsets -> fixed-width (n, w)
//     byte matrix.  numpy needs two (n, w) int64 temp matrices
//     (indices + mask) per call; this is one pass, zero temporaries.
//   - minmax_i64 / bias encode: range scan + delta pack for the
//     uint8/uint16 bias wire formats.
//   - scaled_check_encode: verify bit-exact int32-cents
//     reconstructibility of 2-decimal doubles and emit codes, one
//     pass instead of numpy's four.
//
// Plain C ABI (ctypes-loadable; pybind11 is not available in this
// image).  Single-threaded by design: callers already run on the scan
// decode pool, so parallelism comes from files, not from within a
// column.

#include <cstdint>
#include <cstring>
#include <cmath>

extern "C" {

// ragged bytes -> zero-padded fixed-width matrix.
// offsets has n+1 entries into raw; lens[i] <= w must hold (caller
// clamps); out is n*w bytes, PRE-ZEROED by the caller.
void chars_fill(const uint8_t* raw, const int64_t* offsets,
                const int32_t* lens, int64_t n, int64_t w,
                uint8_t* out) {
    for (int64_t i = 0; i < n; ++i) {
        int64_t len = lens[i];
        if (len > 0) {
            std::memcpy(out + i * w, raw + offsets[i],
                        static_cast<size_t>(len));
        }
    }
}

void minmax_i64(const int64_t* v, int64_t n, int64_t* out_min,
                int64_t* out_max) {
    int64_t mn = v[0], mx = v[0];
    for (int64_t i = 1; i < n; ++i) {
        int64_t x = v[i];
        if (x < mn) mn = x;
        if (x > mx) mx = x;
    }
    *out_min = mn;
    *out_max = mx;
}

void minmax_i32(const int32_t* v, int64_t n, int64_t* out_min,
                int64_t* out_max) {
    int32_t mn = v[0], mx = v[0];
    for (int64_t i = 1; i < n; ++i) {
        int32_t x = v[i];
        if (x < mn) mn = x;
        if (x > mx) mx = x;
    }
    *out_min = mn;
    *out_max = mx;
}

void bias_encode8_i64(const int64_t* v, int64_t n, int64_t base,
                      uint8_t* out) {
    for (int64_t i = 0; i < n; ++i)
        out[i] = static_cast<uint8_t>(v[i] - base);
}

void bias_encode16_i64(const int64_t* v, int64_t n, int64_t base,
                       uint16_t* out) {
    for (int64_t i = 0; i < n; ++i)
        out[i] = static_cast<uint16_t>(v[i] - base);
}

void bias_encode8_i32(const int32_t* v, int64_t n, int64_t base,
                      uint8_t* out) {
    for (int64_t i = 0; i < n; ++i)
        out[i] = static_cast<uint8_t>(static_cast<int64_t>(v[i]) - base);
}

void bias_encode16_i32(const int32_t* v, int64_t n, int64_t base,
                       uint16_t* out) {
    for (int64_t i = 0; i < n; ++i)
        out[i] = static_cast<uint16_t>(static_cast<int64_t>(v[i]) - base);
}

// 2-decimal money check+encode: out[i] = (int32) round(v[i] * 100)
// when EVERY value reconstructs bit-exactly as out[i] / 100.0.
// Returns 1 on success, 0 (out undefined) otherwise.
int scaled_check_encode(const double* v, int64_t n, int32_t* out) {
    for (int64_t i = 0; i < n; ++i) {
        double x = v[i];
        if (!std::isfinite(x)) return 0;
        double s = std::nearbyint(x * 100.0);
        if (s < -2147483648.0 || s > 2147483647.0) return 0;
        int32_t c = static_cast<int32_t>(s);
        double r = static_cast<double>(c) / 100.0;
        // bit comparison: catches -0.0 vs 0.0 and every rounding case
        uint64_t rb, xb;
        std::memcpy(&rb, &r, 8);
        std::memcpy(&xb, &x, 8);
        if (rb != xb) return 0;
        out[i] = c;
    }
    return 1;
}

// ---------------------------------------------------------------- //
// Fast Parquet column-chunk decode (io/fastpar.py's native core).
// The reference decodes Parquet pages ON the GPU via cudf
// (ref: GpuParquetScan.scala:495-560 device decode); on this system
// the host->device link is the scarce resource, so the idiomatic
// move is the opposite: decode + filter on the host at C speed and
// ship only surviving rows over the wire.  These kernels implement
// the two byte-crunching steps: snappy (public format) and the
// Parquet RLE/bit-packed hybrid.
// ---------------------------------------------------------------- //

// Raw snappy block decompress (format: github.com/google/snappy
// format_description.txt).  `in` points AFTER the uncompressed-length
// preamble; out_len must equal the decoded size from the preamble.
// Returns 0 on success, -1 on malformed/overflow input.
int snappy_raw_decompress(const uint8_t* in, int64_t in_len,
                          uint8_t* out, int64_t out_len) {
    int64_t ip = 0, op = 0;
    while (ip < in_len) {
        uint8_t tag = in[ip++];
        uint32_t kind = tag & 3u;
        if (kind == 0) {  // literal
            int64_t len = (tag >> 2) + 1;
            if (len > 60) {
                int n_extra = static_cast<int>(len - 60);
                if (ip + n_extra > in_len) return -1;
                uint32_t l = 0;
                for (int i = 0; i < n_extra; ++i)
                    l |= static_cast<uint32_t>(in[ip + i]) << (8 * i);
                ip += n_extra;
                len = static_cast<int64_t>(l) + 1;
            }
            if (ip + len > in_len || op + len > out_len) return -1;
            std::memcpy(out + op, in + ip, static_cast<size_t>(len));
            ip += len;
            op += len;
            continue;
        }
        int64_t len, offset;
        if (kind == 1) {  // copy, 1-byte offset
            len = ((tag >> 2) & 7u) + 4;
            if (ip >= in_len) return -1;
            offset = (static_cast<int64_t>(tag >> 5) << 8) | in[ip++];
        } else if (kind == 2) {  // copy, 2-byte offset
            len = (tag >> 2) + 1;
            if (ip + 2 > in_len) return -1;
            offset = in[ip] | (static_cast<int64_t>(in[ip + 1]) << 8);
            ip += 2;
        } else {  // copy, 4-byte offset
            len = (tag >> 2) + 1;
            if (ip + 4 > in_len) return -1;
            offset = static_cast<int64_t>(in[ip])
                   | (static_cast<int64_t>(in[ip + 1]) << 8)
                   | (static_cast<int64_t>(in[ip + 2]) << 16)
                   | (static_cast<int64_t>(in[ip + 3]) << 24);
            ip += 4;
        }
        if (offset <= 0 || offset > op || op + len > out_len) return -1;
        const uint8_t* src = out + op - offset;
        if (offset >= len) {
            std::memcpy(out + op, src, static_cast<size_t>(len));
        } else {
            // overlapping copy: byte-at-a-time replication semantics
            for (int64_t i = 0; i < len; ++i) out[op + i] = src[i];
        }
        op += len;
    }
    return op == out_len ? 0 : -1;
}

// Parquet RLE/bit-packed hybrid decode into uint32 values
// (format-specs/Encodings.md).  `in` points at the first run header
// (caller strips the 1-byte bit width of dictionary index streams and
// the 4-byte length prefix of v1 definition levels).  Decodes exactly
// n values; returns 0 on success, -1 on malformed input.
int rle_unpack_u32(const uint8_t* in, int64_t in_len, int bit_width,
                   uint32_t* out, int64_t n) {
    if (bit_width < 0 || bit_width > 32) return -1;
    int64_t ip = 0, op = 0;
    if (bit_width == 0) {
        for (int64_t i = 0; i < n; ++i) out[i] = 0;
        return 0;
    }
    const int byte_w = (bit_width + 7) / 8;
    while (op < n) {
        // varint run header
        uint64_t h = 0;
        int shift = 0;
        while (true) {
            if (ip >= in_len || shift > 63) return -1;
            uint8_t b = in[ip++];
            h |= static_cast<uint64_t>(b & 0x7f) << shift;
            if (!(b & 0x80)) break;
            shift += 7;
        }
        // a malformed header with h >> 1 beyond any real run would
        // overflow the count/nbytes arithmetic below — reject it
        if ((h >> 1) > (1ull << 40)) return -1;
        if (h & 1) {  // bit-packed groups of 8
            int64_t count = static_cast<int64_t>(h >> 1) * 8;
            int64_t nbytes = count * bit_width / 8;
            if (ip + nbytes > in_len) return -1;
            int64_t take = count < n - op ? count : n - op;
            const uint8_t* p = in + ip;
            const uint32_t mask =
                bit_width == 32 ? 0xffffffffu : ((1u << bit_width) - 1);
            for (int64_t i = 0; i < take; ++i) {
                int64_t bit = i * bit_width;
                int64_t byte = bit >> 3;
                int rem = static_cast<int>(bit & 7);
                // values span at most 5 bytes for bit_width <= 32
                uint64_t w = 0;
                int64_t avail = nbytes - byte;
                int need = (rem + bit_width + 7) / 8;
                for (int j = 0; j < need && j < avail; ++j)
                    w |= static_cast<uint64_t>(p[byte + j]) << (8 * j);
                out[op + i] = static_cast<uint32_t>(w >> rem) & mask;
            }
            ip += nbytes;
            op += take;
        } else {  // repeated run
            int64_t count = static_cast<int64_t>(h >> 1);
            if (count < 0 || ip + byte_w > in_len) return -1;
            uint32_t v = 0;
            for (int j = 0; j < byte_w; ++j)
                v |= static_cast<uint32_t>(in[ip + j]) << (8 * j);
            ip += byte_w;
            int64_t take = count < n - op ? count : n - op;
            for (int64_t i = 0; i < take; ++i) out[op + i] = v;
            op += take;
        }
    }
    return 0;
}

}  // extern "C"
