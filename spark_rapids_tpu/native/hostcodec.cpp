// Native host codec for the wire-encoding hot path.
//
// The TPU compute path is XLA/Pallas; the HOST side of the transfer
// layer (columnar/transfer.py) is memory-bound C-style work — exactly
// the part the reference implements natively (ref: the JNI host-side
// copy/assembly helpers under sql-plugin's HostColumnarToGpu and the
// native table assembly in GpuParquetScan.scala:495-560).  These
// kernels replace the numpy fallbacks:
//
//   - chars_fill: ragged UTF-8 bytes + offsets -> fixed-width (n, w)
//     byte matrix.  numpy needs two (n, w) int64 temp matrices
//     (indices + mask) per call; this is one pass, zero temporaries.
//   - minmax_i64 / bias encode: range scan + delta pack for the
//     uint8/uint16 bias wire formats.
//   - scaled_check_encode: verify bit-exact int32-cents
//     reconstructibility of 2-decimal doubles and emit codes, one
//     pass instead of numpy's four.
//
// Plain C ABI (ctypes-loadable; pybind11 is not available in this
// image).  Single-threaded by design: callers already run on the scan
// decode pool, so parallelism comes from files, not from within a
// column.

#include <cstdint>
#include <cstring>
#include <cmath>

extern "C" {

// ragged bytes -> zero-padded fixed-width matrix.
// offsets has n+1 entries into raw; lens[i] <= w must hold (caller
// clamps); out is n*w bytes, PRE-ZEROED by the caller.
void chars_fill(const uint8_t* raw, const int64_t* offsets,
                const int32_t* lens, int64_t n, int64_t w,
                uint8_t* out) {
    for (int64_t i = 0; i < n; ++i) {
        int64_t len = lens[i];
        if (len > 0) {
            std::memcpy(out + i * w, raw + offsets[i],
                        static_cast<size_t>(len));
        }
    }
}

void minmax_i64(const int64_t* v, int64_t n, int64_t* out_min,
                int64_t* out_max) {
    int64_t mn = v[0], mx = v[0];
    for (int64_t i = 1; i < n; ++i) {
        int64_t x = v[i];
        if (x < mn) mn = x;
        if (x > mx) mx = x;
    }
    *out_min = mn;
    *out_max = mx;
}

void minmax_i32(const int32_t* v, int64_t n, int64_t* out_min,
                int64_t* out_max) {
    int32_t mn = v[0], mx = v[0];
    for (int64_t i = 1; i < n; ++i) {
        int32_t x = v[i];
        if (x < mn) mn = x;
        if (x > mx) mx = x;
    }
    *out_min = mn;
    *out_max = mx;
}

void bias_encode8_i64(const int64_t* v, int64_t n, int64_t base,
                      uint8_t* out) {
    for (int64_t i = 0; i < n; ++i)
        out[i] = static_cast<uint8_t>(v[i] - base);
}

void bias_encode16_i64(const int64_t* v, int64_t n, int64_t base,
                       uint16_t* out) {
    for (int64_t i = 0; i < n; ++i)
        out[i] = static_cast<uint16_t>(v[i] - base);
}

void bias_encode8_i32(const int32_t* v, int64_t n, int64_t base,
                      uint8_t* out) {
    for (int64_t i = 0; i < n; ++i)
        out[i] = static_cast<uint8_t>(static_cast<int64_t>(v[i]) - base);
}

void bias_encode16_i32(const int32_t* v, int64_t n, int64_t base,
                       uint16_t* out) {
    for (int64_t i = 0; i < n; ++i)
        out[i] = static_cast<uint16_t>(static_cast<int64_t>(v[i]) - base);
}

// 2-decimal money check+encode: out[i] = (int32) round(v[i] * 100)
// when EVERY value reconstructs bit-exactly as out[i] / 100.0.
// Returns 1 on success, 0 (out undefined) otherwise.
int scaled_check_encode(const double* v, int64_t n, int32_t* out) {
    for (int64_t i = 0; i < n; ++i) {
        double x = v[i];
        if (!std::isfinite(x)) return 0;
        double s = std::nearbyint(x * 100.0);
        if (s < -2147483648.0 || s > 2147483647.0) return 0;
        int32_t c = static_cast<int32_t>(s);
        double r = static_cast<double>(c) / 100.0;
        // bit comparison: catches -0.0 vs 0.0 and every rounding case
        uint64_t rb, xb;
        std::memcpy(&rb, &r, 8);
        std::memcpy(&xb, &x, 8);
        if (rb != xb) return 0;
        out[i] = c;
    }
    return 1;
}

}  // extern "C"
