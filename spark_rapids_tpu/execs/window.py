"""Window exec: all window columns of one (partition_by, order_by) group
in a single segmented-scan XLA program.

Counterpart of GpuWindowExec (ref: GpuWindowExec.scala:27,92) — but where
the reference launches one cudf rolling/group-window kernel per window
aggregation, here the batch is sorted once by (partition keys, order
keys) and every window column (ranking, lead/lag, framed aggregates)
derives from shared segmented-scan primitives (ops.window) inside one
fused program.  Output rows are in sorted order (row order of a window
exec's output is unspecified in SQL, as in Spark).

Out-of-core scaling (ref: GpuWindowExec streaming): with a
partition_by, the planner inserts a hash exchange over the partition
keys and sets `partitioned` — window groups are then co-located per
reduce partition and each partition windows independently, bounding
memory to the largest reduce partition instead of the whole input.
Without partition keys (or on single-partition children) the exec
consumes its input as one batch (spill-registered while collecting,
like the sort exec)."""

from __future__ import annotations

from typing import Iterator, Sequence

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch, concat_batches
from spark_rapids_tpu.columnar.column import AnyColumn, Column
from spark_rapids_tpu.execs.base import MetricTimer, TOTAL_TIME, TpuExec
from spark_rapids_tpu.execs.sort import SortKey
from spark_rapids_tpu.exprs.aggregates import Average, Count, CountStar, \
    Max, Min, Sum
from spark_rapids_tpu.exprs.base import EvalContext
from spark_rapids_tpu.exprs.window import (
    DenseRank,
    Lead,
    Rank,
    RowNumber,
    WindowAgg,
    WindowExpression,
)
from spark_rapids_tpu.ops.groupby import _keys_equal_adjacent, _sum_dtype
from spark_rapids_tpu.ops.sort import SortOrder, sort_permutation
from spark_rapids_tpu.ops import window as W


class TpuWindowExec(TpuExec):
    def __init__(self, window_exprs: Sequence[tuple[WindowExpression, str]],
                 child: TpuExec):
        super().__init__(child)
        assert window_exprs
        self.named = [(we.bind(child.schema), name)
                      for we, name in window_exprs]
        spec0 = self.named[0][0].spec
        for we, _ in self.named[1:]:
            assert (we.spec.partition_by, we.spec.order_by) == \
                (spec0.partition_by, spec0.order_by), \
                "one TpuWindowExec handles one (partition, order) group"
        self.spec = spec0
        self._schema = T.Schema(
            list(child.schema.fields)
            + [T.Field(name, we.dtype, we.nullable)
               for we, name in self.named])

    #: True when the child is hash-partitioned on partition_by: window
    #: groups are partition-local, so each partition windows alone
    partitioned = False

    @property
    def schema(self) -> T.Schema:
        return self._schema

    @property
    def num_partitions(self) -> int:
        return self.children[0].num_partitions if self.partitioned \
            else 1

    def node_desc(self) -> str:
        fns = ", ".join(f"{we.fn.describe()}->{n}" for we, n in self.named)
        tag = " [per-partition]" if self.partitioned else ""
        return f"TpuWindowExec [{fns}] over ({self.spec.describe()})" + tag

    # -- traceable window program --------------------------------------- #

    def _window_batch(self, batch: ColumnarBatch) -> ColumnarBatch:
        spec = self.spec
        n_data = batch.num_cols
        cap = batch.capacity
        ctx = EvalContext.for_batch(batch)
        pkey_cols = [e.eval(ctx) for e in spec.partition_by]
        okey_cols = [k.expr.eval(ctx) for k in spec.order_by]

        # sort by (pkeys, okeys); padding rows land at the back
        aug_schema = T.Schema(
            list(batch.schema.fields)
            + [T.Field(f"__pk{i}", e.dtype)
               for i, e in enumerate(spec.partition_by)]
            + [T.Field(f"__ok{i}", k.expr.dtype)
               for i, k in enumerate(spec.order_by)])
        aug = ColumnarBatch(
            list(batch.columns) + pkey_cols + okey_cols,
            batch.num_rows, aug_schema)
        orders = [SortOrder(n_data + i)
                  for i in range(len(pkey_cols))] + \
                 [SortOrder(n_data + len(pkey_cols) + i, k.descending,
                            k.nulls_last)
                  for i, k in enumerate(spec.order_by)]
        perm = sort_permutation(aug, orders)
        saug = aug.gather(perm, aug.num_rows)
        live = saug.row_mask()

        spkeys = saug.columns[n_data:n_data + len(pkey_cols)]
        sokeys = saug.columns[n_data + len(pkey_cols):]
        idx = jnp.arange(cap, dtype=jnp.int32)

        same_part = jnp.ones((cap,), bool)
        for kc in spkeys:
            same_part = same_part & _keys_equal_adjacent(kc)
        is_start = live & ((idx == 0) | ~same_part)

        same_peer = same_part
        for kc in sokeys:
            same_peer = same_peer & _keys_equal_adjacent(kc)
        peer_start = live & ((idx == 0) | ~same_peer)

        start_idx, end_idx = W.segment_positions(is_start, live)
        _, peer_end = W.segment_positions(peer_start, live)

        sctx = EvalContext.for_batch(saug)
        out_cols: list[AnyColumn] = list(saug.columns[:n_data])
        for we, _name in self.named:
            out_cols.append(self._eval_window_fn(
                we, sctx, live, idx, is_start, peer_start,
                start_idx, end_idx, peer_end, cap, sokeys))
        return ColumnarBatch(out_cols, saug.num_rows, self._schema)

    def _eval_window_fn(self, we: WindowExpression, sctx: EvalContext,
                        live, idx, is_start, peer_start,
                        start_idx, end_idx, peer_end, cap: int,
                        sokeys=()) -> AnyColumn:
        fn = we.fn
        if isinstance(fn, RowNumber):
            rn = (idx - start_idx + 1).astype(jnp.int64)
            return Column(rn, live, T.LONG)
        if isinstance(fn, DenseRank):
            d = jnp.cumsum(peer_start.astype(jnp.int64))
            base = jnp.take(d, jnp.clip(start_idx, 0, cap - 1))
            return Column(d - base + 1, live, T.LONG)
        if isinstance(fn, Rank):
            first_peer = jax.lax.cummax(jnp.where(peer_start, idx, 0))
            r = (first_peer - start_idx + 1).astype(jnp.int64)
            return Column(r, live, T.LONG)
        if isinstance(fn, Lead):  # Lag subclasses Lead
            col = fn.child.eval(sctx)
            g, ok = W.gather_in_segment(col, fn.shift, start_idx, end_idx,
                                        live, cap)
            if fn.default is not None:
                dflt = fn.default.eval(sctx)
                data = jnp.where(ok, g.data, dflt.data)
                valid = jnp.where(ok, g.validity, dflt.validity) & live
                return Column(data, valid, col.dtype)
            return g.with_validity(g.validity & ok)
        assert isinstance(fn, WindowAgg), fn
        return self._eval_window_agg(fn, we, sctx, live, is_start,
                                     start_idx, end_idx, peer_end, cap,
                                     peer_start, sokeys)

    def _eval_window_agg(self, fn: WindowAgg, we: WindowExpression, sctx,
                         live, is_start, start_idx, end_idx,
                         peer_end, cap: int, peer_start=None,
                         sokeys=()) -> Column:
        frame = we.spec.resolved_frame()
        if frame.mode == "rows":
            lo, hi = W.frame_bounds(start_idx, end_idx, frame.start,
                                    frame.end, cap)
        elif frame.start is None and frame.end in (None, 0):
            # range: unbounded preceding .. current peer group / end
            lo = start_idx
            hi = end_idx if frame.end is None else peer_end
        else:  # bounded value-based range frame over the one order key
            k = we.spec.order_by[0]
            lo, hi = W.range_frame_bounds(
                sokeys[0], k.descending, not k.nulls_last,
                frame.start, frame.end, start_idx, end_idx,
                peer_start, peer_end, live, cap)
        agg = fn.agg

        if isinstance(agg, CountStar):
            n = (hi - lo + 1).astype(jnp.int64)
            return Column(jnp.maximum(n, 0), live, T.LONG)

        vcol = agg.inputs()[0].eval(sctx)
        assert isinstance(vcol, Column), "window agg over strings"
        if isinstance(agg, Count):
            _, n = W.windowed_sum_count(vcol, lo, hi, live, T.LONG)
            return Column(n, live, T.LONG)
        if isinstance(agg, Sum):
            out_dtype = _sum_dtype(vcol.dtype)
            s, n = W.windowed_sum_count(vcol, lo, hi, live, out_dtype)
            return Column(s, live & (n > 0), out_dtype)
        if isinstance(agg, Average):
            s, n = W.windowed_sum_count(vcol, lo, hi, live, T.DOUBLE)
            denom = jnp.where(n > 0, n, 1).astype(jnp.float64)
            return Column(s / denom, live & (n > 0), T.DOUBLE)
        assert isinstance(agg, (Min, Max)), agg
        op = "min" if isinstance(agg, Min) else "max"
        out, nonempty = W.windowed_minmax(
            vcol, op, is_start, live, lo, hi,
            anchored_start=frame.start is None, cap=cap)
        return Column(out, live & nonempty, vcol.dtype)

    # -- driver ---------------------------------------------------------- #

    def _cache_key(self) -> tuple:
        from spark_rapids_tpu.execs.jit_cache import expr_key, exprs_key

        spec = self.spec
        return ("window",
                exprs_key(spec.partition_by),
                tuple((expr_key(k.expr), k.descending, k.nulls_last)
                      for k in spec.order_by),
                tuple((expr_key_fn(we), n) for we, n in self.named),
                repr(self._schema))

    def _window_source(self, source) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.execs.jit_cache import cached_jit
        from spark_rapids_tpu.memory import SpillPriorities, get_store

        store = get_store()
        handles = []
        try:
            for b in source:
                handles.append(store.register(
                    b, SpillPriorities.COALESCE_PENDING))
            if not handles:
                return
            batches = [h.get() for h in handles]
            big = batches[0] if len(batches) == 1 else \
                concat_batches(batches)
        finally:
            for h in handles:
                h.close()
        # partitioned check first: the unpartitioned path must not pay
        # a sizing round trip just to test emptiness (the window program
        # handles zero live rows; empty SOURCES returned above)
        if self.partitioned and big.concrete_num_rows() == 0:
            return  # empty reduce partition
        fn = cached_jit(self._cache_key(), lambda: self._window_batch,
                        op=self.name)
        with MetricTimer(self.metrics[TOTAL_TIME], op=self.name):
            out = fn(big.with_device_num_rows())
        yield self._count_output(out)

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        if not self.partitioned:
            assert p == 0
            yield from self.execute()
            return
        # hash exchange upstream co-located each window group in one
        # reduce partition: window it independently (bounded memory)
        yield from self._window_source(
            self.children[0].execute_partition(p))

    def execute(self) -> Iterator[ColumnarBatch]:
        if not self.partitioned:
            yield from self._window_source(self.children[0].execute())
            return
        for p in range(self.num_partitions):
            yield from self.execute_partition(p)


def expr_key_fn(we: WindowExpression) -> tuple:
    """Structural key for one window expression (WindowSpec/WindowFrame
    are not Expressions, so expr_key alone would fall back to object
    repr)."""
    from spark_rapids_tpu.execs.jit_cache import expr_key, exprs_key

    fn = we.fn
    frame = we.spec.resolved_frame()
    fk: tuple
    if isinstance(fn, Lead):
        fk = (type(fn).__name__, expr_key(fn.child), fn.offset,
              expr_key(fn.default) if fn.default is not None else None)
    elif isinstance(fn, WindowAgg):
        fk = ("agg", type(fn.agg).__name__, exprs_key(fn.agg.inputs()))
    else:
        fk = (type(fn).__name__,)
    return fk + (frame.mode, frame.start, frame.end)
