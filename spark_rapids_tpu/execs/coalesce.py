"""Coalesce execs: batch coalescing within a partition, and N child
partitions -> 1 pulled concurrently.

Three reference mechanisms meet here:
- GpuCoalesceBatches (ref: GpuCoalesceBatches.scala:340 with the
  targetSizeBytes goal): concatenate consecutive small columnar batches
  up to a target size before expensive operators, so fused chains,
  joins and aggregates run dense programs over few large blocks instead
  of many starved ones — TpuCoalesceBatchesExec below, inserted by the
  planner under spark.rapids.tpu.sql.coalesce.enabled
  (docs/occupancy.md);
- the plan shape of CoalesceExec / a SinglePartitioning exchange feeding
  a grand aggregate (ref: GpuShuffleExchangeExec.scala:80 with
  GpuSinglePartitioning) — but without the shuffle-manager detour: a
  single consumer needs no partitioned blocks, so routing one-destination
  exchanges through spill-registered shuffle storage is pure overhead;
- the multi-file cloud reader's background thread pool
  (ref: GpuParquetScan.scala:882-895 MultiFileCloudParquetPartitionReader):
  worker threads run whole child partitions (host decode, H2D upload, the
  per-batch jitted programs) ahead of the consumer, so upload and device
  compute overlap across partitions.  A bounded queue provides
  backpressure; the task semaphore caps device residency per worker.

Output order is partition-completion order (like Spark's reduce-side
pulls, batch order *within* a partition is preserved; order across
partitions is not guaranteed — callers needing total order must sort).
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, Optional

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch, concat_batches
from spark_rapids_tpu.config import MAX_CAPACITY, get_conf, register
from spark_rapids_tpu.execs.base import (
    NUM_INPUT_BATCHES,
    NUM_INPUT_ROWS,
    MetricTimer,
    TpuExec,
)
from spark_rapids_tpu.memory import TpuSemaphore

_DONE = object()

COALESCE_ENABLED = register(
    "spark.rapids.tpu.sql.coalesce.enabled", False,
    "Insert TpuCoalesceBatchesExec below fused chains, joins, "
    "aggregates and sorts: consecutive small device batches are "
    "concatenated up to coalesce.targetRows / coalesce.targetBytes "
    "before the expensive operator, so its programs run dense over few "
    "large blocks instead of starved over many small ones (ref: "
    "GpuCoalesceBatches + targetSizeBytes).  Off (the default) the "
    "plan is bit-for-bit unchanged; on, results are bit-identical — "
    "coalescing only re-buckets rows (docs/occupancy.md).")
COALESCE_TARGET_ROWS = register(
    "spark.rapids.tpu.sql.coalesce.targetRows", 1 << 20,
    "Row-count goal per coalesced batch: buffered batches flush once "
    "their combined live rows reach this (the TPU analog of the "
    "reference's targetSizeBytes goal — rows, because XLA programs are "
    "specialized per capacity bucket).",
    check=lambda v: v > 0)
COALESCE_TARGET_BYTES = register(
    "spark.rapids.tpu.sql.coalesce.targetBytes", 128 << 20,
    "Device-byte goal per coalesced batch: buffered batches flush once "
    "their combined device footprint reaches this, whichever of the "
    "row/byte goals hits first (ref: "
    "spark.rapids.sql.batchSizeBytes).",
    check=lambda v: v > 0)


def coalesce_enabled(conf=None) -> bool:
    return bool((conf or get_conf()).get(COALESCE_ENABLED))


class TpuCoalesceBatchesExec(TpuExec):
    """Concatenate consecutive small device batches up to a target size.

    The TPU redesign of GpuCoalesceBatches: instead of cudf's
    Table.concatenate per flush, one CACHED concat program per observed
    (capacities, row-counts) shape packs every part into a fresh
    pad_capacity(total) bucket with dynamic_update_slice — row counts
    are host-known here, so the offsets are static and the program is
    pure data movement (no compaction scan).  Composition contracts:

    - only batches with HOST-known row counts buffer (scan/cache/CPU
      outputs); traced-count batches (filters mid-stream) pass through
      unchanged — coalescing them would force a device sync per batch;
    - EncodedBatch inputs decode eagerly first (the cached decode
      program), so wire components compose;
    - each coalesced output remembers its input row counts in
      `coalesce_seams` (host-side attribute, not part of the pytree):
      the retry ladder's bisect splits along the seam nearest the
      midpoint, so an OOM inside a downstream program retries on the
      original input granularity instead of arbitrary halves;
    - the output is a regular prefix-compact batch: donation,
      speculation and the spill store see nothing new.
    """

    def __init__(self, child: TpuExec,
                 target_rows: Optional[int] = None,
                 target_bytes: Optional[int] = None,
                 goal_rows: Optional[int] = None):
        super().__init__(child)
        # goal_rows: the pre-occupancy exec's parameter name, kept for
        # callers that built plans against it
        self._target_rows = target_rows if target_rows is not None \
            else goal_rows
        self._target_bytes = target_bytes

    @property
    def schema(self) -> T.Schema:
        return self.children[0].schema

    @property
    def num_partitions(self) -> int:
        return self.children[0].num_partitions

    @property
    def output_partitioning(self):
        return self.children[0].output_partitioning

    def node_desc(self) -> str:
        return "TpuCoalesceBatchesExec"

    def additional_metrics(self):
        return [(NUM_INPUT_ROWS, "MODERATE"),
                (NUM_INPUT_BATCHES, "MODERATE"),
                ("numConcats", "MODERATE"),
                ("concatTime", "MODERATE")]

    def _goals(self) -> tuple[int, int, int]:
        conf = get_conf()
        rows = self._target_rows if self._target_rows is not None \
            else int(conf.get(COALESCE_TARGET_ROWS))
        nbytes = self._target_bytes if self._target_bytes is not None \
            else int(conf.get(COALESCE_TARGET_BYTES))
        return rows, nbytes, int(conf.get(MAX_CAPACITY))

    def _concat(self, buf: list[ColumnarBatch]) -> ColumnarBatch:
        """One cached concat program per (schema widths, capacities,
        row counts) shape.  ns are static (host-known) so they sit in
        the structural key — bounded in practice because scans emit
        fixed-size batches with at most one ragged tail per file, and
        the program-census test keeps this honest."""
        from spark_rapids_tpu.columnar.column import pad_capacity
        from spark_rapids_tpu.execs.jit_cache import cached_jit

        ns = tuple(b.num_rows for b in buf)
        caps = tuple(b.capacity for b in buf)
        # the output bucket depends on the thread's capacity POLICY
        # (pow2 vs pow2x3), which the traced pad_capacity call reads at
        # trace time — fold the resolved capacity into the key so
        # sessions under different policies never share this program
        key = ("coalesce", caps, ns, pad_capacity(sum(ns)))
        fn = cached_jit(key, lambda: concat_batches, op=self.name)
        with MetricTimer(self.metrics["concatTime"], op=self.name) as t:
            out = t.observe(fn(buf))
        self.metrics["numConcats"].add(1)
        # host-side seam record for the retry ladder's bisect — NOT in
        # the pytree, so it lives exactly as long as this host object
        out.coalesce_seams = ns
        return out

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.columnar.transfer import EncodedBatch
        from spark_rapids_tpu.memory.store import batch_device_bytes

        target_rows, target_bytes, max_cap = self._goals()
        buf: list[ColumnarBatch] = []
        buf_rows = 0
        buf_bytes = 0

        def flush():
            nonlocal buf, buf_rows, buf_bytes
            if not buf:
                return None
            out = buf[0] if len(buf) == 1 else self._concat(buf)
            buf, buf_rows, buf_bytes = [], 0, 0
            return out

        for batch in self.children[0].execute_partition(p):
            if isinstance(batch, EncodedBatch):
                if batch.num_rows is None:
                    out = flush()
                    if out is not None:
                        yield self._count_output(out)
                    yield self._count_output(batch)
                    continue
                batch = batch.decode_now()
            if type(batch.num_rows) is not int:
                # traced row count: sizing it would sync — pass through
                out = flush()
                if out is not None:
                    yield self._count_output(out)
                yield self._count_output(batch)
                continue
            n = batch.num_rows
            nbytes = batch_device_bytes(batch)
            self.metrics[NUM_INPUT_BATCHES].add(1)
            self.metrics[NUM_INPUT_ROWS].add(n)
            if buf and buf_rows + n > max_cap:
                out = flush()
                if out is not None:
                    yield self._count_output(out)
            buf.append(batch)
            buf_rows += n
            buf_bytes += nbytes
            if buf_rows >= target_rows or buf_bytes >= target_bytes:
                out = flush()
                if out is not None:
                    yield self._count_output(out)
        out = flush()
        if out is not None:
            yield self._count_output(out)


class TpuCoalescePartitionsExec(TpuExec):
    def __init__(self, child: TpuExec):
        super().__init__(child)

    @property
    def schema(self) -> T.Schema:
        return self.children[0].schema

    @property
    def num_partitions(self) -> int:
        return 1

    def node_desc(self) -> str:
        return "TpuCoalescePartitionsExec"

    def additional_metrics(self):
        return [("fetchWaitTime", "MODERATE")]

    def execute(self) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.config import get_conf
        from spark_rapids_tpu.execs.exchange import TASK_THREADS

        child = self.children[0]
        n_parts = child.num_partitions
        threads = min(get_conf().get(TASK_THREADS), max(n_parts, 1))
        if n_parts <= 1 or threads <= 1:
            for b in child.execute():
                yield self._count_output(b)
            return

        out_q: queue.Queue = queue.Queue(maxsize=threads * 2)
        stop = threading.Event()
        next_part = iter(range(n_parts))
        part_lock = threading.Lock()

        def worker() -> None:
            sem = TpuSemaphore.get()
            task_id = threading.get_ident()
            try:
                while not stop.is_set():
                    with part_lock:
                        p = next(next_part, None)
                    if p is None:
                        break
                    for batch in child.execute_partition(p):
                        sem.acquire_if_necessary(task_id)
                        while not stop.is_set():
                            try:
                                out_q.put(batch, timeout=0.1)
                                break
                            except queue.Full:
                                continue
                        if stop.is_set():
                            return
            except BaseException as e:  # surface to the consumer
                out_q.put(e)
            finally:
                sem.release_if_necessary(task_id)
                out_q.put(_DONE)

        workers = [threading.Thread(target=worker, daemon=True)
                   for _ in range(threads)]
        for w in workers:
            w.start()
        done = 0
        import time

        try:
            while done < threads:
                t0 = time.perf_counter_ns()
                item = out_q.get()
                self.metrics["fetchWaitTime"].add(
                    time.perf_counter_ns() - t0)
                if item is _DONE:
                    done += 1
                elif isinstance(item, BaseException):
                    raise item
                else:
                    yield self._count_output(item)
        finally:
            # consumer abandoned (limit) or raised: unblock workers
            stop.set()
            while done < threads:
                item = out_q.get()
                if item is _DONE:
                    done += 1
            for w in workers:
                w.join()
