"""Coalesce-partitions exec: N child partitions -> 1, pulled concurrently.

Two reference mechanisms meet here:
- the plan shape of CoalesceExec / a SinglePartitioning exchange feeding
  a grand aggregate (ref: GpuShuffleExchangeExec.scala:80 with
  GpuSinglePartitioning) — but without the shuffle-manager detour: a
  single consumer needs no partitioned blocks, so routing one-destination
  exchanges through spill-registered shuffle storage is pure overhead;
- the multi-file cloud reader's background thread pool
  (ref: GpuParquetScan.scala:882-895 MultiFileCloudParquetPartitionReader):
  worker threads run whole child partitions (host decode, H2D upload, the
  per-batch jitted programs) ahead of the consumer, so upload and device
  compute overlap across partitions.  A bounded queue provides
  backpressure; the task semaphore caps device residency per worker.

Output order is partition-completion order (like Spark's reduce-side
pulls, batch order *within* a partition is preserved; order across
partitions is not guaranteed — callers needing total order must sort).
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.execs.base import TpuExec
from spark_rapids_tpu.memory import TpuSemaphore

_DONE = object()


class TpuCoalescePartitionsExec(TpuExec):
    def __init__(self, child: TpuExec):
        super().__init__(child)

    @property
    def schema(self) -> T.Schema:
        return self.children[0].schema

    @property
    def num_partitions(self) -> int:
        return 1

    def node_desc(self) -> str:
        return "TpuCoalescePartitionsExec"

    def additional_metrics(self):
        return [("fetchWaitTime", "MODERATE")]

    def execute(self) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.config import get_conf
        from spark_rapids_tpu.execs.exchange import TASK_THREADS

        child = self.children[0]
        n_parts = child.num_partitions
        threads = min(get_conf().get(TASK_THREADS), max(n_parts, 1))
        if n_parts <= 1 or threads <= 1:
            for b in child.execute():
                yield self._count_output(b)
            return

        out_q: queue.Queue = queue.Queue(maxsize=threads * 2)
        stop = threading.Event()
        next_part = iter(range(n_parts))
        part_lock = threading.Lock()

        def worker() -> None:
            sem = TpuSemaphore.get()
            task_id = threading.get_ident()
            try:
                while not stop.is_set():
                    with part_lock:
                        p = next(next_part, None)
                    if p is None:
                        break
                    for batch in child.execute_partition(p):
                        sem.acquire_if_necessary(task_id)
                        while not stop.is_set():
                            try:
                                out_q.put(batch, timeout=0.1)
                                break
                            except queue.Full:
                                continue
                        if stop.is_set():
                            return
            except BaseException as e:  # surface to the consumer
                out_q.put(e)
            finally:
                sem.release_if_necessary(task_id)
                out_q.put(_DONE)

        workers = [threading.Thread(target=worker, daemon=True)
                   for _ in range(threads)]
        for w in workers:
            w.start()
        done = 0
        import time

        try:
            while done < threads:
                t0 = time.perf_counter_ns()
                item = out_q.get()
                self.metrics["fetchWaitTime"].add(
                    time.perf_counter_ns() - t0)
                if item is _DONE:
                    done += 1
                elif isinstance(item, BaseException):
                    raise item
                else:
                    yield self._count_output(item)
        finally:
            # consumer abandoned (limit) or raised: unblock workers
            stop.set()
            while done < threads:
                item = out_q.get()
                if item is _DONE:
                    done += 1
            for w in workers:
                w.join()
