"""Generate exec: explode/posexplode over dense list matrices.

TPU re-design of GpuGenerateExec (ref: sql-plugin/.../GpuGenerateExec.
scala:378 — cudf's explode produces a new table via offsets traversal).
Here the (capacity, max_len) element matrix flattens row-major, a keep
mask marks real elements (plus one NULL slot per empty/NULL row for
explode_outer), and the same cumsum+searchsorted compaction the filter
uses gathers both the repeated parent columns and the element column —
one fused program, output capacity = capacity * max_len."""

from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column, ListColumn
from spark_rapids_tpu.execs.base import BatchFn, FusableExec, TpuExec
from spark_rapids_tpu.exprs.base import EvalContext


class TpuGenerateExec(FusableExec):
    MULTIPLIES_ROWS = True

    def __init__(self, generator, schema: T.Schema, child: TpuExec):
        super().__init__(child)
        self.generator = generator
        self._schema = schema

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def node_desc(self) -> str:
        return f"TpuGenerateExec [{self.generator.name}]"

    def fuse_key(self):
        from spark_rapids_tpu.execs.jit_cache import expr_key

        return ("generate", expr_key(self.generator.child),
                self.generator.pos, self.generator.outer,
                repr(self._schema))

    def fusion_exprs(self):
        return (self.generator.child,)

    def make_batch_fn(self) -> BatchFn:
        gen = self.generator
        schema = self._schema

        def fn(batch: ColumnarBatch) -> ColumnarBatch:
            ctx = EvalContext.for_batch(batch)
            lc = gen.child.eval(ctx)
            assert isinstance(lc, ListColumn)
            cap, L = lc.values.shape
            live = batch.row_mask()
            pos = jnp.arange(L, dtype=jnp.int32)[None, :]
            keep2d = live[:, None] & lc.validity[:, None] \
                & (pos < lc.lengths[:, None])
            if gen.outer:
                # empty or NULL arrays still emit one NULL-element row
                empty = live & (~lc.validity | (lc.lengths == 0))
                keep2d = keep2d | (empty[:, None] & (pos == 0))
                elem_ok2d = lc.elem_validity \
                    & (pos < lc.lengths[:, None]) & lc.validity[:, None]
            else:
                elem_ok2d = lc.elem_validity
            keep = keep2d.reshape(-1)
            flat_cap = cap * L
            csum = jnp.cumsum(keep.astype(jnp.int32))
            n_out = csum[-1]
            src = jnp.searchsorted(
                csum, jnp.arange(flat_cap, dtype=jnp.int32) + 1,
                side="left").astype(jnp.int32)
            src = jnp.minimum(src, flat_cap - 1)
            out_live = jnp.arange(flat_cap, dtype=jnp.int32) < n_out
            parent = src // L
            elem_pos = src - parent * L
            out_cols = []
            for c in batch.columns:
                g = c.gather(parent)
                out_cols.append(g.with_validity(g.validity & out_live))
            if gen.pos:
                # pos is NULL on explode_outer's empty/NULL filler rows
                real2d = lc.validity[:, None] & (pos < lc.lengths[:, None])
                pos_ok = real2d.reshape(-1)[src] & out_live
                out_cols.append(Column(elem_pos, pos_ok, T.INT))
            ev = elem_ok2d.reshape(-1)[src]
            out_cols.append(Column(
                lc.values.reshape(-1)[src], ev & out_live,
                gen.dtype))
            return ColumnarBatch(out_cols, n_out, schema)

        return fn
