"""Dedicated exec for collect_list / collect_set aggregations.

Ragged results break the one-compiled-program aggregate pipeline: the
output list width is data-dependent.  This exec runs the two-phase
design from ops/collect.py — phase 1 (sorted segments + kept counts)
syncs exactly two scalars to the host, which become phase 2's static
shapes (width bucket, group-capacity bucket), so each distinct result
shape compiles once and is reused.

Multi-partition plans hash-exchange on the group keys first (planner),
making partitions KEY-DISJOINT — each reduce partition then collects
independently on device and the union of outputs is the answer, no
cross-partition list merge needed (the same co-partitioning argument
the reference gets from its shuffle; ref: AggregateFunctions.scala
GpuCollectList).  Mixed collect+scalar aggregates still fall back."""

from __future__ import annotations

from typing import Iterator, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch, concat_batches
from spark_rapids_tpu.columnar.column import pad_capacity, pad_width
from spark_rapids_tpu.execs.base import MetricTimer, TOTAL_TIME, TpuExec
from spark_rapids_tpu.exprs.base import EvalContext


class TpuCollectAggExec(TpuExec):
    def __init__(self, groups: Sequence, aggs: Sequence, child: TpuExec):
        super().__init__(child)
        self.groups = list(groups)
        self.aggs = list(aggs)
        self.kinds = [na.fn.collect_kind for na in self.aggs]
        from spark_rapids_tpu.plan.logical import _output_fields

        kf = list(_output_fields(self.groups).fields)
        self._schema = T.Schema(
            kf + [na.output_field() for na in self.aggs])
        self._aug_schema = T.Schema(
            kf + [T.Field(f"__v{i}", na.fn.child.dtype, True)
                  for i, na in enumerate(self.aggs)])

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def node_desc(self) -> str:
        ks = ", ".join(g.name for g in self.groups)
        vs = ", ".join(f"{na.fn.name}({na.fn.child.name})"
                       for na in self.aggs)
        return f"TpuCollectAggExec keys=[{ks}] [{vs}]"

    #: True when the child is hash-partitioned on the group keys
    #: (key-disjoint): collect runs per partition, outputs union
    partitioned = False

    @property
    def num_partitions(self) -> int:
        return self.children[0].num_partitions if self.partitioned \
            else 1

    def _project(self, batch: ColumnarBatch) -> ColumnarBatch:
        ctx = EvalContext.for_batch(batch)
        cols = [g.eval(ctx) for g in self.groups] \
            + [na.fn.child.eval(ctx) for na in self.aggs]
        return ColumnarBatch(cols, batch.num_rows, self._aug_schema)

    def execute(self) -> Iterator[ColumnarBatch]:
        if self.partitioned:
            # sequential per-partition collects: each two-phase
            # program can approach the device budget, so concurrent
            # partitions without the semaphore/backpressure machinery
            # of TpuCoalescePartitionsExec would OOM exactly when the
            # out-of-core path matters most
            for p in range(self.num_partitions):
                yield from self.execute_partition(p)
            return
        yield from self._collect(list(self.children[0].execute()),
                                 emit_empty=True)

    def _collect(self, batches: list,
                 emit_empty: bool) -> Iterator[ColumnarBatch]:
        import jax

        from spark_rapids_tpu.execs.jit_cache import (
            cached_jit,
            exprs_key,
        )
        from spark_rapids_tpu.ops import collect as C

        if not batches:
            return
        big = batches[0] if len(batches) == 1 else concat_batches(batches)
        key = ("collectagg", exprs_key(self.groups),
               exprs_key([na.fn.child for na in self.aggs]),
               tuple(self.kinds), repr(self._aug_schema))
        n_keys = len(self.groups)
        kinds = tuple(self.kinds)

        def phase1(b):
            return C.collect_phase1(self._project(b), n_keys, kinds)

        with MetricTimer(self.metrics[TOTAL_TIME], op=self.name) as t:
            sb, live_s, ng, mk = cached_jit(
                key + ("p1", big.capacity), lambda: phase1,
                op=self.name)(big)
            from spark_rapids_tpu.parallel.pipeline import device_read_many

            num_groups, max_kept = (int(x) for x in
                                    device_read_many([ng, mk],
                                                     tag="collect.size"))
            L = pad_width(max(max_kept, 1))
            out_cap = pad_capacity(max(num_groups, 1))

            def phase2(sb_, live_):
                return C.collect_phase2(sb_, live_, n_keys, kinds, L,
                                        out_cap, self._schema)

            out = t.observe(cached_jit(
                key + ("p2", L, out_cap, sb.capacity),
                lambda: phase2, op=self.name)(sb, live_s))
        import dataclasses

        n_rows = num_groups if n_keys else max(num_groups, 1)
        if n_keys and num_groups == 0:
            return  # grouped collect over empty input: no rows
        if not n_keys and not emit_empty and num_groups == 0:
            return  # empty partition of a partitioned grand collect
        out = dataclasses.replace(out, num_rows=n_rows)
        yield self._count_output(out)

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        if not self.partitioned:
            assert p == 0
            yield from self.execute()
            return
        # key-disjoint partition (hash exchange upstream): independent
        # device collect; the union across partitions is the answer
        yield from self._collect(
            list(self.children[0].execute_partition(p)),
            emit_empty=False)
