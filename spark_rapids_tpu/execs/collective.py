"""Collective (tier-2) exchange-bearing operators.

When the collective shuffle transport is active, the planner lowers
EVERY exchange-bearing pipeline — grouped aggregation, shuffled hash
join, distributed ORDER BY — into fused SPMD programs over the active
mesh (ref: the role GpuShuffleExchangeExecBase + RapidsShuffleTransport
play under GpuHashAggregateExec / GpuShuffledHashJoinBase /
GpuSortExec, re-designed for TPU: map-side work, the murmur3- or
range-routed `all_to_all` over the mesh axis, and reduce-side work are
single shard_map/jit programs — no host hop between map and reduce,
collectives ride ICI scheduled by XLA; SURVEY.md §5.8).

Inputs stream through BOUNDED per-shard rounds (conf
spark.rapids.tpu.shuffle.collective.roundRows): each round stacks at
most that many rows per shard — so a skewed or large child never forces
one stop-the-world host gather (the streaming discipline of the
reference's shuffle writer).

STAGE EXECUTION (docs/spmd.md): with
spark.rapids.tpu.shuffle.collective.spmd.enabled (the default), a
whole query stage lowers to O(1) partitioned pjit programs over the
mesh with NamedSharding end-to-end — rounds are a lax.scan INSIDE the
compiled program (bucketed by .spmd.bucketRounds), inputs arrive as
global sharded arrays, and the per-round host syncs
(concrete_num_rows, shrink) of the legacy host-loop driver are
deferred to ONE stage-exit counts fetch.  spmd.enabled=false keeps
the legacy per-round host loop (one dispatch + 2n syncs per round) —
the digest-comparison baseline for the SPMD path."""

from __future__ import annotations

import dataclasses as _dc
from typing import Iterator, Optional, Sequence

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch, concat_batches
from spark_rapids_tpu.columnar.column import pad_capacity
from spark_rapids_tpu.config import register, get_conf
from spark_rapids_tpu.execs.aggregate import TpuHashAggregateExec
from spark_rapids_tpu.execs.base import MetricTimer, TOTAL_TIME, TpuExec
from spark_rapids_tpu.exprs.aggregates import NamedAgg
from spark_rapids_tpu.exprs.base import EvalContext, Expression
from spark_rapids_tpu.parallel.mesh import DATA_AXIS
from spark_rapids_tpu.trace import ledger as _ledger

COLLECTIVE_ROUND_ROWS = register(
    "spark.rapids.tpu.shuffle.collective.roundRows", 1 << 20,
    "Per-shard row budget of one collective exchange round: child "
    "batches stream through the fused all_to_all program in rounds of "
    "at most this many rows per shard instead of one unbounded gather "
    "(the batch-at-a-time discipline of the reference's shuffle "
    "writer, GpuShuffleExchangeExec.scala:167-270).")

SPMD_STAGE = register(
    "spark.rapids.tpu.shuffle.collective.spmd.enabled", True,
    "Lower each collective query stage (exchange + its fused "
    "agg/join/sort work) to O(1) partitioned pjit programs over the "
    "active mesh with NamedSharding end-to-end: exchange rounds run "
    "as a lax.scan INSIDE the compiled program, inputs arrive as "
    "global sharded arrays, and per-round host syncs are deferred to "
    "one stage-exit counts fetch (docs/spmd.md).  Off: the legacy "
    "host-loop driver — one program dispatch plus per-shard "
    "concrete_num_rows/shrink syncs per round — kept as the "
    "bit-identical digest baseline.  The planner reads this at plan "
    "time (collective.stage_config), so the stage shape is part of "
    "the plan, not a collect-time surprise.")

SPMD_BUCKET_ROUNDS = register(
    "spark.rapids.tpu.shuffle.collective.spmd.bucketRounds", 8,
    "Maximum exchange rounds folded into ONE partitioned stage "
    "program's in-program scan (agg and join stream stages; the sort "
    "stage folds ALL rounds into its single program because range "
    "bounds must see every round's sample).  Bounds the stage's "
    "resident input footprint at bucketRounds x roundRows rows per "
    "shard; round counts inside a bucket pad to a power of two so the "
    "scan length — part of the compiled program's key — takes a "
    "handful of values instead of one executable per data-dependent "
    "round count (docs/spmd.md).",
    check=lambda v: v >= 1)


def stage_config(conf=None) -> tuple[bool, int]:
    """(spmd_enabled, bucket_rounds) — THE planner seam deciding how
    collective stage boundaries compile.  Read at plan time and pinned
    into the exec (and therefore into explain()/the event log's plan
    report), so a conf flip after planning cannot silently change an
    already-planned stage's execution shape."""
    conf = conf or get_conf()
    return bool(conf.get(SPMD_STAGE)), int(conf.get(SPMD_BUCKET_ROUNDS))


def _unify_shards(shards: list[ColumnarBatch]) -> list[ColumnarBatch]:
    """Pad shard batches to one capacity/width profile for stacking
    (shared with the SPMD global-array assembly in parallel/spmd.py)."""
    from spark_rapids_tpu.parallel.spmd import unify_batches

    return unify_batches(shards)


def _fold_groups(groups: list[list[ColumnarBatch]],
                 schema: T.Schema) -> list[ColumnarBatch]:
    """Per-shard batch lists -> one batch per shard (empty batches for
    shards that received nothing)."""
    out = []
    for group in groups:
        if not group:
            out.append(ColumnarBatch.empty(schema))
        elif len(group) == 1:
            out.append(group[0])
        else:
            out.append(concat_batches(group))
    return out


class _CollectiveBase(TpuExec):
    """Shared round-streaming driver for collective execs.

    Subclasses produce their output as ONE batch per mesh shard
    (`_materialize`); per-partition consumers (a sort, limit, or join
    stacked above) read shard p through `execute_partition(p)`."""

    mesh = None  # set by subclass __init__

    def _init_stage(self, spmd: Optional[bool],
                    bucket_rounds: Optional[int]) -> None:
        """Pin the stage execution shape at construction (= plan)
        time; the planner passes stage_config() through so the
        decision is part of the plan."""
        conf_spmd, conf_bucket = stage_config()
        self.spmd_stage = conf_spmd if spmd is None else bool(spmd)
        self.bucket_rounds = max(1, conf_bucket if bucket_rounds is None
                                 else int(bucket_rounds))

    def _stage_desc(self) -> str:
        return (f"stage=spmd(bucket={self.bucket_rounds})"
                if self.spmd_stage else "stage=host-loop")

    @property
    def num_partitions(self) -> int:
        return int(self.mesh.shape[DATA_AXIS])

    def _shard_rounds(self, child: TpuExec
                      ) -> Iterator[list[ColumnarBatch]]:
        """Drain child partitions into per-shard batch groups, yielding
        a round whenever any shard reaches the row budget.  Always
        yields at least one round (of empties) so downstream programs
        emit schema-correct output for empty inputs."""
        n = self.num_partitions
        budget = get_conf().get(COLLECTIVE_ROUND_ROWS)
        per_shard: list[list[ColumnarBatch]] = [[] for _ in range(n)]
        rows = [0] * n
        yielded = False
        for p in range(child.num_partitions):
            for b in child.execute_partition(p):
                r = b.concrete_num_rows()
                tgt = rows.index(min(rows))  # least-loaded shard
                per_shard[tgt].append(_dc.replace(b, num_rows=r))
                rows[tgt] += r
                if max(rows) >= budget:
                    if "collectiveRounds" in self.metrics:
                        self.metrics["collectiveRounds"].add(1)
                    yield _fold_groups(per_shard, child.schema)
                    yielded = True
                    per_shard = [[] for _ in range(n)]
                    rows = [0] * n
        if any(rows) or not yielded:
            if "collectiveRounds" in self.metrics:
                self.metrics["collectiveRounds"].add(1)
            yield _fold_groups(per_shard, child.schema)

    def _exchange_rounds(self, child: TpuExec, step, *extras,
                         out_schema: Optional[T.Schema] = None
                         ) -> list[ColumnarBatch]:
        """Stream the child through `step` round by round, parking each
        round's per-shard outputs shrunk on device; returns one folded
        batch per shard.  `out_schema` is the STEP's output schema
        (defaults to the child's — right for pure routing steps)."""
        from spark_rapids_tpu.parallel.exchange import unstack_batch

        n = self.num_partitions
        parts: list[list[ColumnarBatch]] = [[] for _ in range(n)]
        for shards in self._shard_rounds(child):
            out = step(self._stack(shards), *extras)
            for i, b in enumerate(unstack_batch(out)):
                parts[i].append(self._shrunk(b))
        return _fold_groups(parts, out_schema or child.schema)

    # -- per-partition serving ----------------------------------------- #

    def _materialize(self) -> list[list[ColumnarBatch]]:
        """Output batches per mesh shard (subclass responsibility)."""
        raise NotImplementedError

    #: guards per-instance materialization-lock creation
    _MAT_GUARD = __import__("threading").Lock()

    def _shard_outputs(self) -> list[list[ColumnarBatch]]:
        """Materialize EXACTLY once even under concurrent per-partition
        consumers (an exchange's map-task pool drives every partition
        from its own thread; unsynchronized, N threads would run N
        overlapping SPMD programs and race the jit caches)."""
        import threading

        out = getattr(self, "_shards_out", None)
        if out is not None:
            return out
        with _CollectiveBase._MAT_GUARD:
            lk = getattr(self, "_mat_lock", None)
            if lk is None:
                lk = self._mat_lock = threading.Lock()
        with lk:
            out = getattr(self, "_shards_out", None)
            if out is None:
                out = self._shards_out = self._materialize()
        return out

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        for b in self._shard_outputs()[p]:
            yield self._count_output(b)

    def execute(self) -> Iterator[ColumnarBatch]:
        for p in range(self.num_partitions):
            yield from self.execute_partition(p)

    def _stack(self, shards: list[ColumnarBatch]):
        from spark_rapids_tpu.parallel.exchange import stack_batches

        return stack_batches(_unify_shards(shards))

    @staticmethod
    def _shrunk(batch: ColumnarBatch) -> ColumnarBatch:
        """Shrink a per-shard program output (capacity n_dest * cap) to
        its live prefix so parked rounds don't hold inflated buffers."""
        rows = batch.concrete_num_rows()
        return batch.shrink_to_capacity(pad_capacity(rows))


class TpuCollectiveHashAggregateExec(_CollectiveBase):
    """Grouped aggregation as fused SPMD programs over the active mesh.

    Per round: map-side update aggregation, hash all_to_all on the
    group keys, and reduce-side merge run as ONE program; per-shard
    round results park on device, and a final per-shard local program
    (merge + finalize, no collectives) folds the rounds — same keys
    always land on the same shard, so the cross-round merge is local."""

    def __init__(self, groups: Sequence[Expression],
                 aggs: Sequence[NamedAgg], child: TpuExec, mesh,
                 spmd: Optional[bool] = None,
                 bucket_rounds: Optional[int] = None):
        super().__init__(child)
        self.mesh = mesh
        self._init_stage(spmd, bucket_rounds)
        # the partial-mode exec carries every traceable phase we fuse
        self._agg = TpuHashAggregateExec(groups, aggs, child,
                                         mode="partial")
        self._schema = T.Schema(
            list(self._agg.partial_schema.fields[: self._agg.n_keys])
            + [na.output_field() for na in self._agg.aggs])
        self._step = None
        self._final_step = None

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def node_desc(self) -> str:
        a = self._agg
        keys = ", ".join(e.name for e in a.groups)
        return (f"TpuCollectiveHashAggregateExec keys=[{keys}] "
                f"[all_to_all over mesh axis '{DATA_AXIS}' x"
                f"{self.num_partitions}] [{self._stage_desc()}]")

    def additional_metrics(self):
        return [("collectiveRows", "MODERATE"),
                ("collectiveRounds", "MODERATE")]

    # -- fused phases ----------------------------------------------------- #

    def _pre(self, batch: ColumnarBatch) -> ColumnarBatch:
        return self._agg._update_batch(batch)

    def _merge(self, batch: ColumnarBatch) -> ColumnarBatch:
        return self._agg._merge_batch(batch)

    def _finalize(self, batch: ColumnarBatch) -> ColumnarBatch:
        merged = self._agg._merge_batch(batch)
        ctx = EvalContext.for_batch(merged)
        cols = [e.eval(ctx) for e in self._agg.final_exprs]
        return ColumnarBatch(cols, merged.num_rows, self._schema)

    # -- driver ----------------------------------------------------------- #

    def _materialize(self) -> list[list[ColumnarBatch]]:
        if self.spmd_stage:
            return self._materialize_spmd()
        return self._materialize_host_loop()

    def _materialize_spmd(self) -> list[list[ColumnarBatch]]:
        """The aggregation stage as O(1) partitioned programs: one
        exchange-scan program per round bucket (map-side update ->
        in-program hash all_to_all -> reduce-side merge, all rounds
        folded into a lax.scan), ONE mid-stage counts fetch + shrink,
        then one tail program (cross-round merge + finalize) at tight
        capacity — same keys always land on the same shard, so the
        cross-round fold is shard-local."""
        from spark_rapids_tpu.parallel import spmd as S
        from spark_rapids_tpu.parallel.exchange import exchange_shard

        child = self.children[0]
        n = self.num_partitions
        akey = self._agg._cache_key()
        ko = list(range(self._agg.n_keys))

        def xchg_body(b: ColumnarBatch) -> ColumnarBatch:
            return self._merge(
                exchange_shard(self._pre(b), ko, n, DATA_AXIS))

        with MetricTimer(self.metrics[TOTAL_TIME], op=self.name) as t:
            shrunk: list[list[ColumnarBatch]] = []  # rounds[r][d]
            bucket: list = []

            def flush(bucket):
                bucket = S.pad_rounds_pow2(bucket, child.schema, n)
                xs = S.shard_stack_rounds(bucket, self.mesh)
                prog = S.make_exchange_scan_stage(
                    self.mesh, akey, xchg_body, len(bucket),
                    op=self.name, donate=True)
                shrunk.extend(S.shrink_rounds(prog(xs),
                                              mesh=self.mesh))

            for shards in self._shard_rounds(child):
                bucket.append(shards)
                if len(bucket) == self.bucket_rounds:
                    flush(bucket)
                    bucket = []
            if bucket:
                flush(bucket)
            rounds2 = S.pad_rounds_pow2(
                shrunk, self._agg.partial_schema, n)
            xs2 = S.shard_stack_rounds(rounds2, self.mesh)
            tail = S.make_stage_tail(self.mesh, akey, self._finalize,
                                     len(rounds2), op=self.name,
                                     donate=True)
            final = t.observe(tail(xs2))
        counts = S.stage_counts(final)
        out = []
        for d, b in enumerate(S.unstack_stage(final, counts,
                                              mesh=self.mesh)):
            self.metrics["collectiveRows"].add(int(counts[d]))
            out.append([b])
        return out

    def _materialize_host_loop(self) -> list[list[ColumnarBatch]]:
        from spark_rapids_tpu.parallel.exchange import (
            make_hash_exchange_step,
            make_local_step,
            unstack_batch,
        )

        if self._step is None:
            self._step = make_hash_exchange_step(
                self.mesh, list(range(self._agg.n_keys)),
                pre=self._pre, post=self._merge)
            self._final_step = make_local_step(self.mesh,
                                               self._finalize)
        with MetricTimer(self.metrics[TOTAL_TIME], op=self.name) as t:
            merged = self._exchange_rounds(
                self.children[0], self._step,
                out_schema=self._agg.partial_schema)
            final = t.observe(self._final_step(self._stack(merged)))
        out = []
        for b in unstack_batch(final):
            self.metrics["collectiveRows"].add(b.concrete_num_rows())
            out.append([b])
        return out


class TpuCollectiveHashJoinExec(_CollectiveBase):
    """Shuffled equi-join as fused SPMD programs (the collective analog
    of TpuShuffledHashJoinExec; ref: GpuShuffledHashJoinBase over
    GpuShuffleExchangeExec).  The build (right) side exchanges once by
    right-key hash; each stream round then routes by left-key hash and
    joins locally in the SAME program — co-partitioning makes every
    match shard-local, exactly the property the reference gets from
    co-partitioned shuffle outputs."""

    SUPPORTED_TYPES = ("inner", "left_outer", "left_semi", "left_anti")

    def __init__(self, left_keys, right_keys, join_type: str,
                 left: TpuExec, right: TpuExec, mesh,
                 spmd: Optional[bool] = None,
                 bucket_rounds: Optional[int] = None):
        from spark_rapids_tpu.execs.join import _nullable_fields

        assert join_type in self.SUPPORTED_TYPES, join_type
        super().__init__(left, right)
        self.mesh = mesh
        self._init_stage(spmd, bucket_rounds)
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.join_type = join_type
        if join_type in ("left_semi", "left_anti"):
            self._schema = left.schema
        else:
            rf = _nullable_fields(right.schema) \
                if join_type == "left_outer" else list(right.schema.fields)
            self._schema = T.Schema(list(left.schema.fields) + rf)
        self._build_step = None
        self._join_steps: dict[int, object] = {}

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def node_desc(self) -> str:
        ks = ", ".join(f"{lk.name}={rk.name}" for lk, rk in
                       zip(self.left_keys, self.right_keys))
        return (f"TpuCollectiveHashJoinExec {self.join_type} [{ks}] "
                f"[all_to_all x{self.num_partitions}] "
                f"[{self._stage_desc()}]")

    def additional_metrics(self):
        return [("buildRows", "MODERATE"),
                ("collectiveRounds", "MODERATE")]

    # -- fused bodies ------------------------------------------------------ #

    def _route_build(self, batch: ColumnarBatch) -> jax.Array:
        from spark_rapids_tpu.exprs.hashing import partition_ids

        ctx = EvalContext.for_batch(batch)
        cols = [k.eval(ctx) for k in self.right_keys]
        return partition_ids(cols, batch.capacity, self.num_partitions)

    def _route_stream(self, stream: ColumnarBatch) -> jax.Array:
        from spark_rapids_tpu.exprs.hashing import partition_ids

        sctx = EvalContext.for_batch(stream)
        return partition_ids([k.eval(sctx) for k in self.left_keys],
                             stream.capacity, self.num_partitions)

    def _join_shard(self, stream: ColumnarBatch, build: ColumnarBatch,
                    out_cap: int):
        from spark_rapids_tpu.parallel.exchange import route_shard

        routed = route_shard(stream, self._route_stream(stream),
                             self.num_partitions, DATA_AXIS)
        return self._join_local(routed, build, out_cap)

    def _join_local(self, routed: ColumnarBatch, build: ColumnarBatch,
                    out_cap: int):
        from spark_rapids_tpu.ops.join import (
            expand_pairs,
            gather_joined,
            join_state,
        )

        rctx = EvalContext.for_batch(routed)
        bctx = EvalContext.for_batch(build)
        skc = [k.eval(rctx) for k in self.left_keys]
        bkc = [k.eval(bctx) for k in self.right_keys]
        jt = self.join_type
        st = join_state(build, routed, bkc, skc,
                        "inner" if jt in ("left_semi", "left_anti")
                        else jt)
        if jt in ("left_semi", "left_anti"):
            keep = st.matched_s if jt == "left_semi" \
                else (st.live_s & ~st.matched_s)
            out = routed.compact(keep)
            return out, jnp.sum(keep).astype(jnp.int32)
        total = jnp.sum(st.cnt_s).astype(jnp.int32)
        s_idx, b_idx, pair_live, matched = expand_pairs(st, out_cap)
        out = gather_joined(build, routed, s_idx, b_idx, pair_live,
                            matched, jnp.minimum(total, out_cap),
                            self._schema, stream_first=True)
        return out, total

    def _join_step(self, out_cap: int):
        from spark_rapids_tpu.parallel.exchange import make_join_step

        step = self._join_steps.get(out_cap)
        if step is None:
            step = self._join_steps[out_cap] = make_join_step(
                self.mesh,
                lambda s, b: self._join_shard(s, b, out_cap))
        return step

    # -- driver ------------------------------------------------------------ #

    def _collect_build(self) -> ColumnarBatch:
        """Exchange the build side by right-key hash, in rounds;
        returns the stacked per-shard build batch."""
        from spark_rapids_tpu.parallel.exchange import make_route_step

        if self._build_step is None:
            self._build_step = make_route_step(
                self.mesh, lambda b: self._route_build(b))
        merged = self._exchange_rounds(self.children[1],
                                       self._build_step)
        for b in merged:
            self.metrics["buildRows"].add(b.concrete_num_rows())
        return self._stack(merged)

    def _join_key(self) -> tuple:
        from spark_rapids_tpu.execs.jit_cache import exprs_key

        return ("cjoin", self.join_type, exprs_key(self.left_keys),
                exprs_key(self.right_keys), repr(self._schema))

    def _materialize(self) -> list[list[ColumnarBatch]]:
        if self.spmd_stage:
            return self._materialize_spmd()
        return self._materialize_host_loop()

    def _materialize_spmd(self) -> list[list[ColumnarBatch]]:
        """The join stage as O(1) partitioned programs per side: the
        build side runs one exchange-scan program (route by right-key
        hash, all rounds in one lax.scan) + mid-stage shrink + one
        tail program folding the per-shard build batch; each stream
        bucket runs one exchange-scan program (route by left-key
        hash) + shrink, then one probe program joining the TIGHT
        routed rounds against the resident build shard.  Host syncs
        happen only at stage exits (the shrink counts and each
        bucket's true totals); overflow of the output-capacity guess
        re-dispatches that bucket's probe program at the
        JoinGatherer-style re-bucketed capacity."""
        from spark_rapids_tpu.parallel import spmd as S
        from spark_rapids_tpu.parallel.exchange import route_shard

        n = self.num_partitions
        jkey = self._join_key()
        chunks: list[list[ColumnarBatch]] = [[] for _ in range(n)]
        semi_anti = self.join_type in ("left_semi", "left_anti")

        def build_body(b: ColumnarBatch) -> ColumnarBatch:
            return route_shard(b, self._route_build(b), n, DATA_AXIS)

        def stream_body(b: ColumnarBatch) -> ColumnarBatch:
            return route_shard(b, self._route_stream(b), n, DATA_AXIS)

        with MetricTimer(self.metrics[TOTAL_TIME], op=self.name) as t:
            build_rounds = S.pad_rounds_pow2(
                list(self._shard_rounds(self.children[1])),
                self.children[1].schema, n)
            xs_b = S.shard_stack_rounds(build_rounds, self.mesh)
            bprog = S.make_exchange_scan_stage(
                self.mesh, jkey + ("build",), build_body,
                len(build_rounds), op=self.name, donate=True)
            ys_b = bprog(xs_b)
            bcounts = S.stage_counts(ys_b)
            shrunk_b = S.shrink_rounds(ys_b, bcounts, mesh=self.mesh)
            self.metrics["buildRows"].add(int(bcounts.sum()))
            build_rows = int(bcounts.sum(axis=0).max()) \
                if bcounts.size else 0
            rounds_b = S.pad_rounds_pow2(
                shrunk_b, self.children[1].schema, n)
            btail = S.make_stage_tail(
                self.mesh, jkey + ("buildfold",), lambda b: b,
                len(rounds_b), op=self.name, donate=True)
            build = btail(S.shard_stack_rounds(rounds_b, self.mesh))

            def run_bucket(bucket):
                bucket = S.pad_rounds_pow2(bucket,
                                           self.children[0].schema, n)
                xs = S.shard_stack_rounds(bucket, self.mesh)
                rprog = S.make_exchange_scan_stage(
                    self.mesh, jkey + ("stream",), stream_body,
                    len(bucket), op=self.name, donate=True)
                ys = rprog(xs)
                counts2 = S.stage_counts(ys)
                rounds2 = S.pad_rounds_pow2(
                    S.shrink_rounds(ys, counts2, mesh=self.mesh),
                    self.children[0].schema, n)
                xs2 = S.shard_stack_rounds(rounds2, self.mesh)
                # probe out-capacity from the LIVE routed maximum, not
                # the padded round capacity or the whole build side:
                # pad_capacity honors the pow2x3 bucket policy, so a
                # 5/8-full shard stops forcing expand_pairs to compute
                # on a worst-case pad (MULTICHIP_r06 measured the old
                # max(cap, build_rows) guess at 0.505x per device).
                # An undershoot is safe: the totals check below
                # re-buckets and re-dispatches at the true capacity.
                live_max = int(counts2.max()) if counts2.size else 0
                cap_guess = 64 if semi_anti else pad_capacity(
                    max(live_max, 64))
                while True:
                    if not semi_anti:
                        _ledger.note_occupancy(max(live_max, 1),
                                               cap_guess)
                    prog = S.make_join_scan_stage(
                        self.mesh, jkey + (cap_guess,),
                        lambda s, b, c=cap_guess:
                            self._join_local(s, b, c),
                        len(rounds2), op=self.name)
                    outs, totals = prog(xs2, build)
                    if semi_anti:
                        break
                    worst = int(S.fetch(totals).max())
                    if worst <= cap_guess:
                        break
                    # JoinGatherer-style re-bucket: recompile at the
                    # capacity the data actually needs
                    cap_guess = pad_capacity(worst)
                outs = t.observe(outs)
                per = S.unstack_round_stage(outs, mesh=self.mesh)
                for d in range(n):
                    chunks[d].extend(per[d])

            bucket: list = []
            any_bucket = False
            for shards in self._shard_rounds(self.children[0]):
                bucket.append(shards)
                if len(bucket) == self.bucket_rounds:
                    run_bucket(bucket)
                    any_bucket = True
                    bucket = []
            if bucket or not any_bucket:
                run_bucket(bucket or [
                    [ColumnarBatch.empty(self.children[0].schema)
                     for _ in range(n)]])
        return chunks

    def _materialize_host_loop(self) -> list[list[ColumnarBatch]]:
        from spark_rapids_tpu.parallel import spmd as S
        from spark_rapids_tpu.parallel.exchange import unstack_batch

        chunks: list[list[ColumnarBatch]] = [
            [] for _ in range(self.num_partitions)]
        with MetricTimer(self.metrics[TOTAL_TIME], op=self.name) as t:
            build_stacked = self._collect_build()
            build_rows = int(S.fetch(build_stacked.num_rows).max())
            for shards in self._shard_rounds(self.children[0]):
                n = self.num_partitions
                cap_round = max(s.capacity for s in shards)
                stacked = self._stack(shards)
                # initial output guess: a shard can receive up to the
                # whole round (n * cap_round); matches usually stay
                # near stream row counts
                cap_guess = 64 if self.join_type in (
                    "left_semi", "left_anti") else pad_capacity(
                        max(cap_round * n, build_rows, 64))
                while True:
                    step = self._join_step(cap_guess)
                    out, totals = step(stacked, build_stacked)
                    if self.join_type in ("left_semi", "left_anti"):
                        break
                    worst = int(S.fetch(totals).max())
                    if worst <= cap_guess:
                        break
                    # JoinGatherer-style re-bucket: recompile at the
                    # capacity the data actually needs
                    cap_guess = pad_capacity(worst)
                out = t.observe(out)
                for i, b in enumerate(unstack_batch(out)):
                    if b.concrete_num_rows():
                        chunks[i].append(self._shrunk(b))
        return chunks


class TpuCollectiveSortExec(_CollectiveBase):
    """Distributed ORDER BY as fused SPMD programs (the collective
    analog of range-exchange + per-partition sort; ref:
    GpuRangePartitioner sketch/determineBounds + GpuSortExec).

    Pass 1 streams the child into parked device rounds while sampling
    sort keys; bounds come from the pooled sample; pass 2 routes every
    round through a range-bisect all_to_all (bounds ride as a
    REPLICATED program argument, so one compiled program serves every
    bounds value); each shard then sorts locally — shard index order
    IS the total order."""

    SAMPLE_PER_SHARD = 256

    def __init__(self, keys, child: TpuExec, mesh,
                 spmd: Optional[bool] = None,
                 bucket_rounds: Optional[int] = None):
        super().__init__(child)
        from spark_rapids_tpu.ops.partition import RangePartitioning

        self.mesh = mesh
        self._init_stage(spmd, bucket_rounds)
        self.keys = list(keys)
        n = int(mesh.shape[DATA_AXIS])
        self._part = RangePartitioning(self.keys, n).bind(child.schema)

    @property
    def schema(self) -> T.Schema:
        return self.children[0].schema

    def node_desc(self) -> str:
        ks = ", ".join(
            f"{k.expr.name}{' DESC' if k.descending else ''}"
            for k in self.keys)
        return (f"TpuCollectiveSortExec [{ks}] "
                f"[range all_to_all x{self.num_partitions}] "
                f"[{self._stage_desc()}]")

    def additional_metrics(self):
        return [("collectiveRounds", "MODERATE")]

    @staticmethod
    def _sample_k(rows: int) -> int:
        """Per-batch sample count ~ proportional to rows (one per 64,
        power-of-two bucketed for compile-cache stability, capped) —
        equal per-batch counts would let a 10-row tail batch weigh as
        much as a million-row one when choosing bounds (the weighting
        concern behind GpuRangePartitioner's size-scaled sketch)."""
        k = max(16, min(256, rows // 64))
        return 1 << (k - 1).bit_length()

    def _sort_key(self) -> tuple:
        from spark_rapids_tpu.execs.jit_cache import exprs_key

        return (exprs_key([k.expr for k in self._part.keys]),
                tuple((k.descending, k.nulls_last)
                      for k in self._part.keys))

    def _materialize(self) -> list[list[ColumnarBatch]]:
        if self.spmd_stage:
            return self._materialize_spmd()
        return self._materialize_host_loop()

    def _materialize_spmd(self) -> list[list[ColumnarBatch]]:
        """The distributed ORDER BY as TWO partitioned programs: the
        route program (in-program sampling at host-chosen fractional
        positions — no per-batch row-count sync — all_gather-pooled
        dynamic range bounds, the range-routed all_to_all over a
        scanned rounds axis), ONE mid-stage counts fetch + shrink,
        then the tail program sorting each shard at tight capacity —
        shard index order IS the total order.  The sort stage ignores
        bucketRounds: bounds must see every round's sample, and the
        host-loop path also parked all rounds before routing, so the
        resident footprint is unchanged."""
        from spark_rapids_tpu.ops.sort import sort_permutation
        from spark_rapids_tpu.parallel import spmd as S

        child = self.children[0]
        part = self._part
        n = self.num_partitions
        skey = self._sort_key()

        def local_sort(b: ColumnarBatch) -> ColumnarBatch:
            # sort by the evaluated key batch (works for arbitrary
            # key expressions, not just column refs)
            perm = sort_permutation(part.key_batch(b),
                                    part.key_orders())
            return b.gather(perm, b.num_rows)

        from spark_rapids_tpu.serving import mesh_serving_enabled

        with MetricTimer(self.metrics[TOTAL_TIME], op=self.name) as t:
            raw = list(self._shard_rounds(child))
            if (len(raw) > self.bucket_rounds
                    and mesh_serving_enabled()):
                out = self._spmd_sort_bucketed(raw, local_sort, t)
            else:
                rounds = S.pad_rounds_pow2(raw, child.schema, n)
                xs = S.shard_stack_rounds(rounds, self.mesh)
                fracs = S.sample_fracs(self.mesh, len(rounds),
                                       self.SAMPLE_PER_SHARD)
                rprog = S.make_sort_route_stage(
                    self.mesh, skey, part, len(rounds),
                    self.SAMPLE_PER_SHARD, op=self.name, donate=True)
                routed = rprog(xs, fracs)
                rounds2 = S.pad_rounds_pow2(
                    S.shrink_rounds(routed, mesh=self.mesh),
                    child.schema, n)
                xs2 = S.shard_stack_rounds(rounds2, self.mesh)
                tail = S.make_stage_tail(self.mesh, skey, local_sort,
                                         len(rounds2), op=self.name,
                                         donate=True)
                out = t.observe(tail(xs2))
        counts = S.stage_counts(out)
        return [[b]
                for b in S.unstack_stage(out, counts, mesh=self.mesh)]

    def _spmd_sort_bucketed(self, raw: list, local_sort, t):
        """Bounded-residency sort (mesh serving, docs/pod_serving.md):
        instead of assembling EVERY round into one resident global
        array (the single-program path's footprint is R x n x roundRows
        for the whole stage), sample bucket by bucket (pass 1, one
        bucket stacked at a time), choose bounds once from the pooled
        tiny samples, then range-route bucket by bucket (pass 2, bounds
        as a replicated program argument).  Row placement may differ
        from the single-program path (bounds come from the same
        fraction scheme but bucket-local pooling); the TOTAL order —
        sorted shards concatenated by shard index — is identical by
        construction, because any bounds partition sorts correctly."""
        from spark_rapids_tpu.execs.jit_cache import cached_jit
        from spark_rapids_tpu.ops.range_partition import choose_bounds
        from spark_rapids_tpu.parallel import spmd as S

        child = self.children[0]
        part = self._part
        n = self.num_partitions
        skey = self._sort_key()
        B = self.bucket_rounds
        buckets = [S.pad_rounds_pow2(raw[i:i + B], child.schema, n)
                   for i in range(0, len(raw), B)]

        # pass 1: per-bucket sample programs; only the tiny per-shard
        # key samples stay resident between passes
        samples: list[ColumnarBatch] = []
        for bucket in buckets:
            xs = S.shard_stack_rounds(bucket, self.mesh)
            fracs = S.sample_fracs(self.mesh, len(bucket),
                                   self.SAMPLE_PER_SHARD)
            sprog = S.make_sort_sample_stage(
                self.mesh, skey, part, len(bucket),
                self.SAMPLE_PER_SHARD, op=self.name)
            per = S.unstack_round_stage(sprog(xs, fracs),
                                        mesh=self.mesh)
            for shard_list in per:
                samples.extend(shard_list)
        if not samples:
            samples = [part.key_batch(ColumnarBatch.empty(child.schema))]
        n_live = sum(s.concrete_num_rows() for s in samples)
        jit_bounds = cached_jit(
            ("csortbounds", skey, n_live, n,
             tuple(s.capacity for s in samples)),
            op=self.name,
            make_fn=lambda: lambda ss: choose_bounds(
                concat_batches(ss), part.key_orders(), n, n_live))
        bounds = jit_bounds(samples)

        # pass 2: per-bucket range routing against the shared bounds
        shrunk: list[list[ColumnarBatch]] = []
        for bucket in buckets:
            xs = S.shard_stack_rounds(bucket, self.mesh)
            rprog = S.make_bounds_route_stage(
                self.mesh, skey, part, len(bucket), op=self.name,
                donate=True)
            shrunk.extend(S.shrink_rounds(rprog(xs, bounds),
                                          mesh=self.mesh))
        rounds2 = S.pad_rounds_pow2(shrunk, child.schema, n)
        xs2 = S.shard_stack_rounds(rounds2, self.mesh)
        tail = S.make_stage_tail(self.mesh, skey, local_sort,
                                 len(rounds2), op=self.name,
                                 donate=True)
        return t.observe(tail(xs2))

    def _materialize_host_loop(self) -> list[list[ColumnarBatch]]:
        import numpy as np

        from spark_rapids_tpu.execs.jit_cache import cached_jit, exprs_key
        from spark_rapids_tpu.ops.range_partition import choose_bounds
        from spark_rapids_tpu.parallel.exchange import (
            make_local_step,
            make_route_step,
            unstack_batch,
        )

        part = self._part
        n = self.num_partitions
        pkey = (exprs_key([k.expr for k in part.keys]),
                tuple((k.descending, k.nulls_last) for k in part.keys))
        rng = np.random.default_rng(0x52414E47)

        # pass 1: park rounds + sample keys per shard (sample size
        # scales with batch rows — see _sample_k)
        rounds: list[list[ColumnarBatch]] = []
        samples: list[ColumnarBatch] = []
        with MetricTimer(self.metrics[TOTAL_TIME], op=self.name) as t:
            for shards in self._shard_rounds(self.children[0]):
                rounds.append(shards)
                for s in shards:
                    rows = s.concrete_num_rows()
                    if not rows:
                        continue
                    n_sample = self._sample_k(rows)
                    jit_sample = cached_jit(
                        ("csortsample", pkey, s.capacity, n_sample,
                         repr(s.schema)),
                        op=self.name,
                        make_fn=lambda: lambda b, p: part.key_batch(
                            b).gather(p, p.shape[0]))
                    pos = jnp.asarray(
                        rng.integers(0, rows, n_sample).astype(np.int32))
                    samples.append(jit_sample(s, pos))
            if not samples:
                return [[ColumnarBatch.empty(self.schema)]
                        for _ in range(n)]
            n_live = sum(s.num_rows for s in samples)
            jit_bounds = cached_jit(
                ("csortbounds", pkey, n_live, n,
                 tuple(s.capacity for s in samples)),
                op=self.name,
                make_fn=lambda: lambda ss: choose_bounds(
                    concat_batches(ss), part.key_orders(), n, n_live))
            bounds = jit_bounds(samples)

            # pass 2: range-routed all_to_all per round, then local sort
            route = make_route_step(
                self.mesh,
                lambda b, bd: part.partition_ids_with_bounds(b, bd),
                n_extra=1)
            parts: list[list[ColumnarBatch]] = [[] for _ in range(n)]
            for shards in rounds:
                out = route(self._stack(shards), bounds)
                for i, b in enumerate(unstack_batch(out)):
                    parts[i].append(self._shrunk(b))
            merged = _fold_groups(parts, self.schema)

            def local_sort_fn(b: ColumnarBatch) -> ColumnarBatch:
                # sort by the evaluated key batch (works for arbitrary
                # key expressions, not just column refs)
                from spark_rapids_tpu.ops.sort import sort_permutation

                perm = sort_permutation(part.key_batch(b),
                                        part.key_orders())
                return b.gather(perm, b.num_rows)

            local_sort = make_local_step(self.mesh, local_sort_fn)
            final = t.observe(local_sort(self._stack(merged)))
            return [[b] for b in unstack_batch(final)]
