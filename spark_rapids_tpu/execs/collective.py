"""Collective (tier-2) exchange-bearing operators.

When the collective shuffle transport is active, the planner lowers a
grouped aggregate's partial -> exchange -> final pipeline into ONE fused
SPMD program per query stage (ref: the role GpuShuffleExchangeExecBase +
RapidsShuffleTransport play around GpuHashAggregateExec, re-designed for
TPU: the map-side update aggregation, the murmur3-routed `all_to_all`
over the mesh axis, and the reduce-side merge+finalize are a single
shard_map/jit program — no host hop between map and reduce, collectives
ride ICI scheduled by XLA; SURVEY.md §5.8)."""

from __future__ import annotations

from typing import Iterator, Sequence

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch, concat_batches
from spark_rapids_tpu.columnar.column import (
    Column,
    StringColumn,
    pad_width,
)
from spark_rapids_tpu.execs.aggregate import TpuHashAggregateExec
from spark_rapids_tpu.execs.base import MetricTimer, TOTAL_TIME, TpuExec
from spark_rapids_tpu.exprs.aggregates import NamedAgg
from spark_rapids_tpu.exprs.base import Expression
from spark_rapids_tpu.parallel.mesh import DATA_AXIS


def _repad(batch: ColumnarBatch, cap: int,
           widths: dict[int, int]) -> ColumnarBatch:
    """Pad a batch to a common capacity/string-width profile so per-shard
    leaves stack into one array with a leading device axis."""
    cols = []
    for ci, c in enumerate(batch.columns):
        if isinstance(c, StringColumn):
            w = widths[ci]
            chars = c.chars
            if c.width < w:
                chars = jnp.pad(chars, ((0, 0), (0, w - c.width)))
            if c.capacity < cap:
                pad = cap - c.capacity
                chars = jnp.pad(chars, ((0, pad), (0, 0)))
                cols.append(StringColumn(
                    chars,
                    jnp.pad(c.lengths, (0, pad)),
                    jnp.pad(c.validity, (0, pad))))
            else:
                cols.append(StringColumn(chars, c.lengths, c.validity))
        else:
            if c.capacity < cap:
                pad = cap - c.capacity
                cols.append(Column(jnp.pad(c.data, (0, pad)),
                                   jnp.pad(c.validity, (0, pad)),
                                   c.dtype))
            else:
                cols.append(c)
    return ColumnarBatch(cols, batch.num_rows, batch.schema)


class TpuCollectiveHashAggregateExec(TpuExec):
    """Grouped aggregation as one SPMD program over the active mesh.

    Host side only routes input: child partitions are drained round-robin
    into one batch per shard; everything after the stack — update
    aggregation, hash exchange, merge, finalization — is device code in
    a single compiled step shared across queries with equal structure."""

    def __init__(self, groups: Sequence[Expression],
                 aggs: Sequence[NamedAgg], child: TpuExec, mesh):
        super().__init__(child)
        self.mesh = mesh
        # the partial-mode exec carries every traceable phase we fuse
        self._agg = TpuHashAggregateExec(groups, aggs, child,
                                         mode="partial")
        self._schema = T.Schema(
            list(self._agg.partial_schema.fields[: self._agg.n_keys])
            + [na.output_field() for na in self._agg.aggs])
        self._step = None

    @property
    def schema(self) -> T.Schema:
        return self._schema

    @property
    def num_partitions(self) -> int:
        return int(self.mesh.shape[DATA_AXIS])

    def node_desc(self) -> str:
        a = self._agg
        keys = ", ".join(e.name for e in a.groups)
        return (f"TpuCollectiveHashAggregateExec keys=[{keys}] "
                f"[all_to_all over mesh axis '{DATA_AXIS}' x"
                f"{self.num_partitions}]")

    def additional_metrics(self):
        return [("collectiveRows", "MODERATE")]

    # -- fused phases ----------------------------------------------------- #

    def _pre(self, batch: ColumnarBatch) -> ColumnarBatch:
        return self._agg._update_batch(batch)

    def _post(self, batch: ColumnarBatch) -> ColumnarBatch:
        from spark_rapids_tpu.exprs.base import EvalContext

        merged = self._agg._merge_batch(batch)
        # finalize with THIS exec's output schema (the partial-mode
        # helper's _schema is the partial layout)
        ctx = EvalContext.for_batch(merged)
        cols = [e.eval(ctx) for e in self._agg.final_exprs]
        return ColumnarBatch(cols, merged.num_rows, self._schema)

    # -- driver ----------------------------------------------------------- #

    def _collect_shards(self) -> list[ColumnarBatch]:
        """Drain child partitions round-robin into one batch per shard."""
        import dataclasses as _dc

        n = self.num_partitions
        child = self.children[0]
        per_shard: list[list[ColumnarBatch]] = [[] for _ in range(n)]
        for p in range(child.num_partitions):
            for b in child.execute_partition(p):
                rows = b.concrete_num_rows()
                per_shard[p % n].append(
                    _dc.replace(b, num_rows=rows))
        shards = []
        for group in per_shard:
            if not group:
                shards.append(ColumnarBatch.empty(child.schema))
            elif len(group) == 1:
                shards.append(group[0])
            else:
                shards.append(concat_batches(group))
        # unify shapes for stacking
        cap = max(s.capacity for s in shards)
        widths: dict[int, int] = {}
        for s in shards:
            for ci, c in enumerate(s.columns):
                if isinstance(c, StringColumn):
                    widths[ci] = max(widths.get(ci, 1), c.width)
        for ci in widths:
            widths[ci] = pad_width(widths[ci])
        return [_repad(s, cap, widths) for s in shards]

    def execute(self) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.parallel.exchange import (
            make_hash_exchange_step,
            stack_batches,
            unstack_batch,
        )

        shards = self._collect_shards()
        if self._step is None:
            self._step = make_hash_exchange_step(
                self.mesh, list(range(self._agg.n_keys)),
                pre=self._pre, post=self._post)
        with MetricTimer(self.metrics[TOTAL_TIME]) as t:
            stacked = stack_batches(shards)
            out = t.observe(self._step(stacked))
        for b in unstack_batch(out):
            self.metrics["collectiveRows"].add(b.concrete_num_rows())
            yield self._count_output(b)
