"""Expand exec: every input row emitted once per projection list.

TPU re-design of GpuExpandExec (ref: sql-plugin/.../GpuExpandExec.scala:
67,150 — cudf evaluates each projection over the batch and emits the
concatenated tables).  Here all projections evaluate inside ONE compiled
program: results stack to (n_projections, capacity) per column and a
vectorized gather interleaves them into a prefix-compact output of
capacity `n_projections * capacity` with `n_projections * num_rows` live
rows — no per-projection kernel launches, no host loop."""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column, StringColumn, pad_width
from spark_rapids_tpu.execs.base import BatchFn, FusableExec, TpuExec
from spark_rapids_tpu.exprs.base import EvalContext, Expression


class TpuExpandExec(FusableExec):
    MULTIPLIES_ROWS = True

    def __init__(self, projections: Sequence[Sequence[Expression]],
                 schema: T.Schema, child: TpuExec):
        super().__init__(child)
        self.projections = [list(p) for p in projections]
        self._schema = schema

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def node_desc(self) -> str:
        return f"TpuExpandExec [{len(self.projections)} projections]"

    def fuse_key(self):
        from spark_rapids_tpu.execs.jit_cache import exprs_key

        return ("expand", tuple(exprs_key(p) for p in self.projections),
                repr(self._schema))

    def fusion_exprs(self):
        return tuple(e for p in self.projections for e in p)

    def make_batch_fn(self) -> BatchFn:
        projections = self.projections
        schema = self._schema
        n_proj = len(projections)

        def fn(batch: ColumnarBatch) -> ColumnarBatch:
            cap = batch.capacity
            ctx = EvalContext.for_batch(batch)
            evaluated = [[e.eval(ctx) for e in proj]
                         for proj in projections]
            n = jnp.asarray(batch.num_rows, jnp.int32)
            cap_out = cap * n_proj
            j = jnp.arange(cap_out, dtype=jnp.int32)
            n_safe = jnp.maximum(n, 1)
            p_of_j = jnp.clip(j // n_safe, 0, n_proj - 1)
            i_of_j = j - p_of_j * n_safe
            live = j < n * n_proj
            out_cols = []
            for ci, f in enumerate(schema.fields):
                per_proj = [evaluated[p][ci] for p in range(n_proj)]
                if isinstance(f.dtype, T.StringType):
                    w = pad_width(max(
                        (c.width if isinstance(c, StringColumn) else 1)
                        for c in per_proj))
                    chars, lengths, valid = [], [], []
                    for c in per_proj:
                        if isinstance(c, StringColumn):
                            ch = c.chars
                            if c.width < w:
                                ch = jnp.pad(
                                    ch, ((0, 0), (0, w - c.width)))
                            chars.append(ch)
                            lengths.append(c.lengths.astype(jnp.int32))
                            valid.append(c.validity)
                        else:  # typed-null projection slot
                            chars.append(jnp.zeros((cap, w), jnp.uint8))
                            lengths.append(jnp.zeros(cap, jnp.int32))
                            valid.append(jnp.zeros(cap, bool))
                    sc = jnp.stack(chars)       # (n_proj, cap, w)
                    sl = jnp.stack(lengths)
                    sv = jnp.stack(valid)
                    out_cols.append(StringColumn(
                        sc[p_of_j, i_of_j], sl[p_of_j, i_of_j],
                        sv[p_of_j, i_of_j] & live))
                else:
                    phys = T.to_numpy_dtype(f.dtype)
                    data, valid = [], []
                    for c in per_proj:
                        if isinstance(c, Column) \
                                and not isinstance(c.dtype, T.NullType):
                            data.append(c.data.astype(phys))
                            valid.append(c.validity)
                        else:  # NULL slot (masked grouping column)
                            data.append(jnp.zeros(cap, phys))
                            valid.append(jnp.zeros(cap, bool))
                    sd = jnp.stack(data)        # (n_proj, cap)
                    sv = jnp.stack(valid)
                    out_cols.append(Column(
                        sd[p_of_j, i_of_j],
                        sv[p_of_j, i_of_j] & live, f.dtype))
            return ColumnarBatch(out_cols, n * n_proj, schema)

        return fn
