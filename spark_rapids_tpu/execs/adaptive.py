"""Adaptive (runtime-statistics) execution.

The AQE analog (ref: sql-plugin AQE integration —
GpuCustomShuffleReaderExec.scala coalesced/skew shuffle reads,
GpuTransitionOverrides.scala:65-99 adaptive transitions, and Spark's
AdaptiveSparkPlanExec stage re-optimization): exchanges double as query
stages, and once a map stage materializes, downstream strategy decisions
re-plan against ACTUAL sizes instead of scan-time estimates.

Two adaptive rewrites, both driven by `materialize_stats()` (the
MapOutputStatistics analog on TpuShuffleExchangeExec):

- `TpuAdaptiveJoinExec`: defers the shuffled-vs-broadcast decision to
  runtime.  Both side's map stages run first; if one side's measured
  bytes fit the broadcast threshold the join executes as a broadcast
  hash join reading the already-shuffled blocks (no re-scan — the map
  output IS the build input), otherwise as the planned partition-wise
  join over coalesced reduce partitions.
- `CoalescedShuffleReaderExec`: groups adjacent reduce partitions until
  each group reaches the advisory byte target, so a shuffle that wrote
  many tiny partitions runs few reduce tasks (the
  coalesce-shuffle-partitions rule).

Design note: on TPU the payoff is larger than on GPU — every reduce
task dispatches compiled programs whose shapes bucket by batch size, so
fewer, fuller partitions mean fewer dispatches and better MXU/VPU
utilization, and a runtime broadcast switch removes a whole exchange's
worth of device round trips.
"""

from __future__ import annotations

import threading
from typing import Iterator, Optional, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.config import register, get_conf
from spark_rapids_tpu.execs.base import TpuExec

ADAPTIVE_ENABLED = register(
    "spark.rapids.tpu.sql.adaptive.enabled", True,
    "Re-plan join strategy and reduce-partition grouping against "
    "measured map-output sizes once shuffle stages materialize (the "
    "spark.sql.adaptive.enabled analog).")

ADVISORY_PARTITION_BYTES = register(
    "spark.rapids.tpu.sql.adaptive.advisoryPartitionSizeBytes", 64 << 20,
    "Target bytes per reduce task after adaptive partition coalescing "
    "(the spark.sql.adaptive.advisoryPartitionSizeInBytes analog).")


def plan_coalesced_groups(part_bytes: Sequence[int],
                          target: int) -> list[list[int]]:
    """Group ADJACENT reduce partitions until each group reaches the
    advisory target (hash co-partitioning is preserved only by identical
    adjacent grouping on every side).  Empty partitions merge for free;
    a single oversized partition stays its own group (skew splitting
    would break build-side completeness for joins — documented gap)."""
    groups: list[list[int]] = []
    cur: list[int] = []
    cur_bytes = 0
    for rid, b in enumerate(part_bytes):
        cur.append(rid)
        cur_bytes += b
        if cur_bytes >= target:
            groups.append(cur)
            cur, cur_bytes = [], 0
    if cur:
        groups.append(cur)
    return groups or [[0]]


class CoalescedShuffleReaderExec(TpuExec):
    """Reduce-side reader exposing groups of adjacent shuffle partitions
    as single partitions (ref: GpuCustomShuffleReaderExec's
    CoalescedPartitionSpec handling)."""

    def __init__(self, exchange, groups: list[list[int]]):
        super().__init__(exchange)
        self.groups = groups

    @property
    def schema(self) -> T.Schema:
        return self.children[0].schema

    @property
    def num_partitions(self) -> int:
        return len(self.groups)

    @property
    def output_partitioning(self):
        # grouped partitions still co-partition with any reader using
        # the SAME groups, but not with the raw partitioning width —
        # adaptive join builds both sides with identical groups
        return None

    def node_desc(self) -> str:
        n_raw = self.children[0].num_partitions
        return (f"CoalescedShuffleReaderExec [{n_raw} -> "
                f"{len(self.groups)} partitions]")

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        for rid in self.groups[p]:
            for b in self.children[0].execute_partition(rid):
                yield self._count_output(b)

    def execute(self) -> Iterator[ColumnarBatch]:
        for p in range(self.num_partitions):
            yield from self.execute_partition(p)


class TpuAdaptiveJoinExec(TpuExec):
    """Join whose physical strategy is chosen at first execution from
    measured map-output statistics (ref: Spark's
    DynamicJoinSelection/AdaptiveSparkPlanExec re-optimization, which
    the reference plugs into via GpuCustomShuffleReaderExec).

    Children are the two shuffle exchanges the static planner would
    have used for a partition-wise join; the runtime decision only ever
    *improves* on that plan (broadcast from materialized blocks, or
    coalesced reduce groups), so there is no regression risk relative
    to static planning."""

    def __init__(self, left_keys, right_keys, join_type: str,
                 left_exchange, right_exchange, condition=None):
        super().__init__(left_exchange, right_exchange)
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.join_type = join_type
        self.condition = condition
        self._decided: Optional[TpuExec] = None
        self._decision = "undecided"
        self._lock = threading.Lock()
        # schema comes from the inner join exec; build one eagerly so
        # schema/explain work before execution (the static shape)
        self._template = self._make_shuffled(left_exchange,
                                             right_exchange)

    def _make_shuffled(self, lex, rex) -> TpuExec:
        from spark_rapids_tpu.execs.join import TpuShuffledHashJoinExec

        return TpuShuffledHashJoinExec(
            self.left_keys, self.right_keys, self.join_type, lex, rex,
            condition=self.condition, partition_wise=True)

    @property
    def schema(self) -> T.Schema:
        return self._template.schema

    @property
    def num_partitions(self) -> int:
        # STATIC width (the template's): reading partition counts must
        # never trigger _decide() — the planner inspects num_partitions
        # while building the tree, and materializing map stages at plan
        # time would execute scans for explain-only queries.  The
        # decided exec only ever has <= this many partitions (broadcast
        # keeps the stream width, coalescing shrinks it); the excess
        # partitions execute as empty.
        return self._template.num_partitions

    def node_desc(self) -> str:
        return (f"TpuAdaptiveJoinExec [{self.join_type}] "
                f"strategy={self._decision}")

    def additional_metrics(self):
        return [("adaptiveBroadcasts", "ESSENTIAL"),
                ("coalescedPartitions", "MODERATE")]

    # -- runtime decision ------------------------------------------------ #

    def _decide(self) -> TpuExec:
        with self._lock:
            if self._decided is not None:
                return self._decided
            from spark_rapids_tpu.execs.join import (
                TpuBroadcastHashJoinExec,
            )
            from spark_rapids_tpu.plan.planner import (
                BROADCAST_THRESHOLD,
                broadcast_candidates,
            )

            conf = get_conf()
            thr = conf.get(BROADCAST_THRESHOLD)
            lex, rex = self.children
            lstats = lex.materialize_stats()
            rstats = rex.materialize_stats()
            lbytes = sum(b for b, _ in lstats)
            rbytes = sum(b for b, _ in rstats)

            jt = self.join_type
            candidates = broadcast_candidates(jt, lbytes, rbytes, thr)
            if candidates:
                side, nbytes = min(candidates, key=lambda c: c[1])
                self.metrics["adaptiveBroadcasts"].add(1)
                self._decision = (f"broadcast[{side} "
                                  f"{nbytes >> 10}KiB<=thr]")
                self._decided = TpuBroadcastHashJoinExec(
                    self.left_keys, self.right_keys, jt, lex, rex,
                    condition=self.condition, build_side=side)
            else:
                target = conf.get(ADVISORY_PARTITION_BYTES)
                per_part = [lb + rb for (lb, _), (rb, _)
                            in zip(lstats, rstats)]
                groups = plan_coalesced_groups(per_part, target)
                if len(groups) < len(per_part):
                    self.metrics["coalescedPartitions"].add(
                        len(per_part) - len(groups))
                    self._decision = (f"shuffled[{len(per_part)}->"
                                      f"{len(groups)} parts]")
                    self._decided = self._make_shuffled(
                        CoalescedShuffleReaderExec(lex, groups),
                        CoalescedShuffleReaderExec(rex, groups))
                else:
                    self._decision = "shuffled"
                    self._decided = self._template
            # the decided exec is not a child, so metric collection
            # would miss it: adopt its Metric objects (live references)
            # under this node, keeping only the adaptive-specific ones
            own = {"adaptiveBroadcasts", "coalescedPartitions"}
            for k, v in self._decided.metrics.items():
                if k not in own:
                    self.metrics[k] = v
            return self._decided

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        decided = self._decide()
        if p >= decided.num_partitions:
            return  # coalescing shrank the width; tail partitions empty
        yield from decided.execute_partition(p)

    def execute(self) -> Iterator[ColumnarBatch]:
        yield from self._decide().execute()

    def close(self) -> None:
        # the decided exec is NOT a child (children stay the two
        # exchanges), so default propagation would miss its cleanup —
        # e.g. a runtime broadcast join's spillable build handle
        with self._lock:
            decided = self._decided
        if decided is not None and decided is not self._template:
            decided.close()
        self._template.close()  # idempotently closes the exchanges too
        super().close()
