"""Adaptive (runtime-statistics) execution.

The AQE analog (ref: sql-plugin AQE integration —
GpuCustomShuffleReaderExec.scala coalesced/skew shuffle reads,
GpuTransitionOverrides.scala:65-99 adaptive transitions, and Spark's
AdaptiveSparkPlanExec stage re-optimization): exchanges double as query
stages, and once a map stage materializes, downstream strategy decisions
re-plan against ACTUAL sizes instead of scan-time estimates.

Two adaptive rewrites, both driven by `materialize_stats()` (the
MapOutputStatistics analog on TpuShuffleExchangeExec):

- `TpuAdaptiveJoinExec`: defers the shuffled-vs-broadcast decision to
  runtime.  Both side's map stages run first; if one side's measured
  bytes fit the broadcast threshold the join executes as a broadcast
  hash join reading the already-shuffled blocks (no re-scan — the map
  output IS the build input), otherwise as the planned partition-wise
  join over coalesced reduce partitions.
- `CoalescedShuffleReaderExec`: groups adjacent reduce partitions until
  each group reaches the advisory byte target, so a shuffle that wrote
  many tiny partitions runs few reduce tasks (the
  coalesce-shuffle-partitions rule).

Design note: on TPU the payoff is larger than on GPU — every reduce
task dispatches compiled programs whose shapes bucket by batch size, so
fewer, fuller partitions mean fewer dispatches and better MXU/VPU
utilization, and a runtime broadcast switch removes a whole exchange's
worth of device round trips.
"""

from __future__ import annotations

import threading
from typing import Iterator, Optional, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.config import register, get_conf
from spark_rapids_tpu.execs.base import TpuExec

ADAPTIVE_ENABLED = register(
    "spark.rapids.tpu.sql.adaptive.enabled", True,
    "Re-plan join strategy and reduce-partition grouping against "
    "measured map-output sizes once shuffle stages materialize (the "
    "spark.sql.adaptive.enabled analog).")

ADVISORY_PARTITION_BYTES = register(
    "spark.rapids.tpu.sql.adaptive.advisoryPartitionSizeBytes", 64 << 20,
    "Target bytes per reduce task after adaptive partition coalescing "
    "(the spark.sql.adaptive.advisoryPartitionSizeInBytes analog).")

SKEW_FACTOR = register(
    "spark.rapids.tpu.sql.adaptive.skewJoin.skewedPartitionFactor", 5.0,
    "A reduce partition is skewed when its bytes exceed this multiple "
    "of the median partition size (and the threshold below) — the "
    "spark.sql.adaptive.skewJoin.skewedPartitionFactor analog.")

SKEW_THRESHOLD_BYTES = register(
    "spark.rapids.tpu.sql.adaptive.skewJoin.skewedPartitionThresholdBytes",
    64 << 20,
    "Minimum bytes before a partition is considered skewed (the "
    "spark.sql.adaptive.skewJoin.skewedPartitionThresholdBytes "
    "analog).")


#: one reduce-side read unit: (reduce_id, slice_index, slice_count).
#: (rid, 0, 1) reads the whole partition; (rid, i, k) reads the i-th of
#: k block-wise slices — the stream side of a skew split.  The build
#: side pairs each slice with a FULL (rid, 0, 1) read (build-side
#: completeness per split, Spark's OptimizeSkewedJoin contract).
PartSpec = tuple


def plan_coalesced_groups(part_bytes: Sequence[int],
                          target: int) -> list[list[int]]:
    """Group ADJACENT reduce partitions until each group reaches the
    advisory target (hash co-partitioning is preserved only by identical
    adjacent grouping on every side).  Empty partitions merge for
    free."""
    groups: list[list[int]] = []
    cur: list[int] = []
    cur_bytes = 0
    for rid, b in enumerate(part_bytes):
        cur.append(rid)
        cur_bytes += b
        if cur_bytes >= target:
            groups.append(cur)
            cur, cur_bytes = [], 0
    if cur:
        groups.append(cur)
    return groups or [[0]]


def _skew_split_side(join_type: str) -> Optional[str]:
    """Which side may be sliced without changing join semantics: a
    sliced side's rows each appear in exactly one slice, so inner and
    <side>-preserving joins stay correct; the OTHER side must stay
    complete per slice (it is the hash-build / null-producing side)."""
    if join_type == "inner":
        return "either"
    if join_type in ("left_outer", "left_semi", "left_anti"):
        return "left"
    if join_type == "right_outer":
        return "right"
    return None  # full_outer: no sound single-side split


def plan_skew_groups(lbytes: Sequence[int], rbytes: Sequence[int],
                     target: int, factor: float, threshold: int,
                     join_type: str,
                     lblocks: Optional[Sequence[int]] = None,
                     rblocks: Optional[Sequence[int]] = None
                     ) -> Optional[tuple[list, list, int]]:
    """Skew-aware aligned read plans for both sides.

    Returns (left_groups, right_groups, n_splits) where each group is a
    list of PartSpec read units and the two lists pair 1:1 into
    partition-wise join tasks — or None when nothing is skewed (caller
    falls back to plain coalescing).  A skewed partition becomes k
    tasks: k slices on the splittable side, each paired with a FULL
    read of the partition on the other side (ref:
    GpuCustomShuffleReaderExec's PartialReducerPartitionSpec handling /
    Spark's OptimizeSkewedJoin)."""
    import statistics as _st

    side = _skew_split_side(join_type)
    if side is None or not lbytes:
        return None
    med_l = _st.median(lbytes)
    med_r = _st.median(rbytes)

    def skewed(b, med) -> bool:
        return b > threshold and b > factor * max(med, 1)

    lgroups: list[list[PartSpec]] = []
    rgroups: list[list[PartSpec]] = []
    plain: list[int] = []
    plain_bytes: list[int] = []
    n_splits = 0

    def flush_plain():
        if not plain:
            return
        for grp in plan_coalesced_groups(plain_bytes, target):
            rids = [plain[i] for i in grp]
            lgroups.append([(r, 0, 1) for r in rids])
            rgroups.append([(r, 0, 1) for r in rids])
        plain.clear()
        plain_bytes.clear()

    for rid, (lb, rb) in enumerate(zip(lbytes, rbytes)):
        split_left = skewed(lb, med_l) and side in ("left", "either")
        split_right = skewed(rb, med_r) and side in ("right", "either")
        if split_left and split_right:
            # slicing both sides of one partition needs the cartesian
            # pairing of slices; split only the bigger side instead
            if lb >= rb:
                split_right = False
            else:
                split_left = False
        if not (split_left or split_right):
            plain.append(rid)
            plain_bytes.append(lb + rb)
            continue
        flush_plain()
        big = lb if split_left else rb
        k = max(2, -(-big // max(target, 1)))
        # slices deal BLOCKS round-robin: more slices than committed
        # blocks would be empty tasks that still pay a full build-side
        # read + hash build each
        blocks = (lblocks if split_left else rblocks)
        if blocks is not None and rid < len(blocks):
            k = min(k, max(2, blocks[rid]))
        if blocks is not None and rid < len(blocks) and blocks[rid] <= 1:
            # a single-block partition cannot slice: leave it whole
            plain.append(rid)
            plain_bytes.append(lb + rb)
            continue
        n_splits += k
        for i in range(k):
            if split_left:
                lgroups.append([(rid, i, k)])
                rgroups.append([(rid, 0, 1)])
            else:
                lgroups.append([(rid, 0, 1)])
                rgroups.append([(rid, i, k)])
    if n_splits == 0:
        return None
    flush_plain()
    return lgroups, rgroups, n_splits


class CoalescedShuffleReaderExec(TpuExec):
    """Reduce-side reader exposing groups of shuffle-partition read
    units as single partitions (ref: GpuCustomShuffleReaderExec —
    CoalescedPartitionSpec for adjacent grouping and
    PartialReducerPartitionSpec for skew slices).

    Groups hold PartSpec units: plain int rids (whole partitions) or
    (rid, i, k) tuples reading the i-th of k block-wise slices of a
    skewed partition (blocks deal round-robin by index, which is
    deterministic: the map output order is fixed once committed)."""

    def __init__(self, exchange, groups: list):
        super().__init__(exchange)
        self.groups = [[(g, 0, 1) if isinstance(g, int) else tuple(g)
                        for g in grp] for grp in groups]
        # rids visited more than once (a sliced partition, or the full
        # partition paired against each slice) need the NON-consuming
        # exchange read; single-visit rids keep the consuming read that
        # frees blocks as early as possible
        counts: dict[int, int] = {}
        for grp in self.groups:
            for rid, _i, _k in grp:
                counts[rid] = counts.get(rid, 0) + 1
        self._multi_read = {r for r, c in counts.items() if c > 1}

    @property
    def schema(self) -> T.Schema:
        return self.children[0].schema

    @property
    def num_partitions(self) -> int:
        return len(self.groups)

    @property
    def output_partitioning(self):
        # grouped partitions still co-partition with any reader using
        # the SAME groups, but not with the raw partitioning width —
        # adaptive join builds both sides with identical groups
        return None

    def node_desc(self) -> str:
        n_raw = self.children[0].num_partitions
        n_split = sum(1 for grp in self.groups
                      for (_r, _i, k) in grp if k > 1)
        extra = f", {n_split} skew slices" if n_split else ""
        return (f"CoalescedShuffleReaderExec [{n_raw} -> "
                f"{len(self.groups)} partitions{extra}]")

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        ex = self.children[0]
        for rid, i, k in self.groups[p]:
            if rid in self._multi_read and hasattr(
                    ex, "execute_partition_keep"):
                source = ex.execute_partition_keep(rid)
            else:
                source = ex.execute_partition(rid)
            for bi, b in enumerate(source):
                if k == 1 or bi % k == i:
                    yield self._count_output(b)

    def execute(self) -> Iterator[ColumnarBatch]:
        for p in range(self.num_partitions):
            yield from self.execute_partition(p)


class TpuAdaptiveJoinExec(TpuExec):
    """Join whose physical strategy is chosen at first execution from
    measured map-output statistics (ref: Spark's
    DynamicJoinSelection/AdaptiveSparkPlanExec re-optimization, which
    the reference plugs into via GpuCustomShuffleReaderExec).

    Children are the two shuffle exchanges the static planner would
    have used for a partition-wise join; the runtime decision only ever
    *improves* on that plan (broadcast from materialized blocks, or
    coalesced reduce groups), so there is no regression risk relative
    to static planning."""

    def __init__(self, left_keys, right_keys, join_type: str,
                 left_exchange, right_exchange, condition=None):
        super().__init__(left_exchange, right_exchange)
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.join_type = join_type
        self.condition = condition
        self._decided: Optional[TpuExec] = None
        self._decision = "undecided"
        self._lock = threading.Lock()
        #: set by the runtime-filter planner pass: which side hosts a
        #: filter-building map stage and must materialize FIRST, so the
        #: published filter prunes the other side's scans
        #: (plan/runtime_filter.py build-before-probe ordering)
        self.rf_build_first: Optional[str] = None
        # schema comes from the inner join exec; build one eagerly so
        # schema/explain work before execution (the static shape)
        self._template = self._make_shuffled(left_exchange,
                                             right_exchange)

    def _make_shuffled(self, lex, rex) -> TpuExec:
        from spark_rapids_tpu.execs.join import TpuShuffledHashJoinExec

        return TpuShuffledHashJoinExec(
            self.left_keys, self.right_keys, self.join_type, lex, rex,
            condition=self.condition, partition_wise=True)

    @property
    def schema(self) -> T.Schema:
        return self._template.schema

    @property
    def num_partitions(self) -> int:
        # STATIC width (the template's): reading partition counts must
        # never trigger _decide() — the planner inspects num_partitions
        # while building the tree, and materializing map stages at plan
        # time would execute scans for explain-only queries.  Shrunken
        # widths (broadcast/coalescing) leave the tail partitions empty;
        # EXPANDED widths (skew splits) overflow-drain through the last
        # static partition (see execute_partition).
        return self._template.num_partitions

    def node_desc(self) -> str:
        return (f"TpuAdaptiveJoinExec [{self.join_type}] "
                f"strategy={self._decision}")

    def additional_metrics(self):
        return [("adaptiveBroadcasts", "ESSENTIAL"),
                ("coalescedPartitions", "MODERATE"),
                ("skewSplits", "ESSENTIAL")]

    # -- runtime decision ------------------------------------------------ #

    def _decide(self) -> TpuExec:
        with self._lock:
            if self._decided is not None:
                return self._decided
            from spark_rapids_tpu.execs.join import (
                TpuBroadcastHashJoinExec,
            )
            from spark_rapids_tpu.plan.planner import (
                BROADCAST_THRESHOLD,
                broadcast_candidates,
            )

            conf = get_conf()
            thr = conf.get(BROADCAST_THRESHOLD)
            lex, rex = self.children
            if self.rf_build_first == "right":
                # build-before-probe: the right map stage streams the
                # join's build input through its runtime-filter
                # collector; materializing it first publishes the
                # filter before the left (probe) map stage scans
                rstats = rex.materialize_stats()
                lstats = lex.materialize_stats()
            else:
                lstats = lex.materialize_stats()
                rstats = rex.materialize_stats()
            lbytes = sum(b for b, _ in lstats)
            rbytes = sum(b for b, _ in rstats)

            jt = self.join_type
            candidates = broadcast_candidates(jt, lbytes, rbytes, thr)
            if candidates:
                side, nbytes = min(candidates, key=lambda c: c[1])
                self.metrics["adaptiveBroadcasts"].add(1)
                self._decision = (f"broadcast[{side} "
                                  f"{nbytes >> 10}KiB<=thr]")
                self._decided = TpuBroadcastHashJoinExec(
                    self.left_keys, self.right_keys, jt, lex, rex,
                    condition=self.condition, build_side=side)
            else:
                target = conf.get(ADVISORY_PARTITION_BYTES)
                lb_list = [b for b, _ in lstats]
                rb_list = [b for b, _ in rstats]
                skew = plan_skew_groups(
                    lb_list, rb_list, target, conf.get(SKEW_FACTOR),
                    conf.get(SKEW_THRESHOLD_BYTES), jt,
                    lblocks=lex.block_counts()
                    if hasattr(lex, "block_counts") else None,
                    rblocks=rex.block_counts()
                    if hasattr(rex, "block_counts") else None)
                if skew is not None:
                    lgroups, rgroups, n_splits = skew
                    self.metrics["skewSplits"].add(n_splits)
                    self._decision = (f"shuffled[skew: {n_splits} "
                                      f"splits, {len(lgroups)} tasks]")
                    self._decided = self._make_shuffled(
                        CoalescedShuffleReaderExec(lex, lgroups),
                        CoalescedShuffleReaderExec(rex, rgroups))
                    self._adopt_metrics()
                    return self._decided
                per_part = [lb + rb for lb, rb in zip(lb_list, rb_list)]
                groups = plan_coalesced_groups(per_part, target)
                if len(groups) < len(per_part):
                    self.metrics["coalescedPartitions"].add(
                        len(per_part) - len(groups))
                    self._decision = (f"shuffled[{len(per_part)}->"
                                      f"{len(groups)} parts]")
                    self._decided = self._make_shuffled(
                        CoalescedShuffleReaderExec(lex, groups),
                        CoalescedShuffleReaderExec(rex, groups))
                else:
                    self._decision = "shuffled"
                    self._decided = self._template
            self._adopt_metrics()
            return self._decided

    def _adopt_metrics(self) -> None:
        # the decided exec is not a child, so metric collection would
        # miss it: adopt its Metric objects (live references) under
        # this node, keeping only the adaptive-specific ones
        own = {"adaptiveBroadcasts", "coalescedPartitions", "skewSplits"}
        for k, v in self._decided.metrics.items():
            if k not in own:
                self.metrics[k] = v

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        decided = self._decide()
        n_static = self._template.num_partitions
        if p < decided.num_partitions:
            yield from decided.execute_partition(p)
        # skew splitting can EXPAND the task count past the static
        # width the parent iterates (num_partitions must stay static:
        # parents read it before any partition executes, and deciding
        # at plan time would materialize map stages for explain-only
        # queries).  The last static partition drains the overflow so
        # no task is silently dropped.
        if p == n_static - 1:
            for q in range(n_static, decided.num_partitions):
                yield from decided.execute_partition(q)

    def execute(self) -> Iterator[ColumnarBatch]:
        yield from self._decide().execute()

    def close(self) -> None:
        # the decided exec is NOT a child (children stay the two
        # exchanges), so default propagation would miss its cleanup —
        # e.g. a runtime broadcast join's spillable build handle
        with self._lock:
            decided = self._decided
        if decided is not None and decided is not self._template:
            decided.close()
        self._template.close()  # idempotently closes the exchanges too
        super().close()
