"""Process-global compiled-program cache.

Every collect() builds a fresh exec tree, and jax.jit's compile cache is
per-wrapper — so without sharing, each query run re-traces and
re-compiles XLA programs identical to the last run's.  The reference
never pays this: cudf kernels are pre-compiled native code invoked per
batch (SURVEY.md L0).  The XLA analog is a *structural program key*: two
execs whose compute is determined by equal expression trees / specs share
one jit wrapper, so the second query (and every query after) hits the
compile cache at trace level.

Keys must capture everything the traced function reads that is not part
of the input pytree: bound expression trees (ordinals, dtypes, literal
values), agg specs, static capacities, output schemas.  Input batch
shape/dtype/schema ride the pytree and are keyed by jax itself.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import warnings
from typing import Callable, Optional, Sequence

import jax

from spark_rapids_tpu import trace as _trace
from spark_rapids_tpu.config import register
from spark_rapids_tpu.trace import ledger as _ledger
from spark_rapids_tpu.exprs.base import Expression

DONATION_ENABLED = register(
    "spark.rapids.tpu.sql.fusion.donation.enabled", False,
    "Donate per-batch WIRE-form decode inputs (fresh single-use "
    "uploads) into fused XLA programs via cached_jit's `donate=` arg, "
    "so XLA reuses their HBM for the program's outputs instead of "
    "allocating fresh buffers.  Donated inputs are CONSUMED — the "
    "engine marks them (EncodedBatch.consumed via "
    "transfer.run_consuming) so the retry/split ladder never touches "
    "a donated buffer again; a future donation site over "
    "store-registered batches must first un-register them via "
    "SpillableBatch.mark_consumed (the seam exists and is tested, "
    "but no engine path donates store-registered batches today — "
    "decoded batches carry process-shared arrays and are never "
    "donated).  Off (the default): donate= is ignored and behavior "
    "is bit-for-bit identical to the non-donating engine "
    "(docs/fusion.md).  Read at program-compile time; the "
    "compile-cache key carries the donation state, so flipping it "
    "mid-session compiles fresh programs rather than corrupting "
    "cached ones.")

#: CPU/METAL backends implement donation as a no-op and warn per
#: compile; the engine treats donation as best-effort HBM reuse (the
#: consumed-state bookkeeping is what matters for correctness), so the
#: warning is noise in every non-TPU test run
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")

_LOCK = threading.Lock()
#: LRU: a long-lived process serving many distinct ad-hoc query shapes
#: must not pin every query's exec tree (cached closures retain the exec
#: instance that created them) and jax executable forever.
_CACHE: "collections.OrderedDict" = collections.OrderedDict()
MAX_ENTRIES = 512
#: lookup counters (under _LOCK): a low hit rate on a steady workload
#: means keys are unstable (per-query state leaking into them) and
#: every query is paying trace+compile again — surfaced by
#: cache_stats() in explain("analyze") next to the per-miss
#: jit.cache_miss trace events
_HITS = 0
_MISSES = 0
#: real XLA trace+compiles (under _LOCK): a MISS that restores a
#: persisted AOT program (spark_rapids_tpu/persist.py) is not a
#: compile, so the warm-start smoke's "zero compilations in a warm
#: child" assert taps THIS counter, not _MISSES.  Bumped at a fresh
#: wrapper's FIRST INVOCATION (see _CompileLatch), never at wrapper
#: creation — jax.jit is lazy, and several call sites mint wrappers
#: speculatively that are never dispatched.  compiles <= misses
#: always; the gap is exactly those phantom wrappers.
_COMPILES = 0


def _field_key(v) -> str:
    """Serialize one dataclass field value; recurses into tuples so nested
    containers of Expressions (CaseWhen's branch pairs) serialize
    structurally instead of through Expression.__repr__ (which is
    name-only and would collide across ordinals/dtypes)."""
    if isinstance(v, Expression):
        return expr_key(v)
    if isinstance(v, tuple):
        return "(" + ",".join(_field_key(x) for x in v) + ")"
    return repr(v)


def expr_key(e) -> str:
    """Deterministic structural serialization of a bound expression tree:
    class names plus every dataclass field (ordinals, dtypes, literal
    values) — everything eval() reads."""
    if not isinstance(e, Expression):
        return repr(e)
    if dataclasses.is_dataclass(e):
        parts = [_field_key(getattr(e, f.name))
                 for f in dataclasses.fields(e)]
        return f"{type(e).__name__}[{','.join(parts)}]"
    # a non-dataclass Expression subclass with state would silently share
    # one compiled program across different states — refuse instead of
    # returning a bare class name (cache correctness depends entirely on
    # key completeness)
    raise TypeError(
        f"expression {type(e).__name__} is not a dataclass; expression "
        "classes must be dataclasses so their state serializes into "
        "compile-cache keys")


def exprs_key(es: Sequence) -> tuple:
    return tuple(expr_key(e) for e in es)


def donation_enabled() -> bool:
    """Is buffer donation into fused programs on for this thread's
    conf?  One conf read — callers gate their consumed-state
    bookkeeping on the same value they pass programs through with."""
    from spark_rapids_tpu.config import get_conf

    return bool(get_conf().get(DONATION_ENABLED))


def _validate_donate(donate) -> tuple:
    """Normalize/validate a donate= spec: a tuple of distinct
    non-negative argnums.  Validated HERE, not at jax call time —
    a malformed spec must fail at the compile chokepoint with the
    caller's key in hand, not deep inside jax's pytree plumbing."""
    if isinstance(donate, bool):
        # bool IS int in Python: a natural-looking donate=True would
        # silently normalize to argnum 1 and donate the WRONG buffer
        raise TypeError(
            "cached_jit donate= takes argnums, not a flag; use "
            "donate=(0,) to donate the first argument")
    if isinstance(donate, int):
        donate = (donate,)
    donate = tuple(donate)
    if not donate:
        return ()
    if not all(isinstance(i, int) and not isinstance(i, bool)
               and i >= 0 for i in donate) \
            or len(set(donate)) != len(donate):
        raise TypeError(
            f"cached_jit donate= must be distinct non-negative "
            f"argnums, got {donate!r}")
    return donate


def _shardings_key(in_shardings, out_shardings) -> tuple:
    """Serialize a sharding spec pair for the cache key.  reprs carry
    mesh axis names/sizes and the PartitionSpec but NOT device
    identity — partitioned callers additionally fold
    parallel.mesh.mesh_key(mesh) into their own key (the SPMD stage
    builders do), so two same-shaped meshes over different devices
    never share an executable."""
    def one(s):
        if s is None:
            return None
        if isinstance(s, (tuple, list)):
            return tuple(one(x) for x in s)
        return repr(s)
    return (one(in_shardings), one(out_shardings))


class _CompileLatch:
    """jax.jit compiles LAZILY: wrapper creation traces nothing; the
    first invocation pays trace+compile.  Some call sites mint
    wrappers speculatively (sort's full-sort program when the
    augmented path supersedes it, agg merge/final phases in
    single-partition complete mode) and never dispatch them — no XLA
    compilation ever happens for those keys.  Counting at creation
    would charge these phantom compiles to every fresh process and
    break the warm-start smoke's zero-compiles assert, so _COMPILES
    bumps HERE, once, at the first real call.  Attribute access (the
    ledger cost model's ``.lower``) passes through to the wrapped
    fn."""

    __slots__ = ("_fn", "_fired")

    def __init__(self, fn):
        self._fn = fn
        self._fired = False

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_fn"), name)

    def __call__(self, *args, **kwargs):
        if not self._fired:
            global _COMPILES
            with _LOCK:
                if not self._fired:
                    self._fired = True
                    _COMPILES += 1
        return self._fn(*args, **kwargs)


#: process-wide ENQUEUE gate for PARTITIONED (sharded) programs.
#: XLA's CPU collectives rendezvous per-device participant threads
#: that drain per-device execution queues in FIFO order, so two
#: threads enqueueing two multi-device programs can interleave the
#: per-device queue orders — device 0 queues A-then-B while device 1
#: queues B-then-A, each program's rendezvous waits on participants
#: parked BEHIND the other program, and both stall forever (the
#: `collective_ops_utils` "waiting for all participants" deadlock).
#: Pod-scale serving's concurrent sessions are exactly this shape
#: (docs/pod_serving.md).  Holding the lock across the (async) call
#: makes every device see the same program order — sufficient, IF
#: every multi-device launch goes through the gate: the eager side
#: doors (a sharded array's `__getitem__`, an eager `jnp.max` on a
#: sharded leaf) are closed in exchange.take_piece and the stage-exit
#: device_get fetches.  Single-threaded/mesh-off callers never
#: contend, and program-to-program pipelining is untouched.
_SHARDED_DISPATCH_LOCK = threading.RLock()


class _SerializedDispatch:
    """Wrap a compiled partitioned program so concurrent callers
    ENQUEUE atomically (see _SHARDED_DISPATCH_LOCK): the runtime's
    per-device execution queues drain FIFO, so as long as every
    collective program lands on every device queue in the same order,
    the per-device worker threads reach each program's rendezvous
    together and no program waits on participants parked behind it.
    The call itself stays async — program-to-program overlap and
    host/device overlap are preserved; only the enqueue interleaving
    (the thing two threads can scramble) is serialized.  The eager
    side doors are closed separately (exchange.take_piece, stage-exit
    device_get fetches) — an UNGUARDED multi-device launch between
    two gated ones reintroduces the scramble.  Attribute access
    (``.lower`` for the ledger cost model) passes through."""

    __slots__ = ("_fn",)

    def __init__(self, fn):
        self._fn = fn

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_fn"), name)

    def __call__(self, *args, **kwargs):
        with _SHARDED_DISPATCH_LOCK:
            return self._fn(*args, **kwargs)


def serialize_sharded(fn: Callable) -> Callable:
    """Route a multi-device program compiled OUTSIDE cached_jit (the
    shard_map step builders in parallel/exchange.py) through the same
    process-wide collective dispatch gate — every rendezvous-bearing
    program in the process must share ONE gate or the pool-starvation
    deadlock above comes back through the unguarded door."""
    return _SerializedDispatch(fn)


def cached_jit(key: tuple, make_fn: Callable[[], Callable],
               op: Optional[str] = None,
               donate: "int | Sequence[int] | None" = None,
               in_shardings=None, out_shardings=None,
               meta: Optional[dict] = None):
    """Return a jitted callable shared by every caller presenting `key`.
    `make_fn` is invoked (once) only on a cache miss.

    `op` (the owning exec's name, when the caller has one) labels the
    program in the device-utilization ledger (trace/ledger.py) so
    explain("analyze") can attribute per-operator roofline fractions;
    the cached callable is the ledger's dispatch hook — with the
    ledger off the wrapper is one attribute read and a passthrough
    call, bit-identical to the raw jitted function.

    `donate` (argnums) marks input args whose buffers XLA may reuse
    for the program's outputs (the pjit donate_argnums plumbing —
    SNIPPETS [1][2]).  Honored only when
    spark.rapids.tpu.sql.fusion.donation.enabled is on; the caller
    owns the CONSUMED-state bookkeeping for whatever it donates
    (EncodedBatch.consumed / SpillableBatch.mark_consumed) — a
    donated-then-spilled buffer is a use-after-free.  The donation
    state folds into the cache key, so donating and non-donating
    callers of the same logical program never share a compiled
    executable.

    `in_shardings` / `out_shardings` thread jax.sharding specs
    (NamedSharding pytrees) into the compiled program — the pjit/GSPMD
    plumbing for partitioned SPMD stage programs (SNIPPETS [1][2][3]).
    Sharding is PART of the executable (GSPMD partitions the program
    around it), so the spec pair folds into the cache key; donation
    composes (a donated sharded input's per-device buffers are reused
    for the partitioned outputs).  `meta` attaches static program
    attributes (mesh device count, in-program collective round count)
    to the ledger entry so partitioned programs attribute per-device
    busy time in snapshots/bench."""
    global _HITS, _MISSES, _COMPILES
    donate = _validate_donate(donate) if donate is not None else ()
    if donate and donation_enabled():
        key = key + ("donate", donate)
    else:
        donate = ()
    if in_shardings is not None or out_shardings is not None:
        key = key + ("shardings",
                     _shardings_key(in_shardings, out_shardings))
    with _LOCK:
        fn = _CACHE.get(key)
        if fn is None:
            _MISSES += 1
            if _trace.TRACER.enabled:
                # a miss means a fresh trace+compile is coming for this
                # program shape: the timeline shows WHICH key paid it
                _trace.event("jit.cache_miss", key=repr(key)[:200],
                             cache_size=len(_CACHE))
            # the jit.compile fault seam sits on the miss path only (a
            # cache hit compiles nothing), with in-place recovery
            # (absorb_once) for INJECTED compile faults: spill
            # unpinned buffers, re-check once.  Real XLA compilation
            # happens lazily at the wrapper's first invocation — a
            # real compile OOM therefore surfaces at the CALLER, where
            # the batch ladder / task retry / CPU degrade handle it
            from spark_rapids_tpu.execs.retry import absorb_once
            from spark_rapids_tpu.robustness import faults as _faults

            absorb_once(
                lambda: _faults.fault_point("jit.compile",
                                            key=repr(key)[:80]),
                action="compile_retry")
            # every program the engine compiles flows through here:
            # the ledger wrapper is the single metering point feeding
            # per-program dispatch counts + device time + cost-model
            # attribution (tpulint SRC009 flags raw jax.jit in exec
            # modules for exactly this reason)
            jit_kwargs: dict = {"donate_argnums": donate}
            if in_shardings is not None:
                jit_kwargs["in_shardings"] = in_shardings
            if out_shardings is not None:
                jit_kwargs["out_shardings"] = out_shardings
            # warm-start probe BEFORE tracing (docs/warm_start.md):
            # with persistence on, a structural-key miss first asks the
            # disk store for jax.export artifacts under (key x conf
            # fingerprint); a hit dispatches restored executables and
            # compiles nothing.  Sharded programs are excluded (their
            # sharding specs bind live device objects that don't
            # round-trip a serialize) — EXCEPT under mesh serving
            # (docs/pod_serving.md): a partitioned stage program's key
            # already folds parallel/mesh.mesh_key, so a warm pod
            # restart on the same mesh shape redeploys the exported
            # partitioned executables; an export that cannot serialize
            # degrades to the honest compile through AutoSave's
            # swallowed-error path (persist.errors), never a wrong
            # program.  Off = one conf read in active(), then the
            # identical compile path as ever.
            from spark_rapids_tpu import persist as _persist

            sharded = (in_shardings is not None
                       or out_shardings is not None)
            if sharded:
                from spark_rapids_tpu.serving import (
                    mesh_serving_enabled,
                )
                store = _persist.active() \
                    if mesh_serving_enabled() else None
            else:
                store = _persist.active()
            restored = None
            conf_fp = ""
            if store is not None:
                conf_fp = _persist._conf_fp()[:12]
                exported = store.load_programs(key, conf_fp)
                if exported:
                    restored = _persist.RestoredProgram(
                        key, exported, make_fn, jit_kwargs, store,
                        conf_fp)
            if restored is not None:
                fn = _ledger.LEDGER.wrap(
                    key, restored, op=op, donated=bool(donate),
                    meta={**(meta or {}), "persist_restored": True})
            else:
                jitted = jax.jit(make_fn(), **jit_kwargs)
                if store is not None:
                    jitted = _persist.AutoSave(key, jitted, store,
                                               conf_fp)
                fn = _ledger.LEDGER.wrap(
                    key, _CompileLatch(jitted), op=op,
                    donated=bool(donate), meta=meta)
            if sharded:
                # outside the ledger wrapper: lock WAIT (another
                # session's enqueue) must not inflate this program's
                # attributed dispatch time
                fn = _SerializedDispatch(fn)
            _CACHE[key] = fn
            while len(_CACHE) > MAX_ENTRIES:
                _CACHE.popitem(last=False)
        else:
            _HITS += 1
            _CACHE.move_to_end(key)
        return fn


def cache_size() -> int:
    with _LOCK:
        return len(_CACHE)


def cache_stats() -> dict:
    """Cumulative lookup counters: {hits, misses, size, hit_rate}.
    Callers wanting PER-QUERY figures (explain("analyze")) snapshot
    before/after and diff."""
    with _LOCK:
        total = _HITS + _MISSES
        return {
            "hits": _HITS,
            "misses": _MISSES,
            "compiles": _COMPILES,
            "size": len(_CACHE),
            "hit_rate": round(_HITS / total, 3) if total else 0.0,
        }


def reset_cache_stats() -> None:
    """Zero the lookup counters (the cache itself is untouched)."""
    global _HITS, _MISSES, _COMPILES
    with _LOCK:
        _HITS = 0
        _MISSES = 0
        _COMPILES = 0


def note_external_compile() -> None:
    """A compile happened OUTSIDE the miss path: a RestoredProgram
    saw an argument signature with no persisted artifact and fell
    back to an honest jax.jit.  Bumped so the compiles counter (and
    the warm-start smoke's zero-compiles assert) stays truthful."""
    global _COMPILES
    with _LOCK:
        _COMPILES += 1


def program_census() -> dict[str, int]:
    """Distinct compiled programs per key TAG (the leading string of
    every structural key): the jit-key audit surface behind ROADMAP
    #2's bucketing work.  A steady workload whose census GROWS run
    over run has non-structural values (literals, per-batch counts)
    leaking into its keys — the fusion smoke and
    tests/test_fusion.py's re-key stability test diff this figure
    across identical collects to pin key churn to the tag that minted
    it."""
    with _LOCK:
        keys = list(_CACHE)
    census: dict[str, int] = {}
    for k in keys:
        tag = _ledger.key_tag(k)
        census[tag] = census.get(tag, 0) + 1
    return census


def clear() -> None:
    with _LOCK:
        _CACHE.clear()
