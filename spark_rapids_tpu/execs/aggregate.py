"""Hash-aggregate exec.

TPU re-design of GpuHashAggregateExec
(ref: sql-plugin/.../aggregate.scala:240,282-430): per input batch run an
*update* aggregation, then re-merge the accumulated partial results
whenever they grow past the target batch size (the reference concatenates
and re-aggregates the same way, aggregate.scala:387-395).  On TPU the
per-batch aggregation is the sort-based segmented kernel in ops.groupby —
one fused XLA program — instead of cudf's hash groupby.

Modes follow Spark/the reference:
- ``partial``:  keys ++ partial columns out (feeds an exchange);
- ``final``:    partial-layout in, merged + finalized out;
- ``complete``: full aggregation locally (single-partition plans).

Bounded memory: between input batches only the merged partial batch is
retained (size = O(#distinct keys seen)), matching the reference's
streaming design."""

from __future__ import annotations

from typing import Iterator, Optional, Sequence

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch, concat_batches
from spark_rapids_tpu.columnar.column import pad_capacity
from spark_rapids_tpu.execs.base import MetricTimer, TOTAL_TIME, TpuExec
from spark_rapids_tpu.execs.basic import output_field
from spark_rapids_tpu.exprs.aggregates import NamedAgg
from spark_rapids_tpu.exprs.base import (
    BoundReference,
    EvalContext,
    Expression,
    bind_references,
)
from spark_rapids_tpu.ops.groupby import (
    AggSpec,
    groupby_aggregate,
    reduce_aggregate,
)
from spark_rapids_tpu.trace import ledger as _ledger

#: total partial capacity the one-program fused drain (and the traced
#: device concat) accepts.  The stack+compact inside the program is
#: O(cap log cap) device work — trivial next to the 2-3 link round
#: trips the fusion saves — and must admit coded-group-by partials
#: whose capacity is the padded key domain (MAX_CODED_DOMAIN).
_FUSED_DRAIN_CAP = 1 << 18

#: partials at or below this capacity skip the per-batch sizing sync
#: and shrink entirely: the drain pins all their sizes in one batched
#: fetch instead.  Each skipped sync saves a full device_get round
#: trip — hundreds of ms on a degraded tunnel link.  Sized to cover
#: coded-group-by partials (capacity = padded key domain, up to
#: MAX_CODED_DOMAIN).  Module-level so tests can force the sizing path
#: on small data.
_DEFER_SYNC_CAP = 1 << 18


def _as_device_rows(batch):
    if not isinstance(batch, ColumnarBatch):
        return batch  # EncodedBatch: traced count rides the wire comps
    # promotion hides num_rows from the ledger's occupancy scan; state
    # it while host-known (consumed by the dispatch this feeds)
    if _ledger.LEDGER.enabled and type(batch.num_rows) is int:
        _ledger.note_occupancy(batch.num_rows, batch.capacity)
    return batch.with_device_num_rows()


class TpuHashAggregateExec(TpuExec):
    def __init__(self, groups: Sequence[Expression], aggs: Sequence[NamedAgg],
                 child: TpuExec, mode: str = "complete",
                 goal_rows: Optional[int] = None,
                 input_schema: Optional[T.Schema] = None):
        """`input_schema`: for mode="final" only — the pre-aggregation
        schema the aggregate children refer to (the planner threads the
        original child schema across the partial/exchange/final split);
        defaults to the child schema for the other modes."""
        super().__init__(child)
        assert mode in ("partial", "final", "complete"), mode
        self.mode = mode
        from spark_rapids_tpu.memory.device_manager import (
            effective_batch_size_rows,
        )

        self.goal_rows = goal_rows or effective_batch_size_rows()

        child_schema = child.schema
        bind_schema = input_schema if mode == "final" else child_schema
        assert bind_schema is not None, "final mode requires input_schema"
        self.aggs = [NamedAgg(na.fn.bind(bind_schema), na.out_name)
                     for na in aggs]
        if mode == "final":
            # input already has partial layout: keys ++ partial columns
            self.partial_schema = child_schema
            self.groups = [BoundReference(i, f.dtype, f.nullable, f.name)
                           for i, f in enumerate(
                               child_schema.fields[: len(groups)])]
            self.n_keys = len(groups)
        else:
            self.groups = [bind_references(g, child_schema) for g in groups]
            self.n_keys = len(self.groups)
            key_fields = [output_field(g, i)
                          for i, g in enumerate(self.groups)]
            self.input_exprs = list(self.groups)
            partial_fields: list[T.Field] = []
            for na in self.aggs:
                ins = [bind_references(e, child_schema)
                       for e in na.fn.inputs()]
                self.input_exprs.extend(ins)
                for pi, pdt in enumerate(na.fn.partial_dtypes()):
                    partial_fields.append(
                        T.Field(f"{na.out_name}__p{pi}", pdt, True))
            if not self.input_exprs:
                # COUNT(*)-only grand aggregate: a zero-column projection
                # would lose the batch capacity (ColumnarBatch.capacity is
                # 0 with no columns); carry one constant column
                from spark_rapids_tpu.exprs.base import Literal

                self.input_exprs = [Literal.of(True)]
            self.update_input_schema = T.Schema(
                key_fields + [T.Field(f"__in{i}", e.dtype, e.nullable)
                              for i, e in enumerate(
                                  self.input_exprs[self.n_keys:])])
            self.partial_schema = T.Schema(key_fields + partial_fields)

        # ops over the partial layout for the merge phase
        self.merge_specs: list[AggSpec] = []
        po = self.n_keys
        for na in self.aggs:
            for op, pdt in zip(na.fn.merge_ops(), na.fn.partial_dtypes()):
                self.merge_specs.append(AggSpec(op, po, out_dtype=pdt))
                po += 1

        if mode == "partial":
            self._schema = self.partial_schema
        else:
            key_fields = list(self.partial_schema.fields[: self.n_keys])
            self._schema = T.Schema(
                key_fields + [na.output_field() for na in self.aggs])

        # finalize projection over the partial layout
        self.final_exprs: list[Expression] = [
            BoundReference(i, f.dtype, f.nullable, f.name)
            for i, f in enumerate(self.partial_schema.fields[: self.n_keys])]
        po = self.n_keys
        for na in self.aggs:
            refs = []
            for pdt in na.fn.partial_dtypes():
                pf = self.partial_schema.fields[po]
                refs.append(BoundReference(po, pf.dtype, pf.nullable, pf.name))
                po += 1
            self.final_exprs.append(na.fn.finalize_expr(refs))

        import threading

        self._jit_update = None
        self._jit_update_donated = None
        self._jit_merge = None
        self._jit_finalize = None
        self._jits = None
        self._jit_lock = threading.Lock()

    def _cache_key(self) -> tuple:
        """Structural key for the global compile cache: covers everything
        the three traced phases read off `self`."""
        from spark_rapids_tpu.execs.jit_cache import exprs_key

        update_specs: tuple = ()
        if self.mode != "final":
            update_specs = tuple((s.op, s.ordinal, repr(s.out_dtype))
                                 for s in self._update_specs())
        return (
            "agg", self.mode, self.n_keys,
            exprs_key(getattr(self, "input_exprs", ())),
            repr(getattr(self, "update_input_schema", None)),
            update_specs,
            tuple((s.op, s.ordinal, repr(s.out_dtype))
                  for s in self.merge_specs),
            repr(self.partial_schema),
            exprs_key(self.final_exprs),
            repr(self._schema),
        )

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def node_desc(self) -> str:
        keys = ", ".join(e.name for e in self.groups)
        outs = ", ".join(f"{na.fn.name}->{na.out_name}" for na in self.aggs)
        return f"TpuHashAggregateExec[{self.mode}] keys=[{keys}] [{outs}]"

    def additional_metrics(self):
        return [("numMerges", "MODERATE"), ("specHits", "MODERATE"),
                ("specOverflows", "MODERATE")]

    # -- traceable phases ------------------------------------------------ #

    def _update_specs(self) -> list[AggSpec]:
        specs = []
        io = self.n_keys
        for na in self.aggs:
            n_in = len(na.fn.inputs())
            ops = na.fn.update_ops()
            pdts = na.fn.partial_dtypes()
            for op, pdt in zip(ops, pdts):
                # all current fns have <=1 input; count_star reads none
                ord_ = io if n_in else 0
                specs.append(AggSpec(op, ord_, out_dtype=pdt))
            io += n_in
        return specs

    def _update_batch(self, batch: ColumnarBatch,
                      live_mask=None) -> ColumnarBatch:
        """Project inputs then run the update aggregation (traceable).
        `live_mask` carries fused WHERE predicates from an absorbed
        filter chain — masked rows never existed, but no compaction
        kernels are paid for them."""
        from spark_rapids_tpu.columnar.column import MIN_CAPACITY

        ctx = EvalContext.for_batch(batch)
        cols = [e.eval(ctx) for e in self.input_exprs]
        # Spark inserts NormalizeNaNAndZero under grouping keys (the
        # analyzer's NormalizeFloatingNumbers rule): -0.0 groups AS 0.0
        # and every NaN as the one canonical NaN — normalize here so
        # the emitted key VALUE is canonical too, not just the grouping
        from spark_rapids_tpu.columnar.column import Column as _Col

        for i in range(self.n_keys):
            c = cols[i]
            if isinstance(c, _Col) and isinstance(
                    c.dtype, (T.FloatType, T.DoubleType)):
                d = jnp.where(jnp.isnan(c.data), jnp.nan,
                              jnp.where(c.data == 0, 0.0, c.data)
                              ).astype(c.data.dtype)
                cols[i] = _Col(d, c.validity, c.dtype)
        proj = ColumnarBatch(cols, batch.num_rows, self.update_input_schema)
        specs = self._update_specs()
        if self.n_keys == 0:
            out = reduce_aggregate(proj, specs, self.partial_schema,
                                   live_mask)
            # exactly one live row: compact to the minimum bucket INSIDE
            # the program so no eager slicing (or giant partial buffers)
            # happens outside it
            return out.shrink_to_capacity(MIN_CAPACITY)
        return groupby_aggregate(proj, list(range(self.n_keys)), specs,
                                 self.partial_schema, live_mask)

    def _merge_batch(self, partial: ColumnarBatch) -> ColumnarBatch:
        if self.n_keys == 0:
            from spark_rapids_tpu.columnar.column import MIN_CAPACITY

            return reduce_aggregate(
                partial, self.merge_specs,
                self.partial_schema).shrink_to_capacity(MIN_CAPACITY)
        return groupby_aggregate(partial, list(range(self.n_keys)),
                                 self.merge_specs, self.partial_schema)

    def _drain_final_fused(self, pending, rows_hint: int):
        """Final drain as ONE program: concat (traced stack+compact) +
        merge + finalize, mode-dependent.  Saves 2-3 program executions
        per stream tail vs the stepwise drain — each execution is a
        link round trip on the tunneled backend.  Returns None when the
        shapes don't qualify (large/nested partials), decided WITHOUT
        touching the handles (h.get() would unspill large partials to
        device just to reject them); the caller then runs the stepwise
        path."""
        from spark_rapids_tpu.execs.jit_cache import cached_jit

        if (len(pending) == 1 and self.mode == "partial") \
                or rows_hint > _FUSED_DRAIN_CAP \
                or any(isinstance(f.dtype,
                                  (T.ListType, T.StructType, T.MapType))
                       for f in self.partial_schema.fields):
            return None
        batches = [h.get() for h in pending]
        if sum(b.capacity for b in batches) > _FUSED_DRAIN_CAP:
            return None
        from spark_rapids_tpu.columnar.batch import concat_batches_traced

        mode, n_parts = self.mode, len(batches)

        def prog(bs):
            b = concat_batches_traced(bs) if len(bs) > 1 else bs[0]
            if n_parts > 1 or mode == "final":
                b = self._merge_batch(b)
            if mode != "partial":
                b = self._finalize_batch(b)
            return b

        struct = tuple(
            (b.capacity, isinstance(b.num_rows, int),
             tuple(c.width for c in b.columns if hasattr(c, "width")))
            for b in batches)
        fn = cached_jit(("aggdrainfused", self._cache_key(), struct),
                        lambda: prog, op=self.name)
        with MetricTimer(self.metrics[TOTAL_TIME], op=self.name) as t:
            out = t.observe(fn([b.with_device_num_rows()
                                for b in batches]))
        for h in pending:
            h.close()
        pending.clear()
        return out

    def _jit_concat_traced(self, batches: list[ColumnarBatch]):
        """Device-side stack+compact concat for small partials with
        traced row counts (see columnar.batch.concat_batches_traced).
        Returns None when a column kind is unsupported there."""
        from spark_rapids_tpu.columnar.batch import concat_batches_traced
        from spark_rapids_tpu.execs.jit_cache import cached_jit

        if any(isinstance(f.dtype, (T.ListType, T.StructType, T.MapType))
               for f in batches[0].schema.fields):
            return None
        struct = tuple(
            (b.capacity,
             tuple(c.width for c in b.columns if hasattr(c, "width")))
            for b in batches)
        fn = cached_jit(("aggconcat_traced", self._cache_key(), struct),
                        lambda: concat_batches_traced, op=self.name)
        return fn(batches)

    def _jit_concat(self, batches: list[ColumnarBatch]) -> ColumnarBatch:
        """Concatenate pending partials in ONE compiled program: eager
        per-part update-slices would pay a dispatch round trip each on
        high-latency device links.  Row counts are already host ints
        (pinned after the sizing sync), so the whole concat is static."""
        from spark_rapids_tpu.execs.jit_cache import cached_jit

        struct = tuple(
            (b.capacity, b.num_rows,
             tuple(c.width for c in b.columns
                   if hasattr(c, "width")))
            for b in batches)
        fn = cached_jit(("aggconcat", self._cache_key(), struct),
                        lambda: lambda bs: concat_batches(bs),
                        op=self.name)
        return fn(batches)

    def _finalize_batch(self, partial: ColumnarBatch) -> ColumnarBatch:
        ctx = EvalContext.for_batch(partial)
        cols = [e.eval(ctx) for e in self.final_exprs]
        return ColumnarBatch(cols, partial.num_rows, self._schema)

    # -- streaming driver ------------------------------------------------ #

    @property
    def num_partitions(self) -> int:
        # partial aggregation is narrow (per input partition); final is
        # narrow too because the exchange already made partitions
        # key-disjoint; complete consumes everything into one partition
        if self.mode in ("partial", "final"):
            return self.children[0].num_partitions
        return 1

    @property
    def output_partitioning(self):
        """A final aggregate preserves the feeding exchange's hash
        distribution when that hash is over the group-key ordinals (the
        key columns keep positions and dtypes through finalization)."""
        if self.mode != "final":
            return None
        from spark_rapids_tpu.ops.partition import HashPartitioning

        part = getattr(self.children[0], "output_partitioning", None)
        if isinstance(part, HashPartitioning) and all(
                isinstance(e, BoundReference) and e.ordinal < self.n_keys
                for e in part.exprs):
            return part
        return None

    def _absorbed_chain(self):
        """(fns, source_node, keys) when the fusable child chain folds
        into the update program — the whole filter/project/update path
        then runs as ONE program execution per batch (each execution
        pays a link round trip on the tunneled backend once any D2H
        fetch has happened).  None when the chain needs its own driver
        (ANSI error polling, partition-aware exprs, uncacheable keys).
        Side effect of absorption: the absorbed execs' per-node metrics
        do not tick (their execute() never runs)."""
        with self._jit_lock:
            cached = getattr(self, "_absorb", "unset")
            if cached != "unset":
                return cached
            from spark_rapids_tpu.execs.base import (
                FusableExec,
                fusion_enabled,
            )
            from spark_rapids_tpu.exprs.base import ansi_enabled

            result = None
            child = self.children[0]
            if (self.mode != "final" and fusion_enabled()
                    and isinstance(child, FusableExec)
                    and not ansi_enabled()):
                chain, node, aware, keys = child.fusion_chain()
                if not aware and all(k is not None for k in keys):
                    result = (chain, node, tuple(keys))
            self._absorb = result
            return result

    def _source_node(self) -> TpuExec:
        ch = self._absorbed_chain()
        return ch[1] if ch is not None else self.children[0]

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        if self.mode == "complete":
            assert self.num_partitions == 1
            if p == 0:
                yield from self.execute()
            return
        yield from self._run_stream(self._source_node().execute_partition(p),
                                    emit_empty_default=(p == 0))

    def execute(self) -> Iterator[ColumnarBatch]:
        if self.mode == "complete":
            yield from self._run_stream(self._source_node().execute(),
                                        emit_empty_default=True)
        else:
            for p in range(self.num_partitions):
                yield from self.execute_partition(p)

    def _run_stream(self, source,
                    emit_empty_default: bool) -> Iterator[ColumnarBatch]:
        chain = self._absorbed_chain()
        with self._jit_lock:
            # exchange map tasks run partial aggregates concurrently; a
            # field-by-field lazy init could be observed half-done
            if self._jits is None:
                from spark_rapids_tpu.execs.jit_cache import cached_jit

                key = self._cache_key()
                execs = chain[0] if chain is not None else []
                ckeys = chain[2] if chain is not None else ()
                from spark_rapids_tpu.execs.basic import TpuFilterExec

                # filters become row MASKS (no compaction kernels) when
                # nothing in the chain multiplies rows — row positions
                # then stay stable through the whole chain, and the
                # masked rows simply never join a group
                as_masks = not any(e.MULTIPLIES_ROWS for e in execs)
                stages = []  # ("mask", cond) | ("fn", batch_fn)
                for e in execs:
                    if as_masks and isinstance(e, TpuFilterExec):
                        stages.append(("mask", e.condition))
                    else:
                        stages.append(("fn", e.make_batch_fn()))

                def update_full(b):
                    from spark_rapids_tpu.columnar.transfer import (
                        EncodedBatch,
                    )
                    from spark_rapids_tpu.exprs.base import EvalContext

                    if isinstance(b, EncodedBatch):
                        b = b.decode()  # wire decode fused in-program
                    mask = None
                    for kind, st in stages:
                        if kind == "mask":
                            pred = st.eval(EvalContext.for_batch(b))
                            m = pred.data.astype(bool) & pred.validity
                            mask = m if mask is None else (mask & m)
                        else:
                            b = st(b)
                    return self._update_batch(b, mask)

                upd = cached_jit(key + ("absorb", ckeys, "update"),
                                 lambda: update_full, op=self.name)
                # the donated twin: same traced program, wire
                # components donate_argnums'd so XLA reuses their HBM
                # for the partial columns.  A SEPARATE cached program
                # (cached_jit folds the donation state into the key)
                # because the plain one also serves decoded batches
                # whose arrays — shared validity masks, dictionary
                # sidecars — must never be donated.
                from spark_rapids_tpu.execs.jit_cache import (
                    donation_enabled,
                )

                upd_d = cached_jit(
                    key + ("absorb", ckeys, "update"),
                    lambda: update_full, op=self.name,
                    donate=(0,)) if donation_enabled() else None
                self._jits = (
                    upd, upd_d,
                    cached_jit(key + ("merge",), lambda: self._merge_batch,
                               op=self.name),
                    cached_jit(key + ("final",),
                               lambda: self._finalize_batch,
                               op=self.name))
            (self._jit_update, self._jit_update_donated,
             self._jit_merge, self._jit_finalize) = self._jits

        from spark_rapids_tpu.memory import SpillPriorities, get_store
        from spark_rapids_tpu.parallel import speculation as SP

        store = get_store()
        # pending partials are spillable between merges (the reference
        # plans the same: aggregate.scala:378-386 spill-of-running-agg)
        pending: list = []  # SpillableBatch handles
        #: id(handle) -> (ReadbackFuture, est) for partials whose
        #: sizing readback rides the async harvester (speculative
        #: sizing): the drain reconciles them before its batched fetch
        futs: dict = {}
        pred = SP.predictor(self._cache_key() + ("sizing",)) \
            if SP.speculation_enabled() \
            and SP.tag_enabled("agg.size") else None

        #: handle-ids whose sizing future already fed the predictor —
        #: a drain RE-RUN after an OOM (spill-retry rung) must not
        #: double-observe the same count
        observed: set = set()

        def finish_drain() -> None:
            """COMMIT a drain: release the drained partials.  Kept
            separate from drain_pending so the escalation ladder can
            build (and re-build, after a spill) the drained batch while
            the source partials stay registered — only after the
            consumer of the drain succeeded are they dropped."""
            for h in pending:
                futs.pop(id(h), None)
                h.close()
            pending.clear()
            observed.clear()

        def drain_pending(commit: bool = True) -> ColumnarBatch:
            import dataclasses

            acquired: list = []
            try:
                batches = []
                for h in pending:
                    batches.append(h.get())
                    acquired.append(h)
                # reconcile async sizing futures first: in steady state
                # the harvester already holds the counts, so this is
                # free — a not-yet-done future is the one place the old
                # blocking per-batch sync can still surface (accounted
                # as such)
                for i, h in enumerate(pending):
                    entry = futs.get(id(h))
                    if entry is None \
                            or isinstance(batches[i].num_rows, int):
                        continue
                    fut, est, speculated = entry
                    n = int(fut.result())
                    if pred is not None and id(h) not in observed:
                        observed.add(id(h))
                        pred.observe(n)
                        if speculated:
                            if n <= est:
                                self.metrics["specHits"].add(1)
                                SP.record_hit("agg.size", est, n)
                            else:
                                self.metrics["specOverflows"].add(1)
                                SP.record_overflow("agg.size", est, n)
                    batches[i] = dataclasses.replace(batches[i],
                                                     num_rows=n)
                traced = [i for i, b in enumerate(batches)
                          if not isinstance(b.num_rows, int)]
                if (traced and len(batches) > 1
                        and sum(b.capacity for b in batches)
                        <= _FUSED_DRAIN_CAP):
                    # small partials: concatenate ON DEVICE
                    # (stack+compact, traced total) so the drain needs
                    # no sizing fetch at all — the query's only D2H
                    # round trip stays the final result pull
                    out = self._jit_concat_traced(batches)
                    if out is not None:
                        if commit:
                            finish_drain()
                        return out
                # deferred sizing: pin every traced row count in ONE
                # batched D2H fetch (per-batch device_get round trips
                # dominate grouped-aggregate wall time on high-latency
                # device links)
                if traced:
                    from spark_rapids_tpu.parallel.pipeline import (
                        device_read_many,
                    )

                    ns = device_read_many(
                        [batches[i].num_rows for i in traced],
                        tag="agg.drain")
                    for i, n in zip(traced, ns):
                        batches[i] = dataclasses.replace(
                            batches[i], num_rows=int(n))
                if len(batches) == 1:
                    out = batches[0]
                elif self.n_keys == 0:
                    # grand aggregate: partials are fixed one-row
                    # min-bucket batches, so the concat program's static
                    # key is stable — compile once, then one dispatch
                    # per drain
                    out = self._jit_concat(batches)
                else:
                    # grouped: partial sizes are data-dependent; jitting
                    # here would recompile per distinct row-count
                    # combination
                    out = concat_batches(batches)
            except BaseException:
                # a failed (uncommitted) drain must leave every partial
                # evictable again so the spill-retry rung can actually
                # release pressure before the re-run
                for h in acquired:
                    h.unpin()
                raise
            if commit:
                finish_drain()
            return out

        try:
            yield from self._execute_inner(store, pending, futs, pred,
                                           drain_pending, finish_drain,
                                           source, emit_empty_default)
        finally:
            # a raise (or generator close) anywhere above must not leak
            # registrations into the process-global store
            for h in pending:
                h.close()
            pending.clear()
            futs.clear()

    def _execute_inner(self, store, pending, futs, pred, drain_pending,
                       finish_drain, source, emit_empty_default):
        from spark_rapids_tpu.memory import SpillPriorities
        from spark_rapids_tpu.parallel import speculation as SP

        import dataclasses

        from spark_rapids_tpu.execs import retry as R
        from spark_rapids_tpu.parallel import pipeline as P

        pending_rows = 0

        from spark_rapids_tpu.columnar.transfer import (
            EncodedBatch,
            repair_donated_memo,
            run_consuming,
        )
        from spark_rapids_tpu.execs.base import record_fused_dispatch

        # donated-unit resume bookkeeping: update-output id -> the
        # EncodedBatch memoizing it, so a rollback can repair a memo
        # whose registered copy was spilled (see guarded_retire)
        donated_units: dict = {}

        _ch = self._absorbed_chain()
        # the update itself counts as a chain member: a chain of N
        # fusable execs absorbed into the update is N+1 operators in
        # one program
        chain_len = (len(_ch[0]) + 1) if _ch is not None else 1

        def dispatch(batch):
            """Async half: the update program for batch k+1 is
            dispatched before batch k's sizing sync retires (the same
            lookahead shape as the join probe loop).  Wire-form
            batches route through the DONATED update twin when
            donation is on: run_consuming marks the batch consumed
            and memoizes the output, so a ladder re-run of this unit
            resumes instead of re-executing over donated buffers."""
            with MetricTimer(self.metrics[TOTAL_TIME], op=self.name) as t:
                if self.mode == "final":
                    return batch  # already partial layout
                enc = isinstance(batch, EncodedBatch)
                if enc and self._jit_update_donated is not None:
                    # a retry-ladder re-run of a consumed batch
                    # RESUMES from the memoized output — no program
                    # launches, so the fused-dispatch stats must not
                    # tick (q*_fused_dispatch_savings would otherwise
                    # over-report under --chaos)
                    resumed = batch.consumed
                    out = run_consuming(self._jit_update_donated, batch)
                    donated_units[id(out)] = batch
                    if not resumed:
                        record_fused_dispatch(chain_len,
                                              decode_fused=True)
                else:
                    out = self._jit_update(_as_device_rows(batch))
                    record_fused_dispatch(chain_len, decode_fused=enc)
                return t.observe(out)

        def merge_and_park(park):
            """Re-merge the pending partials as ONE transaction on the
            OOM escalation ladder: drain (uncommitted, restartable) +
            merge under spill-retry, then `park(merged)` registers the
            result — only after THAT succeeds are the drained partials
            released.  Any retryable failure up to the park leaves
            `pending` intact, so the batch ladder can re-run the whole
            unit without losing drained state (the failure mode a
            naive drain-then-merge would silently corrupt)."""
            state: dict = {}

            def att():
                if "b" not in state:
                    state["b"] = drain_pending(commit=False)
                return self._jit_merge(_as_device_rows(state["b"]))

            try:
                merged = R.run_with_oom_retry(att, desc="agg.merge")
                self.metrics["numMerges"].add(1)
                old = list(pending)
                del pending[:]  # park appends the merged entry fresh
                try:
                    R.run_with_oom_retry(lambda: park(merged),
                                         desc="agg.park")
                except BaseException:
                    # park failed for good: restore the drained
                    # partials — the ladder re-runs from intact state
                    pending[:0] = old
                    raise
            except BaseException:
                # ESCALATION with a completed (uncommitted) drain in
                # hand: drop the drain's pins so the partials are
                # evictable again — otherwise each ladder re-run
                # re-drains and re-pins, and release_pressure can
                # never spill exactly the dominant memory
                if "b" in state:
                    for h in pending:
                        h.unpin()
                raise
            fresh = list(pending)
            pending[:] = old
            finish_drain()  # release old partials (+ their futs/marks)
            pending[:] = fresh
            return merged

        def _register_speculative(part) -> None:
            """Speculative sizing for a big partial: the count readback
            goes to the async harvester (submitted BEFORE register — a
            register under pressure may immediately spill the batch),
            the partial stays unshrunk until the drain reconciles, and
            merge bookkeeping runs on the predicted estimate.  An
            overshoot only costs the dead padded rows the drain trims;
            an undershoot only means one merge triggers a batch late."""
            nonlocal pending_rows
            est = pred.predict(cap_ceiling=part.capacity) \
                if pred is not None else None
            speculated = est is not None
            if est is None:
                est = part.capacity
                SP.record_sync("agg.size")  # warm-up: estimate is the
                # conservative capacity bound, not a prediction
            fut = P.device_read_async(part.num_rows, tag="agg.size")
            h = store.register(part, SpillPriorities.AGGREGATE_PARTIAL)
            pending.append(h)
            futs[id(h)] = (fut, est, speculated)
            pending_rows += est

        def retire(part):
            nonlocal pending_rows
            if (not isinstance(part.num_rows, int)
                    and part.capacity <= _DEFER_SYNC_CAP):
                pending.append(store.register(
                    part, SpillPriorities.AGGREGATE_PARTIAL))
                pending_rows += part.capacity  # upper bound; drain pins
                if len(pending) > 1 and pending_rows >= min(
                        self.goal_rows, 2 * _DEFER_SYNC_CAP):
                    # bound pending without a sizing sync: re-merge via
                    # the traced concat; the merged partial stays traced
                    def park(m):
                        pending.append(store.register(
                            m, SpillPriorities.AGGREGATE_PARTIAL))

                    with MetricTimer(self.metrics[TOTAL_TIME], op=self.name) as t:
                        merged = t.observe(merge_and_park(park))
                    pending_rows = merged.capacity
                return
            if pred is not None and not isinstance(part.num_rows, int):
                _register_speculative(part)
                if len(pending) > 1 and pending_rows >= self.goal_rows:
                    def park(m):
                        nonlocal pending_rows
                        pending_rows = 0
                        _register_speculative(m)

                    with MetricTimer(self.metrics[TOTAL_TIME],
                                     op=self.name) as t:
                        t.observe(merge_and_park(park))
                return
            # one sizing sync per batch (free when the update emitted a
            # static count, e.g. grand aggregates); pin the host int into
            # the batch so downstream concat/shrink never re-syncs
            n = P.device_read_int(part.num_rows, tag="agg.size")
            part = dataclasses.replace(part, num_rows=n)
            part = part.shrink_to_capacity(pad_capacity(n))
            pending.append(store.register(
                part, SpillPriorities.AGGREGATE_PARTIAL))
            pending_rows += n
            if len(pending) > 1 and pending_rows >= self.goal_rows:
                def park(m):
                    nonlocal pending_rows
                    # sized before register: a register under pressure
                    # may immediately spill the merged batch
                    pr = P.device_read_int(m.num_rows, tag="agg.size")
                    m = dataclasses.replace(m, num_rows=pr)
                    m = m.shrink_to_capacity(pad_capacity(pr))
                    pending.append(store.register(
                        m, SpillPriorities.AGGREGATE_PARTIAL))
                    pending_rows = pr

                with MetricTimer(self.metrics[TOTAL_TIME], op=self.name) as t:
                    t.observe(merge_and_park(park))

        # Batch-granular OOM split-and-retry: one ladder unit =
        # update-dispatch + retire for one input batch.  retire's side
        # effects (partial registration, merge bookkeeping) roll back
        # on failure so a re-run — at full size or at the split size —
        # starts from clean state; the merge itself is transactional
        # (merge_and_park) so drained partials are never lost to a
        # mid-merge OOM.
        def guarded_retire(part):
            nonlocal pending_rows
            n0 = len(pending)
            r0 = pending_rows
            try:
                retire(part)
            except BaseException:
                eb = donated_units.get(id(part))
                if eb is not None and len(pending) > n0:
                    # every retire path registers the update output
                    # FIRST, so pending[n0] holds part's registration:
                    # if pressure spilled it (deleting the arrays the
                    # memoized donated_out references), restore the
                    # memo through the handle BEFORE the sweep below
                    # drops the only surviving copy — the re-run's
                    # resume must hand downstream a live batch
                    repair_donated_memo(eb, pending[n0])
                for h in pending[n0:]:
                    futs.pop(id(h), None)
                    h.close()
                del pending[n0:]
                pending_rows = r0
                raise
            donated_units.pop(id(part), None)
            return ()

        dispatch_guarded, retire_guarded = R.guarded_pipeline(
            dispatch, guarded_retire, desc="agg.update")
        for _ in P.pipelined(source, dispatch_guarded, retire_guarded,
                             tag="agg.update"):
            pass  # retire yields nothing; pipelined drives the overlap

        if not pending:
            if self.n_keys > 0 or not emit_empty_default:
                return  # grouped aggregate of empty input: no rows
            # grand aggregate of empty input: one default row (only the
            # first partition emits it); absorbed chains start from the
            # SOURCE node's schema (the chain may include projections)
            eb = ColumnarBatch.empty(self._source_node().schema)
            if self.mode != "final":
                eb = self._jit_update(_as_device_rows(eb))
            pending.append(store.register(
                eb, SpillPriorities.AGGREGATE_PARTIAL))

        out = self._drain_final_fused(pending, pending_rows)
        if out is not None:
            yield self._count_output(out)
            return
        with MetricTimer(self.metrics[TOTAL_TIME], op=self.name) as t:
            single = len(pending) == 1
            state: dict = {}

            def final_att():
                # uncommitted drain: a retryable failure anywhere in
                # the tail (concat, merge, finalize) spills + re-runs
                # with every partial still registered
                if "b" not in state:
                    state["b"] = drain_pending(commit=False)
                m = state["b"]
                if not single or self.mode == "final":
                    m = self._jit_merge(_as_device_rows(m))
                if self.mode == "partial":
                    return m
                return self._jit_finalize(_as_device_rows(m))

            out = R.run_with_oom_retry(final_att, desc="agg.drain")
            finish_drain()
            t.observe(out)
        yield self._count_output(out)
