"""Cache exec: materialize-once, re-serve-forever (InMemoryTableScan).

TPU re-design of the reference's cached-batch path (ref: SURVEY
Appendix A — the spark311 shim replaces InMemoryTableScanExec;
docs/additional-functionality/cache-serializer.md describes the
columnar cache serializer).  On this engine a cached subtree's batches
register with the process BufferStore: DEVICE-resident while HBM
allows, spilling to HOST/DISK under pressure like every other
long-lived buffer, and re-materializing on `get()` — so `df.cache()`
costs no dedicated memory pool and participates in the global spill
policy.

First drain: batches stream THROUGH to the consumer while handles
accumulate; the slot publishes only when every partition fully drained
(a LIMIT that stops early must not publish a truncated cache).
Subsequent plans referencing the slot serve straight from the store and
never execute the child (scans are skipped entirely — metric-visible).
"""

from __future__ import annotations

import dataclasses
from typing import Iterator

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.execs.base import MetricTimer, TOTAL_TIME, TpuExec


class TpuCacheExec(TpuExec):
    def __init__(self, slot, child: TpuExec):
        import threading

        super().__init__(child)
        self.slot = slot
        self._staged: dict[int, list] = {}
        self._complete: set[int] = set()
        # partitions may drain concurrently (exchange task threads);
        # the completion check + publish must be one atomic step or two
        # finishers can double-publish (the loser's cleanup would close
        # the winner's handles)
        self._stage_lock = threading.Lock()

    @property
    def schema(self) -> T.Schema:
        return self.children[0].schema

    @property
    def num_partitions(self) -> int:
        if self.slot.filled:
            return max(1, len(self.slot.parts))
        return self.children[0].num_partitions

    def node_desc(self) -> str:
        state = "cached" if self.slot.filled else "materializing"
        return f"TpuCacheExec [{state}]"

    def additional_metrics(self):
        return [("cacheHits", "ESSENTIAL"), ("cacheWrites", "ESSENTIAL")]

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        parts = self.slot.parts
        if parts is not None:
            if p >= len(parts):
                return
            for h in parts[p]:
                with MetricTimer(self.metrics[TOTAL_TIME], op=self.name) as t:
                    out = t.observe(h.get())
                    # keep the entry spillable between queries: the
                    # consumer's pipeline holds the device arrays it
                    # needs; the store may re-spill afterwards
                    h.unpin()
                self.metrics["cacheHits"].add(1)
                yield self._count_output(out)
            return

        from spark_rapids_tpu.memory import SpillPriorities, get_store

        store = get_store()
        staged: list = []
        with self._stage_lock:
            self._staged[p] = staged
        for batch in self.children[0].execute_partition(p):
            n = batch.concrete_num_rows()
            pinned = dataclasses.replace(batch, num_rows=n)
            h = store.register(pinned, SpillPriorities.CACHED)
            h.unpin()
            staged.append(h)
            self.metrics["cacheWrites"].add(1)
            yield self._count_output(batch)
        n_parts = self.children[0].num_partitions
        with self._stage_lock:
            self._complete.add(p)
            if len(self._complete) < n_parts:
                return
            parts = [self._staged.get(i, []) for i in range(n_parts)]
            self._staged = {}
            self._complete = set()
        self.slot.publish(parts)

    def execute(self) -> Iterator[ColumnarBatch]:
        for p in range(self.num_partitions):
            yield from self.execute_partition(p)

    def close(self) -> None:
        # a partial drain (LIMIT, error) must not leak store entries
        with self._stage_lock:
            staged, self._staged = self._staged, {}
            self._complete = set()
        for handles in staged.values():
            for h in handles:
                h.close()
        super().close()
