"""Sort and top-N execs.

TPU counterparts of GpuSortExec (ref: sql-plugin/.../GpuSortExec.scala:
FullSortSingleBatch / SortEachBatch / OutOfCoreSort modes) and
GpuTopN/GpuTakeOrderedAndProjectExec (ref: limit.scala:148,260).

Sort keys are arbitrary expressions: they are projected as appended key
columns, the batch is sorted on them via the total-order-key lexsort in
ops.sort, and the appended columns are dropped — the same bind/project
approach the reference takes with SortOrder child expressions.

Inputs up to `spark.rapids.tpu.sql.sort.singleBatchRows` sort as one
device batch (the reference's FullSortSingleBatch).  Larger inputs take
the out-of-core **sample-split sort**: stream the input into spillable
storage while sampling keys, choose range bounds, split every batch into
key-range buckets (vectorized lexicographic bound search on device, ops.
range_partition), park the grouped rows host-side, then sort each
bounded bucket independently and emit buckets in bound order.  This is
the TPU-idiomatic redesign of GpuOutOfCoreSortIterator
(ref: GpuSortExec.scala:213): the reference's cursor-based k-way merge
is row-at-a-time host logic with per-round device round trips; the
sample-split design is two streaming passes of fixed-shape device
programs."""

from __future__ import annotations

import dataclasses
from typing import Iterator, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch, concat_batches
from spark_rapids_tpu.columnar.column import (
    Column,
    StringColumn,
    pad_capacity,
    pad_width,
)
from spark_rapids_tpu.config import register
from spark_rapids_tpu.execs.base import MetricTimer, TOTAL_TIME, TpuExec
from spark_rapids_tpu.exprs.base import EvalContext, Expression, bind_references
from spark_rapids_tpu.ops.sort import SortOrder, sort_batch

SORT_SINGLE_BATCH_ROWS = register(
    "spark.rapids.tpu.sql.sort.singleBatchRows", 1 << 21,
    "Row threshold above which a global sort switches from one-device-"
    "batch sorting to the out-of-core sample-split sort (the "
    "OutOfCoreSort mode analog, ref: GpuSortExec.scala:38-40).")
SORT_SAMPLE_PER_BATCH = register(
    "spark.rapids.tpu.sql.sort.samplesPerBatch", 128,
    "Rows sampled from each input batch to estimate range-bucket bounds "
    "for the out-of-core sort (ref: GpuRangePartitioner.sketch).")
SORT_MAX_BUCKETS = register(
    "spark.rapids.tpu.sql.sort.maxBuckets", 64,
    "Upper bound on out-of-core sort range buckets (bound-search program "
    "size grows with bucket count).")


@dataclasses.dataclass
class SortKey:
    """Frontend sort key: expression + direction/null placement."""

    expr: Expression
    descending: bool = False
    nulls_last: bool = False


class _SortMixin(TpuExec):
    def _bind(self, keys: Sequence[SortKey], child: TpuExec):
        self.keys = [SortKey(bind_references(k.expr, child.schema),
                             k.descending, k.nulls_last) for k in keys]

    def _keys_cache_key(self) -> tuple:
        from spark_rapids_tpu.execs.jit_cache import expr_key

        return tuple((expr_key(k.expr), k.descending, k.nulls_last)
                     for k in self.keys)

    def _sorted(self, batch: ColumnarBatch) -> ColumnarBatch:
        """Append evaluated key columns, sort, drop them (traceable)."""
        ctx = EvalContext.for_batch(batch)
        n_data = batch.num_cols
        key_cols = [k.expr.eval(ctx) for k in self.keys]
        aug_schema = T.Schema(
            list(batch.schema.fields)
            + [T.Field(f"__sortkey{i}", k.expr.dtype)
               for i, k in enumerate(self.keys)])
        aug = ColumnarBatch(list(batch.columns) + key_cols, batch.num_rows,
                            aug_schema)
        orders = [SortOrder(n_data + i, k.descending, k.nulls_last)
                  for i, k in enumerate(self.keys)]
        out = sort_batch(aug, orders)
        return ColumnarBatch(out.columns[:n_data], out.num_rows, batch.schema)


class TpuSortExec(_SortMixin):
    """scope='global': total order over all input (one output
    partition); scope='partition': sort each child partition (the
    reduce-side sorter below a range exchange — partition index order
    then equals total order); scope='batch': sort each batch
    independently (the SortEachBatch mode used below partial
    aggregations).  `global_sort=False` is the legacy spelling of
    scope='batch'."""

    def __init__(self, keys: Sequence[SortKey], child: TpuExec,
                 global_sort: bool = True, scope: Optional[str] = None):
        super().__init__(child)
        self._bind(keys, child)
        if scope is None:
            scope = "global" if global_sort else "batch"
        assert scope in ("global", "partition", "batch"), scope
        self.scope = scope
        self.global_sort = scope == "global"
        from spark_rapids_tpu.execs.jit_cache import cached_jit

        self._jit_sorted = cached_jit(("sort", self._keys_cache_key()),
                                      lambda: self._sorted,
                                      op=self.name)
        # augmented layout: data columns ++ evaluated key columns
        child_schema = child.schema
        self._n_data = len(child_schema.fields)
        self.aug_schema = T.Schema(
            list(child_schema.fields)
            + [T.Field(f"__sortkey{i}", k.expr.dtype)
               for i, k in enumerate(self.keys)])
        self.aug_orders = [SortOrder(self._n_data + i, k.descending,
                                     k.nulls_last)
                           for i, k in enumerate(self.keys)]

    @property
    def schema(self) -> T.Schema:
        return self.children[0].schema

    def node_desc(self) -> str:
        ks = ", ".join(
            f"{k.expr.name}{' DESC' if k.descending else ''}" for k in self.keys)
        return f"TpuSortExec [{ks}] scope={self.scope}"

    def additional_metrics(self):
        return [("sortBuckets", "MODERATE"), ("oocRows", "MODERATE")]

    @property
    def num_partitions(self) -> int:
        if self.scope == "global":
            return 1
        return self.children[0].num_partitions

    @property
    def output_partitioning(self):
        # a partition-scoped sort preserves the child's distribution
        if self.scope == "partition":
            return self.children[0].output_partitioning
        return None

    # -- traceable pieces ------------------------------------------------ #

    def _augment(self, batch: ColumnarBatch) -> ColumnarBatch:
        ctx = EvalContext.for_batch(batch)
        key_cols = [k.expr.eval(ctx) for k in self.keys]
        return ColumnarBatch(list(batch.columns) + key_cols,
                             batch.num_rows, self.aug_schema)

    def _sort_drop(self, aug: ColumnarBatch) -> ColumnarBatch:
        out = sort_batch(aug, self.aug_orders)
        return ColumnarBatch(out.columns[: self._n_data], out.num_rows,
                             self.schema)

    def _group_by_bounds(self, aug: ColumnarBatch, bounds: ColumnarBatch,
                         n_parts: int):
        """pid per row, rows grouped by bucket, per-bucket counts."""
        from spark_rapids_tpu.ops.range_partition import bucket_ids

        pid = bucket_ids(aug, bounds, self.aug_orders, n_parts - 1)
        live = aug.row_mask()
        key = jnp.where(live, pid, jnp.int32(n_parts))
        order = jnp.argsort(key, stable=True)
        grouped = aug.gather(order, aug.num_rows)
        counts = jax.ops.segment_sum(live.astype(jnp.int32), key,
                                     num_segments=n_parts + 1)[:n_parts]
        return grouped, counts

    # -- driver ---------------------------------------------------------- #

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        if self.scope == "global":
            assert self.num_partitions == 1
            if p == 0:
                yield from self.execute()
            return
        if self.scope == "batch":
            for b in self.children[0].execute_partition(p):
                with MetricTimer(self.metrics[TOTAL_TIME], op=self.name) as t:
                    out = t.observe(self._jit_sorted(
                        b.with_device_num_rows()))
                yield self._count_output(out)
            return
        yield from self._sort_stream(
            self.children[0].execute_partition(p))

    def execute(self) -> Iterator[ColumnarBatch]:
        if self.scope == "global":
            yield from self._sort_stream(self.children[0].execute())
        else:
            for p in range(self.num_partitions):
                yield from self.execute_partition(p)

    def _sort_stream(self, source, depth: int = 0
                     ) -> Iterator[ColumnarBatch]:
        import dataclasses as _dc

        from spark_rapids_tpu.config import get_conf
        from spark_rapids_tpu.execs.jit_cache import cached_jit
        from spark_rapids_tpu.memory import SpillPriorities, get_store

        conf = get_conf()
        single_rows = conf.get(SORT_SINGLE_BATCH_ROWS)
        n_sample = conf.get(SORT_SAMPLE_PER_BATCH)
        store = get_store()
        kkey = self._keys_cache_key()
        jit_aug = cached_jit(("sortaug", kkey, repr(self.aug_schema)),
                             lambda: self._augment, op=self.name)

        # collect phase: augment + register (spillable).  Sampling starts
        # only once the running total crosses the single-batch threshold
        # (small sorts — the common case — pay zero sampling cost);
        # already-registered batches are back-sampled at that point.
        handles: list = []
        rows: list[int] = []
        samples: list[ColumnarBatch] = []
        rng = np.random.default_rng(0x5047 + depth)

        def take_sample(aug, n):
            pos = rng.integers(0, n, n_sample).astype(np.int32)
            jit_sample = cached_jit(
                ("sortsample", kkey, aug.capacity, n_sample,
                 repr(self.aug_schema)),
                lambda: lambda a, p: a.gather(p, n_sample),
                op=self.name)
            samples.append(jit_sample(aug, jnp.asarray(pos, jnp.int32)))

        def pin_deferred() -> None:
            """Fix up capacity-bound row counts with ONE batched fetch
            (deferred batches must not feed sampling or bucket math
            with padding rows counted as live)."""
            nonlocal total
            idxs = list(deferred)
            if not idxs:
                return
            acquired: list = []
            try:
                batches = []
                for i in idxs:
                    batches.append(handles[i].get())
                    acquired.append(handles[i])
                from spark_rapids_tpu.parallel.pipeline import (
                    device_read_many,
                )

                ns = device_read_many([b.num_rows for b in batches],
                                      tag="sort.size")
            except BaseException:
                # a failed acquire/readback must leave the runs
                # evictable: the ladder re-runs this path, and pins
                # left behind would accumulate per attempt, making the
                # out-of-core sort's main memory unspillable
                for h in acquired:
                    h.unpin()
                raise
            for i, b, nn in zip(idxs, batches, ns):
                nn = int(nn)
                total += nn - rows[i]
                rows[i] = nn
                handles[i].unpin()
            deferred.clear()

        from spark_rapids_tpu.execs import retry as R

        try:
            total = 0
            deferred: list[int] = []  # handle indices with capacity-
            # bound row counts (sizing sync skipped)

            def ingest(b) -> None:
                """Augment + register ONE input batch — the
                split-and-retry unit of the OOC sort's collect phase.
                Rolls back its partial bookkeeping (handles/rows/
                samples/deferred/total) on failure so the ladder can
                spill-and-re-run it, or bisect it into two smaller
                runs (more runs is always valid input to the bucket
                merge)."""
                nonlocal total
                h0, r0, s0 = len(handles), len(rows), len(samples)
                d0, t0, rows0 = list(deferred), total, list(rows)
                try:
                    if depth == 0:
                        aug = jit_aug(b.with_device_num_rows())
                    else:
                        aug = b  # recursive input: already augmented
                    if not isinstance(aug.num_rows, int) \
                            and total + aug.capacity <= single_rows:
                        # defer the sizing sync: capacity bounds the
                        # rows, and while the running total stays below
                        # the single-batch threshold the exact count
                        # changes no decision (the sort handles dead
                        # rows).  Each skipped sync saves a device
                        # round trip.  Batches kept capacity-bound
                        # never feed the sample pool.
                        n = aug.capacity
                    else:
                        if deferred:
                            pin_deferred()
                        n = aug.concrete_num_rows()
                        if n == 0:
                            return
                        aug = _dc.replace(aug, num_rows=n)
                    crossing = total <= single_rows < total + n
                    total += n
                    handles.append(store.register(
                        aug, SpillPriorities.COALESCE_PENDING))
                    rows.append(n)
                    if not isinstance(aug.num_rows, int):
                        deferred.append(len(handles) - 1)
                    if crossing and len(handles) > 1:
                        # threshold crossed: back-sample earlier batches
                        for h, hn in zip(handles[:-1], rows[:-1]):
                            prev = h.get()
                            try:
                                take_sample(prev, hn)
                            finally:
                                # a mid-sample failure must not leave
                                # the batch pinned: the ladder's spill
                                # rung needs it evictable on the re-run
                                h.unpin()
                    if total > single_rows:
                        take_sample(aug, n)
                except BaseException:
                    for h in handles[h0:]:
                        h.close()
                    del handles[h0:]
                    rows[:] = rows0[:r0]
                    del samples[s0:]
                    deferred[:] = d0
                    total = t0
                    raise

            for b in source:
                for _ in R.with_split_retry(
                        lambda bb: ingest(bb) or (), b,
                        desc="sort.collect"):
                    pass
            if total == 0:
                return
            if total <= single_rows or len(handles) == 1:
                batches = [h.get() for h in handles]
                if len(batches) > 1:
                    # pin every deferred count in one batched fetch so
                    # the host concat sizes on true rows
                    traced = [i for i, bb in enumerate(batches)
                              if not isinstance(bb.num_rows, int)]
                    if traced:
                        from spark_rapids_tpu.parallel.pipeline import (
                            device_read_many,
                        )

                        ns = device_read_many(
                            [batches[i].num_rows for i in traced],
                            tag="sort.size")
                        for i, nn in zip(traced, ns):
                            batches[i] = _dc.replace(batches[i],
                                                     num_rows=int(nn))
                big = batches[0] if len(batches) == 1 \
                    else concat_batches(batches)
                with MetricTimer(self.metrics[TOTAL_TIME], op=self.name) as t:
                    out = t.observe(self._jit_sort_drop()(
                        big.with_device_num_rows()))
                for h in handles:
                    h.close()
                handles.clear()
                yield self._count_output(out)
                return
            yield from self._merge_buckets(store, handles, rows, samples,
                                           total, single_rows, depth)
        finally:
            for h in handles:
                h.close()

    def _jit_sort_drop(self):
        from spark_rapids_tpu.execs.jit_cache import cached_jit

        return cached_jit(
            ("sortdrop", self._keys_cache_key(), repr(self.aug_schema)),
            lambda: self._sort_drop, op=self.name)

    def _merge_buckets(self, store, handles, rows, samples, total,
                       single_rows, depth: int = 0
                       ) -> Iterator[ColumnarBatch]:
        """Out-of-core phase: bounds -> per-batch range split (device) ->
        host-parked grouped runs -> per-bucket assemble/sort/emit.

        A bucket that still exceeds the single-batch threshold (skewed
        bounds) is recursively re-sampled and re-split once; past the
        recursion limit it sorts as one oversized batch — a single key
        group larger than device memory is the one shape ranges cannot
        subdivide (the cursor-merge alternative pays steady per-round
        host round trips to handle it; documented tradeoff)."""
        from spark_rapids_tpu.config import get_conf
        from spark_rapids_tpu.execs.jit_cache import cached_jit
        from spark_rapids_tpu.memory import SpillPriorities
        from spark_rapids_tpu.memory.store import _batch_to_host
        from spark_rapids_tpu.ops.range_partition import choose_bounds

        conf = get_conf()
        kkey = self._keys_cache_key()
        n_parts = min(max(2, -(-total // single_rows)),
                      conf.get(SORT_MAX_BUCKETS))
        self.metrics["sortBuckets"].add(n_parts)
        self.metrics["oocRows"].add(total)

        # bounds from the pooled fixed-size samples (one compiled program)
        k = len(samples)
        n_sample = samples[0].concrete_num_rows()
        pool_live = k * n_sample

        def pool_and_bound(sample_list):
            pooled = concat_batches(sample_list)
            return choose_bounds(pooled, self.aug_orders, n_parts,
                                 pool_live)

        bounds = cached_jit(
            ("sortbounds", kkey, k, n_sample, n_parts,
             tuple(s.capacity for s in samples)),
            lambda: pool_and_bound, op=self.name)(samples)

        # split phase: group each collected batch by bucket, park on host
        runs: list[tuple[object, np.ndarray, np.ndarray]] = []
        run_handles: list = []
        try:
            for h, n in zip(handles, rows):
                aug = h.get()
                jit_group = cached_jit(
                    ("sortgroup", kkey, n_parts, aug.capacity,
                     repr(self.aug_schema),
                     tuple(getattr(c, "width", 0) for c in aug.columns)),
                    lambda: lambda a, bd: self._group_by_bounds(
                        a, bd, n_parts))
                with MetricTimer(self.metrics[TOTAL_TIME], op=self.name) as t:
                    grouped, counts = jit_group(
                        aug.with_device_num_rows(), bounds)
                    t.observe(grouped)
                from spark_rapids_tpu.parallel.pipeline import device_read

                counts_np = np.asarray(device_read(counts,
                                                   tag="sort.split"))
                import dataclasses as _dc

                grouped = _dc.replace(grouped, num_rows=n)
                arrays = _batch_to_host(grouped)  # D2H + free device copy
                h.close()
                rh = store.register_host(
                    arrays, self.aug_schema,
                    SpillPriorities.COALESCE_PENDING)
                run_handles.append(rh)
                offsets = np.concatenate(
                    [[0], np.cumsum(counts_np)]).astype(np.int64)
                runs.append((rh, counts_np, offsets))
            handles.clear()

            # emit phase: assemble each bucket host-side, sort on device
            fn = self._jit_sort_drop()
            for b in range(n_parts):
                total_b = sum(int(c[b]) for _, c, _ in runs)
                if total_b == 0:
                    continue
                if depth < 1 and total_b > 2 * single_rows:
                    # skewed bucket: recursively sample-split it
                    yield from self._sort_stream(
                        self._bucket_chunks(runs, b, single_rows),
                        depth + 1)
                    continue
                bucket = self._assemble_bucket(runs, b)
                with MetricTimer(self.metrics[TOTAL_TIME], op=self.name) as t:
                    out = t.observe(fn(bucket.with_device_num_rows()))
                yield self._count_output(out)
        finally:
            for rh in run_handles:
                rh.close()

    def _bucket_chunks(self, runs, b: int, chunk_rows: int
                       ) -> Iterator[ColumnarBatch]:
        """Bucket b's rows as a stream of augmented chunk batches (the
        recursive sample-split input); per-run slicing, no global
        assembly."""
        for rh, counts, offsets in runs:
            cnt = int(counts[b])
            if not cnt:
                continue
            start = int(offsets[b])
            for off in range(0, cnt, chunk_rows):
                m = min(chunk_rows, cnt - off)
                yield self._assemble_range(rh, start + off, m)
            rh.unpin()

    def _assemble_range(self, rh, start: int, m: int) -> ColumnarBatch:
        """One run's rows [start, start+m) as a device aug batch."""
        arrays = rh.get_host()
        cap = pad_capacity(m)
        comps: list[np.ndarray] = []
        recipe: list[tuple] = []
        for ci, f in enumerate(self.aug_schema.fields):
            if isinstance(f.dtype, T.StringType):
                chars = arrays[f"c{ci}_chars"][start:start + m]
                w = chars.shape[1]
                cpad = np.zeros((cap, w), np.uint8)
                cpad[:m] = chars
                lpad = np.zeros(cap, np.int32)
                lpad[:m] = arrays[f"c{ci}_lengths"][start:start + m]
                vpad = np.zeros(cap, np.bool_)
                vpad[:m] = arrays[f"c{ci}_valid"][start:start + m]
                recipe.append(("str", len(comps), f.dtype))
                comps.extend([cpad, lpad, vpad])
            else:
                phys = T.to_numpy_dtype(f.dtype)
                dpad = np.zeros(cap, phys)
                dpad[:m] = arrays[f"c{ci}_data"][start:start + m]
                vpad = np.zeros(cap, np.bool_)
                vpad[:m] = arrays[f"c{ci}_valid"][start:start + m]
                recipe.append(("fixed", len(comps), f.dtype))
                comps.extend([dpad, vpad])
        return self._upload_components(comps, recipe, m)

    def _upload_components(self, comps, recipe, num_rows
                           ) -> ColumnarBatch:
        from spark_rapids_tpu.columnar.arrow import (
            _make_unpack,
            _pack_components,
        )
        from spark_rapids_tpu.execs.jit_cache import cached_jit

        buf, layout = _pack_components(comps)
        unpack = cached_jit(("unpack", layout),
                            lambda: _make_unpack(layout), op=self.name)
        dev = unpack(jnp.asarray(buf))
        cols: list = []
        for kind, i, dtype in recipe:
            if kind == "str":
                cols.append(StringColumn(dev[i], dev[i + 1], dev[i + 2]))
            else:
                cols.append(Column(dev[i], dev[i + 1], dtype))
        return ColumnarBatch(cols, num_rows, self.aug_schema)

    def _assemble_bucket(self, runs, b: int) -> Optional[ColumnarBatch]:
        """Concatenate bucket b's row ranges from every host-parked run
        and upload as one packed transfer."""
        total_b = sum(int(counts[b]) for _, counts, _ in runs)
        if total_b == 0:
            return None
        cap = pad_capacity(total_b)
        fields = self.aug_schema.fields
        # fetch each contributing run's host arrays ONCE (a disk-tier
        # entry reloads its file per get_host call), unpin when done
        contributing = [(rh, rh.get_host(), offsets)
                        for rh, counts, offsets in runs if counts[b]]
        comps: list[np.ndarray] = []
        recipe: list[tuple] = []
        for ci, f in enumerate(fields):
            if isinstance(f.dtype, T.StringType):
                pieces = [(arrays[f"c{ci}_chars"][int(offs[b]):
                                                  int(offs[b + 1])],
                           arrays[f"c{ci}_lengths"][int(offs[b]):
                                                    int(offs[b + 1])],
                           arrays[f"c{ci}_valid"][int(offs[b]):
                                                  int(offs[b + 1])])
                          for _, arrays, offs in contributing]
                w = pad_width(max(p[0].shape[1] for p in pieces))
                chars = np.zeros((cap, w), np.uint8)
                lengths = np.zeros(cap, np.int32)
                valid = np.zeros(cap, np.bool_)
                off = 0
                for pc, pl, pv in pieces:
                    m = len(pl)
                    chars[off:off + m, : pc.shape[1]] = pc
                    lengths[off:off + m] = pl
                    valid[off:off + m] = pv
                    off += m
                recipe.append(("str", len(comps), f.dtype))
                comps.extend([chars, lengths, valid])
            else:
                phys = T.to_numpy_dtype(f.dtype)
                data = np.zeros(cap, phys)
                valid = np.zeros(cap, np.bool_)
                off = 0
                for _, arrays, offs in contributing:
                    s, e = int(offs[b]), int(offs[b + 1])
                    m = e - s
                    data[off:off + m] = arrays[f"c{ci}_data"][s:e]
                    valid[off:off + m] = arrays[f"c{ci}_valid"][s:e]
                    off += m
                recipe.append(("fixed", len(comps), f.dtype))
                comps.extend([data, valid])
        for rh, _, _ in contributing:
            rh.unpin()  # stay spillable between buckets
        return self._upload_components(comps, recipe, total_b)


class TpuTakeOrderedAndProjectExec(_SortMixin):
    """ORDER BY ... LIMIT n: keeps a running top-n batch; each incoming
    batch is concatenated, sorted, and truncated to n (the reference's
    per-batch sort+slice then final sort, limit.scala:148)."""

    def __init__(self, n: int, keys: Sequence[SortKey], child: TpuExec,
                 project: Optional[Sequence[Expression]] = None):
        super().__init__(child)
        assert n >= 0
        self.n = n
        self._bind(keys, child)
        self.project = None
        if project is not None:
            self.project = [bind_references(e, child.schema) for e in project]
            from spark_rapids_tpu.execs.basic import output_field

            self._schema = T.Schema(
                [output_field(e, i) for i, e in enumerate(self.project)])
        else:
            self._schema = child.schema

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def node_desc(self) -> str:
        return f"TpuTakeOrderedAndProjectExec n={self.n}"

    def _topn(self, batch: ColumnarBatch) -> ColumnarBatch:
        s = self._sorted(batch)
        return s.slice_prefix(self.n)

    def execute(self) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.execs.jit_cache import cached_jit, exprs_key

        jit_topn = cached_jit(
            ("topn", self.n, self._keys_cache_key()), lambda: self._topn,
            op=self.name)
        top: Optional[ColumnarBatch] = None
        for b in self.children[0].execute():
            with MetricTimer(self.metrics[TOTAL_TIME], op=self.name):
                merged = b if top is None else concat_batches([top, b])
                top = jit_topn(merged.with_device_num_rows())
                # compact so concat_batches sees the concrete top-n rows
                top = ColumnarBatch(top.columns, top.concrete_num_rows(),
                                    top.schema)
        if top is None:
            return
        out = top
        if self.project is not None:
            def proj(batch):
                ctx = EvalContext.for_batch(batch)
                return ColumnarBatch([e.eval(ctx) for e in self.project],
                                     batch.num_rows, self._schema)

            out = cached_jit(
                ("topn_proj", exprs_key(self.project), repr(self._schema)),
                lambda: proj, op=self.name)(out)
        yield self._count_output(out)


class TpuTopNExec(_SortMixin):
    """ORDER BY + LIMIT n as a streaming top-n (ref: GpuTopN /
    Spark's TakeOrderedAndProject) — the full global sort a LIMIT
    would otherwise pay is replaced by a per-batch candidate filter
    plus one tiny final sort.

    Exactness argument: per batch, rows are pruned against the batch's
    n-th best PRIMARY key value under a monotone scalar image of the
    primary order (floats canonicalize NaN to +inf and collapse ±0 —
    order-preserving, possibly tie-collapsing).  Any row strictly worse
    than n rows on the primary alone cannot be in the global top n
    regardless of tiebreak keys, so keeping every row at-or-beyond the
    threshold (ties included, NULLs per null-placement) is a provable
    superset of the answer.  The final multi-key lexsort then runs over
    only the accumulated candidates (typically O(n) per batch)."""

    def __init__(self, n: int, keys: Sequence[SortKey], child: TpuExec):
        super().__init__(child)
        self.n = n
        self._bind(keys, child)
        from spark_rapids_tpu.execs.jit_cache import cached_jit

        self._jit_cand = cached_jit(
            ("topn_cand", self.n, self._keys_cache_key()),
            lambda: self._candidates, op=self.name)
        self._jit_final = cached_jit(
            ("topnfinal", self.n, self._keys_cache_key()),
            lambda: self._final, op=self.name)

    @property
    def schema(self) -> T.Schema:
        return self.children[0].schema

    @property
    def num_partitions(self) -> int:
        return 1

    def node_desc(self) -> str:
        ks = ", ".join(
            f"{k.expr.name}{' DESC' if k.descending else ''}"
            for k in self.keys)
        return f"TpuTopNExec n={self.n} [{ks}]"

    def additional_metrics(self):
        return [("candidateRows", "MODERATE")]

    # -- traceable ------------------------------------------------------- #

    def _primary_scalar(self, kc):
        """Monotone 'larger = selected by top_k' image of the primary
        sort order (descending keeps the value sense; ascending flips
        with overflow-safe bitwise NOT for ints)."""
        k0 = self.keys[0]
        d = kc.data
        if jnp.issubdtype(d.dtype, jnp.floating):
            v = jnp.where(jnp.isnan(d), jnp.inf, d).astype(jnp.float64)
            return v if k0.descending else -v
        v = d.astype(jnp.int64)
        return v if k0.descending else ~v

    def _candidates(self, batch: ColumnarBatch) -> ColumnarBatch:
        ctx = EvalContext.for_batch(batch)
        kc = self.keys[0].expr.eval(ctx)
        live = batch.row_mask()
        valid = kc.validity & live
        s = self._primary_scalar(kc)
        if jnp.issubdtype(s.dtype, jnp.floating):
            lo = jnp.asarray(-jnp.inf, s.dtype)
        else:
            lo = jnp.asarray(jnp.iinfo(jnp.int64).min, s.dtype)
        sm = jnp.where(valid, s, lo)
        k = min(self.n, batch.capacity)
        thr = jax.lax.top_k(sm, k)[0][k - 1]
        mask = valid & (sm >= thr)
        nulls = live & ~kc.validity
        if self.keys[0].nulls_last:
            # NULLs only matter when non-null rows cannot fill the top n
            short = jnp.sum(valid.astype(jnp.int32)) < self.n
            mask = mask | (nulls & short)
        else:
            # NULLs sort first: every one is a candidate (their mutual
            # order is decided by the tiebreak keys)
            mask = mask | nulls
        return batch.compact(mask)

    def _final(self, batch: ColumnarBatch) -> ColumnarBatch:
        return self._sorted(batch).slice_prefix(self.n)

    # -- driver ---------------------------------------------------------- #

    def execute_partition(self, p: int):
        if p == 0:
            yield from self.execute()

    def execute(self):
        import dataclasses

        from spark_rapids_tpu.columnar.batch import concat_batches
        from spark_rapids_tpu.columnar.column import pad_capacity
        from spark_rapids_tpu.memory import SpillPriorities, get_store

        store = get_store()
        pending: list = []
        try:
            for batch in self.children[0].execute():
                with MetricTimer(self.metrics[TOTAL_TIME], op=self.name) as t:
                    cand = t.observe(self._jit_cand(
                        batch.with_device_num_rows()))
                pending.append(store.register(
                    cand, SpillPriorities.COALESCE_PENDING))
            if not pending:
                return
            batches = [h.get() for h in pending]
            # ONE batched sizing fetch, then shrink candidates to their
            # (typically O(n)) real size before the final sort
            from spark_rapids_tpu.parallel.pipeline import device_read_many

            ns = [int(v) for v in device_read_many(
                [b.num_rows for b in batches], tag="sort.size")]
            self.metrics["candidateRows"].add(sum(ns))
            shrunk = []
            for b, nn in zip(batches, ns):
                if nn == 0:
                    continue
                b = dataclasses.replace(b, num_rows=nn)
                shrunk.append(b.shrink_to_capacity(pad_capacity(nn)))
            if not shrunk:
                return
            # candidate volume is unbounded in degenerate shapes (a
            # mostly-NULL nulls-first key keeps every null row): reduce
            # HIERARCHICALLY so no single device batch exceeds the cap
            # — each chunk's top n provably contains every global
            # top-n row the chunk holds, so chunk winners compose
            cap_rows = getattr(self, "reduce_cap_rows",
                               max(4 * self.n, 1 << 16))
            while True:
                total = sum(b.concrete_num_rows() for b in shrunk)
                if len(shrunk) == 1 or total <= cap_rows:
                    break
                chunks: list = []
                cur: list = []
                cur_rows = 0
                for b in shrunk:
                    nb = b.concrete_num_rows()
                    if cur and cur_rows + nb > cap_rows:
                        chunks.append(cur)
                        cur, cur_rows = [], 0
                    cur.append(b)
                    cur_rows += nb
                if cur:
                    chunks.append(cur)
                nxt = []
                for ch in chunks:
                    big = ch[0] if len(ch) == 1 else concat_batches(ch)
                    with MetricTimer(self.metrics[TOTAL_TIME], op=self.name) as t:
                        win = t.observe(self._jit_final(
                            big.with_device_num_rows()))
                    wn = win.concrete_num_rows()
                    win = dataclasses.replace(win, num_rows=wn)
                    nxt.append(win.shrink_to_capacity(pad_capacity(wn)))
                nxt_total = sum(b.concrete_num_rows() for b in nxt)
                shrunk = nxt  # winners are <= n rows each: keep them
                if nxt_total >= total:
                    break  # no further reduction possible
            big = shrunk[0] if len(shrunk) == 1 else \
                concat_batches(shrunk)
            with MetricTimer(self.metrics[TOTAL_TIME], op=self.name) as t:
                out = t.observe(self._jit_final(
                    big.with_device_num_rows()))
            yield self._count_output(out)
        finally:
            for h in pending:
                h.close()
