"""Sort and top-N execs.

TPU counterparts of GpuSortExec (ref: sql-plugin/.../GpuSortExec.scala:
FullSortSingleBatch / SortEachBatch / OutOfCoreSort modes) and
GpuTopN/GpuTakeOrderedAndProjectExec (ref: limit.scala:148,260).

Sort keys are arbitrary expressions: they are projected as appended key
columns, the batch is sorted on them via the total-order-key lexsort in
ops.sort, and the appended columns are dropped — the same bind/project
approach the reference takes with SortOrder child expressions.

The full sort currently concatenates to a single batch (the reference's
FullSortSingleBatch); the out-of-core merge path arrives with the spill
store (SURVEY.md build stage 2)."""

from __future__ import annotations

import dataclasses
from typing import Iterator, Optional, Sequence

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch, concat_batches
from spark_rapids_tpu.execs.base import MetricTimer, TOTAL_TIME, TpuExec
from spark_rapids_tpu.exprs.base import EvalContext, Expression, bind_references
from spark_rapids_tpu.ops.sort import SortOrder, sort_batch


@dataclasses.dataclass
class SortKey:
    """Frontend sort key: expression + direction/null placement."""

    expr: Expression
    descending: bool = False
    nulls_last: bool = False


class _SortMixin(TpuExec):
    def _bind(self, keys: Sequence[SortKey], child: TpuExec):
        self.keys = [SortKey(bind_references(k.expr, child.schema),
                             k.descending, k.nulls_last) for k in keys]

    def _keys_cache_key(self) -> tuple:
        from spark_rapids_tpu.execs.jit_cache import expr_key

        return tuple((expr_key(k.expr), k.descending, k.nulls_last)
                     for k in self.keys)

    def _sorted(self, batch: ColumnarBatch) -> ColumnarBatch:
        """Append evaluated key columns, sort, drop them (traceable)."""
        ctx = EvalContext.for_batch(batch)
        n_data = batch.num_cols
        key_cols = [k.expr.eval(ctx) for k in self.keys]
        aug_schema = T.Schema(
            list(batch.schema.fields)
            + [T.Field(f"__sortkey{i}", k.expr.dtype)
               for i, k in enumerate(self.keys)])
        aug = ColumnarBatch(list(batch.columns) + key_cols, batch.num_rows,
                            aug_schema)
        orders = [SortOrder(n_data + i, k.descending, k.nulls_last)
                  for i, k in enumerate(self.keys)]
        out = sort_batch(aug, orders)
        return ColumnarBatch(out.columns[:n_data], out.num_rows, batch.schema)


class TpuSortExec(_SortMixin):
    """global=True: total order over all input (single concatenated batch
    for now); global=False: sort each batch independently (the
    SortEachBatch mode used below partial aggregations)."""

    def __init__(self, keys: Sequence[SortKey], child: TpuExec,
                 global_sort: bool = True):
        super().__init__(child)
        self._bind(keys, child)
        self.global_sort = global_sort
        from spark_rapids_tpu.execs.jit_cache import cached_jit

        self._jit_sorted = cached_jit(("sort", self._keys_cache_key()),
                                      lambda: self._sorted)

    @property
    def schema(self) -> T.Schema:
        return self.children[0].schema

    def node_desc(self) -> str:
        ks = ", ".join(
            f"{k.expr.name}{' DESC' if k.descending else ''}" for k in self.keys)
        return f"TpuSortExec [{ks}] global={self.global_sort}"

    def execute(self) -> Iterator[ColumnarBatch]:
        if self.global_sort:
            # collected input registers with the spill store so a
            # larger-than-HBM collection degrades to host/disk instead
            # of OOM (ref: GpuOutOfCoreSortIterator's spillable pending
            # queues, GpuSortExec.scala:213)
            from spark_rapids_tpu.memory import SpillPriorities, get_store

            store = get_store()
            handles = []
            try:
                for b in self.children[0].execute():
                    handles.append(store.register(
                        b, SpillPriorities.COALESCE_PENDING))
                if not handles:
                    return
                batches = [h.get() for h in handles]
                big = batches[0] if len(batches) == 1 \
                    else concat_batches(batches)
            finally:
                for h in handles:
                    h.close()
            with MetricTimer(self.metrics[TOTAL_TIME]):
                out = self._jit_sorted(big.with_device_num_rows())
            yield self._count_output(out)
        else:
            for b in self.children[0].execute():
                with MetricTimer(self.metrics[TOTAL_TIME]):
                    out = self._jit_sorted(b.with_device_num_rows())
                yield self._count_output(out)


class TpuTakeOrderedAndProjectExec(_SortMixin):
    """ORDER BY ... LIMIT n: keeps a running top-n batch; each incoming
    batch is concatenated, sorted, and truncated to n (the reference's
    per-batch sort+slice then final sort, limit.scala:148)."""

    def __init__(self, n: int, keys: Sequence[SortKey], child: TpuExec,
                 project: Optional[Sequence[Expression]] = None):
        super().__init__(child)
        assert n >= 0
        self.n = n
        self._bind(keys, child)
        self.project = None
        if project is not None:
            self.project = [bind_references(e, child.schema) for e in project]
            from spark_rapids_tpu.execs.basic import output_field

            self._schema = T.Schema(
                [output_field(e, i) for i, e in enumerate(self.project)])
        else:
            self._schema = child.schema

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def node_desc(self) -> str:
        return f"TpuTakeOrderedAndProjectExec n={self.n}"

    def _topn(self, batch: ColumnarBatch) -> ColumnarBatch:
        s = self._sorted(batch)
        return s.slice_prefix(self.n)

    def execute(self) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.execs.jit_cache import cached_jit, exprs_key

        jit_topn = cached_jit(
            ("topn", self.n, self._keys_cache_key()), lambda: self._topn)
        top: Optional[ColumnarBatch] = None
        for b in self.children[0].execute():
            with MetricTimer(self.metrics[TOTAL_TIME]):
                merged = b if top is None else concat_batches([top, b])
                top = jit_topn(merged.with_device_num_rows())
                # compact so concat_batches sees the concrete top-n rows
                top = ColumnarBatch(top.columns, top.concrete_num_rows(),
                                    top.schema)
        if top is None:
            return
        out = top
        if self.project is not None:
            def proj(batch):
                ctx = EvalContext.for_batch(batch)
                return ColumnarBatch([e.eval(ctx) for e in self.project],
                                     batch.num_rows, self._schema)

            out = cached_jit(
                ("topn_proj", exprs_key(self.project), repr(self._schema)),
                lambda: proj)(out)
        yield self._count_output(out)
