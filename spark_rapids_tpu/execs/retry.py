"""Task failure detection and recovery.

The reference leans on two layers the TPU runtime must reproduce
itself (SURVEY.md §5.3): Spark's task re-execution (deterministic
lineage — a failed task re-runs from its inputs) and the plugin's
OOM-retry framework (ref: RmmRapidsRetryIterator.scala `withRetry` /
`withRetryNoSplit` — release what the task holds, spill, and
split-and-retry the input batch on GPU OOM).

TPU analog — an ESCALATION LADDER, cheapest rung first:

1. `run_with_oom_retry(fn)`: spill every unpinned device buffer and
   re-run the closure (the withRetryNoSplit shape, for restartable
   non-streaming work: a merge drain, an H2D upload, a compile).
2. `with_split_retry(run, batch)`: the batch-granular rung threaded
   through the join/aggregate/sort/exchange stream loops — on a
   retryable failure, spill + re-run the batch; on a second failure,
   BISECT the batch (via SpillableBatch, down to
   `spark.rapids.tpu.task.retry.minSplitRows`) and process the halves
   recursively (the withRetry + splitSpillableInHalfByRows shape).
3. `with_task_retries(fn)`: whole-task re-run from lineage (the
   spark.task.maxFailures analog), with jittered doubling backoff so
   concurrent sessions retrying the same pressure event don't
   stampede in lockstep.
4. `should_cpu_fallback(exc)`: per-query degrade to the CPU engine
   (the sick-executor blacklisting analog, applied in session.py).

- `classify(exc)` / `is_retryable(exc)`: device/transient failures
  (XLA RESOURCE_EXHAUSTED, UNAVAILABLE/DEADLINE_EXCEEDED link hiccups,
  connection resets, our own reservation failures) are RETRYABLE;
  everything else (assertion, user error) fails fast.  tpulint SRC008
  flags broad `except` clauses in execs//io//shuffle/ that swallow
  exceptions without consulting this gate.
- every rung reports absorbed injected faults to
  robustness.faults.note_recovered, and process-global counters
  (`retry_stats()`) feed the bench `*_retry_splits` /
  `*_spills_under_pressure` fields.
- tasks that produce shuffle output buffer it locally and COMMIT
  atomically at task end (exchange.py) so a failed attempt leaves no
  partial blocks behind — the MapStatus commit protocol.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Iterator, Optional, TypeVar

from spark_rapids_tpu.config import register, get_conf

TASK_MAX_FAILURES = register(
    "spark.rapids.tpu.task.maxFailures", 3,
    "Attempts per deterministic task before the failure propagates "
    "(the spark.task.maxFailures analog).")

CPU_FALLBACK_ON_DEVICE_ERROR = register(
    "spark.rapids.tpu.sql.recovery.cpuFallbackOnDeviceError", True,
    "After task retries are exhausted on a DEVICE/transient error, "
    "re-run the whole query on the CPU engine instead of failing it "
    "(the sick-executor blacklisting analog).")

RETRY_BACKOFF_S = register(
    "spark.rapids.tpu.task.retryBackoffSeconds", 0.2,
    "Base sleep between task attempts (doubles per attempt, with "
    "+-50% jitter so concurrent sessions retrying the same pressure "
    "event spread out instead of stampeding in lockstep).")

SPLIT_RETRY_ENABLED = register(
    "spark.rapids.tpu.task.retry.splitEnabled", True,
    "On a second OOM for the same stream batch (after one "
    "spill-and-retry), bisect the batch and process the halves "
    "recursively instead of failing the task (the split-and-retry of "
    "the reference's RmmRapidsRetryIterator.withRetry).")

SPLIT_MIN_ROWS = register(
    "spark.rapids.tpu.task.retry.minSplitRows", 1024,
    "Floor for batch bisection: a batch at or below this many rows is "
    "never split further — the failure escalates to the whole-task "
    "retry (and ultimately the per-query CPU fallback) instead.",
    check=lambda v: v >= 1)

#: substrings of device/transient error text that justify a retry.
#: Deliberately NOT "INTERNAL": compiler/unsupported-HLO failures are
#: deterministic INTERNAL errors — retrying them wastes backoff and a
#: CPU degrade would hide the bug from users and CI.
_RETRYABLE_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "out of memory",
    "OutOfMemory",
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "Socket closed",
    "connection reset",
    "Connection reset",
    "ECONNRESET",
)

T = TypeVar("T")

#: jittered backoff RNG — deliberately unseeded state per process (the
#: whole point is that two processes sleep different amounts)
_JITTER = random.Random()

# -- recovery observability ------------------------------------------- #

_STATS_LOCK = threading.Lock()
_STATS = {"splits": 0, "spill_retries": 0, "task_retries": 0,
          "cpu_fallbacks": 0}


def _bump(key: str, n: int = 1) -> None:
    with _STATS_LOCK:
        _STATS[key] += n


def retry_stats() -> dict:
    """Process-global recovery counters: {splits, spill_retries,
    task_retries, cpu_fallbacks} — bench.py resets per query."""
    with _STATS_LOCK:
        return dict(_STATS)


def reset_retry_stats() -> None:
    with _STATS_LOCK:
        for k in _STATS:
            _STATS[k] = 0


def is_retryable(exc: BaseException) -> bool:
    """Device / transient failure (retry may succeed) vs logic error
    (fail fast)."""
    from spark_rapids_tpu.serving.cancel import QueryCancelled

    if isinstance(exc, QueryCancelled):
        # cancellation/deadline is a VERDICT, not a fault: no retry,
        # no split, no CPU degrade — the query unwinds (its message
        # must never be marker-matched into a retry)
        return False
    if isinstance(exc, MemoryError):
        return True
    from spark_rapids_tpu.shuffle.net import FetchFailedError

    if isinstance(exc, FetchFailedError):
        # remote shuffle peer died mid-fetch: the retried attempt
        # re-resolves peers (the FetchFailedException contract)
        return True
    if isinstance(exc, RuntimeError):  # XlaRuntimeError subclasses it
        text = str(exc)
        return any(m in text for m in _RETRYABLE_MARKERS)
    return False


def classify(exc: BaseException) -> str:
    """'retryable' | 'fatal' — the single classification gate every
    recovery path must consult before absorbing an exception (tpulint
    SRC008 flags broad except clauses in execs//io//shuffle/ that
    swallow without routing through here)."""
    return "retryable" if is_retryable(exc) else "fatal"


def _release_pressure() -> None:
    """Free what this process can before a retry attempt — the
    spill-everything step of the reference's retry framework."""
    try:
        from spark_rapids_tpu.memory import get_store

        get_store().spill_all_unpinned()
    except Exception as e:  # noqa: BLE001 — best-effort pressure relief
        classify(e)  # a failed spill never masks the original error
    import gc

    gc.collect()


#: public alias for the fault sites that recover in place
release_pressure = _release_pressure


def _sleep_backoff(base: float, attempt: int) -> None:
    """Doubling backoff with +-50% jitter (decorrelates concurrent
    sessions retrying the same pressure event)."""
    if base <= 0:
        return
    time.sleep(base * (2 ** attempt) * (0.5 + _JITTER.random()))


def _note_recovered_all(caught: list, action: str) -> None:
    from spark_rapids_tpu.robustness import faults as _faults

    for e in caught:
        _faults.note_recovered(e, action=action)


def absorb_once(fn: Callable[[], T], action: str) -> T:
    """THE in-place recovery shape shared by the fault seams (upload,
    compile): run the restartable closure; on ONE retryable failure
    release pressure (spill everything unpinned), re-run, and credit
    the absorbed fault; a second failure escalates to the ladder /
    task retry / CPU degrade."""
    try:
        return fn()
    except BaseException as e:  # noqa: BLE001 - classified below
        if not is_retryable(e):
            raise
        _release_pressure()
        out = fn()
        from spark_rapids_tpu.robustness import faults as _faults

        _faults.note_recovered(e, action=action)
        return out


def _retry_loop(fn: Callable[[], T], stat_key: str, action: str,
                attempts: Optional[int] = None) -> T:
    """The one release-pressure retry loop behind both the spill rung
    and the whole-task rung: classify, count, spill everything
    unpinned, jittered doubling backoff, credit absorbed injected
    faults on eventual success."""
    from spark_rapids_tpu.serving.cancel import check_point

    conf = get_conf()
    attempts = attempts if attempts is not None \
        else max(1, conf.get(TASK_MAX_FAILURES))
    backoff = conf.get(RETRY_BACKOFF_S)
    caught: list[BaseException] = []
    for attempt in range(attempts):
        try:
            out = fn()
        except BaseException as e:  # noqa: BLE001 - classified below
            if not is_retryable(e) or attempt == attempts - 1:
                raise
            # a cancelled query must not burn backoff sleeps and
            # re-attempts on work nobody will consume
            check_point()
            caught.append(e)
            _bump(stat_key)
            _release_pressure()
            _sleep_backoff(backoff, attempt)
            continue
        if caught:
            _note_recovered_all(caught, action)
        return out
    raise caught[-1]  # unreachable; keeps type checkers honest


def with_task_retries(fn: Callable[[], T], desc: str = "task") -> T:
    """Run a deterministic task closure with device-error retries.
    The closure must be safe to re-run from scratch (lineage: pure
    function of its exec-tree inputs)."""
    return _retry_loop(fn, "task_retries", f"task_retry:{desc}")


def run_with_oom_retry(fn: Callable[[], T], desc: str = "op",
                       attempts: Optional[int] = None) -> T:
    """Spill-and-retry a RESTARTABLE closure (rung 1 of the ladder, the
    withRetryNoSplit shape): on a retryable failure, release pressure
    (spill every unpinned buffer) and re-run.  The closure must have no
    partial externally-visible effects — callers keep their own state
    in closures so a re-run resumes instead of redoing (see the
    aggregate's merge drain)."""
    return _retry_loop(fn, "spill_retries", f"spill_retry:{desc}",
                       attempts)


# -- batch bisection --------------------------------------------------- #


def _desharded(batch):
    """Re-place a batch whose leaves are mesh-sharded (or scattered
    across devices) onto ONE device before the ladder's row-indexed
    gathers: bisection slices leaf-by-leaf with plain `gather`/`slice`
    ops that assume fully-addressable single-device arrays, and a
    multi-device leaf would either fail the trace or silently gather a
    single shard's rows.  Under mesh serving (the only producer of
    sharded stage leaves) the move routes through
    parallel/placement.adopt_batch — the single device_put choke point
    (SRC016) — so it shows up in the placement counters instead of
    vanishing into an untracked transfer."""
    import jax

    target = None
    for c in getattr(batch, "columns", ()):
        for leaf in jax.tree_util.tree_leaves(c):
            if isinstance(leaf, jax.Array):
                try:
                    devs = leaf.devices()
                except Exception:
                    continue
                if len(devs) > 1:
                    target = sorted(devs, key=lambda d: d.id)[0]
                    break
        if target is not None:
            break
    if target is None:
        return batch
    from spark_rapids_tpu.parallel import placement as _placement

    return _placement.adopt_batch(batch, target)


def bisect_batch(batch):
    """Split a device batch into (first_half, second_half) along the
    row axis.  Runs only on the failure path (after a spill), so the
    sizing sync and the eager gathers are off the happy path by
    construction.  EncodedBatch inputs decode first (splitting wire
    components is plan-specific; the decoded form is universal).

    A COALESCED batch (TpuCoalesceBatchesExec output, carrying
    `coalesce_seams`) splits at the seam boundary nearest the midpoint
    instead of n//2, and each half inherits its side's seams: the retry
    ladder walks a coalesced batch back down the producer's original
    batch granularity, so the bucket shapes the recovery dispatches at
    are ones the compile cache has already seen."""
    import dataclasses

    import jax.numpy as jnp

    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.columnar.column import pad_capacity
    from spark_rapids_tpu.columnar.transfer import EncodedBatch

    seams = getattr(batch, "coalesce_seams", None)
    if isinstance(batch, EncodedBatch):
        # a consumed (donated) batch has no device buffers left to
        # split; decode_now refuses it with ConsumedBatchError
        # (non-retryable) — callers gate on _batch_rows first, so the
        # ladder escalates instead of bisecting freed HBM
        batch = batch.decode_now()
    batch = _desharded(batch)
    n = batch.concrete_num_rows()
    assert n >= 2, f"cannot bisect a {n}-row batch"
    batch = dataclasses.replace(batch, num_rows=n)
    lo = n // 2
    first_seams = second_seams = None
    if seams and len(seams) >= 2 and sum(seams) == n:
        offs, acc = [], 0
        for s in seams[:-1]:
            acc += s
            offs.append(acc)
        cut = min(offs, key=lambda o: abs(o - lo))
        if 0 < cut < n:
            lo = cut
            k = offs.index(cut) + 1
            first_seams, second_seams = seams[:k], seams[k:]
    first = batch.slice_prefix(lo).shrink_to_capacity(pad_capacity(lo))
    cap = batch.capacity
    # gather DIRECTLY at the half's padded capacity: this path runs
    # precisely because the device is out of memory, so a full-capacity
    # gather followed by a shrink (an up-to-2x transient per column)
    # could re-OOM the recovery rung itself
    out_cap = pad_capacity(n - lo)
    idx = jnp.minimum(jnp.arange(out_cap, dtype=jnp.int32) + lo,
                      cap - 1)
    cols = [c.gather(idx) for c in batch.columns]
    live = jnp.arange(out_cap, dtype=jnp.int32) < (n - lo)
    cols = [c.with_validity(c.validity & live) for c in cols]
    second = ColumnarBatch(cols, n - lo, batch.schema)
    if first_seams and len(first_seams) >= 2:
        first.coalesce_seams = first_seams
    if second_seams and len(second_seams) >= 2:
        second.coalesce_seams = second_seams
    return first, second


def _batch_rows(batch) -> Optional[int]:
    """Concrete row count for split decisions; None when even the
    readback fails (then splitting is off the table anyway).
    EncodedBatch (the encoded scan path — the aggregate's primary
    input) carries a host-known count, or exposes it as its wire `n`
    component."""
    try:
        from spark_rapids_tpu.columnar.transfer import EncodedBatch

        if isinstance(batch, EncodedBatch):
            if batch.consumed:
                # donated into a fused program: its buffers are gone,
                # so bisection is off the table — rows=None keeps the
                # ladder on the retry/escalate rungs, which resume
                # from the memoized program output (run_consuming)
                # without touching the consumed buffer
                return None
            if batch.num_rows is not None:
                return int(batch.num_rows)
            from spark_rapids_tpu.parallel.pipeline import (
                device_read_int,
            )

            return device_read_int(batch.live_count, tag="retry.size")
        return batch.concrete_num_rows()
    except Exception as e:  # noqa: BLE001 — split gating only
        classify(e)
        return None


def with_split_retry(run, batch, desc: str = "batch",
                     first_attempt=None, initial_error=None,
                     _depth: int = 0) -> Iterator:
    """THE batch-granular escalation ladder (generator), threaded
    through the streaming loops of join/aggregate/sort/exchange.

    ``run(batch)`` processes one input batch and returns an iterable of
    output chunks (or an empty iterable for sink-style loops); it must
    roll back its own partial side effects when it raises, so a re-run
    is clean.  On a retryable failure with nothing yielded yet:

    1. spill every unpinned device buffer and re-run the batch;
    2. on a second failure, BISECT the batch and recurse on the halves
       (each parked spillably while the other runs), down to
       spark.rapids.tpu.task.retry.minSplitRows;
    3. at the floor (or once output already streamed downstream, where
       a re-run would duplicate rows), re-raise — the whole-task retry
       and per-query CPU fallback rungs take over.

    ``first_attempt`` lets a software-pipelined caller hand in the
    already-dispatched in-flight state for attempt zero (PR4's
    speculative dispatch): if that attempt fails, the speculated chunk
    is discarded and retries RE-DISPATCH from the input batch — at the
    split size after a bisect — so no predictor entry leaks.
    ``initial_error`` seeds the ladder with a failure that happened at
    dispatch time, before any attempt could run here."""
    from spark_rapids_tpu.robustness import faults as _faults
    from spark_rapids_tpu.serving.cancel import check_point

    conf = get_conf()
    attempts = max(1, conf.get(TASK_MAX_FAILURES))
    backoff = conf.get(RETRY_BACKOFF_S)
    min_rows = conf.get(SPLIT_MIN_ROWS)
    split_on = conf.get(SPLIT_RETRY_ENABLED)
    caught: list[BaseException] = []
    failures = 0
    action = "spill_retry"
    if initial_error is not None:
        caught.append(initial_error)
        failures = 1
        _bump("spill_retries")
        _release_pressure()
        _sleep_backoff(backoff, 0)  # same decorrelation as every rung
    while True:
        emitted = False
        try:
            _faults.fault_point("exec.batch", desc=desc)
            it = first_attempt() if first_attempt is not None \
                else run(batch)
            first_attempt = None
            if it is not None:
                for out in it:
                    emitted = True
                    yield out
            break  # success
        except BaseException as e:  # noqa: BLE001 - classified below
            first_attempt = None
            if not is_retryable(e) or emitted:
                # output already streamed downstream: a re-run would
                # duplicate rows — escalate to the task/query rungs
                raise
            failures += 1
            caught.append(e)
            # between rungs: a cancelled query escalates OUT of the
            # ladder instead of spilling/splitting for nobody
            check_point()
            if failures == 1:
                # rung 1: release pressure, retry at full size
                _bump("spill_retries")
                _release_pressure()
                _sleep_backoff(backoff, 0)
                continue
            rows = _batch_rows(batch) if split_on else None
            if rows is not None and rows >= 2 and rows > min_rows \
                    and _depth < 32:
                # rung 2: bisect and recurse — each half re-enters the
                # ladder with its own spill/split budget
                _bump("splits")
                action = "split"
                _release_pressure()
                for half in _split_spillable(batch):
                    yield from with_split_retry(
                        run, half, desc=desc, _depth=_depth + 1)
                break
            if failures < attempts:
                _release_pressure()
                _sleep_backoff(backoff, failures - 1)
                continue
            raise
    if caught:
        _note_recovered_all(caught, f"{action}:{desc}")


def _split_spillable(batch):
    """Bisect, parking the second half as a SpillableBatch while the
    first half processes (under the very pressure that forced the
    split, holding both halves device-resident un-spillably would
    defeat the point).  Registration failures degrade to processing
    the half directly — the split itself must never make things
    worse."""
    first, second = bisect_batch(batch)
    handle = None
    try:
        from spark_rapids_tpu.memory import SpillPriorities, get_store

        handle = get_store().register(
            second, SpillPriorities.ACTIVE_ON_DECK)
        handle.unpin()
    except Exception as e:  # noqa: BLE001 — parking is best-effort
        classify(e)
        handle = None
    try:
        yield first
        if handle is not None:
            try:
                second = handle.get()
            finally:
                # close AFTER get: the entry may have spilled; get()
                # re-materialized it and the batch now owns the arrays
                handle.close()
            handle = None
        yield second
    finally:
        # abandoned between yields (first half's ladder re-raised, or
        # a LIMIT stopped consuming): the parked registration must not
        # outlive the generator in the process-global store
        if handle is not None:
            handle.close()


def guarded_pipeline(dispatch, retire, desc: str, after=None):
    """Wire a pipelined dispatch/retire stream loop into the split
    ladder: returns (dispatch_guarded, retire_guarded) for
    parallel.pipeline.pipelined.  A dispatch-time retryable failure is
    carried into the ladder as its first failure; a retire-time
    failure discards the in-flight entry and re-dispatches from the
    input batch (at the split size after a bisect).  `retire` must
    roll back its own partial side effects when it raises.  `after`,
    when given, runs once per input batch after its ladder unit
    completes (the exchange's opportunistic in-flight drain — work
    that must stay OUTSIDE the ladder because its items are their own
    retry transactions)."""
    def dispatch_guarded(batch):
        try:
            return ("ok", dispatch(batch), batch, None)
        except BaseException as e:  # noqa: BLE001 - classified below
            if not is_retryable(e):
                raise
            return ("failed", None, batch, e)

    def rerun(b):
        return retire(dispatch(b))

    def retire_guarded(tagged):
        kind, entry, batch, err = tagged
        if kind == "ok":
            gen = with_split_retry(rerun, batch, desc=desc,
                                   first_attempt=lambda: retire(entry))
        else:
            gen = with_split_retry(rerun, batch, desc=desc,
                                   initial_error=err)
        if after is None:
            return gen

        def with_after():
            yield from gen
            after()

        return with_after()

    return dispatch_guarded, retire_guarded


def note_cpu_fallback(exc: BaseException) -> None:
    """Account a query-level CPU degrade (the ladder's last rung):
    ticks the public cpu_fallbacks counter and credits an injected
    fault's site if one is in the cause chain."""
    _bump("cpu_fallbacks")
    from spark_rapids_tpu.robustness import faults as _faults

    _faults.note_recovered(exc, action="cpu_fallback")


def should_cpu_fallback(exc: BaseException) -> bool:
    """After retries: degrade the query to the CPU engine?"""
    return get_conf().get(CPU_FALLBACK_ON_DEVICE_ERROR) \
        and is_retryable(exc)
