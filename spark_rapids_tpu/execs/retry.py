"""Task failure detection and recovery.

The reference leans on two layers the TPU runtime must reproduce
itself (SURVEY.md §5.3): Spark's task re-execution (deterministic
lineage — a failed task re-runs from its inputs) and the plugin's
OOM-retry framework (ref: RmmRapidsRetryIterator.scala `withRetry` —
split-and-retry on GPU OOM after releasing what the task holds).

TPU analog:

- `classify(exc)`: device/transient failures (XLA RESOURCE_EXHAUSTED,
  remote-link UNAVAILABLE/INTERNAL hiccups, our own reservation
  failures) are RETRYABLE; everything else (assertion, user error)
  fails fast.
- `with_task_retries(fn)`: re-runs a deterministic task closure up to
  `spark.rapids.tpu.task.maxFailures` times (Spark's
  spark.task.maxFailures).  Between attempts it RELEASES pressure the
  way the reference's retry framework does: spill every unpinned
  device buffer to host and drop cached compiled-program handles that
  pin donated buffers.
- tasks that produce shuffle output buffer it locally and COMMIT
  atomically at task end (exchange.py) so a failed attempt leaves no
  partial blocks behind — the MapStatus commit protocol.

Unrecoverable DEVICE loss degrades the whole query to the CPU engine
when `spark.rapids.tpu.sql.recovery.cpuFallbackOnDeviceError` is on
(the executor-blacklisting analog: keep answering queries on a sick
host, just slower).
"""

from __future__ import annotations

import time
from typing import Callable, TypeVar

from spark_rapids_tpu.config import register, get_conf

TASK_MAX_FAILURES = register(
    "spark.rapids.tpu.task.maxFailures", 3,
    "Attempts per deterministic task before the failure propagates "
    "(the spark.task.maxFailures analog).")

CPU_FALLBACK_ON_DEVICE_ERROR = register(
    "spark.rapids.tpu.sql.recovery.cpuFallbackOnDeviceError", True,
    "After task retries are exhausted on a DEVICE/transient error, "
    "re-run the whole query on the CPU engine instead of failing it "
    "(the sick-executor blacklisting analog).")

RETRY_BACKOFF_S = register(
    "spark.rapids.tpu.task.retryBackoffSeconds", 0.2,
    "Base sleep between task attempts (doubles per attempt).")

#: substrings of device/transient error text that justify a retry.
#: Deliberately NOT "INTERNAL": compiler/unsupported-HLO failures are
#: deterministic INTERNAL errors — retrying them wastes backoff and a
#: CPU degrade would hide the bug from users and CI.
_RETRYABLE_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "out of memory",
    "OutOfMemory",
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "Socket closed",
    "connection reset",
)

T = TypeVar("T")


def is_retryable(exc: BaseException) -> bool:
    """Device / transient failure (retry may succeed) vs logic error
    (fail fast)."""
    if isinstance(exc, MemoryError):
        return True
    from spark_rapids_tpu.shuffle.net import FetchFailedError

    if isinstance(exc, FetchFailedError):
        # remote shuffle peer died mid-fetch: the retried attempt
        # re-resolves peers (the FetchFailedException contract)
        return True
    if isinstance(exc, RuntimeError):  # XlaRuntimeError subclasses it
        text = str(exc)
        return any(m in text for m in _RETRYABLE_MARKERS)
    return False


def _release_pressure() -> None:
    """Free what this process can before a retry attempt — the
    spill-everything step of the reference's retry framework."""
    try:
        from spark_rapids_tpu.memory import get_store

        get_store().spill_all_unpinned()
    except Exception:
        pass
    import gc

    gc.collect()


def with_task_retries(fn: Callable[[], T], desc: str = "task") -> T:
    """Run a deterministic task closure with device-error retries.
    The closure must be safe to re-run from scratch (lineage: pure
    function of its exec-tree inputs)."""
    conf = get_conf()
    attempts = max(1, conf.get(TASK_MAX_FAILURES))
    backoff = conf.get(RETRY_BACKOFF_S)
    last: BaseException | None = None
    for attempt in range(attempts):
        try:
            return fn()
        except BaseException as e:  # noqa: BLE001 - classified below
            if not is_retryable(e) or attempt == attempts - 1:
                raise
            last = e
            _release_pressure()
            time.sleep(backoff * (2 ** attempt))
    raise last  # unreachable; keeps type checkers honest


def should_cpu_fallback(exc: BaseException) -> bool:
    """After retries: degrade the query to the CPU engine?"""
    return get_conf().get(CPU_FALLBACK_ON_DEVICE_ERROR) \
        and is_retryable(exc)
