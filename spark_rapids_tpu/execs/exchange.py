"""Shuffle exchange exec.

Counterpart of GpuShuffleExchangeExecBase (ref: sql-plugin/.../sql/
rapids/execution/GpuShuffleExchangeExec.scala:80,167-270): the map stage
partitions every child batch (murmur3-pmod on device), writes the slices
to the in-process shuffle manager (device-resident, spillable at
shuffle-output priority), and reduce partitions read their blocks back.
Map tasks (one per child partition) run on a thread pool gated by the
task semaphore — the execution model of Spark executor task slots +
GpuSemaphore.  On a multi-chip mesh the planner can instead lower an
exchange+aggregation pair to the fused collective all_to_all program in
parallel.exchange (SURVEY.md §5.8 tier-2 path)."""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.config import register, get_conf
from spark_rapids_tpu.execs.base import MetricTimer, TOTAL_TIME, TpuExec
from spark_rapids_tpu.memory import TpuSemaphore
from spark_rapids_tpu.ops.partition import (
    Partitioning,
    RoundRobinPartitioning,
    split_batch,
)
from spark_rapids_tpu.shuffle import get_shuffle_manager

SHUFFLE_PARTITIONS = register(
    "spark.rapids.tpu.sql.shuffle.partitions", 8,
    "Number of reduce partitions for shuffle exchanges (the "
    "spark.sql.shuffle.partitions analog).")
TASK_THREADS = register(
    "spark.rapids.tpu.sql.taskThreads", 4,
    "Host threads running map tasks concurrently (device work "
    "serializes on the chip; threads overlap host IO/decode).")


class TpuShuffleExchangeExec(TpuExec):
    def __init__(self, partitioning: Partitioning, child: TpuExec):
        super().__init__(child)
        self.partitioning = partitioning.bind(child.schema)
        self._map_done = False
        self._map_lock = threading.Lock()
        self._shuffle_id = None
        self._pid_fns: dict = {}
        self._pid_lock = threading.Lock()

    @property
    def schema(self) -> T.Schema:
        return self.children[0].schema

    @property
    def num_partitions(self) -> int:
        return self.partitioning.num_partitions

    @property
    def output_partitioning(self):
        return self.partitioning

    def node_desc(self) -> str:
        return f"TpuShuffleExchangeExec {self.partitioning.describe()}"

    def additional_metrics(self):
        return [("shuffleWriteRows", "ESSENTIAL"),
                ("mapTasks", "MODERATE")]

    # -- map stage -------------------------------------------------------- #

    def _run_map_task(self, child_part: int) -> None:
        from spark_rapids_tpu.execs.retry import with_task_retries

        with_task_retries(lambda: self._map_task_attempt(child_part),
                          desc=f"map task {child_part}")
        self.metrics["mapTasks"].add(1)

    def _map_task_attempt(self, child_part: int) -> None:
        """One attempt of a deterministic map task.  Output batches
        register with the spill store immediately (spillable under
        pressure) but publish to the shuffle manager only when the
        whole attempt COMMITS — a failed attempt closes its handles
        and leaves no partial blocks (MapStatus commit protocol; the
        retry wrapper then re-runs from lineage)."""
        sem = TpuSemaphore.get()
        task_id = threading.get_ident() ^ (child_part << 20)
        manager = get_shuffle_manager()
        n = self.num_partitions
        part = self.partitioning
        pid_fn = None
        if n > 1:  # single destination never reads partition ids
            key = 0
            if isinstance(part, RoundRobinPartitioning):
                # offset per map task so output stays balanced (the
                # reference randomizes the start position per task)
                key = child_part % n
                part = RoundRobinPartitioning(n, start=key)
            with self._pid_lock:
                pid_fn = self._pid_fns.get(key)
                if pid_fn is None:
                    from spark_rapids_tpu.execs.jit_cache import (
                        cached_jit,
                        exprs_key,
                    )

                    ck = ("part", type(part).__name__, part.num_partitions,
                          getattr(part, "start", 0),
                          exprs_key(getattr(part, "exprs", ())))
                    pid_fn = self._pid_fns[key] = cached_jit(
                        ck, lambda: part.partition_ids,
                        op=self.name)
        from collections import deque

        from spark_rapids_tpu.columnar.column import pad_capacity
        from spark_rapids_tpu.memory import SpillPriorities, get_store
        from spark_rapids_tpu.ops.partition import (
            split_batch_dispatch,
            split_batch_finish,
        )
        from spark_rapids_tpu.parallel import pipeline as P
        from spark_rapids_tpu.parallel import speculation as SP

        store = get_store()
        pending: list[tuple[int, object, int, int]] = []
        spec_on = SP.speculation_enabled()
        #: (grouped, counts-or-None, ReadbackFuture) whose split counts
        #: ride the async harvester; finished opportunistically in
        #: stream order, drained at task end (map output order does not
        #: matter, only the commit does).  BOUNDED: queued grouped
        #: batches are full-capacity device buffers the spill store
        #: cannot see yet (they register only once their counts
        #: arrive), so past the bound the head is finished BLOCKING —
        #: the same natural backpressure the synchronous readback gave,
        #: just `max_inflight` batches later
        inflight: deque = deque()
        max_inflight = P.stage_depth() + 1

        def dispatch(batch):
            """Async half: partition-id program + grouping sort for
            batch k+1 dispatch before batch k's count readback."""
            sem.acquire_if_necessary(task_id)
            batch = batch.with_device_num_rows()
            if pid_fn is None:
                return batch, None
            return split_batch_dispatch(batch, pid_fn(batch), n)

        def register_slices(subs) -> None:
            """Host half: register the non-empty reduce slices once the
            per-partition counts are host-side."""
            for rid, (sub, rows) in enumerate(subs):
                if rows:
                    sub = sub.shrink_to_capacity(pad_capacity(rows))
                    h = store.register(
                        sub, SpillPriorities.OUTPUT_FOR_SHUFFLE)
                    h.unpin()
                    pending.append((rid, h, h.nbytes, rows))

        from spark_rapids_tpu.execs import retry as R

        def finish_inflight(item) -> None:
            """Register the slices of one harvested batch — its own
            spill-retry transaction (slice registrations roll back, the
            cached ReadbackFuture re-resolves for free); an exhausted
            retry escalates to the whole-task rung, where the atomic
            commit protocol keeps correctness."""
            grouped, has_counts, fut = item

            def att():
                n0 = len(pending)
                try:
                    v = fut.result()
                    if has_counts:
                        register_slices(
                            (sub, sub.num_rows) for sub in
                            split_batch_finish(grouped, v, n))
                    else:
                        register_slices([(grouped, int(v))])
                except BaseException:
                    for _rid, h, _b, _r in pending[n0:]:
                        h.close()
                    del pending[n0:]
                    raise

            R.run_with_oom_retry(att, desc="exchange.finish")

        def finish_entry(entry):
            """Sizing half for one dispatched batch — the split-retry
            unit's tail.  With speculation on, the count readback is
            HARVESTED asynchronously: the map loop keeps dispatching
            while the harvester pulls counts, and slices register as
            their counts arrive (zero blocking syncs in steady state).
            Off, it is the one blocking batched readback per input
            batch, as before.  Rolls back its own slice registrations
            (and its own in-flight entry) on failure so the ladder can
            re-run the batch — at the split size after a bisect —
            without duplicating reduce blocks."""
            grouped, counts = entry
            n0 = len(pending)
            own = None
            try:
                if spec_on:
                    fut = P.device_read_async(
                        counts if counts is not None
                        else grouped.num_rows,
                        tag="exchange.split")
                    own = (grouped, counts is not None, fut)
                    inflight.append(own)
                elif counts is None:
                    rows = P.device_read_int(grouped.num_rows,
                                             tag="exchange.split")
                    register_slices([(grouped, rows)])
                else:
                    counts_np = P.device_read(counts,
                                              tag="exchange.split")
                    register_slices(
                        (sub, sub.num_rows) for sub in
                        split_batch_finish(grouped, counts_np, n))
            except BaseException:
                if own is not None:
                    try:
                        inflight.remove(own)
                    except ValueError:
                        pass  # already drained (its slices roll back)
                for _rid, h, _b, _r in pending[n0:]:
                    h.close()
                del pending[n0:]
                raise
            return ()

        def drain_opportunistic():
            # opportunistic in-flight drain OUTSIDE the ladder: each
            # harvested item is its own retry transaction above
            while inflight and (inflight[0][2].done()
                                or len(inflight) > max_inflight):
                finish_inflight(inflight.popleft())

        dispatch_guarded, retire_guarded = R.guarded_pipeline(
            dispatch, finish_entry, desc="exchange.map",
            after=drain_opportunistic)

        try:
            for _ in P.pipelined(
                    self.children[0].execute_partition(child_part),
                    dispatch_guarded, retire_guarded,
                    tag="exchange.map"):
                pass
            while inflight:
                finish_inflight(inflight.popleft())
        except BaseException:
            for _rid, h, _b, _r in pending:
                h.close()
            raise
        finally:
            sem.release_if_necessary(task_id)
        try:
            manager.commit_task(self._shuffle_id, pending)
        except BaseException:
            for _rid, h, _b, _r in pending:
                h.close()
            raise
        for _rid, _h, _b, rows in pending:
            self.metrics["shuffleWriteRows"].add(rows)

    def _ensure_map_stage(self) -> None:
        from spark_rapids_tpu.ops.partition import RangePartitioning

        with self._map_lock:
            if self._map_done:
                return
            self._shuffle_id = get_shuffle_manager().new_shuffle_id()
            n_tasks = self.children[0].num_partitions
            threads = min(get_conf().get(TASK_THREADS), max(n_tasks, 1))
            with MetricTimer(self.metrics[TOTAL_TIME], op=self.name):
                if isinstance(self.partitioning, RangePartitioning):
                    self._run_range_map_stage(threads)
                else:
                    self._run_tasks(self._run_map_task, n_tasks, threads)
            self._map_done = True

    # -- range partitioning: two-pass map stage -------------------------- #
    # Bounds must exist before any batch can be split, and bounds come
    # from a global sample — so pass 1 streams the child into spillable
    # storage while sampling keys (ref: GpuRangePartitioner.sketch), and
    # pass 2 splits the parked batches against the chosen bounds
    # (ref: determineBounds + the device upper-bound search :167).

    def _run_range_map_stage(self, threads: int) -> None:
        import dataclasses as _dc

        import numpy as np

        from spark_rapids_tpu.execs.jit_cache import cached_jit, exprs_key
        from spark_rapids_tpu.execs.sort import SORT_SAMPLE_PER_BATCH
        from spark_rapids_tpu.memory import SpillPriorities, get_store
        from spark_rapids_tpu.ops.range_partition import choose_bounds

        part = self.partitioning
        n = self.num_partitions
        n_sample = get_conf().get(SORT_SAMPLE_PER_BATCH)
        pkey = (exprs_key([k.expr for k in part.keys]),
                tuple((k.descending, k.nulls_last) for k in part.keys))
        store = get_store()
        manager = get_shuffle_manager()
        sem = TpuSemaphore.get()
        rng = np.random.default_rng(0x52414E47)
        rng_lock = threading.Lock()
        handles: list = []
        samples: list = []
        state_lock = threading.Lock()

        def pass1(child_part: int) -> None:
            from spark_rapids_tpu.execs.retry import with_task_retries

            def attempt():
                """Accumulates locally; merges into the shared state
                only on success so a retried attempt never double-adds
                samples or leaks handles."""
                task_id = threading.get_ident() ^ (child_part << 20)
                local_s: list = []
                local_h: list = []
                try:
                    for batch in self.children[0].execute_partition(
                            child_part):
                        sem.acquire_if_necessary(task_id)
                        rows = batch.concrete_num_rows()
                        if rows == 0:
                            continue
                        batch = _dc.replace(batch, num_rows=rows)
                        jit_sample = cached_jit(
                            ("rangesample", pkey, batch.capacity,
                             n_sample, repr(batch.schema)),
                            op=self.name,
                            make_fn=lambda: lambda b, p: part.key_batch(
                                b).gather(p, n_sample))
                        with rng_lock:
                            pos = rng.integers(0, rows, n_sample).astype(
                                np.int32)
                        local_s.append(
                            jit_sample(batch, jnp.asarray(pos,
                                                          jnp.int32)))
                        local_h.append(store.register(
                            batch, SpillPriorities.COALESCE_PENDING))
                except BaseException:
                    for h in local_h:
                        h.close()
                    raise
                finally:
                    sem.release_if_necessary(task_id)
                with state_lock:
                    samples.extend(local_s)
                    handles.extend(local_h)

            with_task_retries(attempt, desc=f"range pass1 {child_part}")

        n_tasks = self.children[0].num_partitions
        self._run_tasks(pass1, n_tasks, threads)
        if not handles:
            return

        k = len(samples)
        pool_live = k * n_sample
        orders = part.key_orders()

        def pool_and_bound(sample_list):
            from spark_rapids_tpu.columnar.batch import concat_batches

            pooled = concat_batches(sample_list)
            return choose_bounds(pooled, orders, n, pool_live)

        bounds = cached_jit(
            ("rangebounds", pkey, k, n_sample, n,
             tuple(s.capacity for s in samples)),
            lambda: pool_and_bound, op=self.name)(samples)

        from spark_rapids_tpu.columnar.column import pad_capacity

        def pass2(idx: int) -> None:
            from spark_rapids_tpu.execs.retry import with_task_retries

            def attempt():
                """Buffers output handles and commits atomically (same
                MapStatus protocol as the hash map task)."""
                task_id = threading.get_ident() ^ (idx << 20) ^ 0x2
                pending: list = []
                h = handles[idx]
                try:
                    batch = h.get()
                    sem.acquire_if_necessary(task_id)
                    pid_fn = cached_jit(
                        ("rangepid", pkey, n, batch.capacity,
                         repr(batch.schema)),
                        lambda: lambda b, bd:
                            part.partition_ids_with_bounds(b, bd))
                    subs = split_batch(batch, pid_fn(batch, bounds), n)
                    for rid, sub in enumerate(subs):
                        rows = sub.concrete_num_rows()
                        if rows:
                            sub = sub.shrink_to_capacity(
                                pad_capacity(rows))
                            bh = store.register(
                                sub, SpillPriorities.OUTPUT_FOR_SHUFFLE)
                            bh.unpin()
                            pending.append((rid, bh, bh.nbytes, rows))
                except BaseException:
                    for _rid, bh, _b, _r in pending:
                        bh.close()
                    h.unpin()  # input stays retryable
                    raise
                finally:
                    sem.release_if_necessary(task_id)
                try:
                    manager.commit_task(self._shuffle_id, pending)
                except BaseException:
                    for _rid, bh, _b, _r in pending:
                        bh.close()
                    h.unpin()
                    raise
                for _rid, _bh, _b, rows in pending:
                    self.metrics["shuffleWriteRows"].add(rows)

            with_task_retries(attempt, desc=f"range pass2 {idx}")
            # Close the input AFTER the retry wrapper: anything that runs
            # post-commit inside the retried closure would, on failure,
            # re-run the attempt and publish the same reduce blocks twice
            # (the commit must be the attempt's final observable effect).
            handles[idx].close()

        try:
            self._run_tasks(pass2, len(handles), threads)
        finally:
            for h in handles:
                h.close()

    def _run_tasks(self, fn, n_tasks: int, threads: int) -> None:
        if threads <= 1 or n_tasks <= 1:
            for p in range(n_tasks):
                fn(p)
            return
        # conf is THREAD-LOCAL: install the calling (session) thread's
        # snapshot on every pool thread, or each task silently reads
        # defaults (batch sizing, pipeline depth/kill-switch, chunk
        # rows) for everything executing below the exchange.  The trace
        # correlation context makes the same hop, so map-task spans
        # stay attributable to the query that dispatched them — and so
        # does the query's cancel token, so a cancelled query's map
        # tasks unwind at their own checkpoints instead of running the
        # whole map stage for nobody.
        from spark_rapids_tpu import trace as _trace
        from spark_rapids_tpu.config import get_conf, set_conf
        from spark_rapids_tpu.serving import cancel as _cancel

        conf = get_conf()
        tctx = _trace.current_context()
        ctok = _cancel.current_token()

        def run(p: int) -> None:
            set_conf(conf)
            # no op= attr here: the exec's MetricTimer span already
            # covers the map stage, and a second op-keyed span per task
            # would double-count the exchange in span_stats
            with _trace.attach_context(tctx), \
                    _cancel.attach_token(ctok), \
                    _trace.span("exchange.task", task=p):
                fn(p)

        with ThreadPoolExecutor(max_workers=threads) as pool:
            futures = [pool.submit(run, p) for p in range(n_tasks)]
            for f in futures:
                f.result()

    # -- reduce side ------------------------------------------------------ #

    def materialize_stats(self) -> list[tuple[int, int]]:
        """Run the map stage (once) and return per-reduce-partition
        (bytes, rows) — the query-stage materialization adaptive
        execution builds on (ref: ShuffleQueryStageExec.mapStats)."""
        self._ensure_map_stage()
        return get_shuffle_manager().partition_stats(
            self._shuffle_id, self.num_partitions)

    def block_counts(self) -> list[int]:
        """Committed blocks per reduce partition (map stage must have
        materialized; callers go through materialize_stats first)."""
        self._ensure_map_stage()
        return get_shuffle_manager().block_counts(
            self._shuffle_id, self.num_partitions)

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        self._ensure_map_stage()
        for b in get_shuffle_manager().read(self._shuffle_id, p):
            yield self._count_output(b)

    def execute_partition_keep(self, p: int) -> Iterator[ColumnarBatch]:
        """Non-consuming variant for readers that visit a reduce
        partition more than once (skew-split slices); blocks stay
        registered until close()/unregister."""
        self._ensure_map_stage()
        for b in get_shuffle_manager().read_keep(self._shuffle_id, p):
            yield self._count_output(b)

    def execute(self) -> Iterator[ColumnarBatch]:
        for p in range(self.num_partitions):
            yield from self.execute_partition(p)

    def close(self) -> None:
        """Drop any unread shuffle blocks (a downstream limit may abandon
        reduce partitions; without this their SpillableBatch handles stay
        registered in the process-global store forever)."""
        super().close()
        if self._shuffle_id is not None:
            get_shuffle_manager().unregister(self._shuffle_id)
            self._shuffle_id = None
            self._map_done = False
