from spark_rapids_tpu.execs.base import TpuExec, TpuMetric, FusableExec  # noqa: F401
from spark_rapids_tpu.execs.basic import (  # noqa: F401
    TpuBatchSourceExec,
    TpuFilterExec,
    TpuProjectExec,
    TpuRangeExec,
    TpuUnionExec,
)
