"""Limit execs (ref: sql-plugin/.../limit.scala GpuLocalLimitExec :123,
GpuGlobalLimitExec :128, GpuCollectLimitExec).

Single-partition streaming: truncate batches until the limit is
satisfied.  slice_prefix is a logical truncation (validity mask update),
so no data movement happens on device."""

from __future__ import annotations

from typing import Iterator

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.execs.base import TpuExec


class TpuLocalLimitExec(TpuExec):
    def __init__(self, n: int, child: TpuExec):
        super().__init__(child)
        assert n >= 0
        self.n = n

    @property
    def schema(self) -> T.Schema:
        return self.children[0].schema

    def node_desc(self) -> str:
        return f"{type(self).__name__} n={self.n}"

    def _limited(self, source) -> Iterator[ColumnarBatch]:
        remaining = self.n
        for b in source:
            if remaining <= 0:
                return
            n = b.concrete_num_rows()
            if n <= remaining:
                remaining -= n
                yield self._count_output(b)
            else:
                out = b.slice_prefix(remaining)
                out = ColumnarBatch(out.columns, remaining, out.schema)
                remaining = 0
                yield self._count_output(out)

    @property
    def num_partitions(self) -> int:
        return self.children[0].num_partitions

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        """Narrow: caps each partition at n (ref: GpuLocalLimitExec)."""
        yield from self._limited(self.children[0].execute_partition(p))


class TpuGlobalLimitExec(TpuLocalLimitExec):
    """Wide: caps the total across partitions (ref: GpuGlobalLimitExec;
    Spark runs it on a single partition after an exchange — here the
    child partitions are consumed sequentially, stopping early)."""

    @property
    def num_partitions(self) -> int:
        return 1

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        if p == 0:
            yield from self.execute()

    def execute(self) -> Iterator[ColumnarBatch]:
        yield from self._limited(self.children[0].execute())


class TpuCollectLimitExec(TpuGlobalLimitExec):
    """Collect-to-driver limit (ref: GpuCollectLimitExec): LocalLimit on
    every child partition, then a single-partition global cap.  The
    local stage prunes each partition to at most n rows BEFORE the
    cross-partition drain, so a `LIMIT 10` over a wide child never
    materializes more than n rows per partition."""

    def execute(self) -> Iterator[ColumnarBatch]:
        child = self.children[0]

        def local_then_concat():
            for p in range(child.num_partitions):
                remaining = self.n
                for b in child.execute_partition(p):
                    if remaining <= 0:
                        break
                    rows = b.concrete_num_rows()
                    if rows > remaining:
                        out = b.slice_prefix(remaining)
                        b = ColumnarBatch(out.columns, remaining,
                                          out.schema)
                        rows = remaining
                    remaining -= rows
                    yield b

        yield from self._limited(local_then_concat())
