"""Limit execs (ref: sql-plugin/.../limit.scala GpuLocalLimitExec :123,
GpuGlobalLimitExec :128, GpuCollectLimitExec).

Single-partition streaming: truncate batches until the limit is
satisfied.  slice_prefix is a logical truncation (validity mask update),
so no data movement happens on device."""

from __future__ import annotations

from typing import Iterator

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.execs.base import TpuExec


class TpuLocalLimitExec(TpuExec):
    def __init__(self, n: int, child: TpuExec):
        super().__init__(child)
        assert n >= 0
        self.n = n

    @property
    def schema(self) -> T.Schema:
        return self.children[0].schema

    def node_desc(self) -> str:
        return f"{type(self).__name__} n={self.n}"

    def execute(self) -> Iterator[ColumnarBatch]:
        remaining = self.n
        for b in self.children[0].execute():
            if remaining <= 0:
                return
            n = b.concrete_num_rows()
            if n <= remaining:
                remaining -= n
                yield self._count_output(b)
            else:
                out = b.slice_prefix(remaining)
                out = ColumnarBatch(out.columns, remaining, out.schema)
                remaining = 0
                yield self._count_output(out)


class TpuGlobalLimitExec(TpuLocalLimitExec):
    """Same mechanics per partition; the planner places it after a
    single-partition exchange the way Spark does."""
