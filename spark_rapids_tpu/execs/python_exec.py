"""Arrow python-worker exec.

Counterpart of GpuArrowEvalPythonExec / GpuMapInPandasExec (ref:
sql-plugin python exec rules + python/rapids/worker.py): each device
batch crosses to host Arrow, runs through the process-isolated worker
pool (bounded by the worker semaphore), and the declared-schema result
re-enters the device path.  The transition cost is inherent to python
UDFs on any accelerator — the reference pays the same GPU->JVM->python
round trip."""

from __future__ import annotations

import threading
from typing import Iterator

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.execs.base import MetricTimer, TOTAL_TIME, TpuExec


class TpuMapInArrowExec(TpuExec):
    def __init__(self, fn, schema: T.Schema, child: TpuExec):
        super().__init__(child)
        self.fn = fn
        self._schema = schema
        self._pool = None
        self._pool_lock = threading.Lock()

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def node_desc(self) -> str:
        name = getattr(self.fn, "__name__", "fn")
        return f"TpuMapInArrowExec [{name}]"

    def additional_metrics(self):
        return [("pythonBatches", "ESSENTIAL")]

    @property
    def num_partitions(self) -> int:
        return self.children[0].num_partitions

    def _get_pool(self):
        with self._pool_lock:
            if self._pool is None:
                from spark_rapids_tpu.python_worker import (
                    PythonWorkerPool,
                )

                self._pool = PythonWorkerPool(self.fn)
            return self._pool

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.columnar.arrow import (
            from_arrow,
            schema_to_arrow,
            to_arrow,
        )

        aschema = schema_to_arrow(self._schema)
        pool = self._get_pool()
        for b in self.children[0].execute_partition(p):
            with MetricTimer(self.metrics[TOTAL_TIME]):
                out = pool.run(to_arrow(b)).cast(aschema)
            self.metrics["pythonBatches"].add(1)
            yield self._count_output(from_arrow(out))

    def execute(self) -> Iterator[ColumnarBatch]:
        for p in range(self.num_partitions):
            yield from self.execute_partition(p)

    def close(self) -> None:
        super().close()
        with self._pool_lock:
            if self._pool is not None:
                self._pool.close()
                self._pool = None
