"""Arrow python-worker exec.

Counterpart of GpuArrowEvalPythonExec / GpuMapInPandasExec (ref:
sql-plugin python exec rules + python/rapids/worker.py): each device
batch crosses to host Arrow, runs through the process-isolated worker
pool (bounded by the worker semaphore), and the declared-schema result
re-enters the device path.  The transition cost is inherent to python
UDFs on any accelerator — the reference pays the same GPU->JVM->python
round trip."""

from __future__ import annotations

import threading
from typing import Iterator

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.execs.base import MetricTimer, TOTAL_TIME, TpuExec


class TpuMapInArrowExec(TpuExec):
    def __init__(self, fn, schema: T.Schema, child: TpuExec):
        super().__init__(child)
        self.fn = fn
        self._schema = schema
        self._pool = None
        self._pool_lock = threading.Lock()

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def node_desc(self) -> str:
        name = getattr(self.fn, "__name__", "fn")
        return f"TpuMapInArrowExec [{name}]"

    def additional_metrics(self):
        return [("pythonBatches", "ESSENTIAL")]

    @property
    def num_partitions(self) -> int:
        return self.children[0].num_partitions

    def _get_pool(self):
        with self._pool_lock:
            if self._pool is None:
                from spark_rapids_tpu.python_worker import (
                    PythonWorkerPool,
                )

                self._pool = PythonWorkerPool(self.fn)
            return self._pool

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.columnar.arrow import (
            from_arrow,
            schema_to_arrow,
            to_arrow,
        )

        aschema = schema_to_arrow(self._schema)
        pool = self._get_pool()
        for b in self.children[0].execute_partition(p):
            with MetricTimer(self.metrics[TOTAL_TIME], op=self.name):
                out = pool.run(to_arrow(b)).cast(aschema)
            self.metrics["pythonBatches"].add(1)
            yield self._count_output(from_arrow(out))

    def execute(self) -> Iterator[ColumnarBatch]:
        for p in range(self.num_partitions):
            yield from self.execute_partition(p)

    def close(self) -> None:
        super().close()
        with self._pool_lock:
            if self._pool is not None:
                self._pool.close()
                self._pool = None


# ------------------------------------------------------------------ #
# Pandas exec family (ref: sql/rapids/execution/python/* —
# GpuMapInPandasExec, GpuFlatMapGroupsInPandasExec,
# GpuAggregateInPandasExec, GpuWindowInPandasExecBase).  All ride the
# same process-isolated Arrow worker pool; pandas conversion happens
# INSIDE the worker so the parent never imports the user's frame.
# Grouped variants rely on the planner's hash exchange making reduce
# partitions key-disjoint, exactly like the reference's required
# ClusteredDistribution.
# ------------------------------------------------------------------ #


def _map_in_pandas_wrapper(tbl, fn=None, aschema=None):
    import pyarrow as pa

    out = fn(tbl.to_pandas())
    return pa.Table.from_pandas(out, schema=aschema,
                                preserve_index=False)


def _grouped_apply_wrapper(tbl, fn=None, key_names=None, aschema=None):
    """applyInPandas: fn(group frame) -> frame, concatenated."""
    import pandas as pd
    import pyarrow as pa

    df = tbl.to_pandas()
    if df.empty:
        return aschema.empty_table()
    if not key_names:  # keyless: the whole frame is one group
        groups = [df]
    else:
        groups = [g for _, g in df.groupby(key_names, dropna=False,
                                           sort=False)]
    outs = [fn(g.reset_index(drop=True)) for g in groups]
    out = pd.concat(outs, ignore_index=True) if outs else None
    if out is None or out.empty:
        return aschema.empty_table()
    return pa.Table.from_pandas(out, schema=aschema,
                                preserve_index=False)


def _grouped_agg_wrapper(tbl, aggs=None, key_names=None, aschema=None):
    """AggregateInPandas: per group, each (fn, input_col) produces one
    scalar; output = keys + scalars."""
    import pandas as pd
    import pyarrow as pa

    df = tbl.to_pandas()
    if df.empty and key_names:
        return aschema.empty_table()
    rows = []
    if not key_names:
        # keyless: ONE grand-aggregate row even over empty input (each
        # fn sees an empty Series), matching Spark's global-aggregate
        # convention
        rows.append({out_name: fn(df[in_col])
                     for out_name, fn, in_col in aggs})
        out = pd.DataFrame(rows, columns=[f.name for f in aschema])
        return pa.Table.from_pandas(out, schema=aschema,
                                    preserve_index=False)
    for key, g in df.groupby(key_names, dropna=False, sort=False):
        if not isinstance(key, tuple):
            key = (key,)
        row = dict(zip(key_names, key))
        for out_name, fn, in_col in aggs:
            row[out_name] = fn(g[in_col])
        rows.append(row)
    out = pd.DataFrame(rows, columns=[f.name for f in aschema])
    return pa.Table.from_pandas(out, schema=aschema,
                                preserve_index=False)


def _window_in_pandas_wrapper(tbl, fns=None, key_names=None,
                              aschema=None):
    """WindowInPandas, unbounded frames: fn(series) -> scalar
    broadcast to every row of its group (the frame shape
    GpuWindowInPandasExecBase serves)."""
    import pyarrow as pa

    df = tbl.to_pandas()
    if df.empty:
        return aschema.empty_table()
    for out_name, fn, in_col in fns:
        if key_names:
            df[out_name] = df.groupby(
                key_names, dropna=False)[in_col].transform(fn)
        else:
            df[out_name] = fn(df[in_col])
    return pa.Table.from_pandas(df, schema=aschema,
                                preserve_index=False)


class TpuMapInPandasExec(TpuMapInArrowExec):
    """mapInPandas (ref: GpuMapInPandasExec): the arrow exec with
    pandas conversion in the worker."""

    def __init__(self, fn, schema: T.Schema, child: TpuExec):
        import functools

        from spark_rapids_tpu.columnar.arrow import schema_to_arrow

        wrapped = functools.partial(_map_in_pandas_wrapper, fn=fn,
                                    aschema=schema_to_arrow(schema))
        super().__init__(wrapped, schema, child)
        self._user_fn = fn

    def node_desc(self) -> str:
        name = getattr(self._user_fn, "__name__", "fn")
        return f"TpuMapInPandasExec [{name}]"


class _GroupedPandasBase(TpuMapInArrowExec):
    """Shared driver for key-disjoint grouped pandas execs: each
    (hash-exchanged) partition concats to one table and makes ONE
    worker round (groups are complete within a partition)."""

    def _keyless_emits_on_empty(self) -> bool:
        """Keyless AGGREGATES emit one grand row over empty input;
        apply/map-style grouped execs emit nothing."""
        return False

    def execute_partition(self, p: int):
        from spark_rapids_tpu.columnar.arrow import (
            from_arrow,
            schema_to_arrow,
            to_arrow,
        )
        from spark_rapids_tpu.columnar.batch import concat_batches

        aschema = schema_to_arrow(self._schema)
        batches = list(self.children[0].execute_partition(p))
        if not batches:
            if p == 0 and self._keyless_emits_on_empty():
                # keyless pandas aggregate over empty input: Spark's
                # global-aggregate convention emits one row computed
                # over the empty series
                from spark_rapids_tpu.columnar.batch import ColumnarBatch

                batches = [ColumnarBatch.empty(self.children[0].schema)]
            else:
                return
        big = batches[0] if len(batches) == 1 else \
            concat_batches(batches)
        if big.concrete_num_rows() == 0 and p != 0:
            return
        with MetricTimer(self.metrics[TOTAL_TIME], op=self.name):
            out = self._get_pool().run(to_arrow(big)).cast(aschema)
        self.metrics["pythonBatches"].add(1)
        yield self._count_output(from_arrow(out))


class TpuFlatMapGroupsInPandasExec(_GroupedPandasBase):
    """applyInPandas / flatMapGroupsInPandas
    (ref: GpuFlatMapGroupsInPandasExec)."""

    def __init__(self, key_names, fn, schema: T.Schema, child: TpuExec):
        import functools

        from spark_rapids_tpu.columnar.arrow import schema_to_arrow

        wrapped = functools.partial(
            _grouped_apply_wrapper, fn=fn, key_names=list(key_names),
            aschema=schema_to_arrow(schema))
        super().__init__(wrapped, schema, child)
        self._user_fn = fn
        self.key_names = list(key_names)

    def node_desc(self) -> str:
        name = getattr(self._user_fn, "__name__", "fn")
        return (f"TpuFlatMapGroupsInPandasExec [{name}] "
                f"keys={self.key_names}")


class TpuAggregateInPandasExec(_GroupedPandasBase):
    """Pandas UDAFs per group (ref: GpuAggregateInPandasExec):
    `aggs` = [(out_name, fn(series) -> scalar, input_col)]."""

    def __init__(self, key_names, aggs, schema: T.Schema,
                 child: TpuExec):
        import functools

        from spark_rapids_tpu.columnar.arrow import schema_to_arrow

        wrapped = functools.partial(
            _grouped_agg_wrapper, aggs=list(aggs),
            key_names=list(key_names),
            aschema=schema_to_arrow(schema))
        super().__init__(wrapped, schema, child)
        self.key_names = list(key_names)
        self.aggs = list(aggs)

    def _keyless_emits_on_empty(self) -> bool:
        return not self.key_names

    def node_desc(self) -> str:
        fns = ", ".join(n for n, _, _ in self.aggs)
        return (f"TpuAggregateInPandasExec [{fns}] "
                f"keys={self.key_names}")


class TpuWindowInPandasExec(_GroupedPandasBase):
    """Pandas window UDFs over UNBOUNDED frames
    (ref: GpuWindowInPandasExecBase — the whole-partition-frame case):
    fn(series) -> scalar, broadcast to the group's rows."""

    def __init__(self, key_names, fns, schema: T.Schema,
                 child: TpuExec):
        import functools

        from spark_rapids_tpu.columnar.arrow import schema_to_arrow

        wrapped = functools.partial(
            _window_in_pandas_wrapper, fns=list(fns),
            key_names=list(key_names),
            aschema=schema_to_arrow(schema))
        super().__init__(wrapped, schema, child)
        self.key_names = list(key_names)
        self.fns = list(fns)

    def node_desc(self) -> str:
        fns = ", ".join(n for n, _, _ in self.fns)
        return (f"TpuWindowInPandasExec [{fns}] "
                f"keys={self.key_names}")


def _cogroup_wrapper(tbl, fn=None, left_keys=None, right_keys=None,
                     aschema=None, n_left_cols=None, left_names=None,
                     right_names=None):
    """flatMapCoGroupsInPandas: the exec ships BOTH co-partitioned
    sides in one table (left rows then right rows, prefixed columns);
    the worker splits at the ARROW level — slicing before to_pandas so
    null padding never degrades dtypes (int64 keys stay int64) — then
    co-groups left keys against right keys and applies
    fn(left_df, right_df)."""
    import pandas as pd
    import pyarrow as pa

    n_l = int(pa.compute.sum(
        pa.compute.equal(tbl["__side"], 0)).as_py() or 0)
    lt = tbl.slice(0, n_l).select(
        list(range(1, 1 + n_left_cols))).rename_columns(left_names)
    rt = tbl.slice(n_l).select(
        list(range(1 + n_left_cols,
                   tbl.num_columns))).rename_columns(right_names)
    left = lt.to_pandas()
    right = rt.to_pandas()
    lgroups = {k: g for k, g in left.groupby(left_keys, dropna=False,
                                             sort=False)}
    rgroups = {k: g for k, g in right.groupby(right_keys, dropna=False,
                                              sort=False)}
    outs = []
    empty_l = left.iloc[0:0]
    empty_r = right.iloc[0:0]
    for key in dict.fromkeys(list(lgroups) + list(rgroups)):
        g_l = lgroups.get(key, empty_l).reset_index(drop=True)
        g_r = rgroups.get(key, empty_r).reset_index(drop=True)
        outs.append(fn(g_l, g_r))
    out = pd.concat(outs, ignore_index=True) if outs else None
    if out is None or out.empty:
        return aschema.empty_table()
    return pa.Table.from_pandas(out, schema=aschema,
                                preserve_index=False)


class TpuFlatMapCoGroupsInPandasExec(TpuExec):
    """cogroup().applyInPandas (ref: GpuFlatMapCoGroupsInPandasExec):
    both sides hash-exchanged on their keys (co-partitioned), each
    reduce partition ships as one combined table to the worker."""

    def __init__(self, left_keys, right_keys, fn, schema: T.Schema,
                 left: TpuExec, right: TpuExec):
        super().__init__(left, right)
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.fn = fn
        self._schema = schema
        self._pool = None
        self._pool_lock = threading.Lock()

    @property
    def schema(self) -> T.Schema:
        return self._schema

    @property
    def num_partitions(self) -> int:
        return self.children[0].num_partitions

    def node_desc(self) -> str:
        name = getattr(self.fn, "__name__", "fn")
        return (f"TpuFlatMapCoGroupsInPandasExec [{name}] "
                f"keys={self.left_keys}")

    def additional_metrics(self):
        return [("pythonBatches", "ESSENTIAL")]

    def _get_pool(self):
        import functools

        from spark_rapids_tpu.columnar.arrow import schema_to_arrow

        with self._pool_lock:
            if self._pool is None:
                from spark_rapids_tpu.python_worker import (
                    PythonWorkerPool,
                )

                ls = self.children[0].schema
                rs = self.children[1].schema
                wrapped = functools.partial(
                    _cogroup_wrapper, fn=self.fn,
                    left_keys=self.left_keys,
                    right_keys=self.right_keys,
                    aschema=schema_to_arrow(self._schema),
                    n_left_cols=len(ls.fields),
                    left_names=[f.name for f in ls.fields],
                    right_names=[f.name for f in rs.fields])
                self._pool = PythonWorkerPool(wrapped)
            return self._pool

    def _combined(self, p: int):
        """One host table carrying both sides of partition p."""
        import pyarrow as pa

        from spark_rapids_tpu.columnar.arrow import (
            schema_to_arrow,
            to_arrow,
        )
        from spark_rapids_tpu.columnar.batch import concat_batches

        sides = []
        for ci in (0, 1):
            batches = list(self.children[ci].execute_partition(p))
            if batches:
                big = batches[0] if len(batches) == 1 else \
                    concat_batches(batches)
                sides.append(to_arrow(big))
            else:
                sides.append(schema_to_arrow(
                    self.children[ci].schema).empty_table())
        lt, rt = sides
        n_l, n_r = lt.num_rows, rt.num_rows
        if n_l == 0 and n_r == 0:
            return None
        import numpy as np

        side = pa.array(np.concatenate(
            [np.zeros(n_l, np.int8), np.ones(n_r, np.int8)]))
        arrays = [side]
        names = ["__side"]
        for i, f in enumerate(lt.schema):
            arrays.append(pa.concat_arrays(
                [lt.column(i).combine_chunks(),
                 pa.nulls(n_r, f.type)]))
            names.append(f"__l_{f.name}")
        for i, f in enumerate(rt.schema):
            arrays.append(pa.concat_arrays(
                [pa.nulls(n_l, f.type),
                 rt.column(i).combine_chunks()]))
            names.append(f"__r_{f.name}")
        return pa.Table.from_arrays(arrays, names)

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.columnar.arrow import (
            from_arrow,
            schema_to_arrow,
        )

        combined = self._combined(p)
        if combined is None:
            return
        aschema = schema_to_arrow(self._schema)
        with MetricTimer(self.metrics[TOTAL_TIME], op=self.name):
            out = self._get_pool().run(combined).cast(aschema)
        self.metrics["pythonBatches"].add(1)
        yield self._count_output(from_arrow(out))

    def execute(self) -> Iterator[ColumnarBatch]:
        for p in range(self.num_partitions):
            yield from self.execute_partition(p)

    def close(self) -> None:
        super().close()
        with self._pool_lock:
            if self._pool is not None:
                self._pool.close()
                self._pool = None
