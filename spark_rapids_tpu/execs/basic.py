"""Basic physical operators: source, project, filter, range, union.

TPU counterparts of the reference's basicPhysicalOperators.scala:
GpuProjectExec (:83), GpuFilterExec (:184), GpuRangeExec (:245),
GpuUnionExec (:287), GpuCoalesceExec (:408).

Project and filter are FusableExecs: a Filter(Project(Filter(...)))
pipeline executes as one jitted XLA program per batch.  Filter keeps
batches prefix-compact via ColumnarBatch.compact (stable argsort on the
keep mask) — the XLA equivalent of cudf's stream-compaction gather.
"""

from __future__ import annotations

from typing import Iterator, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column, pad_capacity
from spark_rapids_tpu.exprs.base import (
    Alias,
    EvalContext,
    Expression,
    bind_references,
)
from spark_rapids_tpu.execs.base import BatchFn, FusableExec, TpuExec


def output_field(e: Expression, i: int) -> T.Field:
    name = e.name if isinstance(e, Alias) or hasattr(e, "col_name") \
        else f"col{i}"
    if isinstance(e, Alias):
        name = e.out_name
    elif getattr(e, "col_name", ""):
        name = e.col_name  # type: ignore[attr-defined]
    return T.Field(name, e.dtype, e.nullable)


class TpuBatchSourceExec(TpuExec):
    """Leaf exec over pre-materialized device batches (test aid and the
    receiving side of exchanges)."""

    def __init__(self, batches: Sequence[ColumnarBatch], schema: T.Schema):
        super().__init__()
        self._batches = list(batches)
        self._schema = schema

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def execute(self) -> Iterator[ColumnarBatch]:
        for b in self._batches:
            yield self._count_output(b)


class TpuProjectExec(FusableExec):
    """Bind refs, eval each projection over the batch
    (ref: basicPhysicalOperators.scala:110-119 projectAndClose)."""

    def __init__(self, exprs: Sequence[Expression], child: TpuExec):
        super().__init__(child)
        self.exprs = [bind_references(e, child.schema) for e in exprs]
        self._schema = T.Schema(
            [output_field(e, i) for i, e in enumerate(self.exprs)])

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def node_desc(self) -> str:
        return f"TpuProjectExec [{', '.join(e.name for e in self.exprs)}]"

    def make_batch_fn(self) -> BatchFn:
        exprs = self.exprs
        schema = self._schema

        def fn(batch: ColumnarBatch) -> ColumnarBatch:
            ctx = EvalContext.for_batch(batch)
            cols = [e.eval(ctx) for e in exprs]
            return ColumnarBatch(cols, batch.num_rows, schema)

        return fn

    def fuse_key(self):
        from spark_rapids_tpu.execs.jit_cache import exprs_key

        return ("project", exprs_key(self.exprs), repr(self._schema))

    def fusion_exprs(self):
        return tuple(self.exprs)


class TpuFilterExec(FusableExec):
    """Eval predicate -> compact (ref: basicPhysicalOperators.scala:184,230).

    NULL predicate results drop the row (SQL WHERE semantics)."""

    def __init__(self, condition: Expression, child: TpuExec):
        super().__init__(child)
        self.condition = bind_references(condition, child.schema)

    @property
    def schema(self) -> T.Schema:
        return self.children[0].schema

    def node_desc(self) -> str:
        return f"TpuFilterExec [{self.condition!r}]"

    def make_batch_fn(self) -> BatchFn:
        cond = self.condition

        def fn(batch: ColumnarBatch) -> ColumnarBatch:
            ctx = EvalContext.for_batch(batch)
            pred = cond.eval(ctx)
            keep = pred.data.astype(bool) & pred.validity
            return batch.compact(keep)

        return fn

    def fuse_key(self):
        from spark_rapids_tpu.execs.jit_cache import expr_key

        return ("filter", expr_key(self.condition))

    def fusion_exprs(self):
        return (self.condition,)


class TpuRangeExec(TpuExec):
    """Generate a range on device (ref: basicPhysicalOperators.scala:245)."""

    def __init__(self, start: int, end: int, step: int = 1,
                 batch_rows: Optional[int] = None):
        super().__init__()
        self.start, self.end, self.step = start, end, step
        from spark_rapids_tpu.memory.device_manager import (
            effective_batch_size_rows,
        )

        self.batch_rows = batch_rows or effective_batch_size_rows()
        self._schema = T.Schema([T.Field("id", T.LONG, False)])

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def _total(self) -> int:
        return max(0, -(-(self.end - self.start) // self.step))

    @property
    def num_partitions(self) -> int:
        return max(1, -(-self._total() // self.batch_rows))

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        total = self._total()
        emitted = p * self.batch_rows
        if emitted >= total and total > 0:
            return
        n = min(self.batch_rows, total - emitted)
        if total == 0:
            n = 0
        cap = pad_capacity(n)
        base = self.start + emitted * self.step
        data = base + jnp.arange(cap, dtype=jnp.int64) * self.step
        valid = jnp.arange(cap, dtype=jnp.int32) < n
        col = Column(data, valid, T.LONG)
        yield self._count_output(ColumnarBatch([col], n, self._schema))


class TpuUnionExec(TpuExec):
    """Concatenation of children outputs (ref: GpuUnionExec,
    basicPhysicalOperators.scala:287) — streams batches through."""

    def __init__(self, *children: TpuExec):
        super().__init__(*children)

    @property
    def schema(self) -> T.Schema:
        return self.children[0].schema

    @property
    def num_partitions(self) -> int:
        return sum(c.num_partitions for c in self.children)

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        schema = self.schema
        for child in self.children:
            if p < child.num_partitions:
                for b in child.execute_partition(p):
                    # re-tag with union schema (names from first child)
                    yield self._count_output(
                        ColumnarBatch(b.columns, b.num_rows, schema))
                return
            p -= child.num_partitions


# batch coalescing moved to execs/coalesce.py (the planner-inserted
# occupancy exec with cached concat programs + retry seams); re-exported
# here because plan rules and older callers import it from this module
from spark_rapids_tpu.execs.coalesce import (  # noqa: E402,F401
    TpuCoalesceBatchesExec,
)
