"""Physical operator (exec) base classes and metrics.

TPU re-design of the reference's GpuExec
(ref: sql-plugin/.../GpuExec.scala:40-217 — doExecuteColumnar contract +
tiered GpuMetric hierarchy).

The TPU twist: execs that are pure per-batch transforms (project, filter,
...) expose `make_batch_fn()`, and `execute()` *fuses* every consecutive
fusable ancestor into ONE `jax.jit` program per pipeline — the columnar
equivalent of Spark's whole-stage codegen, and the idiomatic XLA answer to
the reference's per-operator cudf kernel launches: one compiled program per
(pipeline, capacity-bucket) with all elementwise work fused by the
compiler.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterator, Optional

import jax
import jax.numpy as jnp

from spark_rapids_tpu import trace as _trace
from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.config import METRICS_LEVEL, get_conf


class TpuMetric:
    """A named counter, levelled like the reference's ESSENTIAL/MODERATE/
    DEBUG GpuMetrics (ref: GpuExec.scala:32-160).

    Counts may be *deferred device scalars* (`add_lazy`): a filtered
    batch's row count lives on device, and forcing it per batch would put
    a host<->device round trip in every operator's hot loop.  Deferred
    counts are summed with one transfer when the metric is read, and
    flushed in bulk past a bound so a long query does not pin one tiny
    device buffer per batch."""

    __slots__ = ("name", "level", "_value", "_pending", "_lock")

    _FLUSH_AT = 1024

    def __init__(self, name: str, level: str = "MODERATE"):
        self.name = name
        self.level = level
        self._value = 0
        self._pending: list = []  # device int scalars, flushed on read
        self._lock = threading.Lock()

    def add(self, v: int) -> None:
        with self._lock:
            self._value += v

    def add_lazy(self, v) -> None:
        """Add a host int now or a device scalar at read time."""
        if isinstance(v, int):
            self.add(v)
            return
        with self._lock:
            self._pending.append(v)
            if len(self._pending) < self._FLUSH_AT:
                return
            pending, self._pending = self._pending, []
        # blocking transfer outside the lock
        import numpy as _np

        s = sum(int(_np.asarray(x).sum()) for x in jax.device_get(pending))
        with self._lock:
            self._value += s

    @property
    def value(self) -> int:
        with self._lock:
            pending, self._pending = self._pending, []
        if pending:
            import numpy as _np

            s = sum(int(_np.asarray(x).sum())
                    for x in jax.device_get(pending))
            with self._lock:
                self._value += s
        with self._lock:
            return self._value

    def __repr__(self) -> str:
        return f"{self.name}={self.value}"

    @staticmethod
    def flush_many(metrics: "Sequence[TpuMetric]") -> None:
        """Settle deferred device counts for MANY metrics with ONE
        device transfer.  Per-metric flushing costs a full link round
        trip each on tunneled backends (~100ms); a whole-tree metrics
        snapshot must pay one."""
        import numpy as _np

        grabbed: list[tuple["TpuMetric", list]] = []
        for m in metrics:
            with m._lock:
                if m._pending:
                    grabbed.append((m, m._pending))
                    m._pending = []
        if not grabbed:
            return
        fetched = jax.device_get([p for _m, p in grabbed])
        for (m, _p), vals in zip(grabbed, fetched):
            s = sum(int(_np.asarray(x).sum()) for x in vals)
            with m._lock:
                m._value += s


METRICS_DEVICE_SYNC = None  # registered lazily to avoid an import cycle


def _device_sync_enabled() -> bool:
    global METRICS_DEVICE_SYNC
    if METRICS_DEVICE_SYNC is None:
        from spark_rapids_tpu.config import register

        METRICS_DEVICE_SYNC = register(
            "spark.rapids.tpu.sql.metrics.deviceSync", True,
            "Block on the produced batch inside metric timers so "
            "totalTime measures device execution, not async dispatch. "
            "Disable to trade metric accuracy for pipeline overlap "
            "within a task.")
    return get_conf().get(METRICS_DEVICE_SYNC)


class _MetricReaper:
    """Background completion-waiter making operator timers measure device
    execution without blocking the producing pipeline: timed regions hand
    their output arrays here, and a daemon thread records
    dispatch-to-completion elapsed time into the metric.  The producing
    thread keeps dispatching (overlap preserved); the clock still stops
    only when the device work is done — the truth the reference gets from
    synchronous NVTX ranges around blocking cudf calls."""

    _instance: Optional["_MetricReaper"] = None
    _lock = threading.Lock()

    def __init__(self) -> None:
        self._q: "queue.Queue" = queue.Queue()
        self._thread = threading.Thread(
            target=self._run, name="tpu-metric-reaper", daemon=True)
        self._thread.start()

    @classmethod
    def get(cls) -> "_MetricReaper":
        with cls._lock:
            if cls._instance is None:
                cls._instance = _MetricReaper()
            return cls._instance

    def submit(self, metric: TpuMetric, t0: int, observed) -> None:
        # derive zero-row SENTINELS from the observed arrays on the
        # producing thread: the sentinel's completion implies the
        # producer program finished (data dependency + in-order device
        # execution), and the reaper exclusively owns it — polling the
        # observed arrays themselves would race the spill store's
        # .delete() (is_ready on a deleted PJRT buffer segfaults).
        # Per-leaf derivation (trace.ledger.derive_sentinels): a
        # donated fused program's output can mix live and consumed
        # leaves, and one dead leaf must not drop the whole sample
        from spark_rapids_tpu.trace.ledger import derive_sentinels

        sentinels = derive_sentinels(observed)
        # no live device leaves (host-only output, or every leaf
        # already consumed): the worker records the elapsed wall with
        # no readiness wait — the timer still ticks, like the
        # non-observing MetricTimer branch
        # correlation context crosses to the reaper thread by capture
        ctx = _trace.current_context() if _trace.TRACER.enabled else None
        self._q.put((metric, t0, sentinels, ctx))

    def flush(self) -> None:
        """Wait until every submitted region has been timed."""
        self._q.join()

    def _run(self) -> None:
        while True:
            metric, t0, sentinels, ctx = self._q.get()
            try:
                # POLL readiness instead of block_until_ready: on remote
                # PJRT backends a blocking wait from this thread
                # serializes the whole client — concurrent device_put
                # calls from task threads stall for seconds behind it
                # (measured: 4ms -> 2.5s per 24MB upload).  is_ready()
                # is a local, lock-free check; 1ms polling granularity
                # is far below any per-op time worth recording.
                w0 = time.perf_counter_ns()
                for x in sentinels:
                    while not x.is_ready():
                        time.sleep(0.001)
                now = time.perf_counter_ns()
                metric.add(now - t0)
                if _trace.TRACER.enabled:
                    with _trace.attach_context(ctx):
                        _trace.record_complete(
                            f"metric.settle.{metric.name}", w0, now - w0,
                            metric=metric.name)
            except Exception:
                pass
            finally:
                self._q.task_done()


class MetricTimer:
    """Context manager adding elapsed ns to a metric — the NVTX-with-metric
    pattern (ref: NvtxWithMetrics.scala:25-42).

    JAX dispatch is asynchronous; to make `totalTime` mean device time the
    timed region registers its output via `observe(batch)` and the elapsed
    time is recorded when the output's device work completes (measured on
    a background thread so the pipeline keeps overlapping).  Disable via
    spark.rapids.tpu.sql.metrics.deviceSync to time dispatch only.

    With `op` set (the owning exec's name) and tracing enabled, the
    timed region is also recorded as an ``exec.<op>`` span — the
    NvtxWithMetrics pairing: operators get timeline spans for free
    wherever they already time themselves."""

    def __init__(self, metric: Optional[TpuMetric],
                 op: Optional[str] = None):
        self.metric = metric
        self.op = op
        self._observed = None

    def observe(self, out):
        """Register the region's device output to be waited on."""
        self._observed = out
        return out

    def __enter__(self):
        self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        if self.op is not None and _trace.TRACER.enabled:
            # the dispatch-side interval (device settlement is the
            # reaper's metric.settle span)
            _trace.record_complete(
                f"exec.{self.op}", self.t0,
                time.perf_counter_ns() - self.t0, op=self.op)
        if self.metric is None:
            return False
        if self._observed is not None and exc[0] is None \
                and _device_sync_enabled():
            _MetricReaper.get().submit(self.metric, self.t0, self._observed)
        else:
            self.metric.add(time.perf_counter_ns() - self.t0)
        return False


# standard metric names (ref: GpuExec.scala companion constants)
NUM_OUTPUT_ROWS = "numOutputRows"
NUM_OUTPUT_BATCHES = "numOutputBatches"
TOTAL_TIME = "totalTime"
NUM_INPUT_ROWS = "numInputRows"
NUM_INPUT_BATCHES = "numInputBatches"


class TpuExec:
    """Base physical operator producing an iterator of device batches."""

    def __init__(self, *children: "TpuExec"):
        self.children: list[TpuExec] = list(children)
        self.metrics: dict[str, TpuMetric] = {}
        for name in (NUM_OUTPUT_ROWS, NUM_OUTPUT_BATCHES, TOTAL_TIME):
            self.metrics[name] = TpuMetric(name, "ESSENTIAL")
        for name, lvl in self.additional_metrics():
            self.metrics[name] = TpuMetric(name, lvl)

    # -- overridables ---------------------------------------------------- #

    @property
    def schema(self) -> T.Schema:
        raise NotImplementedError

    def additional_metrics(self) -> list[tuple[str, str]]:
        return []

    # -- partitioned execution (the Spark task-per-partition model, ref:
    # SURVEY.md §2.9).  Narrow execs propagate the child's partitioning;
    # wide execs (global sort/limit, broadcast-style join, complete
    # aggregation) consume every child partition and emit ONE.  Execs
    # must override execute() (wide) or execute_partition() (narrow). -- #

    @property
    def num_partitions(self) -> int:
        return 1

    @property
    def output_partitioning(self):
        """The data distribution this exec's output satisfies (a
        Partitioning, or None = unknown) — the planner's
        EnsureRequirements analog uses it to skip redundant exchanges."""
        return None

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        """Produce one output partition's batches."""
        assert self.num_partitions == 1, type(self).__name__
        if p == 0:
            yield from self.execute()

    def execute(self) -> Iterator[ColumnarBatch]:
        """All partitions, chained (ref: GpuExec.doExecuteColumnar)."""
        for p in range(self.num_partitions):
            yield from self.execute_partition(p)

    def close(self) -> None:
        """Release query-lifetime resources (shuffle blocks, broadcast
        batches).  Called by the query root when the plan is drained or
        abandoned; propagates down the tree."""
        for c in self.children:
            c.close()

    # -- plumbing -------------------------------------------------------- #

    @property
    def name(self) -> str:
        return type(self).__name__

    def node_desc(self) -> str:
        return self.name

    def tree_string(self, indent: int = 0) -> str:
        s = "  " * indent + "+- " + self.node_desc() + "\n"
        for c in self.children:
            s += c.tree_string(indent + 1)
        return s

    def _count_output(self, batch: ColumnarBatch) -> ColumnarBatch:
        # THE per-operator cooperative cancellation checkpoint: every
        # exec counts each output batch here, so one check covers the
        # whole tree's stream loops (serving/cancel.py; one
        # thread-local read when no token is attached)
        from spark_rapids_tpu.serving.cancel import check_point

        check_point()
        self.metrics[NUM_OUTPUT_BATCHES].add(1)
        # device-scalar row counts are deferred (summed when the metric is
        # read) — forcing them here would put a host round trip in every
        # operator's per-batch loop
        self.metrics[NUM_OUTPUT_ROWS].add_lazy(batch.num_rows)
        return batch

    def collect_metrics(self) -> dict[str, dict[str, int]]:
        _MetricReaper.get().flush()  # settle in-flight device timings
        level = get_conf().get(METRICS_LEVEL)
        rank = {"ESSENTIAL": 0, "MODERATE": 1, "DEBUG": 2}[level]
        out = {}
        for node in self._walk():
            m = {k: v.value for k, v in node.metrics.items()
                 if rank >= {"ESSENTIAL": 0, "MODERATE": 1,
                             "DEBUG": 2}[v.level]}
            out.setdefault(node.name, {}).update(m)
        return out

    def _walk(self):
        yield self
        for c in self.children:
            yield from c._walk()


BatchFn = Callable[[ColumnarBatch], ColumnarBatch]


FUSION_ENABLED = None  # registered lazily to avoid import-order churn


def _fusion_conf():
    global FUSION_ENABLED
    if FUSION_ENABLED is None:
        from spark_rapids_tpu.config import register

        FUSION_ENABLED = register(
            "spark.rapids.tpu.sql.fusion.enabled", True,
            "Whole-stage program fusion: compile consecutive fusable "
            "execs (filter/project/...), the wire decode of an "
            "encoded scan batch, and the hash aggregate's update "
            "phase into ONE XLA program per (pipeline key, capacity "
            "bucket) — the XLA analog of Spark's WholeStageCodegen "
            "(docs/fusion.md).  Off: every exec compiles and "
            "dispatches its own per-batch program and scans upload "
            "eagerly-decoded batches — the dispatch-soup baseline "
            "the fusion smoke measures against.  Results are "
            "bit-identical either way.")
    return FUSION_ENABLED


def fusion_enabled() -> bool:
    return get_conf().get(_fusion_conf())


WARM_DISPATCH_BUDGET = None  # registered lazily, like FUSION_ENABLED


def _budget_conf():
    global WARM_DISPATCH_BUDGET
    if WARM_DISPATCH_BUDGET is None:
        from spark_rapids_tpu.config import register

        WARM_DISPATCH_BUDGET = register(
            "spark.rapids.tpu.sql.fusion.warmDispatchBudget", 256,
            "Per-query WARM dispatch budget: the maximum ledger "
            "program-launch count a warm (compile-cache-hot) "
            "milestone query may pay per collect before the bench "
            "dispatch-budget gate and run_fusion_smoke fail the "
            "round.  Turns ROADMAP #2's dispatch-soup diagnosis "
            "(HC010) into a regression GATE instead of a diagnostic: "
            "un-fusing a chain or destabilizing a jit key shows up as "
            "a hard assertion, not a slow drift.  0 disables the "
            "gate.", check=lambda v: v >= 0)
    return WARM_DISPATCH_BUDGET


def warm_dispatch_budget() -> int:
    return int(get_conf().get(_budget_conf()))


#: process-global fusion activity counters (reset per bench query like
#: the pipeline/speculation/ledger stats): `chains` = fused chain
#: programs BUILT (>= 2 execs, or 1 exec + in-program wire decode);
#: `fused_dispatches` = executions of such programs;
#: `saved_dispatches` = program launches those executions did NOT pay
#: vs the unfused engine (chain length - 1, +1 when the wire decode
#: rode inside) — bench.py's q*_fusion_chains /
#: q*_fused_dispatch_savings fields.
_FUSION_LOCK = threading.Lock()
_FUSION_STATS = {"chains": 0, "fused_dispatches": 0,
                 "saved_dispatches": 0}


def record_fused_chain() -> None:
    """One fused chain planned for the current query (called by the
    planner's _plan_fusion, once per 'one program' line it reports —
    so the counter agrees with explain()'s Fusion section by
    construction)."""
    with _FUSION_LOCK:
        _FUSION_STATS["chains"] += 1


def record_fused_dispatch(n_execs: int, decode_fused: bool) -> None:
    saved = (n_execs - 1) + (1 if decode_fused else 0)
    if saved <= 0:
        return
    with _FUSION_LOCK:
        _FUSION_STATS["fused_dispatches"] += 1
        _FUSION_STATS["saved_dispatches"] += saved


def fusion_stats() -> dict:
    with _FUSION_LOCK:
        return dict(_FUSION_STATS)


def reset_fusion_stats() -> None:
    with _FUSION_LOCK:
        for k in _FUSION_STATS:
            _FUSION_STATS[k] = 0


class FusableExec(TpuExec):
    """An exec that is a pure per-batch device transform (narrow: output
    partitioning == child's).  Consecutive fusable execs compile into a
    single XLA program per batch pipeline, shared across partitions."""

    def make_batch_fn(self) -> BatchFn:
        """Return a traceable ColumnarBatch -> ColumnarBatch function."""
        raise NotImplementedError

    def fuse_key(self):
        """Structural key identifying this exec's batch fn for the global
        compile cache (None = not cacheable; the pipeline then compiles
        per exec instance)."""
        return None

    def fusion_exprs(self):
        """The expression trees this exec evaluates per batch; used to
        detect PartitionAware expressions needing partition context."""
        return ()

    #: True for execs whose output row count differs from their input's
    #: (Expand/Generate): a PartitionAware exec above one must not fuse
    #: across it — the shared row_offset would advance by INPUT rows
    #: while ids were assigned per OUTPUT row
    MULTIPLIES_ROWS = False

    @property
    def num_partitions(self) -> int:
        return self.children[0].num_partitions

    def fusion_chain(self):
        """(fns, source_node, aware, keys): the composed per-batch
        transform chain rooted here, UN-jitted — minor-first (fns[0]
        runs first).  `keys` are the per-exec fuse keys (None entries =
        uncacheable).  Lets a non-fusable CONSUMER (e.g. the hash
        aggregate's update phase) absorb this chain into its own traced
        program, so the whole scan->filter->update path is one program
        execution per batch — on the tunneled backend each execution
        pays a link round trip once any D2H fetch has occurred, so
        program count, not FLOPs, bounds small-query latency."""
        from spark_rapids_tpu.exprs.nondeterministic import (
            tree_is_partition_aware,
        )

        def is_aware(x: "FusableExec") -> bool:
            return any(tree_is_partition_aware(e)
                       for e in x.fusion_exprs())

        # walk down through fusable children, composing their batch fns;
        # stop before a row-multiplying exec if anything above it needs
        # partition context (its row_offset counts THIS chain's input).
        # With fusion disabled the chain is just this exec — every
        # operator dispatches its own program (the unfused baseline
        # the fusion smoke and the on/off digest gates compare).
        execs: list[FusableExec] = [self]
        node: TpuExec = self.children[0]
        aware = is_aware(self)
        if fusion_enabled():
            while isinstance(node, FusableExec):
                if aware and node.MULTIPLIES_ROWS:
                    break
                execs.append(node)  # type: ignore[arg-type]
                aware = aware or is_aware(node)
                node = node.children[0]
        return (list(reversed(execs)), node, aware,
                [e.fuse_key() for e in execs])

    def _fused_pipeline(self):
        cached = getattr(self, "_fused", None)
        if cached is not None:
            return cached
        chain, node, aware, keys = self.fusion_chain()
        fns: list[BatchFn] = [e.make_batch_fn() for e in chain]
        from spark_rapids_tpu.exprs.base import (
            ansi_capture,
            ansi_enabled,
            fold_ansi_flags,
        )

        ansi = ansi_enabled()
        if aware:
            from spark_rapids_tpu.exprs.base import partition_info

            def pipeline(batch: ColumnarBatch, pidx, off):
                with partition_info(pidx, off):
                    if ansi:
                        with ansi_capture() as flags:
                            for f in fns:
                                batch = f(batch)
                        return batch, fold_ansi_flags(flags)
                    for f in fns:
                        batch = f(batch)
                return batch
        else:
            def pipeline(batch: ColumnarBatch):  # type: ignore[misc]
                if ansi:
                    with ansi_capture() as flags:
                        for f in fns:
                            batch = f(batch)
                    return batch, fold_ansi_flags(flags)
                for f in fns:
                    batch = f(batch)
                return batch

        if all(k is not None for k in keys):
            from spark_rapids_tpu.execs.jit_cache import cached_jit

            jitted = cached_jit(("fused", tuple(keys), ansi),
                                lambda: pipeline, op=self.name)
        else:
            jitted = jax.jit(pipeline)
        self._fused = (jitted, node, aware, ansi, len(chain))
        return self._fused

    def _fused_pipeline_encoded(self):
        """Jitted pipeline variant whose input is a wire-form
        EncodedBatch: the decode runs inside the same program as the
        transform chain (one execution per batch).  Returns
        (jitted, donated, n_execs); with donation enabled the wire
        components are donate_argnums'd into the program — they are
        fresh per-batch uploads consumed exactly once, so XLA may
        write the decoded columns into their HBM (the driver marks
        the batch consumed via transfer.run_consuming)."""
        cached = getattr(self, "_fused_enc", None)
        if cached is not None:
            return cached
        chain, node, aware, keys = self.fusion_chain()
        fns = [e.make_batch_fn() for e in chain]
        from spark_rapids_tpu.exprs.base import (
            ansi_capture,
            ansi_enabled,
            fold_ansi_flags,
        )

        ansi = ansi_enabled()

        def pipeline(eb):
            batch = eb.decode()
            if ansi:
                with ansi_capture() as flags:
                    for f in fns:
                        batch = f(batch)
                return batch, fold_ansi_flags(flags)
            for f in fns:
                batch = f(batch)
            return batch

        donated = False
        if all(k is not None for k in keys):
            from spark_rapids_tpu.execs.jit_cache import (
                cached_jit,
                donation_enabled,
            )

            donated = donation_enabled()
            jitted = cached_jit(("fusedenc", tuple(keys), ansi),
                                lambda: pipeline, op=self.name,
                                donate=(0,))
        else:
            jitted = jax.jit(pipeline)
        self._fused_enc = (jitted, donated, len(chain))
        return self._fused_enc

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.columnar.transfer import (
            EncodedBatch,
            run_consuming,
        )
        from spark_rapids_tpu.exprs.base import raise_if_ansi_error
        from spark_rapids_tpu.trace import ledger as _ledger

        fused, node, aware, ansi, n_execs = self._fused_pipeline()
        if aware:
            pidx = jnp.asarray(p, jnp.int32)
            off = jnp.asarray(0, jnp.int64)
        for batch in node.execute_partition(p):
            if isinstance(batch, EncodedBatch):
                if aware:
                    # partition-aware chains thread (pidx, off) through
                    # a different signature; decode eagerly instead
                    batch = batch.decode_now()
                else:
                    fn_enc, donated, n_enc = \
                        self._fused_pipeline_encoded()
                    # consumed = a re-run resuming from the memoized
                    # output; no program launches, stats must not tick
                    resumed = donated and batch.consumed
                    with MetricTimer(self.metrics[TOTAL_TIME], op=self.name) as t:
                        out = run_consuming(fn_enc, batch) if donated \
                            else fn_enc(batch)
                        if ansi:
                            out, err = out
                            raise_if_ansi_error(jax.device_get(err))
                        out = t.observe(out)
                    if not resumed:
                        record_fused_dispatch(n_enc, decode_fused=True)
                    yield self._count_output(out)
                    continue
            # the promotion below hides num_rows from the ledger's
            # argument scan (device scalar); state it while host-known
            if _ledger.LEDGER.enabled and type(batch.num_rows) is int:
                _ledger.note_occupancy(batch.num_rows, batch.capacity)
            b = batch.with_device_num_rows()
            with MetricTimer(self.metrics[TOTAL_TIME], op=self.name) as t:
                if aware:
                    out = fused(b, pidx, off)
                    # row_offset advances by the INPUT batch's live rows
                    # (lazy device add; no sync)
                    off = off + jnp.asarray(b.num_rows, jnp.int64)
                else:
                    out = fused(b)
                if ansi:
                    out, err = out
                    # the one host sync ANSI mode costs: the program
                    # can't raise, so the error code is polled here
                    # (the reference pays the same via cudf's throw)
                    raise_if_ansi_error(jax.device_get(err))
                out = t.observe(out)
            record_fused_dispatch(n_execs, decode_fused=False)
            yield self._count_output(out)

    def execute(self) -> Iterator[ColumnarBatch]:
        for p in range(self.num_partitions):
            yield from self.execute_partition(p)
