"""Physical operator (exec) base classes and metrics.

TPU re-design of the reference's GpuExec
(ref: sql-plugin/.../GpuExec.scala:40-217 — doExecuteColumnar contract +
tiered GpuMetric hierarchy).

The TPU twist: execs that are pure per-batch transforms (project, filter,
...) expose `make_batch_fn()`, and `execute()` *fuses* every consecutive
fusable ancestor into ONE `jax.jit` program per pipeline — the columnar
equivalent of Spark's whole-stage codegen, and the idiomatic XLA answer to
the reference's per-operator cudf kernel launches: one compiled program per
(pipeline, capacity-bucket) with all elementwise work fused by the
compiler.
"""

from __future__ import annotations

import time
from typing import Callable, Iterator, Optional

import jax

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.config import METRICS_LEVEL, get_conf


class TpuMetric:
    """A named counter, levelled like the reference's ESSENTIAL/MODERATE/
    DEBUG GpuMetrics (ref: GpuExec.scala:32-160)."""

    __slots__ = ("name", "level", "value")

    def __init__(self, name: str, level: str = "MODERATE"):
        self.name = name
        self.level = level
        self.value = 0

    def add(self, v: int) -> None:
        self.value += v

    def __repr__(self) -> str:
        return f"{self.name}={self.value}"


class MetricTimer:
    """Context manager adding elapsed ns to a metric — the NVTX-with-metric
    pattern (ref: NvtxWithMetrics.scala:25-42)."""

    def __init__(self, metric: Optional[TpuMetric]):
        self.metric = metric

    def __enter__(self):
        self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        if self.metric is not None:
            self.metric.add(time.perf_counter_ns() - self.t0)
        return False


# standard metric names (ref: GpuExec.scala companion constants)
NUM_OUTPUT_ROWS = "numOutputRows"
NUM_OUTPUT_BATCHES = "numOutputBatches"
TOTAL_TIME = "totalTime"
NUM_INPUT_ROWS = "numInputRows"
NUM_INPUT_BATCHES = "numInputBatches"


class TpuExec:
    """Base physical operator producing an iterator of device batches."""

    def __init__(self, *children: "TpuExec"):
        self.children: list[TpuExec] = list(children)
        self.metrics: dict[str, TpuMetric] = {}
        for name in (NUM_OUTPUT_ROWS, NUM_OUTPUT_BATCHES, TOTAL_TIME):
            self.metrics[name] = TpuMetric(name, "ESSENTIAL")
        for name, lvl in self.additional_metrics():
            self.metrics[name] = TpuMetric(name, lvl)

    # -- overridables ---------------------------------------------------- #

    @property
    def schema(self) -> T.Schema:
        raise NotImplementedError

    def additional_metrics(self) -> list[tuple[str, str]]:
        return []

    # -- partitioned execution (the Spark task-per-partition model, ref:
    # SURVEY.md §2.9).  Narrow execs propagate the child's partitioning;
    # wide execs (global sort/limit, broadcast-style join, complete
    # aggregation) consume every child partition and emit ONE.  Execs
    # must override execute() (wide) or execute_partition() (narrow). -- #

    @property
    def num_partitions(self) -> int:
        return 1

    @property
    def output_partitioning(self):
        """The data distribution this exec's output satisfies (a
        Partitioning, or None = unknown) — the planner's
        EnsureRequirements analog uses it to skip redundant exchanges."""
        return None

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        """Produce one output partition's batches."""
        assert self.num_partitions == 1, type(self).__name__
        if p == 0:
            yield from self.execute()

    def execute(self) -> Iterator[ColumnarBatch]:
        """All partitions, chained (ref: GpuExec.doExecuteColumnar)."""
        for p in range(self.num_partitions):
            yield from self.execute_partition(p)

    def close(self) -> None:
        """Release query-lifetime resources (shuffle blocks, broadcast
        batches).  Called by the query root when the plan is drained or
        abandoned; propagates down the tree."""
        for c in self.children:
            c.close()

    # -- plumbing -------------------------------------------------------- #

    @property
    def name(self) -> str:
        return type(self).__name__

    def node_desc(self) -> str:
        return self.name

    def tree_string(self, indent: int = 0) -> str:
        s = "  " * indent + "+- " + self.node_desc() + "\n"
        for c in self.children:
            s += c.tree_string(indent + 1)
        return s

    def _count_output(self, batch: ColumnarBatch) -> ColumnarBatch:
        self.metrics[NUM_OUTPUT_BATCHES].add(1)
        # concrete_num_rows syncs when num_rows is a device scalar; by this
        # point the batch has already been computed, so the sync is cheap
        self.metrics[NUM_OUTPUT_ROWS].add(batch.concrete_num_rows())
        return batch

    def collect_metrics(self) -> dict[str, dict[str, int]]:
        level = get_conf().get(METRICS_LEVEL)
        rank = {"ESSENTIAL": 0, "MODERATE": 1, "DEBUG": 2}[level]
        out = {}
        for node in self._walk():
            m = {k: v.value for k, v in node.metrics.items()
                 if rank >= {"ESSENTIAL": 0, "MODERATE": 1,
                             "DEBUG": 2}[v.level]}
            out.setdefault(node.name, {}).update(m)
        return out

    def _walk(self):
        yield self
        for c in self.children:
            yield from c._walk()


BatchFn = Callable[[ColumnarBatch], ColumnarBatch]


class FusableExec(TpuExec):
    """An exec that is a pure per-batch device transform (narrow: output
    partitioning == child's).  Consecutive fusable execs compile into a
    single XLA program per batch pipeline, shared across partitions."""

    def make_batch_fn(self) -> BatchFn:
        """Return a traceable ColumnarBatch -> ColumnarBatch function."""
        raise NotImplementedError

    def fuse_key(self):
        """Structural key identifying this exec's batch fn for the global
        compile cache (None = not cacheable; the pipeline then compiles
        per exec instance)."""
        return None

    @property
    def num_partitions(self) -> int:
        return self.children[0].num_partitions

    def _fused_pipeline(self):
        cached = getattr(self, "_fused", None)
        if cached is not None:
            return cached
        # walk down through fusable children, composing their batch fns
        execs: list[FusableExec] = [self]
        node: TpuExec = self.children[0]
        while isinstance(node, FusableExec):
            execs.append(node)  # type: ignore[arg-type]
            node = node.children[0]
        fns: list[BatchFn] = [e.make_batch_fn() for e in reversed(execs)]

        def pipeline(batch: ColumnarBatch) -> ColumnarBatch:
            for f in fns:
                batch = f(batch)
            return batch

        keys = [e.fuse_key() for e in execs]
        if all(k is not None for k in keys):
            from spark_rapids_tpu.execs.jit_cache import cached_jit

            jitted = cached_jit(("fused", tuple(keys)), lambda: pipeline)
        else:
            jitted = jax.jit(pipeline)
        self._fused = (jitted, node)
        return self._fused

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        fused, node = self._fused_pipeline()
        for batch in node.execute_partition(p):
            with MetricTimer(self.metrics[TOTAL_TIME]):
                out = fused(batch.with_device_num_rows())
            yield self._count_output(out)

    def execute(self) -> Iterator[ColumnarBatch]:
        for p in range(self.num_partitions):
            yield from self.execute_partition(p)
