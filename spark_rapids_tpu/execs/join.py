"""Join execs.

TPU counterparts of GpuShuffledHashJoinBase / GpuBroadcastHashJoinExec /
GpuHashJoin (ref: sql-plugin/.../GpuShuffledHashJoinBase.scala:28,
shims/spark301/.../GpuBroadcastHashJoinExec.scala,
sql/rapids/execution/GpuHashJoin.scala:62): the build side is collected
into a single device batch (the reference requires the same,
RequireSingleBatch), then every stream batch probes it through the dense
group-id kernel in ops.join.  Output sizing mirrors JoinGatherer: one
device->host sync per stream batch reads the pair count, then a
statically-shaped expansion program (globally cached per capacity
bucket) emits the joined batch.

Three physical strategies (chosen by the planner, like GpuOverrides
choosing BroadcastHashJoin vs ShuffledHashJoin by build-side size):
- `TpuShuffledHashJoinExec` (default): wide — consume everything, one
  output partition;
- `TpuShuffledHashJoinExec(partition_wise=True)`: children are
  co-hash-partitioned exchanges; partition p joins build part p against
  stream part p (bounded memory, partition-parallel);
- `TpuBroadcastHashJoinExec`: small build side collected ONCE and shared
  across all stream partitions (the broadcast), stream stays partitioned
  — dimension tables never shuffle.

Join types: inner, left_outer, right_outer (side-swapped), full_outer,
left_semi, left_anti, cross.  Inner joins with a residual condition and
keyless conditional inner joins (nested-loop via the constant-key cross
trick) apply the condition as a post-filter; conditional outer joins
fall back to the CPU engine (as the reference falls back for cases cudf
cannot express)."""

from __future__ import annotations

import threading
from typing import Iterator, Optional, Sequence

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch, concat_batches
from spark_rapids_tpu.columnar.column import pad_capacity
from spark_rapids_tpu.execs.base import MetricTimer, TOTAL_TIME, TpuExec
from spark_rapids_tpu.exprs.base import (
    EvalContext,
    Expression,
    bind_references,
)
from spark_rapids_tpu.config import get_conf, register
from spark_rapids_tpu.ops.join import (
    expand_pairs,
    gather_joined,
    join_state,
)

JOIN_OUTPUT_CHUNK_ROWS = register(
    "spark.rapids.tpu.sql.join.outputChunkRows", 1 << 22,
    "Join output is produced in spillable chunks of at most this many "
    "rows per stream batch instead of one data-dependent gather (the "
    "JoinGatherer target-size chunking, ref: JoinGatherer.scala:55).")

JOIN_TYPES = ("inner", "left_outer", "right_outer", "full_outer",
              "left_semi", "left_anti", "cross")


def _nullable_fields(schema: T.Schema) -> list[T.Field]:
    return [T.Field(f.name, f.dtype, True) for f in schema.fields]


class _HashJoinBase(TpuExec):
    """Shared machinery: schema/keys resolution, build collection, the
    probe-expand-condition loop, full-outer unmatched emission."""

    def __init__(self, left_keys: Sequence[Expression],
                 right_keys: Sequence[Expression], join_type: str,
                 left: TpuExec, right: TpuExec,
                 condition: Optional[Expression] = None,
                 build_side: Optional[str] = None):
        super().__init__(left, right)
        assert join_type in JOIN_TYPES, join_type
        self.join_type = join_type
        if join_type == "cross" or not left_keys:
            # cross product AND keyless conditional inner joins (nested
            # loop): equi-join on a constant key — every pair shares the
            # single group, the residual condition filters
            from spark_rapids_tpu.exprs.base import Literal

            if join_type not in ("cross", "inner"):
                raise NotImplementedError(
                    "keyless joins only for inner/cross (planner falls "
                    "back otherwise)")
            left_keys = [Literal.of(1)]
            right_keys = [Literal.of(1)]
        self.left_keys = [bind_references(k, left.schema) for k in left_keys]
        self.right_keys = [bind_references(k, right.schema)
                           for k in right_keys]
        if condition is not None and join_type != "inner":
            raise NotImplementedError(
                "residual join conditions only on inner joins (planner "
                "falls back otherwise)")
        joined_schema = T.Schema(list(left.schema.fields)
                                 + list(right.schema.fields))
        self.condition = (bind_references(condition, joined_schema)
                          if condition is not None else None)

        # build = the side NOT preserved by an outer/semi/anti join;
        # inner/cross may build either side (planner picks the smaller)
        if join_type in ("inner", "cross") and build_side is not None:
            assert build_side in ("left", "right")
            self.build_is_right = build_side == "right"
        else:
            self.build_is_right = join_type != "right_outer"
        lf, rf = list(left.schema.fields), list(right.schema.fields)
        if join_type in ("left_outer", "full_outer"):
            rf = _nullable_fields(right.schema)
        if join_type in ("right_outer", "full_outer"):
            lf = _nullable_fields(left.schema)
        if join_type in ("left_semi", "left_anti"):
            self._schema = left.schema
        else:
            self._schema = T.Schema(lf + rf)

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def node_desc(self) -> str:
        ks = ", ".join(f"{l.name}={r.name}" for l, r in
                       zip(self.left_keys, self.right_keys))
        return f"{self.name} {self.join_type} [{ks}]"

    def additional_metrics(self):
        return [("buildRows", "MODERATE"), ("probeBatches", "MODERATE"),
                ("specHits", "MODERATE"), ("specOverflows", "MODERATE")]

    @property
    def _build_child(self) -> TpuExec:
        return self.children[1] if self.build_is_right else self.children[0]

    @property
    def _stream_child(self) -> TpuExec:
        return self.children[0] if self.build_is_right else self.children[1]

    # -- build collection ------------------------------------------------ #

    def _collect_batches(self, batches) -> Optional[ColumnarBatch]:
        from spark_rapids_tpu.memory import SpillPriorities, get_store

        store = get_store()
        handles = []
        try:
            for bb in batches:
                handles.append(store.register(
                    bb, SpillPriorities.JOIN_BUILD))
            if not handles:
                return None
            collected = [h.get() for h in handles]
            b = collected[0] if len(collected) == 1 \
                else concat_batches(collected)
        finally:
            for h in handles:
                h.close()
        self.metrics["buildRows"].add(b.concrete_num_rows())
        return b

    def _empty_build(self) -> ColumnarBatch:
        return ColumnarBatch.empty(self._build_child.schema)

    # -- probe machinery ------------------------------------------------- #

    def _probe(self, build: ColumnarBatch, stream: ColumnarBatch):
        """Traceable: key eval + join state (tuple of arrays)."""
        build_keys = self.right_keys if self.build_is_right else self.left_keys
        stream_keys = self.left_keys if self.build_is_right else self.right_keys
        bctx = EvalContext.for_batch(build)
        sctx = EvalContext.for_batch(stream)
        bkc = [k.eval(bctx) for k in build_keys]
        skc = [k.eval(sctx) for k in stream_keys]
        # the stream side is the preserved side for every outer variant
        jt = "left_outer" if self.join_type in (
            "left_outer", "right_outer", "full_outer") else "inner" \
            if self.join_type == "cross" else self.join_type
        st = join_state(build, stream, bkc, skc, jt)
        total = jnp.sum(st.cnt_s).astype(jnp.int32)
        return st, total

    def _expand(self, build, stream, st, total, offset, out_cap: int):
        s_idx, b_idx, pair_live, matched = expand_pairs(st, out_cap,
                                                        offset)
        num_rows = jnp.clip(
            jnp.asarray(total, jnp.int32)
            - jnp.asarray(offset, jnp.int32), 0, out_cap)
        stream_first = self.build_is_right
        return gather_joined(build, stream, s_idx, b_idx, pair_live,
                             matched, num_rows, self._schema,
                             stream_first=stream_first)

    def _cache_key(self) -> tuple:
        """Computed once per exec: the serialization is recursive and the
        hot probe loop must not re-pay it per stream batch."""
        key = getattr(self, "_ck", None)
        if key is None:
            from spark_rapids_tpu.execs.jit_cache import exprs_key

            key = self._ck = (
                "join", self.join_type, self.build_is_right,
                exprs_key(self.left_keys), exprs_key(self.right_keys),
                # the child schema split matters too: cached closures read
                # the stream/build child schemas, and two joins with the
                # same joined output but different left/right splits must
                # not share programs
                repr(self.children[0].schema), repr(self.children[1].schema),
                repr(self._schema))
        return key

    def _jit_expand(self, out_cap: int):
        """One cached jitted expansion program per output bucket (the
        JoinGatherer-chunking analog of compile caching); memoized per
        instance so the per-batch path is a dict hit."""
        cache = getattr(self, "_expand_cache", None)
        if cache is None:
            cache = self._expand_cache = {}
        fn = cache.get(out_cap)
        if fn is None:
            from functools import partial

            from spark_rapids_tpu.execs.jit_cache import cached_jit

            fn = cache[out_cap] = cached_jit(
                self._cache_key() + ("expand", out_cap),
                lambda: partial(self._expand, out_cap=out_cap),
                op=self.name)
        return fn

    @property
    def _jit_condition(self):
        fn = getattr(self, "_cond_fn", None)
        if fn is None:
            from spark_rapids_tpu.execs.jit_cache import (
                cached_jit,
                expr_key,
            )

            cond = self.condition

            def apply(batch):
                ctx = EvalContext.for_batch(batch)
                p = cond.eval(ctx)
                return batch.compact(p.data.astype(bool) & p.validity)

            fn = self._cond_fn = cached_jit(
                ("join_cond", expr_key(cond)), lambda: apply,
                op=self.name)
        return fn

    def _join_stream(self, build: Optional[ColumnarBatch],
                     stream_batches) -> Iterator[ColumnarBatch]:
        """Probe every stream batch against the build batch; for
        full_outer, finish with the unmatched build rows.

        The stream loop is SOFTWARE-PIPELINED (parallel.pipeline): the
        probe for batch k+1 is dispatched before batch k's single
        pair-count readback, so JAX's async dispatch runs probe(k+1)
        concurrently with the readback wait — the one structural
        serialization BENCH_r05 traced the Q3 deficit to (ref: the
        reference gets the same overlap from JoinGatherer's bounded
        gathers + the stream iterator's prefetch).

        With SPECULATIVE SIZING on (parallel.speculation, the default),
        even that readback leaves the critical path: the expansion for
        batch k is dispatched at the predictor's capacity bucket inside
        dispatch(k) itself — before anyone knows the true pair count —
        and the count is harvested asynchronously.  retire(k) then only
        reconciles: a hit yields the already-dispatched chunk, an
        undershoot appends continuation chunks from offset=cap (the
        expand_pairs live mask makes both safe; no rollback exists).
        Steady state runs with ZERO blocking sizing readbacks; warm-up
        batches pay the conservative sync and seed the predictor."""
        if build is None:
            if self.join_type in ("inner", "left_semi", "cross"):
                return  # empty build: no output
            build = self._empty_build()

        from spark_rapids_tpu.execs.jit_cache import cached_jit
        from spark_rapids_tpu.parallel import pipeline as P
        from spark_rapids_tpu.parallel import speculation as SP

        jit_probe = cached_jit(self._cache_key() + ("probe",),
                               lambda: self._probe, op=self.name)
        jit_semi_compact = cached_jit(
            ("semi_compact",), lambda: lambda stream, keep:
            stream.compact(keep), op=self.name)
        matched_b_acc = None
        sizes_output = self.join_type not in ("left_semi", "left_anti")
        pred = SP.predictor(self._cache_key() + ("sizing",)) \
            if sizes_output and SP.speculation_enabled() \
            and SP.tag_enabled("join.probe") else None
        chunk = get_conf().get(JOIN_OUTPUT_CHUNK_ROWS)
        chunk_cap_ceiling = pad_capacity(chunk)

        build = build.with_device_num_rows()

        def dispatch(stream):
            """Async half: probe dispatch (+ semi/anti compaction,
            which needs no readback).  With a warmed-up predictor the
            output expansion at the SPECULATED bucket is dispatched
            here too, and the true pair count goes to the async
            harvester — nothing in this batch waits on the link."""
            nonlocal matched_b_acc
            self.metrics["probeBatches"].add(1)
            out = None
            spec = None
            with MetricTimer(self.metrics[TOTAL_TIME], op=self.name) as t:
                stream = stream.with_device_num_rows()
                st, total = jit_probe(build, stream)
                if self.join_type == "full_outer":
                    m = st.matched_b
                    matched_b_acc = m if matched_b_acc is None \
                        else (matched_b_acc | m)
                if not sizes_output:
                    keep = st.matched_s if self.join_type == "left_semi" \
                        else (st.live_s & ~st.matched_s)
                    out = t.observe(jit_semi_compact(stream, keep))
                else:
                    t.observe(total)
                    cap = pred.predict(cap_ceiling=chunk_cap_ceiling) \
                        if pred is not None else None
                    if cap is not None:
                        o = self._jit_expand(cap)(
                            build, stream, st, total,
                            jnp.asarray(0, jnp.int32))
                        if self.condition is not None:
                            o = self._jit_condition(o)
                        spec = (cap, t.observe(o))
            fut = P.device_read_async(total, tag="join.probe") \
                if spec is not None else None
            return stream, st, total, out, spec, fut

        def retire(entry):
            """Reconciliation half.  Speculated batches harvest the
            (usually already-fetched) count and either yield the
            in-flight chunk (hit) or continue from offset=cap
            (undershoot).  Warm-up / speculation-off batches pay the
            one blocking readback per stream batch, as before."""
            stream, st, total, out, spec, fut = entry
            if out is not None:
                yield self._count_output(out)
                return
            if fut is not None:
                # usually free (harvested); a genuine stall on a
                # backlogged harvester must still land in this
                # operator's clock like the sync it replaced
                with MetricTimer(self.metrics[TOTAL_TIME], op=self.name):
                    n_total = int(fut.result())
            else:
                with MetricTimer(self.metrics[TOTAL_TIME], op=self.name):
                    n_total = P.device_read_int(total, tag="join.probe")
                if pred is not None:
                    SP.record_sync("join.probe")
            if pred is not None:
                # a ladder re-run of a failed batch re-dispatches and
                # observes the same count again (and may re-tick
                # specHits/specOverflows): the EWMA skew is bounded to
                # failure paths and re-observing the true count is
                # harmless, so no cross-attempt dedup is attempted
                pred.observe(n_total)
            if not n_total:
                if spec is not None:
                    # sync-free even though the chunk is discarded
                    self.metrics["specHits"].add(1)
                    SP.record_hit("join.probe", spec[0], 0)
                return
            start = 0
            if spec is not None:
                cap, o = spec
                if n_total <= cap:
                    self.metrics["specHits"].add(1)
                    SP.record_hit("join.probe", cap, n_total)
                    yield self._count_output(o)
                    return
                # undershoot: the speculated chunk covers [0, cap);
                # continuation chunks pick up from there — expand_pairs
                # is offset-windowed, so no work is redone or rolled
                # back
                self.metrics["specOverflows"].add(1)
                SP.record_overflow("join.probe", cap, n_total)
                yield self._count_output(o)
                start = cap
            out_cap = pad_capacity(min(n_total - start, chunk))
            # target-size chunks, spillable between yields (ref:
            # JoinGatherer.scala:55,138 — output in bounded gathers,
            # never one giant batch).  Each chunk's compute gets its
            # own timed region so consumer time between yields never
            # lands in this operator's clock.
            for off in range(start, n_total, out_cap):
                with MetricTimer(self.metrics[TOTAL_TIME], op=self.name):
                    o = self._jit_expand(out_cap)(
                        build, stream, st, total,
                        jnp.asarray(off, jnp.int32))
                    if self.condition is not None:
                        o = self._jit_condition(o)
                yield self._count_output(o)

        # Batch-granular OOM split-and-retry (execs/retry.py): each
        # stream batch is one ladder unit.  dispatch failures carry
        # their error into the ladder as the first failure; retire
        # failures discard the in-flight (possibly speculated) entry
        # and RE-DISPATCH from the input batch — at the split size
        # after a bisect, re-predicting through the live predictor, so
        # no stale predictor capacity leaks into the retried chunks.
        from spark_rapids_tpu.execs.retry import guarded_pipeline

        dispatch_guarded, retire_guarded = guarded_pipeline(
            dispatch, retire, desc="join.probe")
        yield from P.pipelined(stream_batches, dispatch_guarded,
                               retire_guarded, tag="join.probe")

        if self.join_type == "full_outer":
            yield from self._emit_unmatched_build(build, matched_b_acc)

    def _emit_unmatched_build(self, build: ColumnarBatch,
                              matched_b: Optional[jax.Array]):
        """Remaining full-outer rows: build rows no stream batch matched,
        with NULLs for the stream side."""
        if matched_b is None:
            matched_b = jnp.zeros((build.capacity,), bool)

        def unmatched(build, matched_b):
            keep = build.row_mask() & ~matched_b
            compacted = build.compact(keep)
            stream_schema = self._stream_child.schema
            null_cols = []
            from spark_rapids_tpu.exprs.base import Literal

            ctx = EvalContext.for_batch(compacted)
            dead = jnp.zeros((compacted.capacity,), bool)
            for f in stream_schema.fields:
                lit_null = Literal.of(None, f.dtype) \
                    if not isinstance(f.dtype, T.StringType) \
                    else Literal.of(None, T.STRING)
                c = lit_null.eval(ctx)
                null_cols.append(c.with_validity(dead))
            if self.build_is_right:
                cols = null_cols + list(compacted.columns)
            else:
                cols = list(compacted.columns) + null_cols
            return ColumnarBatch(cols, compacted.num_rows, self._schema)

        from spark_rapids_tpu.execs.jit_cache import cached_jit

        out = cached_jit(self._cache_key() + ("unmatched",),
                         lambda: unmatched,
                         op=self.name)(build, matched_b)
        if out.concrete_num_rows() > 0:
            yield self._count_output(out)


class TpuRuntimeFilterBuildExec(TpuExec):
    """Streaming pass-through inserted by the runtime-filter planner
    pass (plan/runtime_filter.py) on the BUILD side of an eligible
    join: every batch flows through unchanged while its join-key
    columns fold into device-resident Bloom bits + min/max
    accumulators; when the last partition drains, the finished filter
    is fetched once (a few KB) and published to the probe side's
    scans.

    Sits either directly under the join (wide/broadcast shapes — the
    join collects build before streaming probe) or under the build
    exchange (partition-wise/adaptive shapes — the map stage drains the
    whole build input before the probe stage materializes, with
    execs/adaptive.py ordering build-before-probe).  Per-batch updates
    are async device dispatches; the one blocking readback happens at
    finalize, through the sanctioned pipeline API."""

    def __init__(self, child: TpuExec, entries):
        super().__init__(child)
        #: [(bound key Expression, RuntimeFilter)]
        self.entries = list(entries)
        self._lock = threading.Lock()
        self._acc = None  # merged per-filter device states
        self._parts_done: set = set()
        self._published = False

    @property
    def schema(self) -> T.Schema:
        return self.children[0].schema

    @property
    def num_partitions(self) -> int:
        return self.children[0].num_partitions

    @property
    def output_partitioning(self):
        return self.children[0].output_partitioning

    def node_desc(self) -> str:
        ks = ", ".join(rf.describe() for _k, rf in self.entries)
        return f"{self.name} [{ks}]"

    def additional_metrics(self):
        return [("rfBuildTime", "ESSENTIAL"), ("rfKeys", "MODERATE")]

    def _jit_update(self):
        fn = getattr(self, "_update_fn", None)
        if fn is None:
            from spark_rapids_tpu.execs.jit_cache import (
                cached_jit,
                exprs_key,
            )
            from spark_rapids_tpu.plan import runtime_filter as RF

            entries = self.entries
            specs = tuple((rf.n_bits, rf.n_hashes, rf.is64, rf.use_bloom)
                          for _k, rf in entries)

            def update(states, batch):
                ctx = EvalContext.for_batch(batch)
                live = batch.row_mask()
                out = []
                for (key, rf), st in zip(entries, states):
                    col = key.eval(ctx)
                    contrib = live & col.validity
                    out.append(RF.device_update(
                        st, col, contrib, rf.n_bits, rf.n_hashes,
                        rf.is64, rf.use_bloom))
                return tuple(out)

            fn = self._update_fn = cached_jit(
                ("rf.update", exprs_key([k for k, _ in entries]), specs,
                 repr(self.schema)), lambda: update, op=self.name)
        return fn

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.plan import runtime_filter as RF

        states = [RF.device_init_state(rf.n_bits, rf.use_bloom)
                  for _k, rf in self.entries]
        update = self._jit_update()
        for batch in self.children[0].execute_partition(p):
            from spark_rapids_tpu.columnar.transfer import EncodedBatch

            if isinstance(batch, EncodedBatch):
                # key eval needs decoded columns; the consumer above
                # still receives the original wire-form batch
                decoded = batch.decode_now()
            else:
                decoded = batch
            with MetricTimer(self.metrics[TOTAL_TIME],
                             op=self.name) as t:
                states = update(tuple(states),
                                decoded.with_device_num_rows())
                t.observe(states)
            yield self._count_output(batch)
        self._merge_and_maybe_publish(p, states)

    def _merge_and_maybe_publish(self, p: int, states) -> None:
        from spark_rapids_tpu.plan import runtime_filter as RF

        with self._lock:
            if self._published:
                return
            if self._acc is None:
                self._acc = list(states)
            else:
                self._acc = [RF.device_merge_states(a, s)
                             for a, s in zip(self._acc, states)]
            self._parts_done.add(p)
            if len(self._parts_done) < self.num_partitions:
                return
            self._published = True
            acc = self._acc
            self._acc = None
        for (_k, rf), st in zip(self.entries, acc):
            RF.finalize(rf, st)
            self.metrics["rfKeys"].add(rf.n_keys)
            self.metrics["rfBuildTime"].add(int(rf.build_ms * 1e6))

    def execute(self) -> Iterator[ColumnarBatch]:
        for p in range(self.num_partitions):
            yield from self.execute_partition(p)


class TpuShuffledHashJoinExec(_HashJoinBase):
    """partition_wise=False: wide — collect the whole build side, stream
    every partition, one output partition.  partition_wise=True: children
    are co-hash-partitioned on the join keys; partition p joins build
    part p against stream part p (ref: the exchange-fed
    GpuShuffledHashJoinExec plan shape)."""

    def __init__(self, *args, partition_wise: bool = False, **kwargs):
        super().__init__(*args, **kwargs)
        self.partition_wise = partition_wise
        if partition_wise:
            assert (self._build_child.num_partitions
                    == self._stream_child.num_partitions), \
                "partition-wise join needs co-partitioned children"

    @property
    def num_partitions(self) -> int:
        return self._stream_child.num_partitions if self.partition_wise \
            else 1

    def node_desc(self) -> str:
        pw = " partition_wise" if self.partition_wise else ""
        return super().node_desc() + pw

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        if not self.partition_wise:
            assert self.num_partitions == 1
            if p == 0:
                yield from self.execute()
            return
        build = self._collect_batches(
            self._build_child.execute_partition(p))
        yield from self._join_stream(
            build, self._stream_child.execute_partition(p))

    def execute(self) -> Iterator[ColumnarBatch]:
        if self.partition_wise:
            for p in range(self.num_partitions):
                yield from self.execute_partition(p)
            return
        build = self._collect_batches(self._build_child.execute())
        yield from self._join_stream(build, self._stream_child.execute())


class TpuBroadcastHashJoinExec(_HashJoinBase):
    """Small build side collected once and shared across all stream
    partitions — the dimension side of a star join never shuffles
    (ref: GpuBroadcastHashJoinExec; here 'broadcast' = one shared
    device-resident batch, since a single process serves every task;
    multi-host broadcast rides the exchange layer later).

    full_outer is excluded: unmatched-build emission needs matched flags
    merged across ALL stream partitions, which a streaming narrow exec
    cannot do (the planner keeps full_outer on the shuffled path).

    The collected build batch lives in the buffer store as a spillable
    entry (high BROADCAST priority, so it spills last) instead of being
    pinned un-spillably for the exec's lifetime: each stream partition
    pins it only while joining, and builds near the broadcast threshold
    times many concurrent joins stay inside the HBM budget manager."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        assert self.join_type != "full_outer", \
            "broadcast join cannot implement full_outer"
        self._build_lock = threading.Lock()
        self._build_handle = None  # Optional[SpillableBatch]
        self._build_done = False

    @property
    def num_partitions(self) -> int:
        return self._stream_child.num_partitions

    def _get_build_handle(self):
        from spark_rapids_tpu.memory import SpillPriorities, get_store

        with self._build_lock:
            if not self._build_done:
                b = self._collect_batches(self._build_child.execute())
                if b is not None:
                    self._build_handle = get_store().register(
                        b, SpillPriorities.BROADCAST)
                    self._build_handle.unpin()
                self._build_done = True
            return self._build_handle

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        h = self._get_build_handle()
        build = h.get() if h is not None else None
        try:
            yield from self._join_stream(
                build, self._stream_child.execute_partition(p))
        finally:
            if h is not None:
                h.unpin()

    def execute(self) -> Iterator[ColumnarBatch]:
        for p in range(self.num_partitions):
            yield from self.execute_partition(p)

    def close(self) -> None:
        with self._build_lock:
            if self._build_handle is not None:
                self._build_handle.close()
                self._build_handle = None
            self._build_done = False
        super().close()
