"""Warm-start persistence: the on-disk tier under the process caches.

Steady state is a handful of fused programs per query with zero warm
jit misses (docs/fusion.md), but every process restart recompiles the
world — at fleet scale a rollout is a cold-start storm.  The reference
never pays this: cudf kernels are pre-compiled native code shipped in
the plugin jar.  The XLA analog is serialization of the compiled
artifacts themselves, and this module is the single validated store
for all three tiers (docs/warm_start.md):

- **AOT programs**: on a structural-key miss, ``execs/jit_cache``
  probes this store BEFORE tracing.  Entries are ``jax.export``
  serializations of the jitted program, one per (structural jit key x
  conf fingerprint x argument signature); restores dispatch through
  :class:`RestoredProgram` (still ledger-wrapped by the caller, so
  restored programs attribute dispatches like compiled ones), and the
  XLA persistent compilation cache is pointed at ``<dir>/xla`` on
  activation so the backend compile of a restored module is a disk
  hit too.  Fresh compiles serialize back ASYNCHRONOUSLY
  (:class:`AutoSave` captures each new argument signature off the
  critical path).
- **prepared-plan metadata**: ``serving/plan_cache`` entries rehydrate
  their template metadata from (structural plan key x conf
  fingerprint) — the lowered exec tree itself holds live closures and
  device buffers and is rebuilt, immediately hitting the AOT tier.
- **result frames**: ``serving/work_share`` result-cache entries (the
  exact Arrow-IPC frame plus the ``plan_source_digests`` stat-triple
  invalidation tokens) persist verbatim and restore lazily on first
  key probe, re-entering the BufferStore host tier.

Validation discipline — every failure mode is an HONEST MISS, never a
wrong answer: entries carry a magic prefix, a JSON header with the
payload length + sha256 checksum, and an environment stamp
(jax/jaxlib version + device fingerprint, checked for program
entries); writes go to a unique temp file then ``os.replace`` (atomic
on POSIX — a torn write or a concurrent-writer race leaves either the
old entry or a complete new one, and a truncated file fails the
checksum).  A byte-budget LRU sweep (``persist.maxBytes``, mtime
order, entries touched on hit) bounds the footprint.

Cost discipline: ``spark.rapids.tpu.persist.enabled=false`` (the
default) is ONE conf read at each probe site and nothing else — no
store object, no thread, behavior bit-identical to the non-persisting
engine (asserted by tests/test_persist.py).  tpulint SRC015 (error)
forbids raw ``open()``/``pickle`` writes of executables anywhere else
in the engine, so every disk artifact flows through this writer.
"""

from __future__ import annotations

import collections
import concurrent.futures as _cf
import hashlib
import json
import os
import tempfile
import threading
import time
import uuid
from typing import Any, Optional

from spark_rapids_tpu.config import register

PERSIST_ENABLED = register(
    "spark.rapids.tpu.persist.enabled", False,
    "Master switch for the on-disk warm-start cache "
    "(docs/warm_start.md): AOT program entries (jax.export "
    "serializations probed by the jit cache before tracing, written "
    "back asynchronously on compile), prepared-plan metadata and "
    "result-cache frames, plus the XLA persistent compilation cache "
    "pointed at <persist.dir>/xla.  Off (the default) = one conf "
    "read per probe site, dispatch pattern and results bit-identical "
    "to the non-persisting engine.  bench.py --cold-start N measures "
    "the warm-vs-empty restart cost this cache removes.")

PERSIST_DIR = register(
    "spark.rapids.tpu.persist.dir", "",
    "Root directory of the warm-start cache (programs/, plans/, "
    "results/, xla/ under it).  Empty (the default) resolves to a "
    "per-user directory under the system temp dir.  Processes "
    "sharing a dir share entries; concurrent writers are safe "
    "(unique temp file + atomic rename, checksum-validated reads).")

PERSIST_MAX_BYTES = register(
    "spark.rapids.tpu.persist.maxBytes", 512 << 20,
    "Byte budget of the warm-start cache's validated entries "
    "(programs + plans + results; the xla/ subdir is managed by "
    "jax's own compilation cache).  Past it, a least-recently-used "
    "sweep (mtime order; entries are touched on hit) deletes oldest "
    "entries after each write (docs/warm_start.md).",
    check=lambda v: v >= 0)

PERSIST_MIN_HIT_RATE = register(
    "spark.rapids.tpu.persist.health.minHitRate", 0.5,
    "HC017 (tools/history) flags a query window that probed the "
    "warm-start cache and paid real compiles while its persist hit "
    "rate sat under this floor — a cold process against a supposedly "
    "warm disk cache mostly missed: stale entries (jax/device/conf "
    "drift) or a wrong persist.dir (docs/warm_start.md).")

PERSIST_XLA_CACHE = register(
    "spark.rapids.tpu.persist.xlaCache.enabled", True,
    "Point jax's persistent XLA compilation cache at "
    "<persist.dir>/xla on activation, so the backend compilation of "
    "restored (and fresh) programs is itself a disk hit in later "
    "processes.  Process-global jax config: the first activating "
    "conf wins for the process lifetime (docs/warm_start.md).")

#: bump when the entry layout changes: old-format files read as
#: honest misses instead of parse errors
FORMAT_VERSION = 1
_MAGIC = b"TPUPERSIST1\n"
_SUFFIX = ".tpup"

#: cap on distinct argument signatures auto-saved per program key —
#: a shape-churning key (the thing program_census exists to catch)
#: must not fill the store with one entry per batch shape
MAX_SIGS_PER_KEY = 8

# ------------------------------------------------------------------ #
# Process-global counters (the `persist.*` event-log surface)
# ------------------------------------------------------------------ #

_STATS_LOCK = threading.Lock()
_STATS: "collections.Counter" = collections.Counter()

_STAT_KEYS = (
    "hits", "misses", "writes", "evictions", "errors",
    "plan_hits", "plan_writes", "result_hits", "result_writes",
    "fallback_compiles",
)


def tick(key: str, n: float = 1) -> None:
    with _STATS_LOCK:
        _STATS[key] += n


def stats() -> dict:
    """Cumulative process-wide persist counters.  ``hits``/``misses``
    count PROGRAM store probes (the cold-start hit-rate surface);
    ``deserialize_ms``/``serialize_ms`` are cumulative milliseconds
    spent restoring / exporting program entries."""
    with _STATS_LOCK:
        out = {k: _STATS.get(k, 0) for k in _STAT_KEYS}
        out["deserialize_ms"] = round(_STATS.get("deserialize_ms", 0.0), 3)
        out["serialize_ms"] = round(_STATS.get("serialize_ms", 0.0), 3)
    total = out["hits"] + out["misses"]
    out["hit_rate"] = round(out["hits"] / total, 3) if total else 0.0
    return out


def reset_stats() -> None:
    with _STATS_LOCK:
        _STATS.clear()


# ------------------------------------------------------------------ #
# Fingerprints / signatures
# ------------------------------------------------------------------ #


def device_fingerprint() -> str:
    """Stable identity of the device set a program was compiled for:
    platform + device kind + count, hashed.  A serialized executable
    restored onto different hardware must read as a miss, not a
    wrong-target deserialize."""
    try:
        import jax

        devs = [(d.platform, getattr(d, "device_kind", ""))
                for d in jax.devices()]
    except Exception:
        devs = []
    return hashlib.sha256(repr(devs).encode()).hexdigest()[:16]


def env_stamp() -> dict:
    """The validated environment stamp written into every entry header
    (docs/warm_start.md key anatomy).  Program entries check all of
    it; plan/result entries (version-agnostic JSON / Arrow IPC) check
    only the format version."""
    out = {"format": FORMAT_VERSION, "device": device_fingerprint()}
    try:
        import jax

        out["jax"] = jax.__version__
    except Exception:
        out["jax"] = ""
    try:
        import jaxlib

        out["jaxlib"] = getattr(jaxlib, "__version__", "")
    except Exception:
        out["jaxlib"] = ""
    return out


def args_signature(args: tuple, kwargs: dict
                   ) -> tuple[Optional[str], Optional[tuple]]:
    """(signature digest, aval pytree) for one call's arguments, or
    (None, None) when any leaf lacks shape/dtype (Python scalars,
    opaque objects — such calls are never persisted).  The digest
    covers the tree structure plus every leaf's (shape, dtype): the
    per-signature identity under one structural jit key, stable
    across processes because structural keys carry no addresses."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    parts: list[str] = []
    avals = []
    for x in leaves:
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is None or dtype is None:
            return None, None
        try:
            shape = tuple(int(s) for s in shape)
        except TypeError:
            return None, None
        parts.append(f"{shape}:{dtype}")
        avals.append(jax.ShapeDtypeStruct(shape, dtype))
    payload = repr(treedef) + "|" + ";".join(parts)
    sig = hashlib.sha256(payload.encode()).hexdigest()[:16]
    return sig, jax.tree_util.tree_unflatten(treedef, avals)


_EXPORT_REG_LOCK = threading.Lock()
_EXPORT_REG_DONE = False


def _ensure_export_registrations() -> None:
    """Register jax.export (de)serialization for the engine's custom
    pytree node classes (ColumnarBatch, the column hierarchy,
    EncodedBatch): exported program calling conventions embed the
    in/out pytree structure, and jax refuses unregistered node types.
    Aux data is engine-owned static metadata (schemas, dtypes, decode
    plans — plain dataclasses/tuples), round-tripped via pickle; this
    module is the one blessed pickle surface for executables (SRC015).
    Must run in BOTH the exporting and the restoring process before
    the first serialize/deserialize — both store paths call it."""
    global _EXPORT_REG_DONE
    with _EXPORT_REG_LOCK:
        if _EXPORT_REG_DONE:
            return
        import pickle

        from jax import export as _export

        from spark_rapids_tpu.columnar.batch import ColumnarBatch
        from spark_rapids_tpu.columnar.column import (
            Column,
            ListColumn,
            MapColumn,
            StringColumn,
            StructColumn,
        )
        from spark_rapids_tpu.columnar.transfer import EncodedBatch

        for cls in (ColumnarBatch, Column, StringColumn, ListColumn,
                    StructColumn, MapColumn, EncodedBatch):
            try:
                _export.register_pytree_node_serialization(
                    cls,
                    serialized_name=f"spark_rapids_tpu.{cls.__name__}",
                    serialize_auxdata=pickle.dumps,
                    deserialize_auxdata=pickle.loads)
            except ValueError:
                pass  # an earlier partial registration pass got it
        _EXPORT_REG_DONE = True


def _key_digest(key: Any) -> str:
    return hashlib.sha256(repr(key).encode()).hexdigest()[:20]


def _conf_fp(conf=None) -> str:
    from spark_rapids_tpu.config import get_conf
    from spark_rapids_tpu.eventlog import conf_fingerprint

    return conf_fingerprint(conf or get_conf())


# ------------------------------------------------------------------ #
# The validated store
# ------------------------------------------------------------------ #

_KINDS = ("programs", "plans", "results")


class PersistStore:
    """One warm-start cache directory (see module doc).  All disk
    writes flow through :meth:`_write_entry` (unique temp file +
    ``os.replace``); all reads through :meth:`_read_entry` (magic +
    header + checksum + stamp validation — any failure deletes the
    entry and reads as None)."""

    def __init__(self, root: str):
        self.root = root
        for kind in _KINDS:
            os.makedirs(os.path.join(root, kind), exist_ok=True)

    # -- low-level entry format ------------------------------------- #

    def _write_entry(self, path: str, meta: dict, payload: bytes) -> bool:
        header = {
            "stamp": env_stamp(),
            "meta": meta,
            "len": len(payload),
            "sha256": hashlib.sha256(payload).hexdigest(),
        }
        blob = _MAGIC + json.dumps(header).encode() + b"\n" + payload
        d = os.path.dirname(path)
        tmp = os.path.join(
            d, f".tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}")
        try:
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
        except OSError:
            tick("errors")
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False
        tick("writes")
        return True

    def _read_entry(self, path: str, check_env: bool
                    ) -> Optional[tuple[dict, bytes]]:
        """(meta, payload) or None — corrupt/stale/torn entries are
        deleted and read as honest misses."""
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError:
            return None
        try:
            if not blob.startswith(_MAGIC):
                raise ValueError("bad magic")
            rest = blob[len(_MAGIC):]
            nl = rest.index(b"\n")
            header = json.loads(rest[:nl])
            payload = rest[nl + 1:]
            if len(payload) != int(header["len"]):
                raise ValueError("truncated payload")
            if hashlib.sha256(payload).hexdigest() != header["sha256"]:
                raise ValueError("checksum mismatch")
            stamp = header.get("stamp") or {}
            if int(stamp.get("format", -1)) != FORMAT_VERSION:
                raise ValueError("format mismatch")
            if check_env:
                want = env_stamp()
                for k in ("jax", "jaxlib", "device"):
                    if stamp.get(k) != want[k]:
                        raise ValueError(f"stale {k} stamp")
        except (ValueError, KeyError, TypeError, json.JSONDecodeError):
            tick("errors")
            self._delete(path)
            return None
        self._touch(path)
        return header.get("meta") or {}, payload

    @staticmethod
    def _delete(path: str) -> None:
        try:
            os.unlink(path)
        except OSError:
            pass

    @staticmethod
    def _touch(path: str) -> None:
        try:
            os.utime(path)
        except OSError:
            pass

    # -- eviction / gauges ------------------------------------------ #

    def _entry_files(self) -> list[tuple[float, int, str]]:
        out: list[tuple[float, int, str]] = []
        for kind in _KINDS:
            d = os.path.join(self.root, kind)
            try:
                names = os.listdir(d)
            except OSError:
                continue
            for name in names:
                if not name.endswith(_SUFFIX):
                    continue
                p = os.path.join(d, name)
                try:
                    st = os.stat(p)
                except OSError:
                    continue
                out.append((st.st_mtime, st.st_size, p))
        return out

    def evict_over_budget(self, max_bytes: int) -> int:
        """LRU sweep by mtime (hits touch entries): delete oldest
        validated entries until the footprint fits.  Returns the
        number evicted."""
        files = sorted(self._entry_files())
        total = sum(sz for _m, sz, _p in files)
        n = 0
        for _mtime, size, path in files:
            if total <= max_bytes:
                break
            self._delete(path)
            total -= size
            n += 1
        if n:
            tick("evictions", n)
        return n

    def bytes_used(self) -> int:
        """Total on-disk footprint (validated entries + the xla/
        compilation cache) — the `persist_cache.bytes` gauge."""
        total = 0
        for dirpath, _dirs, names in os.walk(self.root):
            for name in names:
                try:
                    total += os.stat(os.path.join(dirpath, name)).st_size
                except OSError:
                    continue
        return total

    # -- programs ---------------------------------------------------- #

    def _program_path(self, key: Any, conf_fp: str, sig: str) -> str:
        return os.path.join(
            self.root, "programs",
            f"{_key_digest(key)}-{conf_fp}-{sig}{_SUFFIX}")

    def load_programs(self, key: Any, conf_fp: str) -> dict:
        """{signature -> deserialized jax.export.Exported} for every
        valid entry under (key x conf fingerprint); {} is a miss.
        Ticks `persist.hits` per restored program or one
        `persist.misses`, plus cumulative `deserialize_ms`."""
        prefix = f"{_key_digest(key)}-{conf_fp}-"
        d = os.path.join(self.root, "programs")
        try:
            names = sorted(os.listdir(d))
        except OSError:
            names = []
        out: dict = {}
        t0 = time.perf_counter()
        candidates = [n for n in names
                      if n.startswith(prefix) and n.endswith(_SUFFIX)]
        if candidates:
            _ensure_export_registrations()
        for name in candidates:
            path = os.path.join(d, name)
            rec = self._read_entry(path, check_env=True)
            if rec is None:
                continue
            meta, payload = rec
            try:
                from jax import export as _export

                exp = _export.deserialize(payload)
            except Exception:
                tick("errors")
                self._delete(path)
                continue
            sig = str(meta.get("sig", ""))
            if sig:
                out[sig] = exp
        if out:
            tick("hits", len(out))
            tick("deserialize_ms", (time.perf_counter() - t0) * 1e3)
        else:
            tick("misses")
        return out

    def save_program_async(self, key: Any, conf_fp: str, sig: str,
                           jitted_fn, avals: tuple,
                           max_bytes: int) -> None:
        """Schedule one (key x conf x signature) export+write on the
        background writer — serialize-back stays off the critical
        path.  Export failures (unexportable program, donation quirks
        on exotic backends) are swallowed into `persist.errors`: the
        query already has its answer."""
        path = self._program_path(key, conf_fp, sig)
        if os.path.exists(path):
            return
        meta = {"sig": sig, "tag": key[0] if isinstance(key, tuple)
                and key and isinstance(key[0], str) else "prog"}
        _submit(self._save_program_job, path, meta, jitted_fn, avals,
                max_bytes)

    def _save_program_job(self, path: str, meta: dict, jitted_fn,
                          avals: tuple, max_bytes: int) -> None:
        t0 = time.perf_counter()
        try:
            from jax import export as _export

            _ensure_export_registrations()
            aval_args, aval_kwargs = avals
            blob = _export.export(jitted_fn)(
                *aval_args, **aval_kwargs).serialize()
        except Exception:
            tick("errors")
            return
        if self._write_entry(path, meta, blob):
            tick("serialize_ms", (time.perf_counter() - t0) * 1e3)
            self.evict_over_budget(max_bytes)

    # -- plans ------------------------------------------------------- #

    def _plan_path(self, key: str) -> str:
        return os.path.join(self.root, "plans", f"plan-{key}{_SUFFIX}")

    def load_plan(self, key: str) -> Optional[dict]:
        rec = self._read_entry(self._plan_path(key), check_env=False)
        if rec is None:
            return None
        tick("plan_hits")
        return rec[0]

    def save_plan_async(self, key: str, meta: dict,
                        max_bytes: int) -> None:
        _submit(self._save_small_job, self._plan_path(key), meta, b"",
                max_bytes, "plan_writes")

    # -- results ----------------------------------------------------- #

    def _result_path(self, key: str) -> str:
        return os.path.join(self.root, "results", f"res-{key}{_SUFFIX}")

    def load_result(self, key: str) -> Optional[tuple[dict, bytes]]:
        """(meta, Arrow-IPC payload) or None.  Digest verification
        against the CURRENT source stat triples is the CALLER's job
        (work_share) — this layer only proves the bytes are the bytes
        that were written."""
        return self._read_entry(self._result_path(key), check_env=False)

    def save_result_async(self, key: str, meta: dict, payload: bytes,
                          max_bytes: int) -> None:
        path = self._result_path(key)
        if os.path.exists(path):
            return
        _submit(self._save_small_job, path, meta, payload, max_bytes,
                "result_writes")

    def delete_result(self, key: str) -> None:
        self._delete(self._result_path(key))

    def _save_small_job(self, path: str, meta: dict, payload: bytes,
                        max_bytes: int, stat_key: str) -> None:
        if self._write_entry(path, meta, payload):
            tick(stat_key)
            self.evict_over_budget(max_bytes)


# ------------------------------------------------------------------ #
# Activation / the background writer
# ------------------------------------------------------------------ #

_STORES_LOCK = threading.Lock()
_STORES: dict[str, PersistStore] = {}
_XLA_CACHE_DIR: Optional[str] = None  # guard: _STORES_LOCK
#: jax compilation-cache config as it stood before activation, so
#: reset_for_tests restores an outer harness's cache dir (the test
#: suite points one at a shared tmp dir) instead of clobbering it
_XLA_PREV: Optional[tuple] = None  # guard: _STORES_LOCK
_WRITER: Optional[_cf.ThreadPoolExecutor] = None  # guard: _STORES_LOCK
_PENDING: "set[_cf.Future]" = set()
_PENDING_LOCK = threading.Lock()


def _default_dir() -> str:
    who = f"{os.getuid()}" if hasattr(os, "getuid") else "user"
    return os.path.join(tempfile.gettempdir(), f"tpu-persist-{who}")


def active(conf=None) -> Optional[PersistStore]:
    """The store for the current conf, or None when persistence is
    off — the disabled path is exactly ONE conf read (the cost
    contract every probe site inherits)."""
    from spark_rapids_tpu.config import get_conf

    conf = conf or get_conf()
    if not bool(conf.get(PERSIST_ENABLED)):
        return None
    root = str(conf.get(PERSIST_DIR) or "") or _default_dir()
    root = os.path.abspath(root)
    with _STORES_LOCK:
        store = _STORES.get(root)
        if store is None:
            try:
                store = PersistStore(root)
            except OSError:
                tick("errors")
                return None
            _STORES[root] = store
            _activate_xla_cache_locked(root, conf)
    return store


def _activate_xla_cache_locked(root: str, conf) -> None:
    """Point jax's persistent compilation cache at <root>/xla (first
    activating dir wins for the process — the config is jax-global).
    Failures are non-fatal: the AOT tier still works, restored
    modules just pay a backend re-compile."""
    global _XLA_CACHE_DIR, _XLA_PREV
    if not bool(conf.get(PERSIST_XLA_CACHE)) or _XLA_CACHE_DIR:
        return
    xdir = os.path.join(root, "xla")
    try:
        os.makedirs(xdir, exist_ok=True)
        import jax

        prev = (
            getattr(jax.config, "jax_compilation_cache_dir", None),
            getattr(jax.config,
                    "jax_persistent_cache_min_compile_time_secs", 1.0),
            getattr(jax.config,
                    "jax_persistent_cache_min_entry_size_bytes", 0),
        )
        jax.config.update("jax_compilation_cache_dir", xdir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update(
            "jax_persistent_cache_min_entry_size_bytes", -1)
        _XLA_PREV = prev
        _XLA_CACHE_DIR = xdir
    except Exception:
        tick("errors")


def _submit(fn, *args) -> None:
    global _WRITER
    with _STORES_LOCK:
        if _WRITER is None:
            _WRITER = _cf.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="tpu-persist")
        writer = _WRITER
    fut = writer.submit(fn, *args)
    with _PENDING_LOCK:
        _PENDING.add(fut)
    fut.add_done_callback(_discard_pending)


def _discard_pending(fut: "_cf.Future") -> None:
    with _PENDING_LOCK:
        _PENDING.discard(fut)


def flush(timeout: float = 30.0) -> bool:
    """Drain the background writer (bench/smoke/test barrier before a
    child process probes the store).  True when everything landed."""
    with _PENDING_LOCK:
        pending = list(_PENDING)
    if not pending:
        return True
    done, not_done = _cf.wait(pending, timeout=timeout)
    return not not_done


def cache_bytes() -> int:
    """The `persist_cache.bytes` telemetry gauge: total on-disk
    footprint of every store this process activated (0 without a
    single dir walk when persistence never activated)."""
    with _STORES_LOCK:
        stores = list(_STORES.values())
    return sum(s.bytes_used() for s in stores)


def max_bytes(conf=None) -> int:
    from spark_rapids_tpu.config import get_conf

    return int((conf or get_conf()).get(PERSIST_MAX_BYTES))


def reset_for_tests() -> None:
    """Tests / bench phase boundaries: drain writes, forget activated
    stores, release the process-global XLA cache pointer (so a later
    suite member is not writing compilation-cache files into a
    deleted temp dir), zero the counters."""
    global _XLA_CACHE_DIR, _XLA_PREV
    flush(timeout=10.0)
    with _STORES_LOCK:
        _STORES.clear()
        if _XLA_CACHE_DIR is not None:
            try:
                import jax

                prev = _XLA_PREV or (None, 1.0, 0)
                jax.config.update("jax_compilation_cache_dir", prev[0])
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs",
                    prev[1])
                jax.config.update(
                    "jax_persistent_cache_min_entry_size_bytes",
                    prev[2])
            except Exception:
                pass
            _XLA_CACHE_DIR = None
            _XLA_PREV = None
    reset_stats()


# ------------------------------------------------------------------ #
# Program wrappers (used by execs/jit_cache on the miss path)
# ------------------------------------------------------------------ #


class RestoredProgram:
    """A disk-restored program: dispatches by argument signature to
    ``jax.jit(exported.call)`` artifacts (trace/compile skipped; the
    backend compile of the exported module rides the XLA persistent
    cache).  An UNSEEN signature falls back to an honest compile via
    the original ``make_fn`` — counted as a real compile
    (jit_cache.note_external_compile) and auto-saved for the next
    process.  The caller wraps the whole object with the device
    ledger, so restored programs attribute dispatches and cost bytes
    exactly like compiled ones."""

    def __init__(self, key: Any, exported: dict, make_fn, jit_kwargs,
                 store: PersistStore, conf_fp: str):
        self._key = key
        self._exported = exported          # sig -> Exported (consumed)
        self._compiled: dict = {}          # sig -> callable
        self._make_fn = make_fn
        self._jit_kwargs = dict(jit_kwargs)
        self._store = store
        self._conf_fp = conf_fp
        self._fallback = None
        self._lock = threading.Lock()

    def __call__(self, *args, **kwargs):
        sig, avals = args_signature(args, kwargs)
        fn = self._compiled.get(sig) if sig is not None else None
        if fn is None:
            fn = self._bind(sig, avals)
        return fn(*args, **kwargs)

    def lower(self, *args, **kwargs):
        """Cost-model seam (trace/ledger._capture_cost): delegate to
        the signature's bound executable so restored programs report
        flops / bytes accessed like compiled ones.  An unbound
        signature raises; the ledger records zero cost rather than
        compiling anything here."""
        sig, _ = args_signature(args, kwargs)
        fn = self._compiled.get(sig) if sig is not None else None
        if fn is None:
            raise AttributeError("lower: signature not bound")
        return fn.lower(*args, **kwargs)

    def _bind(self, sig: Optional[str], avals):
        import jax

        with self._lock:
            if sig is not None:
                fn = self._compiled.get(sig)
                if fn is not None:
                    return fn
                exp = self._exported.pop(sig, None)
                if exp is not None:
                    fn = jax.jit(exp.call)
                    self._compiled[sig] = fn
                    return fn
            # unseen (or unserializable) signature: the honest
            # compile path, once, shared across such signatures
            fn = self._fallback
            if fn is None:
                from spark_rapids_tpu.execs.jit_cache import (
                    note_external_compile,
                )

                note_external_compile()
                tick("fallback_compiles")
                fn = jax.jit(self._make_fn(), **self._jit_kwargs)
                fn = AutoSave(self._key, fn, self._store, self._conf_fp)
                self._fallback = fn
            if sig is not None:
                self._compiled[sig] = fn
            return fn


class AutoSave:
    """Serialize-back wrapper around a freshly compiled program: the
    first call per argument signature (capped at MAX_SIGS_PER_KEY)
    schedules an async ``jax.export`` + validated write, off the
    critical path.  The wrapped call itself is untouched — results
    are bit-identical with persistence on or off."""

    __slots__ = ("_key", "_fn", "_store", "_conf_fp", "_seen",
                 "_max_bytes")

    def __init__(self, key: Any, fn, store: PersistStore,
                 conf_fp: str):
        self._key = key
        self._fn = fn
        self._store = store
        self._conf_fp = conf_fp
        self._seen: set = set()
        self._max_bytes = max_bytes()

    def __getattr__(self, name):
        # non-call attribute access (the ledger cost model's .lower)
        # passes through to the jitted fn
        return getattr(object.__getattribute__(self, "_fn"), name)

    def __call__(self, *args, **kwargs):
        out = self._fn(*args, **kwargs)
        if len(self._seen) < MAX_SIGS_PER_KEY:
            sig, avals = args_signature(args, kwargs)
            if sig is not None and sig not in self._seen:
                self._seen.add(sig)
                self._store.save_program_async(
                    self._key, self._conf_fp, sig, self._fn, avals,
                    self._max_bytes)
        return out
