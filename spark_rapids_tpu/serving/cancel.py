"""Cooperative query cancellation: tokens, deadlines, and the
per-tenant circuit breaker.

The serving tier could admit, share, and fuse work for N tenants
(PR8/PR12) but never STOP any of it: once admitted, a query ran to
completion or process death.  The reference leans on Spark's
task-kill/stage-abort machinery for exactly this (SURVEY §2.9 task
model); this module is the TPU engine's analog, built cooperative
(the engine's blocking seams poll, nothing is killed mid-dispatch —
a TPU program cannot be preempted anyway, so the useful granularity
is *between* batches and *inside* waits):

- :class:`CancelToken` — one per in-flight query, carried across
  thread hops with the same capture/attach discipline the tracer's
  correlation context uses (:func:`current_token` on the dispatching
  side, :func:`attach_token` on the receiving thread), so prefetch
  stage producers, the exchange map pool and shared-scan
  subscribers all observe the same token.  Three trigger sources:
  explicit ``session.cancel()`` / ``PreparedQuery.cancel()``, a
  per-query deadline (``spark.rapids.tpu.serving.deadlineMs``,
  enforced from the admission queue onward so a query whose deadline
  expires while queued is shed with ZERO device work), and the
  fault seam below.
- :func:`check_point` — THE cooperative checkpoint, planted at the
  engine's stream seams (per-operator batch counting, the pipeline
  channel waits, the admission wait, retry-ladder re-attempts,
  shuffle fetch retries, shared-scan subscriber waits, the streaming
  result fetch).  No token attached = one thread-local read.  It is
  also the ``cancel.check`` fault-injection site: an armed schedule
  (robustness/faults.py) converts an injected hit into a REAL
  cancellation of the current token, so chaos runs exercise the
  production unwind path deterministically.
- a per-tenant **circuit breaker**
  (``serving.breaker.{failureThreshold,cooldownMs}``): a tenant whose
  admitted queries keep dying (crash or deadline — the poison-query
  signature) is quarantined at admission (:class:`TenantQuarantined`)
  for the cooldown instead of re-entering the WFQ queue forever;
  after the cooldown ONE probe query is admitted (half-open) and its
  outcome closes or re-opens the breaker.  Explicit user cancels are
  breaker-neutral.  State machine: closed -> (failureThreshold
  consecutive failures) open -> (cooldownMs) half-open -> closed on
  probe success / open on probe failure.

Unwind contract (tested by the cancellation-storm acceptance test):
a :class:`QueryCancelled` raised at any checkpoint rides the SAME
teardown paths a failure does — admission entries removed and slots
released, pipeline producers closed and joined, shared-scan
leaderships aborted (subscribers fall back), exec trees closed
(shuffle blocks dropped, SpillableBatches freed), semaphore permits
released — and the event log records the query with
``engine="cancelled"`` / ``"deadline_exceeded"``.  A cancelled query
is an observable outcome, not a leak; the post-storm process gauges
(permits, store bytes, stage threads, in-flight shares) return to
baseline exactly.

Cost discipline: ``serving.cancellation.enabled=false`` makes
:func:`begin` a single conf read returning None, every checkpoint one
thread-local read, and the engine's plan/readback pattern bit-identical
to the uncancellable engine (asserted in tests/test_cancellation.py).
Docs: docs/robustness.md (cancellation semantics), docs/serving.md
(deadline + breaker operations).
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Iterator, Optional

from spark_rapids_tpu import trace as _tr
from spark_rapids_tpu.config import register
from spark_rapids_tpu.robustness.lock_tracker import tracked_lock
from spark_rapids_tpu.serving.scheduler import AdmissionRejected

CANCEL_ENABLED = register(
    "spark.rapids.tpu.serving.cancellation.enabled", True,
    "Arm the cooperative cancellation substrate: every collect carries "
    "a CancelToken honoring session.cancel()/PreparedQuery.cancel(), "
    "the per-query deadline (serving.deadlineMs) and the per-tenant "
    "circuit breaker.  Off = one conf read per query, no token exists, "
    "and the engine's plan/readback pattern is bit-identical to the "
    "uncancellable engine (docs/robustness.md).")

DEADLINE_MS = register(
    "spark.rapids.tpu.serving.deadlineMs", 0.0,
    "Per-query deadline in milliseconds (0 = none).  Enforced from the "
    "admission queue onward: a query whose deadline expires while "
    "queued is shed with zero device work (no jit dispatch, no "
    "upload); one that expires mid-flight unwinds cooperatively at "
    "the next checkpoint.  Either way the query raises QueryCancelled "
    "(reason deadline_exceeded) and its event-log record carries "
    "engine=\"deadline_exceeded\" (docs/serving.md).",
    check=lambda v: v >= 0)

BREAKER_THRESHOLD = register(
    "spark.rapids.tpu.serving.breaker.failureThreshold", 0,
    "Consecutive failed queries (crash or deadline_exceeded; explicit "
    "cancels are neutral) after which a tenant's circuit breaker "
    "OPENS: further admissions raise TenantQuarantined for "
    "breaker.cooldownMs, so a poison query stops consuming WFQ slots. "
    "0 disables the breaker.  Scoped to the serving tier "
    "(serving.maxConcurrent > 0).", check=lambda v: v >= 0)

BREAKER_COOLDOWN_MS = register(
    "spark.rapids.tpu.serving.breaker.cooldownMs", 5000.0,
    "How long an OPEN tenant breaker quarantines before admitting one "
    "half-open probe query; the probe's outcome closes the breaker or "
    "re-opens it for another cooldown (docs/serving.md).",
    check=lambda v: v >= 0)

BREAKER_MAX_TRIPS = register(
    "spark.rapids.tpu.serving.breaker.health.maxTrips", 0,
    "HC013 (tools/history) flags a query window whose "
    "cancel.breaker_trips counter delta exceeds this — tenants are "
    "crash-looping into quarantine faster than the fleet should "
    "tolerate (docs/serving.md).", check=lambda v: v >= 0)

#: poll granularity for interruptible waits (SRC012: every wait on the
#: serving path is bounded); grants/publishes still wake waiters via
#: notify, so this bounds only cancel/deadline RESPONSE latency
WAIT_POLL_S = 0.05


class QueryCancelled(RuntimeError):
    """The query was cancelled (``reason="cancelled"``) or its deadline
    expired (``reason="deadline_exceeded"``).  NEVER retryable: the
    retry ladder, the CPU-degrade rung and the fetch retry loop all
    fail fast on it (execs/retry.is_retryable gates on this type)."""

    def __init__(self, reason: str, detail: str = "",
                 query_id: Optional[int] = None):
        msg = reason if not detail else f"{reason}: {detail}"
        if query_id is not None:
            msg += f" (query_id={query_id})"
        super().__init__(msg)
        self.reason = reason
        self.detail = detail
        self.query_id = query_id
        #: set once a per-query record was emitted, so the outer
        #: collect wrapper does not double-record
        self.recorded = False


class TenantQuarantined(AdmissionRejected):
    """This tenant's circuit breaker is OPEN (its queries kept dying):
    the serving tier sheds the query at admission instead of letting a
    poison query consume another WFQ slot.  Subclasses
    AdmissionRejected so load-shedding callers handle both alike;
    retry after serving.breaker.cooldownMs."""


class CancelToken:
    """One query's cancellation state.  Thread-safe; crossed between
    threads by capture/attach (see module doc).  ``cancel()`` is
    first-writer-wins: the first reason sticks."""

    __slots__ = ("tenant", "deadline_ns", "query_id", "reason",
                 "detail", "_mu")

    def __init__(self, tenant: str = "default",
                 deadline_ms: Optional[float] = None):
        self.tenant = tenant
        self.deadline_ns = (
            time.monotonic_ns() + int(deadline_ms * 1e6)
            if deadline_ms else None)
        self.query_id: Optional[int] = None
        self.reason: Optional[str] = None
        self.detail = ""
        self._mu = threading.Lock()

    def cancel(self, reason: str = "cancelled",
               detail: str = "") -> bool:
        """Request cancellation; False if already cancelled (the first
        reason sticks).  Wakes nothing by itself — the query's blocked
        seams poll on the WAIT_POLL_S cadence."""
        with self._mu:
            if self.reason is not None:
                return False
            self.reason = reason
            self.detail = detail
        if _tr.TRACER.enabled:
            _tr.event("cancel.request", reason=reason,
                      query_id=self.query_id, tenant=self.tenant)
        return True

    @property
    def cancelled(self) -> bool:
        return self.reason is not None

    def expired(self) -> bool:
        return self.deadline_ns is not None \
            and time.monotonic_ns() >= self.deadline_ns

    def remaining_s(self) -> Optional[float]:
        if self.deadline_ns is None:
            return None
        return (self.deadline_ns - time.monotonic_ns()) / 1e9

    def check(self) -> None:
        """Raise :class:`QueryCancelled` if cancelled or past
        deadline; otherwise return (two attribute reads)."""
        r = self.reason
        if r is None and self.deadline_ns is not None \
                and time.monotonic_ns() >= self.deadline_ns:
            self.cancel("deadline_exceeded",
                        detail="per-query deadline "
                               "(serving.deadlineMs) exceeded")
            r = self.reason
        if r is not None:
            if _tr.TRACER.enabled:
                _tr.event("cancel.unwind", reason=r,
                          query_id=self.query_id)
            raise QueryCancelled(r, self.detail, self.query_id)


def describe_token(tok: CancelToken) -> dict:
    """JSON-safe view of one token's state — the ops plane's
    ``/queries`` cancel column (obs/__init__.py)."""
    return {
        "tenant": tok.tenant,
        "query_id": tok.query_id,
        "reason": tok.reason,
        "detail": tok.detail or None,
        "deadline_remaining_s": (
            round(tok.remaining_s(), 3)
            if tok.deadline_ns is not None else None),
    }


class TokenSet:
    """A lock-protected set of live tokens — the session's (and each
    PreparedQuery's) handle for ``cancel()``."""

    def __init__(self):
        self._mu = threading.Lock()
        self._toks: set = set()  # guard: _mu

    def add(self, tok: Optional[CancelToken]) -> None:
        if tok is None:
            return
        with self._mu:
            self._toks.add(tok)

    def discard(self, tok: Optional[CancelToken]) -> None:
        if tok is None:
            return
        with self._mu:
            self._toks.discard(tok)

    def __len__(self) -> int:
        with self._mu:
            return len(self._toks)

    def cancel(self, query_id: Optional[int] = None,
               reason: str = "cancelled") -> int:
        """Cancel every tracked in-flight query (or just ``query_id``);
        returns how many tokens this call newly cancelled.  Queries
        still in the admission queue have no query id yet and are only
        matched by the cancel-all form."""
        with self._mu:
            toks = list(self._toks)
        n = 0
        for t in toks:
            if query_id is None or t.query_id == query_id:
                if t.cancel(reason):
                    n += 1
        return n


# ------------------------------------------------------------------ #
# Thread-local carry (the tracer-context discipline)
# ------------------------------------------------------------------ #

_TL = threading.local()

#: process-wide live-token gauge (telemetry's cancel.active)
_ACTIVE = 0
_ACTIVE_MU = tracked_lock("cancel.active")


def current_token() -> Optional[CancelToken]:
    """This thread's token (capture on the dispatching side before a
    thread hop, :func:`attach_token` on the receiving side — exactly
    the tracer-context / conf-snapshot hop discipline)."""
    return getattr(_TL, "token", None)


@contextlib.contextmanager
def attach_token(tok: Optional[CancelToken]) -> Iterator[None]:
    """Install a token on the current thread for the block (a nested
    query's token shadows the outer one; the outer is restored on
    exit)."""
    prev = getattr(_TL, "token", None)
    _TL.token = tok
    try:
        yield
    finally:
        _TL.token = prev


def check_point() -> None:
    """THE cooperative cancellation checkpoint (and the
    ``cancel.check`` fault seam): no token attached = one thread-local
    read.  An armed injected hit cancels the CURRENT token and unwinds
    through the real cancellation path — chaos runs exercise the
    production teardown, not a test-only shortcut."""
    tok = getattr(_TL, "token", None)
    if tok is None:
        return
    from spark_rapids_tpu.robustness import faults as _faults

    try:
        _faults.fault_point("cancel.check")
    except _faults.InjectedFault as e:
        tok.cancel("cancelled", detail=str(e))
    tok.check()


def poll_timeout(tok: Optional[CancelToken],
                 default: float = WAIT_POLL_S) -> float:
    """Bound for one blocking-wait slice: the poll cadence, clipped to
    the token's remaining deadline so expiry is observed promptly."""
    if tok is None:
        return default
    rem = tok.remaining_s()
    if rem is None:
        return default
    return max(0.0, min(default, rem))


# ------------------------------------------------------------------ #
# Per-query lifecycle (session.py's prologue/epilogue hooks)
# ------------------------------------------------------------------ #


def begin(conf, tenant: str = "default") -> Optional[CancelToken]:
    """The query-boundary hook: None after ONE conf read when
    cancellation is disabled; otherwise a fresh token carrying the
    conf deadline (serving.deadlineMs, 0 = none)."""
    global _ACTIVE
    if not conf.get(CANCEL_ENABLED):
        return None
    dl = float(conf.get(DEADLINE_MS))
    tok = CancelToken(tenant, deadline_ms=dl if dl > 0 else None)
    with _ACTIVE_MU:
        _ACTIVE += 1
    return tok


def end(tok: Optional[CancelToken]) -> None:
    global _ACTIVE
    if tok is None:
        return
    with _ACTIVE_MU:
        _ACTIVE -= 1


def active_count() -> int:
    with _ACTIVE_MU:
        return _ACTIVE


# ------------------------------------------------------------------ #
# Outcome counters (the event log's cancel.* surface)
# ------------------------------------------------------------------ #

_STATS_MU = threading.Lock()
_STATS = {"cancelled": 0, "deadline_exceeded": 0, "breaker_trips": 0,
          "quarantined": 0}


def tick_outcome(reason: str) -> None:
    """Count one unwound query by reason (session.py's cancellation
    epilogue calls this exactly once per cancelled query)."""
    key = "deadline_exceeded" if reason == "deadline_exceeded" \
        else "cancelled"
    with _STATS_MU:
        _STATS[key] += 1


def stats() -> dict:
    with _STATS_MU:
        return dict(_STATS)


def reset_stats() -> None:
    with _STATS_MU:
        for k in _STATS:
            _STATS[k] = 0


# ------------------------------------------------------------------ #
# Per-tenant circuit breaker
# ------------------------------------------------------------------ #


class _Breaker:
    __slots__ = ("failures", "state", "open_until_ns", "probing")

    def __init__(self):
        # every _Breaker lives in _BREAKERS and is mutated only under
        # the module-level registry lock (a per-instance lock would
        # add nothing: admit/result always resolve tenant -> breaker
        # under _BREAKERS_MU anyway)
        self.failures = 0           # guard: _BREAKERS_MU
        self.state = "closed"       # guard: _BREAKERS_MU
        self.open_until_ns = 0      # guard: _BREAKERS_MU
        self.probing = False        # guard: _BREAKERS_MU


_BREAKERS: dict[str, _Breaker] = {}
_BREAKERS_MU = tracked_lock("cancel.breakers")


def breaker_admit(conf, tenant: str) -> None:
    """Admission-time gate: raise :class:`TenantQuarantined` while the
    tenant's breaker is open (or while its half-open probe is still in
    flight).  Disabled (failureThreshold <= 0, the default) this is
    one conf read."""
    thr = int(conf.get(BREAKER_THRESHOLD))
    if thr <= 0:
        return
    now = time.monotonic_ns()
    with _BREAKERS_MU:
        b = _BREAKERS.get(tenant)
        if b is None:
            b = _BREAKERS[tenant] = _Breaker()
        if b.state == "open":
            if now < b.open_until_ns:
                quarantine = True
            else:
                b.state = "half_open"
                b.probing = True  # this query is the probe
                quarantine = False
        elif b.state == "half_open":
            quarantine = b.probing  # one probe at a time
            if not quarantine:
                b.probing = True
        else:
            quarantine = False
        if quarantine:
            remain_ms = max(0.0, (b.open_until_ns - now) / 1e6) \
                if b.state == "open" else 0.0
    if quarantine:
        with _STATS_MU:
            _STATS["quarantined"] += 1
        if _tr.TRACER.enabled:
            _tr.event("breaker.quarantined", tenant=tenant)
        raise TenantQuarantined(
            f"tenant {tenant!r} is quarantined (circuit breaker "
            f"open after repeated failures; retry in "
            f"~{remain_ms:.0f}ms or after a successful probe)")


def breaker_release(conf, tenant: str) -> None:
    """Release a claimed half-open probe WITHOUT counting an outcome:
    the probe query exited through a breaker-neutral path — explicit
    user cancel, abandoned stream, or it never got admitted at all
    (queue full, deadline expired while queued).  The breaker stays
    half-open and the NEXT query becomes the probe; without this, a
    lost probe would leave ``probing`` set forever and quarantine the
    tenant with no escape.  No-op for closed/open breakers and when
    the breaker is disabled."""
    if int(conf.get(BREAKER_THRESHOLD)) <= 0:
        return
    with _BREAKERS_MU:
        b = _BREAKERS.get(tenant)
        if b is not None and b.state == "half_open":
            b.probing = False


def breaker_result(conf, tenant: str, ok: bool) -> None:
    """Outcome hook for an ADMITTED query: success closes/heals, a
    failure (crash or deadline_exceeded — explicit cancels never reach
    here) counts toward the threshold; a failed half-open probe
    re-opens for another cooldown."""
    thr = int(conf.get(BREAKER_THRESHOLD))
    if thr <= 0:
        return
    cooldown_ns = int(float(conf.get(BREAKER_COOLDOWN_MS)) * 1e6)
    tripped = False
    with _BREAKERS_MU:
        b = _BREAKERS.get(tenant)
        if b is None:
            b = _BREAKERS[tenant] = _Breaker()
        if b.state == "half_open":
            b.probing = False
            if ok:
                b.state = "closed"
                b.failures = 0
            else:
                b.state = "open"
                b.open_until_ns = time.monotonic_ns() + cooldown_ns
                tripped = True
        elif ok:
            b.failures = 0
        else:
            b.failures += 1
            if b.failures >= thr:
                b.state = "open"
                b.open_until_ns = time.monotonic_ns() + cooldown_ns
                b.failures = 0
                tripped = True
    if tripped:
        with _STATS_MU:
            _STATS["breaker_trips"] += 1
        if _tr.TRACER.enabled:
            _tr.event("breaker.trip", tenant=tenant)


def breaker_state(tenant: str) -> str:
    """'closed' | 'open' | 'half_open' (tests/observability)."""
    with _BREAKERS_MU:
        b = _BREAKERS.get(tenant)
        return b.state if b is not None else "closed"


def reset_breakers() -> None:
    with _BREAKERS_MU:
        _BREAKERS.clear()


def reset() -> None:
    """Test isolation: breakers + outcome counters (live tokens are
    owned by their queries and left alone)."""
    reset_breakers()
    reset_stats()
